#!/usr/bin/env python
"""On-chip bit-exactness check for the sort-partitioned binning kernel.

The tests in tests/test_partitioned.py run the kernel in interpret mode
(CPU); Mosaic lowering on the real chip differs (layouts, bf16 matmul
accumulation order), so after any kernel change this script must pass on
the TPU before the change counts as verified. Compares the partitioned
raster bit-for-bit against the XLA scatter contract at the headline
window for clustered, adversarial-uniform, and boundary-straddling
inputs, across the swept tunable space.

    PYTHONPATH=. python tools/verify_partitioned_onchip.py [--state FILE]

``--state FILE`` records each (case, combo) verdict as it lands, and a
re-run skips combos already verified — the axon relay dies mid-run
often enough that all-or-nothing verification never finishes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np


def _load_epoch_mod():
    """Load tools/_epoch.py by path (tools/ is not a package)."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_epoch.py")
    spec = importlib.util.spec_from_file_location("_epoch", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _kernel_epoch():
    """Hash of the kernel sources under verification (tools/_epoch.py).
    State keys are prefixed with this, so editing ANY verified kernel
    invalidates every recorded verdict — the script's contract ("after
    any kernel change this must pass on the TPU") cannot be satisfied
    by stale entries from the pre-change kernel (round-5 review
    finding). This script hashes itself in too: changing the
    cases/shapes/rng here must also invalidate old verdicts — they
    were produced by the old inputs."""
    return _load_epoch_mod().kernel_epoch(
        extra_paths=(os.path.abspath(__file__),))


EPOCH = _kernel_epoch()
RETRY_ERRORS = False

#: Combos skipped this run because their failure was classified
#: transient: they are NOT settled into state, so they stay UNVERIFIED
#: under the current epoch and the exit code must say so (the round-5
#: relay run "passed" with rc 0 while whole sections had silently
#: skipped — automation read partial coverage as verified).
TRANSIENT_SKIPS = 0


def _ek(key):
    return f"{EPOCH}|{key}"


def _load_state(path):
    if not path or not os.path.exists(path):
        return {}
    out = {}
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn final line from a killed writer
            out.update(rec)
    return out


def _append_state(path, state, key, ok):
    state[_ek(key)] = ok  # keep the in-memory view (and tally) current
    if not path:
        return
    with open(path, "a") as f:
        f.write(json.dumps({_ek(key): ok}) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _settled(state, key):
    """A combo is settled if it verified bit-exact under the CURRENT
    kernel epoch, OR it failed to compile/run on this chip (recorded as
    "error:..."): one toolchain regression must not re-burn its compile
    timeout on every resume, and must never abort the remaining combos
    (the round-5 x64 flat-sort scoped-vmem OOM killed the whole run
    mid-artifact). ``--retry-errors`` unsettles the error entries once
    the toolchain is fixed."""
    v = state.get(_ek(key))
    if v is True:
        return True
    return (not RETRY_ERRORS
            and isinstance(v, str) and v.startswith("error:"))


#: Exception types that mark a chip-side failure as TRANSIENT (relay
#: death, worker restart, network): these are NOT settled into state —
#: the next resume simply retries the combo. Only deterministic
#: failures (the compile helper rejecting the program) are worth
#: remembering.
_TRANSIENT_EXC_TYPES = (ConnectionError, TimeoutError, OSError)

#: gRPC status codes the runtime wraps transient transport failures in.
#: jax surfaces them as XlaRuntimeError/JaxRuntimeError whose message
#: STARTS with the status name (e.g. "UNAVAILABLE: TPU worker process
#: crashed or restarted" — the observed bench_job killer), so the code
#: is parsed from the message prefix rather than substring-matched
#: anywhere in the text (a kernel asserting about a "connection matrix"
#: must not read as a network blip).
_TRANSIENT_GRPC_CODES = frozenset({
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED", "CANCELLED",
})


def _is_transient(e: BaseException) -> bool:
    """Transient = retry-worthy: a transport/availability exception
    type, or a runtime error carrying a transient gRPC status code as
    its message prefix."""
    if isinstance(e, _TRANSIENT_EXC_TYPES):
        return True
    head = str(e).lstrip().split(":", 1)[0].strip().upper()
    return head in _TRANSIENT_GRPC_CODES


def _run_combo(state_path, state, key, fn):
    """Run one combo's device computation; a compile/runtime failure is
    recorded and reported instead of killing the run. Returns the result
    or None on failure."""
    global TRANSIENT_SKIPS
    try:
        return fn()
    except Exception as e:  # noqa: BLE001 — record any chip-side failure
        msg = f"{type(e).__name__}: {str(e)[:300]}"
        if _is_transient(e):
            TRANSIENT_SKIPS += 1
            print(json.dumps({"combo": key, "transient": msg}), flush=True)
            return None
        _append_state(state_path, state, key, f"error:{msg}")
        print(json.dumps({"combo": key, "error": f"error:{msg}"}),
              flush=True)
        return None


def _epoch_tally(state):
    """Verdict counts scanned from the state itself (this epoch only):
    resume-proof — a combo that errored in a PREVIOUS run of the same
    epoch stays visible in this run's artifact instead of vanishing
    behind the skip path."""
    ok = fail = err = 0
    prefix = f"{EPOCH}|"
    for k, v in state.items():
        if not k.startswith(prefix):
            continue
        if v is True:
            ok += 1
        elif v is False:
            fail += 1
        elif isinstance(v, str) and v.startswith("error:"):
            err += 1
    return ok, fail, err


def _verdict(fail_n: int, err_n: int, transients: int) -> str:
    if fail_n:
        return "MISMATCH"
    if transients:
        return "UNSETTLED"
    return "BIT-EXACT+ERRORS" if err_n else "BIT-EXACT"


def _final_rc(fail_n: int, err_n: int, transients: int) -> int:
    """1: bit-exactness mismatch (kernel wrong); 4: combos skipped on
    transient failures — they remain UNVERIFIED under this epoch, so
    the run is incomplete, not passed (the round-5 relay run exited 0
    with silent skips and automation read partial coverage as verified;
    4 is deliberately outside the runner's ok_rcs so it retries); 3:
    combos that never ran (deterministic compile/runtime error) —
    automation must not read "every combo that ran passed" as
    "verified" when whole sections errored."""
    if fail_n:
        return 1
    if transients:
        return 4
    return 3 if err_n else 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--state", default=None,
                    help="JSONL checkpoint; verified combos are skipped")
    ap.add_argument("--retry-errors", action="store_true",
                    help="re-run combos recorded as compile/runtime "
                    "errors (use after a toolchain fix)")
    args = ap.parse_args()
    global RETRY_ERRORS
    RETRY_ERRORS = args.retry_errors
    state = _load_state(args.state)
    print(json.dumps({"kernel_epoch": EPOCH}), flush=True)
    import jax
    import jax.numpy as jnp

    # On CPU the kernel silently runs in interpret mode — the exact
    # path the interpret-mode tests already cover. Verifying Mosaic
    # lowering requires the real chip; anything else must fail loudly.
    platform = jax.devices()[0].platform
    if platform == "cpu":
        print(json.dumps({"error": "refusing to verify on CPU "
                          "(interpret mode is not Mosaic)",
                          "device": platform}))
        return 2

    from heatmap_tpu.ops import window_from_bounds
    from heatmap_tpu.ops.histogram import bin_rowcol_window
    from heatmap_tpu.ops.partitioned import bin_rowcol_window_partitioned
    from heatmap_tpu.tilemath import mercator

    win = window_from_bounds((44.0, 51.0), (-127.0, -117.0), zoom=15,
                             align_levels=12, pad_multiple=256)
    rng = np.random.default_rng(0)
    n = 1 << 22

    def project(lat, lon):
        r, c, v = mercator.project_points(jnp.asarray(lat), jnp.asarray(lon),
                                          win.zoom, dtype=jnp.float32)
        return r, c, v

    cases = {}
    # Clustered: hot core + sparse fringe (the good-chunk fast path).
    lat = np.concatenate([47.6 + rng.normal(0, 0.02, n // 2),
                          47.6 + rng.normal(0, 0.8, n // 2)]).astype(np.float32)
    lon = np.concatenate([-122.3 + rng.normal(0, 0.03, n // 2),
                          -122.3 + rng.normal(0, 1.2, n // 2)]).astype(np.float32)
    cases["clustered"] = (lat, lon)
    # Adversarial uniform over the whole window: every chunk straddles
    # many blocks -> exercises the lax.cond full-scatter fallback.
    cases["uniform"] = (
        rng.uniform(44.0, 51.0, n).astype(np.float32),
        rng.uniform(-127.0, -117.0, n).astype(np.float32),
    )
    # Out-of-window + single-cell pileup (tail & overflow paths).
    lat = np.full(n, 47.6, np.float32)
    lon = np.full(n, -122.3, np.float32)
    lat[: n // 8] = rng.uniform(-60.0, 85.0, n // 8)
    lon[: n // 8] = rng.uniform(-180.0, 179.9, n // 8)
    cases["pileup"] = (lat, lon)

    # Every combo names "streams" explicitly: checkpoint keys must not
    # alias across DEFAULT_STREAMS flips (the round-2 1->8 flip turned
    # the old "{}" key into a different configuration). The pre-flip
    # "{}"/bare-tunable entries in existing state files recorded
    # streams=1 runs and stay as history; the list below covers the
    # flat-sort path explicitly plus the PRODUCTION default shape
    # (streams=8) across the tunable grid.
    combos = [
        {"streams": 1},
        {"streams": 8},
        {"streams": 32},
        {"streams": 8, "block_cells": 1 << 12},
        {"streams": 8, "block_cells": 1 << 14},
        {"streams": 8, "chunk": 512},
        {"streams": 8, "chunk": 2048},
        {"streams": 8, "bad_frac": 32},
        {"streams": 8, "bad_frac": 128},
    ]
    done = 0
    for name, (lat, lon) in cases.items():
        todo = [kw for kw in combos
                if not _settled(
                    state, f"{name}|{json.dumps(kw, sort_keys=True)}")]
        if not todo:
            done += len(combos)
            continue
        r, c, v = project(lat, lon)
        expected = np.asarray(bin_rowcol_window(r, c, win, valid=v))
        for kw in combos:
            key = f"{name}|{json.dumps(kw, sort_keys=True)}"
            if _settled(state, key):
                done += 1
                continue
            got = _run_combo(args.state, state, key,
                             lambda: np.asarray(bin_rowcol_window_partitioned(
                                 r, c, win, valid=v, interpret=False, **kw)))
            if got is None:
                done += 1
                continue
            ok = bool((got == expected).all())
            _append_state(args.state, state, key, ok)
            done += 1
            print(json.dumps({"case": name, "kw": kw, "bit_exact": ok,
                              "total": int(expected.sum())}), flush=True)
            if not ok:
                bad = np.argwhere(got != expected)
                print(f"  first diffs at {bad[:5].tolist()}", flush=True)

    # Weighted variant (after the count gate — counts decide the
    # headline routing): integer-valued f32 weights make the sums
    # order-independent, so bit-exactness vs the weighted scatter is
    # the on-chip contract exactly as for counts — PROVIDED every
    # per-cell sum stays below 2^24. The pileup case drops ~7/8 of the
    # 2^22 points into one cell, so weights must be <= 3 to keep that
    # cell's sum (~3.7M * 3 = 11M) inside the exact range.
    w_int = jnp.asarray(rng.integers(0, 4, n).astype(np.float32))
    weighted_combos = [{"streams": 1}, {"streams": 8}]
    for name, (lat, lon) in cases.items():
        todo = [kw for kw in weighted_combos
                if not _settled(
                    state,
                    f"{name}|weighted|{json.dumps(kw, sort_keys=True)}")]
        if not todo:
            done += len(weighted_combos)
            continue
        r, c, v = project(lat, lon)
        expected = np.asarray(bin_rowcol_window(
            r, c, win, weights=w_int, valid=v))
        for kw in weighted_combos:
            key = f"{name}|weighted|{json.dumps(kw, sort_keys=True)}"
            if _settled(state, key):
                done += 1
                continue
            got = _run_combo(args.state, state, key,
                             lambda: np.asarray(bin_rowcol_window_partitioned(
                                 r, c, win, weights=w_int, valid=v,
                                 interpret=False, **kw)))
            if got is None:
                done += 1
                continue
            ok = bool((got == expected).all())
            _append_state(args.state, state, key, ok)
            done += 1
            print(json.dumps({"case": name, "weighted": True, "kw": kw,
                              "bit_exact": ok,
                              "total": float(expected.sum())}), flush=True)
            if not ok:
                bad = np.argwhere(got != expected)
                print(f"  first diffs at {bad[:5].tolist()}", flush=True)
    # Everything below runs with x64 ENABLED — the batch job's actual
    # configuration (z21 precision policy, int64 composite keys). The
    # sections above ran with x64 off, which round 2 learned is a
    # DIFFERENT Mosaic lowering: weak Python-int literals trace as
    # int64 under x64 and can break kernel lowering outright
    # (tests/test_lowering.py pins the lowering; this section pins
    # on-chip execution bit-exactness in the x64 world).
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    # Window kernels under x64, f64 projection -> int64 rows/cols,
    # exactly as run_job hands them to the binning backend.
    x64_combos = [{"streams": 1}, {"streams": 8}]
    for name in ("clustered", "pileup"):
        lat, lon = cases[name]
        todo = [kw for kw in x64_combos
                if not _settled(
                    state, f"{name}|x64|{json.dumps(kw, sort_keys=True)}")
                or not _settled(
                    state,
                    f"{name}|x64|weighted|{json.dumps(kw, sort_keys=True)}")]
        if not todo:
            done += 2 * len(x64_combos)
            continue
        r, c, v = mercator.project_points(
            jnp.asarray(lat, jnp.float64), jnp.asarray(lon, jnp.float64),
            win.zoom, dtype=jnp.float64)
        expected = np.asarray(bin_rowcol_window(r, c, win, valid=v))
        expected_w = np.asarray(bin_rowcol_window(
            r, c, win, weights=w_int, valid=v))
        for kw in x64_combos:
            for wtd in (False, True):
                key = (f"{name}|x64|weighted|{json.dumps(kw, sort_keys=True)}"
                       if wtd else
                       f"{name}|x64|{json.dumps(kw, sort_keys=True)}")
                if _settled(state, key):
                    done += 1
                    continue
                got = _run_combo(
                    args.state, state, key,
                    lambda: np.asarray(bin_rowcol_window_partitioned(
                        r, c, win, weights=w_int if wtd else None, valid=v,
                        interpret=False, **kw)))
                if got is None:
                    done += 1
                    continue
                exp = expected_w if wtd else expected
                ok = bool((got == exp).all())
                _append_state(args.state, state, key, ok)
                done += 1
                print(json.dumps({"case": name, "x64": True,
                                  "weighted": wtd, "kw": kw,
                                  "bit_exact": ok}), flush=True)

    # shard_map + pallas on the real chip: a 1-device mesh exercises
    # the mesh kernels' Mosaic compile (pallas_call under shard_map,
    # check_vma=False) that the 8-CPU-mesh tests can only run in
    # interpret mode. Routing backend="partitioned" explicitly — auto
    # picks it for this window anyway, but the artifact should name
    # what it verified.
    from heatmap_tpu.parallel import (
        bin_points_replicated,
        bin_points_rowsharded,
        make_mesh,
    )

    mesh1 = make_mesh(data=1, tile=1)
    lat, lon = cases["clustered"]
    dla = jnp.asarray(lat, jnp.float64)
    dlo = jnp.asarray(lon, jnp.float64)
    mesh_fns = {
        # psum over a pallas output (the replicated merge) and
        # psum_scatter over one (the rowsharded merge) are different
        # Mosaic/collective compositions; gate both.
        "mesh1|x64|replicated-partitioned": lambda: bin_points_replicated(
            dla, dlo, win, mesh1, backend="partitioned"),
        "mesh1|x64|rowsharded-partitioned": lambda: bin_points_rowsharded(
            dla, dlo, win, mesh1, backend="partitioned"),
    }
    expected_mesh = None
    for key, fn in mesh_fns.items():
        if _settled(state, key):
            done += 1
            continue
        if expected_mesh is None:
            r, c, v = mercator.project_points(dla, dlo, win.zoom,
                                              dtype=jnp.float64)
            expected_mesh = np.asarray(bin_rowcol_window(r, c, win, valid=v))
        got = _run_combo(args.state, state, key,
                         lambda: np.asarray(fn()))
        if got is None:
            done += 1
            continue
        ok = bool((got == expected_mesh).all())
        _append_state(args.state, state, key, ok)
        done += 1
        print(json.dumps({"case": key, "bit_exact": ok}), flush=True)

    # Multi-channel cascade segment-reduction kernel
    # (ops/sparse_partitioned.py): bit-exact vs aggregate_sorted_keys
    # under real Mosaic lowering. Interpret-mode tests pass; this is
    # the gate before pyramid_sparse_morton_partitioned routes anywhere.

    from heatmap_tpu.ops.sparse import aggregate_sorted_keys
    from heatmap_tpu.ops.sparse_partitioned import (
        aggregate_sorted_keys_partitioned,
    )

    sent = np.iinfo(np.int64).max
    kn = 1 << 22
    kcases = {
        "seg-clustered": np.sort(
            rng.choice(1 << 42, kn // 64, replace=False)[
                rng.integers(0, kn // 64, kn)
            ].astype(np.int64)),
        "seg-unique": np.sort(
            rng.choice(1 << 50, kn, replace=False).astype(np.int64)),
        "seg-pileup": np.sort(np.concatenate([
            np.full(kn - kn // 8, 123456789, np.int64),
            rng.choice(1 << 40, kn // 8, replace=False).astype(np.int64),
        ])),
    }
    kcombos = [{}, {"block_cells": 1 << 12}, {"slab": 1 << 20},
               {"streams": 4, "slab": 1 << 20}]
    for name, keys in kcases.items():
        todo = [kw for kw in kcombos
                if not _settled(
                    state, f"{name}|{json.dumps(kw, sort_keys=True)}")]
        if not todo:
            done += len(kcombos)
            continue
        dk = jnp.asarray(keys, jnp.int64)
        wu, ws, wn = aggregate_sorted_keys(
            dk, jnp.ones(kn, jnp.int32), kn, sentinel=sent)
        wu, ws, m = np.asarray(wu), np.asarray(ws), int(wn)
        for kw in kcombos:
            key = f"{name}|{json.dumps(kw, sort_keys=True)}"
            if _settled(state, key):
                done += 1
                continue
            res = _run_combo(
                args.state, state, key,
                lambda: [np.asarray(a) for a in
                         aggregate_sorted_keys_partitioned(
                             dk, kn, sentinel=sent, interpret=False, **kw)])
            if res is None:
                done += 1
                continue
            gu, gs, gn = res
            ok = (int(gn) == m
                  and bool((gu[:m] == wu[:m]).all())
                  and bool((gs[:m] == ws[:m]).all()))
            _append_state(args.state, state, key, ok)
            done += 1
            print(json.dumps({"case": name, "kw": kw, "bit_exact": ok,
                              "uniques": m}), flush=True)

    ok_n, fail_n, err_n = _epoch_tally(state)
    print(json.dumps({
        "device": jax.devices()[0].platform,
        "kernel_epoch": EPOCH,
        "bit_exact": ok_n,
        "failures": fail_n,
        "errors": err_n,
        "transient_skips": TRANSIENT_SKIPS,
        "combos_done": done,
        "verdict": _verdict(fail_n, err_n, TRANSIENT_SKIPS),
    }), flush=True)
    return _final_rc(fail_n, err_n, TRANSIENT_SKIPS)


if __name__ == "__main__":
    sys.exit(main())
