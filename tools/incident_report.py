#!/usr/bin/env python
"""Fold an incident bundle into a human post-mortem report.

An incident bundle (obs/incident.py) is a self-contained directory —
``trace.json`` (flight-recorder spans as Chrome trace-event JSON),
``events.json`` (recent event tail), ``metrics.json`` (full registry
snapshot), ``state.json`` (healthz / fleet / config providers), and
``manifest.json`` (trigger envelope). This tool reads one and prints
the story an on-call wants first:

- the trigger edge (what flushed the bundle, when, under which run);
- the critical path of the slowest captured trace (the exact
  tools/trace_analyze.py analysis, partial-tree tolerant);
- the event tail leading up to the flush (errors, sheds, faults last);
- what changed in the telemetry window before the trigger — per-series
  first→last movement from the embedded ``telemetry.json`` raw-tier
  history (obs/timeseries.py), biggest movers first;
- headline failure metrics (5xx, sheds, breaker opens, incidents);
- the degraded/breaker state the serving tier reported.

    python tools/incident_report.py incidents/<run_id>-<seq> [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import trace_analyze  # noqa: E402

# Metrics worth a headline row when present (failure shapes first).
_HEADLINE = (
    "http_requests_total",
    "requests_shed_total",
    "breaker_transitions_total",
    "faults_injected_total",
    "incidents_total",
    "recorder_dropped_total",
    "slo_breaches_total",
)


def load_bundle(path: str) -> dict:
    """``{manifest, trace_spans, events, metrics, state}`` from a
    bundle directory; missing files load as empty (a size-capped
    bundle still reports what it kept)."""
    if not os.path.isdir(path):
        raise SystemExit(f"{path!r} is not an incident bundle directory")

    def _load(name, default):
        full = os.path.join(path, name)
        if not os.path.exists(full):
            return default
        with open(full) as f:
            return json.load(f)

    return {
        "manifest": _load("manifest.json", {}),
        "trace_spans": trace_analyze.load_events(path)
        if os.path.exists(os.path.join(path, "trace.json")) else [],
        "events": _load("events.json", []),
        "metrics": _load("metrics.json", {}),
        "state": _load("state.json", {}),
        "telemetry": _load("telemetry.json", {}),
    }


def telemetry_deltas(telemetry: dict, limit: int = 24) -> dict:
    """Per-series movement over the embedded pre-trigger window.

    Each row summarizes one series' raw-tier history — first and last
    bucket value, the window min/max, and the first→last delta — sorted
    by relative movement so the biggest movers (the "what changed"
    answer) print first. Points are ``[ts,min,max,sum,count,last]``
    rows from TimeSeriesStore.recent_window.
    """
    series = telemetry.get("series") or {}
    rows = []
    for key, entry in series.items():
        pts = entry.get("points") or []
        if not pts:
            continue
        first, last = pts[0][5], pts[-1][5]
        lo = min(p[1] for p in pts)
        hi = max(p[2] for p in pts)
        denom = max(abs(first), abs(last), 1e-9)
        rows.append({
            "series": key,
            "first": first,
            "last": last,
            "delta": last - first,
            "min": lo,
            "max": hi,
            "buckets": len(pts),
            "rel_change": abs(last - first) / denom,
        })
    rows.sort(key=lambda r: (-r["rel_change"], r["series"]))
    return {
        "window_s": telemetry.get("window_s"),
        "from": telemetry.get("from"),
        "to": telemetry.get("to"),
        "n_series": len(series),
        "truncated_series": telemetry.get("truncated_series", 0),
        "movers": rows[:limit],
    }


def headline_metrics(metrics: dict) -> list[dict]:
    """Flatten the snapshot's failure-shaped series into table rows."""
    rows = []
    for name in _HEADLINE:
        entry = metrics.get(name)
        if not entry:
            continue
        for sample in entry.get("samples", ()):
            value = sample.get("value", sample.get("count"))
            labels = ",".join(f"{k}={v}" for k, v
                              in sorted(sample.get("labels", {}).items()))
            rows.append({"metric": name, "labels": labels, "value": value})
    return rows


def event_tail(events: list, n: int = 20) -> list[dict]:
    """The last ``n`` events, compacted to the fields that matter."""
    out = []
    for rec in events[-n:]:
        row = {"ts": rec.get("ts"), "event": rec.get("event")}
        for key in ("status", "route", "cause", "site", "slo", "error",
                    "trigger", "detail", "trace_id"):
            if key in rec:
                row[key] = rec[key]
        out.append(row)
    return out


def build_report(bundle: dict, top: int = 8) -> dict:
    manifest = bundle["manifest"]
    trace = (trace_analyze.analyze(bundle["trace_spans"], top=top)
             if bundle["trace_spans"] else
             {"n_spans": 0, "n_traces": 0, "traces": [], "top_self": []})
    return {
        "trigger": manifest.get("trigger"),
        "detail": manifest.get("detail"),
        "run_id": manifest.get("run_id"),
        "seq": manifest.get("seq"),
        "ts": manifest.get("ts"),
        "bytes": manifest.get("bytes"),
        "recorder": manifest.get("recorder"),
        "trace": trace,
        "event_tail": event_tail(bundle["events"]),
        "metrics": headline_metrics(bundle["metrics"]),
        "telemetry": telemetry_deltas(bundle.get("telemetry") or {}),
        "state": bundle["state"],
    }


def format_report(report: dict, max_traces: int = 2) -> str:
    lines = [
        f"incident {report['run_id']}-{report['seq']}  "
        f"trigger={report['trigger']}"
        + (f"  detail={report['detail']}" if report.get("detail") else ""),
        f"ts={report['ts']}  bundle_bytes={report['bytes']}",
    ]
    rcd = report.get("recorder") or {}
    if rcd:
        lines.append(
            f"recorder: spans={rcd.get('spans')} events={rcd.get('events')} "
            f"dropped={rcd.get('dropped')} "
            f"promoted={rcd.get('promoted_traces')} trace(s)")
    if report["metrics"]:
        lines += ["", "failure metrics:"]
        for row in report["metrics"]:
            label = f"{{{row['labels']}}}" if row["labels"] else ""
            lines.append(f"  {row['metric']}{label} = {row['value']}")
    tel = report.get("telemetry") or {}
    if tel.get("movers"):
        window = tel.get("window_s")
        head = (f"what changed in the {window:.0f} s before the trigger"
                if isinstance(window, (int, float))
                else "what changed before the trigger")
        if tel.get("truncated_series"):
            head += f" ({tel['truncated_series']} series truncated)"
        lines += ["", head + ":"]
        lines.append(f"  {'series':<40} {'first':>10} {'last':>10} "
                     f"{'delta':>10} {'min':>10} {'max':>10}")
        for row in tel["movers"]:
            lines.append(
                f"  {row['series']:<40} {row['first']:>10.4g} "
                f"{row['last']:>10.4g} {row['delta']:>+10.4g} "
                f"{row['min']:>10.4g} {row['max']:>10.4g}")
    if report["event_tail"]:
        lines += ["", "event tail (oldest first):"]
        for row in report["event_tail"]:
            extra = " ".join(f"{k}={v}" for k, v in row.items()
                             if k not in ("ts", "event"))
            lines.append(f"  {row['ts']}: {row['event']}  {extra}".rstrip())
    trace = report["trace"]
    if trace["n_spans"]:
        lines += ["", trace_analyze.format_report(trace,
                                                  max_traces=max_traces)]
    else:
        lines += ["", "no spans captured (recorder ring was empty)"]
    state = report.get("state") or {}
    for name in sorted(state):
        lines += ["", f"state[{name}]:",
                  json.dumps(state[name], indent=1, sort_keys=True,
                             default=str)]
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="post-mortem table from an incident bundle")
    ap.add_argument("bundle", help="incident bundle directory "
                    "(incidents/<run_id>-<seq>/)")
    ap.add_argument("--top", type=int, default=8,
                    help="rows in the self-time table")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    ap.add_argument("--max-traces", type=int, default=2,
                    help="traces printed in table mode")
    args = ap.parse_args()
    report = build_report(load_bundle(args.bundle), top=args.top)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(format_report(report, max_traces=args.max_traces))
    return 0


if __name__ == "__main__":
    sys.exit(main())
