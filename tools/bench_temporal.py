#!/usr/bin/env python
"""Temporal-plane bench: fold latency per cut, retraction throughput,
and time-axis query latency: BENCH_temporal.json.

Four headline sections (docs/temporal.md):

- ``fold``     p50/p99 wall ms of a full ``fold_levels`` pass per cut
               kind (``alltime``, ``as_of``, ``window``, ``decay``)
               over a bucketed store — every iteration re-selects and
               re-merges, nothing is cached, so this is the cold-tile
               render cost a cache miss pays;
- ``serve``    p50/p99 of one ServeApp temporal tile request with the
               cache DISABLED-by-rotation (a fresh key per request via
               distinct as_of cuts), next to the all-time tile on the
               same store — the quotient is the temporal overhead a
               miss pays over the plain path;
- ``retract``  rows/sec for a predicate retraction (journal scan ->
               signed counter-batches), measured end to end including
               the cascade applies, plus the byte gate: the retracted
               store must equal a clean recompute over the survivors;
- ``growth``   p50/p99 of ``op=topk_growth`` evaluations and the
               stamped ``max_err`` at the default coefficient budget.

The ``alltime_byte_identical`` gate pins the tentpole invariant while
the clocks run: fold(all buckets + live) must equal the un-bucketed
overlay byte for byte. bench_gate never folds temporal cells when the
gate fails.

    PYTHONPATH=.:$PYTHONPATH python tools/bench_temporal.py \
        [--points 20000] [--iters 30] [--out BENCH_temporal.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def _pct(vals: list, q: float) -> float | None:
    if not vals:
        return None
    s = sorted(vals)
    return s[min(len(s) - 1, int(q * len(s)))]


def _timed_source(n: int, seed: int, t0: float, span: float):
    """Synthetic GPS points with timestamps spread over [t0, t0+span)
    so compaction lands them across several buckets."""
    import numpy as np

    rng = np.random.default_rng(seed)
    return {
        "latitude": rng.uniform(-60.0, 70.0, n),
        "longitude": rng.uniform(-179.0, 179.0, n),
        "user_id": ["u%d" % (j % 5) for j in range(n)],
        "timestamp": [str(t0 + span * j / n) for j in range(n)],
    }


def _levelbytes(levels: list) -> list:
    import numpy as np

    out = []
    for lvl in levels:
        rec = {}
        for k, v in sorted(lvl.items()):
            if hasattr(v, "__len__") and not isinstance(v, str):
                a = np.asarray(v)
                rec[k] = (str(a.dtype), a.tobytes())
            else:
                rec[k] = v
        out.append(rec)
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--points", type=int, default=20000)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--out", default="BENCH_temporal.json")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from heatmap_tpu import delta
    from heatmap_tpu.delta.compact import load_overlay_levels
    from heatmap_tpu.delta.retract import parse_where, retract_predicate
    from heatmap_tpu.pipeline import BatchJobConfig
    from heatmap_tpu.serve import ServeApp, TileCache, TileStore
    from heatmap_tpu.temporal import fold as tfold
    from heatmap_tpu.temporal import timequery

    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=6,
                         result_delta=2)
    tmp = tempfile.mkdtemp(prefix="bench-temporal-")
    root = os.path.join(tmp, "store")
    os.makedirs(root)
    tfold.ensure_config(root, width=3600.0, fanout=4, keep=4, tiers=3)

    # 4 timed epochs spanning ~20 tier-0 buckets, then a bucketed
    # compaction and one live epoch on top (the fold's worst case:
    # buckets + live units in one merge).
    per = max(1, args.points // 5)
    t0 = 1_500_000_000.0
    build_s = time.monotonic()
    for i in range(4):
        delta.apply_batch(root, delta.ColumnsSource(
            _timed_source(per, i, t0 + i * 18_000, 18_000.0)), cfg)
    delta.compact(root, retention=10)
    delta.apply_batch(root, delta.ColumnsSource(
        _timed_source(per, 9, t0 + 4 * 18_000, 18_000.0)), cfg)
    build_s = time.monotonic() - build_s

    ref = tfold.newest_edge(root, tfold.temporal_config(root))
    cuts = {
        "alltime": {},
        "as_of": {"as_of": t0 + 40_000},
        "window": {"window": 86_400.0},
        "decay": {"decay": 7200.0},
    }
    fold = {}
    gate = None
    for name, kw in cuts.items():
        times = []
        for _ in range(max(3, args.iters // 3)):
            it0 = time.monotonic()
            sel = tfold.select_fold(root, **kw)
            levels = tfold.fold_levels(
                root, sel, decay_half_life=kw.get("decay"))
            times.append((time.monotonic() - it0) * 1000.0)
        fold[name] = {"ms": {"p50": _pct(times, 0.5),
                             "p99": _pct(times, 0.99)},
                      "units": len(sel.buckets) + len(sel.live)
                      + (1 if sel.none else 0)}
        if name == "alltime":
            gate = _levelbytes(levels) == _levelbytes(
                load_overlay_levels(root))

    # Serve leg: rotate the as_of cut each request so every hit is a
    # genuine miss, next to the plain all-time tile on the same app.
    app = ServeApp(TileStore(f"delta:{root}"), TileCache())
    edges = sorted({b["t1"] for b in tfold.select_fold(root).buckets})
    serve = {}
    for leg, paths in {
        "temporal": [f"/tiles/default/2/1/1.json?as_of={edges[i % len(edges)]}"
                     for i in range(args.iters)],
        "alltime": ["/tiles/default/2/1/1.json"] * args.iters,
    }.items():
        times = []
        for i, path in enumerate(paths):
            if leg == "alltime":
                app.cache.clear()
            it0 = time.monotonic()
            res = app.handle("GET", path)
            times.append((time.monotonic() - it0) * 1000.0)
            assert res[0] in (200, 404), f"{path} -> {res[0]}"
        serve[leg] = {"ms": {"p50": _pct(times, 0.5),
                             "p99": _pct(times, 0.99)}}

    # Retraction leg on a twin store: drop one of the five synthetic
    # users end to end, then gate against the survivor recompute.
    rootr = os.path.join(tmp, "store-retract")
    roots = os.path.join(tmp, "store-survivors")
    for r in (rootr, roots):
        os.makedirs(r)
        tfold.ensure_config(r, width=3600.0, fanout=4, keep=4, tiers=3)
    import numpy as np

    rcols = _timed_source(per, 17, t0, 18_000.0)
    keep = [j for j, u in enumerate(rcols["user_id"]) if u != "u0"]
    scols = {k: ([v[j] for j in keep] if isinstance(v, list)
                 else np.asarray(v)[keep]) for k, v in rcols.items()}
    delta.apply_batch(rootr, delta.ColumnsSource(rcols), cfg)
    delta.apply_batch(roots, delta.ColumnsSource(scols), cfg)
    it0 = time.monotonic()
    summary = retract_predicate(rootr, parse_where(["user=u0"]))
    retract_s = time.monotonic() - it0
    retract = {
        "rows": summary["rows"], "batches": summary["batches"],
        "scanned": summary["scanned"], "seconds": round(retract_s, 3),
        "rows_per_s": (summary["rows"] / retract_s) if retract_s else None,
        "byte_identical": _levelbytes(load_overlay_levels(rootr))
        == _levelbytes(load_overlay_levels(roots)),
    }

    # Time-axis query leg: repeated topk_growth evaluations (the serve
    # layer caches by selection token; this measures the evaluator).
    times = []
    doc = None
    for _ in range(max(3, args.iters // 3)):
        it0 = time.monotonic()
        doc = timequery.topk_growth(root, user="all", timespan="alltime",
                                    zoom=8, window=86_400.0, k=20,
                                    coeffs=timequery.DEFAULT_COEFFS)
        times.append((time.monotonic() - it0) * 1000.0)
    growth = {"ms": {"p50": _pct(times, 0.5), "p99": _pct(times, 0.99)},
              "slots": doc["slots"], "max_err": doc["max_err"],
              "cells": len(doc["cells"])}

    out = {
        "schema": "heatmap-tpu.bench_temporal.v1",
        "points": args.points, "iters": args.iters,
        "build_seconds": round(build_s, 1), "ref_edge": ref,
        "alltime_byte_identical": bool(gate),
        "fold": fold, "serve": serve, "retract": retract,
        "growth": growth,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({k: out[k] for k in
                      ("alltime_byte_identical", "fold", "retract",
                       "growth")}, indent=2, sort_keys=True))
    import shutil

    shutil.rmtree(tmp, ignore_errors=True)
    if not gate or not retract["byte_identical"]:
        print("bench_temporal: BYTE GATE FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
