"""Kernel-epoch hashing shared by the on-chip tooling.

The "kernel epoch" is a short content hash over every source file whose
edit invalidates recorded on-chip verdicts: the kernels under test AND
the reference implementations the expected values come from. The verify
tool prefixes its checkpoint keys with it, the runner records it in
done.json (so a kernel edit re-queues verification), and apply_decisions
refuses to act on seg-* verdicts from a stale epoch.

Deliberately dependency-free (stdlib only): the runner imports it on
hosts where jax/numpy may be absent or broken, and it must never
trigger package imports just to compute a hash. tools/ is not a
package, so consumers load it by path:

    spec = importlib.util.spec_from_file_location(
        "_epoch", os.path.join(TOOLS_DIR, "_epoch.py"))
"""

from __future__ import annotations

import hashlib
import os

#: Files (relative to the heatmap_tpu package root) hashed into the
#: epoch. Both sides of every on-chip comparison: partitioned kernels,
#: the scatter/aggregate references, and the projection feeding them.
KERNEL_FILES = (
    "ops/partitioned.py",
    "ops/sparse_partitioned.py",
    "ops/pallas_kernels.py",
    "parallel/sharded.py",
    "ops/histogram.py",
    "ops/sparse.py",
    "tilemath/mercator.py",
)


def package_root() -> str:
    """Path of the heatmap_tpu package, resolved relative to tools/."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "heatmap_tpu")


def kernel_epoch(extra_paths=()) -> str:
    """10-hex content hash over KERNEL_FILES plus ``extra_paths``.

    ``extra_paths`` lets a consumer fold its own source into the epoch
    (the verify script hashes itself: changing its cases/shapes/rng
    must also invalidate old verdicts — they were produced by the old
    inputs).
    """
    root = package_root()
    h = hashlib.sha256()
    for rel in KERNEL_FILES:
        with open(os.path.join(root, rel), "rb") as f:
            h.update(f.read())
    for p in extra_paths:
        with open(p, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:10]
