#!/usr/bin/env python
"""Evaluate the pending measurement-gated decisions (PERF_NOTES.md)
against the collected on-chip evidence.

Reads onchip_state/sweep.jsonl + verify.jsonl and prints one JSON line
per decision rule: satisfied / refuted / insufficient-data, with the
numbers that decided it. Read-only — flips stay deliberate, human
commits; this tool just removes the re-derivation work (and the
temptation to flip on a misremembered number).

    PYTHONPATH=. python tools/apply_decisions.py [--state-dir onchip_state]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os


def _verify_epoch():
    """The CURRENT kernel epoch as the verify tool computes it
    (tools/_epoch.py over the kernel sources, plus the verify script
    itself): seg-* verdicts recorded under any other epoch (or the
    legacy un-prefixed keys) are stale — produced by a different kernel
    or reference — and must not gate a routing flip."""
    d = os.path.dirname(os.path.abspath(__file__))
    spec = importlib.util.spec_from_file_location(
        "_epoch", os.path.join(d, "_epoch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.kernel_epoch(
        extra_paths=(os.path.join(d, "verify_partitioned_onchip.py"),))


def _repo_defaults():
    """What the repo currently ships, for each decided knob — so a
    measured winner that has already been committed reports "applied"
    instead of a stale "FLIP" that reads like unfinished work. Returns
    None when the package will not import here (decision evaluation
    must still run on a bare host)."""
    try:
        import inspect
        import types

        import jax

        from heatmap_tpu.ops import histogram, partitioned
        from heatmap_tpu.pipeline.batch import BatchJobConfig

        # Behavioral probe of _pick_backend's weighted large-window
        # routing: fake a TPU platform (the routing is platform-gated)
        # and ask it about a window above PALLAS_AUTO_MAX_CELLS.
        big = histogram.Window(zoom=15, row0=0, col0=0,
                               height=1024, width=1280)
        orig = jax.devices
        jax.devices = lambda *a, **k: [types.SimpleNamespace(platform="tpu")]
        try:
            weighted_route = histogram._pick_backend("auto", big,
                                                     weighted=True)
            # Read under the fake TPU too: the cascade "auto" route is
            # platform-gated (scatter off TPU, where pallas only
            # interprets), and the decision is about what ships ON the
            # chip.
            cascade_default = BatchJobConfig().resolved_cascade_backend
        finally:
            jax.devices = orig
        sig = inspect.signature(partitioned.bin_rowcol_window_partitioned)
        return {
            "weighted_route": weighted_route,
            "bad_frac": sig.parameters["bad_frac"].default,
            "cascade_backend": cascade_default,
        }
    except Exception:  # noqa: BLE001 — introspection is best-effort
        return None


def _load_jsonl(path):
    out = {}
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "config" in rec:  # sweep rows
                    out[rec["config"]] = rec
                elif rec.get("check") == "stream":  # bench_stream rows
                    key = (f"stream {rec.get('backend')} "
                           f"b={rec.get('batch')} {rec.get('device')}")
                    out[key] = rec
                else:  # verify rows: {key: bool}
                    out.update(rec)
    except OSError:
        pass
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--state-dir", default="onchip_state")
    args = ap.parse_args()
    sweep = _load_jsonl(os.path.join(args.state_dir, "sweep.jsonl"))
    verify = _load_jsonl(os.path.join(args.state_dir, "verify.jsonl"))

    def ms(name):
        rec = sweep.get(name)
        return rec.get("ms") if rec else None

    decisions = []
    defaults = _repo_defaults()

    # Rule (a): weighted large-window routing flips to partitioned only
    # if weighted k=8 beats the weighted scatter (k=1 already lost).
    # Once the repo routes it, the verdict reads "applied" — a stale
    # "FLIP" would look like an unlanded decision forever.
    w_scatter, w_part8 = ms("xla-scatter weighted"), ms("partitioned weighted k=8")
    if w_scatter is None or w_part8 is None:
        verdict = "insufficient-data"
    elif w_part8 < w_scatter:
        if defaults and defaults["weighted_route"] == "partitioned":
            verdict = ("applied (_pick_backend routes weighted large "
                       "windows to partitioned)")
        else:
            verdict = ("FLIP (_pick_backend: route weighted large "
                       "windows to partitioned)")
    else:
        verdict = "keep scatter"
    decisions.append({
        "decision": "weighted-routing",
        "verdict": verdict,
        "weighted_scatter_ms": w_scatter,
        "weighted_partitioned_k8_ms": w_part8,
        "repo_default": defaults["weighted_route"] if defaults else None,
    })

    # Rule (b): cascade_backend default flips to partitioned for count
    # jobs only if the pyramid16 A/B wins AND the seg-* verify cases
    # are bit-exact under Mosaic.
    epoch = _verify_epoch()
    seg_keys = [k for k in verify if k.startswith(f"{epoch}|seg-")]
    seg_ok = bool(seg_keys) and all(verify[k] is True for k in seg_keys)
    c_scatter = ms("cascade-pyramid16 scatter")
    candidates = {
        "partitioned": ms("cascade-pyramid16 partitioned"),
        "partitioned k=4": ms("cascade-pyramid16 partitioned k=4"),
    }
    best_name, best_ms = None, None
    for name, val in candidates.items():
        if val is not None and (best_ms is None or val < best_ms):
            best_name, best_ms = name, val
    if c_scatter is None or best_ms is None:
        verdict = "insufficient-data"
    elif not seg_ok:
        verdict = ("blocked: seg-* verify cases not all bit-exact"
                   if seg_keys else "blocked: no seg-* verify results")
    elif best_ms < c_scatter:
        if (defaults and defaults["cascade_backend"] == "partitioned"
                and best_name.startswith("partitioned")):
            verdict = ("applied (count jobs resolve cascade_backend to "
                       f"'partitioned'; measured best: '{best_name}')")
        else:
            verdict = (f"FLIP (BatchJobConfig.cascade_backend -> "
                       f"'{best_name}' for count jobs)")
    else:
        verdict = "keep scatter"
    decisions.append({
        "decision": "cascade-backend",
        "verdict": verdict,
        "pyramid16_scatter_ms": c_scatter,
        "pyramid16_partitioned_ms": candidates["partitioned"],
        "pyramid16_partitioned_k4_ms": candidates["partitioned k=4"],
        "seg_verify_count": len(seg_keys),
        "seg_verify_all_ok": seg_ok,
        "seg_verify_epoch": epoch,
        "repo_default": defaults["cascade_backend"] if defaults else None,
    })

    # Rule (c): bad_frac default if the tail-cap win composes with k=8.
    k8 = ms("partitioned bc=65536 chunk=1024 bf=8 k=8")
    k8_bf32 = ms("partitioned bc=65536 chunk=1024 bf=32 k=8")
    k8_bf128 = ms("partitioned bc=65536 chunk=1024 bf=128 k=8")
    best_bf, best_bf_ms = 8, k8
    for bf, val in ((32, k8_bf32), (128, k8_bf128)):
        if val is not None and best_bf_ms is not None and val < best_bf_ms:
            best_bf, best_bf_ms = bf, val
    cur_bf = defaults["bad_frac"] if defaults else None
    if k8 is None or (k8_bf32 is None and k8_bf128 is None):
        verdict = "insufficient-data"
    elif best_bf == cur_bf:
        verdict = f"applied (partitioned default bad_frac = {best_bf})"
    elif best_bf != 8:
        verdict = f"FLIP (partitioned default bad_frac -> {best_bf})"
    else:
        verdict = "keep bad_frac=8"
    decisions.append({
        "decision": "bad-frac-default",
        "verdict": verdict,
        "k8_bf8_ms": k8, "k8_bf32_ms": k8_bf32, "k8_bf128_ms": k8_bf128,
        "repo_default": cur_bf,
    })

    # Rule (d): StreamConfig.backend default stays "auto" unless a
    # pinned backend beats the auto-routed pick by >10% on chip
    # (BASELINE config 4; rows from tools/bench_stream.py).
    stream_rows = {
        k: v for k, v in sweep.items()
        if k.startswith("stream ") and v.get("device") != "cpu"
        and "error" not in v
    }
    auto_rows = [v for k, v in stream_rows.items() if " auto " in f" {k} "
                 or k.startswith("stream auto ")]
    pinned = [(k, v) for k, v in stream_rows.items()
              if not k.startswith("stream auto ")]
    if not auto_rows or not pinned:
        verdict = "insufficient-data"
        best_pin, auto_pts = None, None
    else:
        auto_pts = max(v["pts_per_s"] for v in auto_rows)
        best_pin = max(pinned, key=lambda kv: kv[1]["pts_per_s"])
        if best_pin[1]["pts_per_s"] > 1.10 * auto_pts:
            verdict = (f"FLIP (StreamConfig.backend -> "
                       f"{best_pin[1]['backend']!r})")
        else:
            verdict = "keep auto"
    decisions.append({
        "decision": "stream-backend",
        "verdict": verdict,
        "auto_pts_per_s": auto_pts,
        "best_pinned": best_pin[0] if best_pin else None,
        "best_pinned_pts_per_s": best_pin[1]["pts_per_s"] if best_pin else None,
        "onchip_rows": len(stream_rows),
    })

    for rec in decisions:
        print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
