#!/usr/bin/env python
"""REAL multi-process validation of the multihost layer (CPU, gloo).

Round 2 pinned the pod paths with algebra tests and injected
transports ("true multi-process DCN runs need a pod"); that was wrong
— JAX's distributed runtime + gloo CPU collectives run fine as k
local processes. This tool spawns k children that jointly execute the
ACTUAL code paths end to end:

- ``jax.distributed.initialize`` (the multihost.initialize ordering
  contract) with 1 CPU device per process;
- process-sharded ingest (``shard_source_rows`` batch slices);
- ``egress="gather"``: gather_blobs' framed u8 allgather over the
  real runtime — every process's merged dict must equal a
  single-process ``run_job`` oracle;
- ``egress="sharded"``: scatter_blobs' ``lax.all_to_all`` byte
  exchange over a 1-device-per-process mesh — each process's owned
  shard must carry exactly its blob_owner keys, per-host JSONL sink
  shards must reassemble to the oracle;
- columnar sharded egress: scatter_levels + per-host LevelArraysSink
  dirs reassembling to the oracle's level arrays.

Usage:
    PYTHONPATH=.:$PYTHONPATH python tools/multiproc_check.py \
        [--k 2] [--n 3000] [--timeout 600]

Prints one JSON line per process plus a final parent verdict line:
    {"check": "multiproc", "ok": true, "k": 2, "n": 3000, ...}
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

_CHILD = r"""
import json, os, sys
import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.config.update("jax_enable_x64", True)

coord, pid, k, n, work = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
    sys.argv[5],
)
jax.distributed.initialize(coord, num_processes=k, process_id=pid)

from heatmap_tpu.io.sinks import (
    JSONLBlobSink, LevelArraysSink, open_sink, per_process_sink_spec,
)
from heatmap_tpu.io.sources import SyntheticSource
from heatmap_tpu.parallel.multihost import blob_owner, run_job_multihost
from heatmap_tpu.pipeline import BatchJobConfig, run_job

from jax.experimental import multihost_utils


def barrier(tag):
    multihost_utils.process_allgather(np.asarray([pid]))


def blobs_equal(got, want):
    # Decoded equality: re-encoded collision merges may reorder the
    # inner dicts, so string equality is too strict.
    return set(got) == set(want) and all(
        json.loads(got[key]) == json.loads(want[key]) for key in want
    )


cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=8)
src = SyntheticSource(n=n, seed=13)
batch = 256
checks = {}

# Oracle: plain single-process job over the whole source. Local
# compute only (1 local CPU device) — safe under the distributed init.
want = run_job(SyntheticSource(n=n, seed=13), config=cfg,
               batch_size=batch, max_points_in_flight=0)

# 1) gather egress over the real framed allgather. Colliding blobs
# (straddling host shards) re-encode after the merge, so inner-dict
# key order may differ from the oracle's — compare decoded.
got = run_job_multihost(src, config=cfg, batch_size=batch,
                        egress="gather")
checks["gather_equals_oracle"] = blobs_equal(got, want)

# 1b) weighted job over the same transport: f64 per-point sums must
# merge across hosts exactly like counts (linearity).


class _WSrc:
    n = n

    def batches(self, batch_size):
        off = 0
        for b in SyntheticSource(n=n, seed=13).batches(batch_size):
            m = len(b["latitude"])
            b = dict(b)
            # Deterministic integer-valued weights from the GLOBAL row
            # position (batches() yields the full stream in order even
            # when shard_source_rows later filters) -> exact f64 sums.
            b["value"] = ((np.arange(off, off + m) % 7) + 1).astype(
                np.float64
            )
            off += m
            yield b


wcfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=8, weighted=True)
want_w = run_job(_WSrc(), config=wcfg, batch_size=batch,
                 max_points_in_flight=0)
got_w = run_job_multihost(_WSrc(), config=wcfg, batch_size=batch,
                          egress="gather")
checks["weighted_gather_equals_oracle"] = blobs_equal(got_w, want_w)

# 1c) bounded slice ingest over the same transport: each process
# streams its slice through the CHUNKED cascade + host merge
# (max_points_in_flight now composes with multi-process runs, VERDICT
# r3 missing #5) — ~700-point chunks force several chunks per slice,
# and blobs must still equal the unbounded oracle exactly.
got_b = run_job_multihost(src, config=cfg, batch_size=batch,
                          egress="gather", max_points_in_flight=700)
checks["bounded_gather_equals_oracle"] = blobs_equal(got_b, want)

# 1d) bounded + WEIGHTED (integer-valued f64 weights sum exactly under
# any chunk/host split) and bounded + SHARDED egress (each process's
# owned shard carries only its keys, values equal the oracle's).
got_wb = run_job_multihost(_WSrc(), config=wcfg, batch_size=batch,
                           egress="gather", max_points_in_flight=700)
checks["bounded_weighted_equals_oracle"] = blobs_equal(got_wb, want_w)
owned_b = run_job_multihost(src, config=cfg, batch_size=batch,
                            egress="sharded", max_points_in_flight=700)
# Completeness, not just consistency: this process's shard must hold
# EXACTLY the oracle keys it owns (every process holds the full
# oracle, so the expected set is computable locally) — a bounded-path
# regression that drops chunks or invents keys fails the set equality
# instead of passing vacuously / dying on a KeyError.
expected_owned = {key for key in want if blob_owner(key, k) == pid}
checks["bounded_sharded_owned_ok"] = (
    set(owned_b) == expected_owned
    and all(json.loads(owned_b[key]) == json.loads(want[key])
            for key in owned_b)
)

# 1e) per-host DP INSIDE the multi-process run: each process shards
# its slice's cascade over its own local devices (8 virtual CPU
# devices per child under the suite's XLA_FLAGS, 1 otherwise — both
# legal), then the cross-process gather merges as usual. The v5e-pod
# layout: DP over local chips x process-sharded ingest.
dp_cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=8,
                        data_parallel=True)
got_dp = run_job_multihost(src, config=dp_cfg, batch_size=batch,
                           egress="gather")
checks["dp_gather_equals_oracle"] = blobs_equal(got_dp, want)

# 1f) per-host DP with the coarse-prefix regrouped merge (the
# O(uniques/k) route): local all_to_all range regroup inside each
# process, cross-process gather unchanged — same oracle bar.
pfx_cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=8,
                         data_parallel=True, dp_merge="prefix")
got_pfx = run_job_multihost(src, config=pfx_cfg, batch_size=batch,
                            egress="gather")
checks["dp_prefix_gather_equals_oracle"] = blobs_equal(got_pfx, want)

# 2) sharded blob egress over the real all_to_all; per-host JSONL.
# open_sink(per_process_sink_spec(...)) is exactly the CLI's path —
# the tool must exercise the production spec parser, not re-parse.
with open_sink(per_process_sink_spec(f"jsonl:{work}/blobs.jsonl",
                                     pid)) as sink:
    owned = run_job_multihost(src, sink, cfg, batch_size=batch,
                              egress="sharded")
checks["owned_keys_are_mine"] = all(
    blob_owner(key, k) == pid for key in owned
)
barrier("blobs-written")
if pid == 0:
    merged = {}
    for i in range(k):
        merged.update(JSONLBlobSink.load(f"{work}/blobs.jsonl.p{i:03d}"))
    import json as _json
    checks["sharded_union_equals_oracle"] = (
        set(merged) == set(want)
        and all(merged[key] == _json.loads(want[key]) for key in want)
    )

# 3) columnar sharded egress: per-host level-array dirs.
stats = run_job_multihost(
    src, open_sink(per_process_sink_spec(f"arrays:{work}/cols", pid)),
    cfg, batch_size=batch, egress="sharded",
)
checks["columnar_stats"] = stats.get("egress") == "levels-sharded"
barrier("cols-written")
if pid == 0:
    ref_dir = os.path.join(work, "oracle-cols")
    run_job(SyntheticSource(n=n, seed=13), LevelArraysSink(ref_dir),
            config=cfg, batch_size=batch, max_points_in_flight=0)
    want_cols = LevelArraysSink.load(ref_dir)
    per_host = [LevelArraysSink.load(f"{work}/cols/host{i:03d}")
                for i in range(k)]
    # Zoom SETS must agree too: a spurious extra level (or one missing
    # everywhere) is a real divergence, not something to skip over.
    got_zooms = set().union(*(set(h) for h in per_host))
    ok = got_zooms == set(want_cols)
    for zoom, wlvl in want_cols.items():
        if not ok:
            break
        rows = {c: [] for c in ("row", "col", "value", "user", "timespan")}
        for got_cols in per_host:
            if zoom in got_cols:
                for c in rows:
                    rows[c].append(got_cols[zoom][c])
        if not rows["value"]:
            ok = False
            break
        cat = {c: np.concatenate(rows[c]) for c in rows}
        if len(cat["value"]) != len(wlvl["value"]):
            ok = False
            break
        order_g = np.lexsort((cat["col"], cat["row"], cat["user"],
                              cat["timespan"]))
        order_w = np.lexsort((wlvl["col"], wlvl["row"], wlvl["user"],
                              wlvl["timespan"]))
        for c in rows:
            if not np.array_equal(np.asarray(cat[c])[order_g],
                                  np.asarray(wlvl[c])[order_w]):
                ok = False
        if not ok:
            break
    checks["columnar_union_equals_oracle"] = ok

# 4) cross-process COMPUTE collectives: the binning/aggregation
# kernels on a mesh spanning both processes (1 device each) — psum /
# psum_scatter / all_gather ride the inter-process transport, exactly
# the pod layout (DCN instead of gloo, same program).
from heatmap_tpu.ops import (
    aggregate_keys, bin_points_window, window_from_bounds,
)
from heatmap_tpu.parallel import (
    aggregate_keys_sharded, bin_points_replicated, bin_points_rowsharded,
)
from heatmap_tpu.parallel.multihost import make_hybrid_mesh

# The hybrid mesh spans EVERY device of every process (8 local CPU
# devices per child under the test suite's XLA_FLAGS, 1 otherwise) —
# the realistic pod shape: intra-process "ICI" + inter-process gloo.
mesh = make_hybrid_mesh()
ndev = jax.device_count()  # k * local_device_count
rng = np.random.default_rng(17)
n_pts = ndev * k * (4096 // (ndev * k))  # divides shards for ANY k/ndev
lats = rng.uniform(35.0, 55.0, n_pts)
lons = rng.uniform(-5.0, 20.0, n_pts)
win = window_from_bounds((35.0, 55.0), (-5.0, 20.0), zoom=9,
                         align_levels=0, pad_multiple=ndev)
from jax.sharding import NamedSharding, PartitionSpec as P

sharding = NamedSharding(mesh, P("data"))
lo, hi = pid * (n_pts // k), (pid + 1) * (n_pts // k)
glat = jax.make_array_from_process_local_data(sharding, lats[lo:hi])
glon = jax.make_array_from_process_local_data(sharding, lons[lo:hi])
local_raster = np.asarray(bin_points_window(lats, lons, win))

raster = bin_points_replicated(glat, glon, win, mesh)
got_raster = np.asarray(list(raster.addressable_shards)[0].data)
checks["crossproc_psum_binning"] = bool(
    (got_raster == local_raster).all()
)

# psum_scatter path: the merged raster stays row-sharded — EVERY
# local band (8 per process under the suite's virtual-device flags)
# must equal the oracle's corresponding rows.
rowsharded = bin_points_rowsharded(glat, glon, win, mesh)
checks["crossproc_psum_scatter_binning"] = all(
    bool((np.asarray(s.data) == local_raster[s.index]).all())
    for s in rowsharded.addressable_shards
)

keys = rng.integers(0, 500, n_pts).astype(np.int32)
gkeys = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), keys[lo:hi]
)
gu, gs, gn = aggregate_keys_sharded(gkeys, mesh, capacity=512)
lu, ls, ln = aggregate_keys(keys, capacity=512)
n_unique = int(np.asarray(list(gn.addressable_shards)[0].data))
lu_n = int(ln)
checks["crossproc_aggregate_keys"] = (
    n_unique == lu_n
    and bool(
        (np.asarray(list(gu.addressable_shards)[0].data)[:n_unique]
         == np.asarray(lu)[:lu_n]).all()
    )
    and bool(  # the reduce-by-key SUMS must survive the merge too
        (np.asarray(list(gs.addressable_shards)[0].data)[:n_unique]
         == np.asarray(ls)[:lu_n]).all()
    )
)

barrier("done")
print(json.dumps({"pid": pid, "ok": all(checks.values()),
                  "checks": checks}), flush=True)
sys.exit(0 if all(checks.values()) else 1)
"""


_SKEW_CHILD = r"""
import json, sys
import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.config.update("jax_enable_x64", True)

coord, pid, k = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
jax.distributed.initialize(coord, num_processes=k, process_id=pid)

from heatmap_tpu.parallel.multihost import _alltoall_bytes


def payload(src, dst):
    # ONE hot pair (1 -> 0) 100x the rest: the skew shape that made
    # the old dense (k, global-max) frame pad every row.
    n = 200_000 if (src, dst) == (1, 0) else 2_000
    rng = np.random.default_rng(1000 * src + dst)
    return rng.integers(0, 256, n).astype(np.uint8).tobytes()


dest = [payload(pid, d) for d in range(k)]
# max_bytes=300k: the dense frame (k x 200_008 = 800k at k=4) would
# have refused; the shift-decomposed exchange fits because no process
# RECEIVES more than ~206k, and chunk_bytes=64k keeps every collective
# buffer small regardless of the hot payload's size.
got = _alltoall_bytes(dest, max_bytes=300_000, chunk_bytes=64_000)
ok = all(got[s] == payload(s, pid) for s in range(k))
print(json.dumps({"pid": pid, "ok": bool(ok),
                  "checks": {"skew_alltoall": bool(ok)}}), flush=True)
sys.exit(0 if ok else 1)
"""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=2)
    ap.add_argument("--n", type=int, default=3000)
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--skew-only", action="store_true",
                    help="run only the skewed byte-exchange check "
                         "(fast; use --k 4 to exercise several shift "
                         "rounds)")
    args = ap.parse_args()
    child_src = _SKEW_CHILD if args.skew_only else _CHILD

    import shutil

    work = tempfile.mkdtemp(prefix="multiproc-check-")
    env = dict(os.environ)
    env["PYTHONPATH"] = "." + os.pathsep + env.get("PYTHONPATH", "")
    coord = f"127.0.0.1:{free_port()}"
    t0 = time.perf_counter()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", child_src, coord, str(i), str(args.k),
             str(args.n), work],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for i in range(args.k)
    ]
    ok = True
    reports = []
    # --timeout is a TOTAL budget shared across the children: one hung
    # child must not push the parent past its caller's deadline, and a
    # killed coordinator leaves peers stuck in collectives, so every
    # child is reaped before exit — no orphaned JAX grandchildren.
    deadline = time.monotonic() + args.timeout
    try:
        for p in procs:
            try:
                out, err = p.communicate(
                    timeout=max(1.0, deadline - time.monotonic())
                )
            except subprocess.TimeoutExpired:
                ok = False
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                out, err = p.communicate()
            for line in out.splitlines():
                if line.startswith("{"):
                    print(line, flush=True)
                    reports.append(json.loads(line))
            if p.returncode != 0:
                ok = False
                tail = err.strip().splitlines()[-8:]
                print(f"[child rc={p.returncode}] " + " | ".join(tail),
                      file=sys.stderr, flush=True)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
        shutil.rmtree(work, ignore_errors=True)
    ok = ok and len(reports) == args.k and all(r["ok"] for r in reports)
    print(json.dumps({
        "check": "multiproc", "ok": ok, "k": args.k, "n": args.n,
        "s": round(time.perf_counter() - t0, 1),
    }), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
