#!/usr/bin/env python
"""Write-plane bench: multi-writer throughput + enqueue->servable lag
at writers ∈ {1, 2, 4}: BENCH_writeplane.json.

Each cell drains the same hotspot-clustered synthetic stream (the
Zipf-ish mixture ``SyntheticSource`` generates — a few metro hotspots
absorb most points, the shape that makes range partitioning earn its
keep) through ``writeplane.run_plane_ingest`` with N pumps, then
byte-gates the plane against a single-writer delta store fed the
identical micro-batches. Cells that fail the byte gate report
``byte_identical: false`` and are never folded into the trend state
(tools/bench_gate.py skips them).

Measured per cell:

- ``pts_per_s``   completed points / drain wall seconds;
- ``lag_s``       enqueue -> servable p50/p99: micro-batch enqueued at
                  the router -> covered by a flipped manifest epoch
                  (``PlaneStats.lags_s``);
- ``publishes``   manifest epochs flipped during the drain.

The 1-writer cell runs first so a warm jax cache can only ever favor
it; multi-writer cells still win on wall clock because per-range
applies overlap across pump threads.

    PYTHONPATH=.:$PYTHONPATH python tools/bench_writeplane.py \
        [--points 20000] [--writers 1,2,4] [--micro-batch 2048] \
        [--out BENCH_writeplane.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time


def _pct(sorted_vals: list, q: float) -> float | None:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def _collect_docs(store) -> dict:
    """Every servable JSON tile: {(layer, z, x, y): bytes} — the byte
    gate enumerates tiles from the level Morton codes, so the stores
    must agree on which tiles exist, not just their contents."""
    import numpy as np

    from heatmap_tpu.serve.render import tile_json_bytes
    from heatmap_tpu.tilemath.morton import morton_decode_np

    docs = {}
    for name, layer in store.layers.items():
        if name == "default":
            continue
        shift = 2 * layer.result_delta
        for want, level in layer.levels.items():
            z = want - layer.result_delta
            if z < 0:
                continue
            rows, cols = morton_decode_np(np.unique(level.codes >> shift))
            for r, c in zip(rows, cols):
                docs[(name, z, int(c), int(r))] = tile_json_bytes(
                    layer, z, int(c), int(r))
    return docs


def bench_cell(spec: str, n_writers: int, micro_batch: int,
               tmpdir: str, ref_docs: dict) -> dict:
    from heatmap_tpu.io import open_source
    from heatmap_tpu.pipeline import BatchJobConfig
    from heatmap_tpu.serve import TileStore
    from heatmap_tpu.writeplane import (PlaneConfig, WritePlane,
                                        run_plane_ingest)

    # Routed sub-batch sizes vary tick to tick (a range owns whatever
    # share of each micro-batch falls in its interval), so the cells
    # run the pow2 bucketed compile cache — byte-neutral by contract
    # (delta/compact.py CONFIG_FIELDS) and the only way multi-writer
    # wall clock measures applies instead of XLA compiles.
    config = BatchJobConfig(detail_zoom=11, min_detail_zoom=5,
                            result_delta=3, pad_bucketing="pow2",
                            pad_bucket_min=1 << 8)
    root = os.path.join(tmpdir, f"plane-{n_writers}")
    plane = WritePlane(root, config, PlaneConfig(n_writers=n_writers))
    t0 = time.perf_counter()
    stats = run_plane_ingest(plane, open_source(spec),
                             micro_batch=micro_batch)
    wall_s = time.perf_counter() - t0
    docs = _collect_docs(TileStore(f"writeplane:{root}"))
    byte_identical = docs == ref_docs
    lags = sorted(stats.lags_s)
    shutil.rmtree(root, ignore_errors=True)
    return {
        "writers": n_writers,
        "batches": stats.batches,
        "completed": stats.completed,
        "failed": stats.failed,
        "points": stats.points,
        "pts_per_s": round(stats.points / wall_s, 1) if wall_s else None,
        "lag_s": {"p50": _pct(lags, 0.50), "p99": _pct(lags, 0.99)},
        "publishes": stats.publishes,
        "byte_identical": byte_identical,
        "wall_s": round(wall_s, 3),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", type=int, default=20_000)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--writers", default="1,2,4",
                    help="comma list of writer counts")
    ap.add_argument("--micro-batch", type=int, default=2048)
    ap.add_argument("--out", default="BENCH_writeplane.json")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from heatmap_tpu import delta
    from heatmap_tpu.io import open_source
    from heatmap_tpu.pipeline import BatchJobConfig
    from heatmap_tpu.serve import TileStore

    spec = f"synthetic:{args.points}:{args.seed}"
    counts = [int(w) for w in args.writers.split(",") if w.strip()]
    tmpdir = tempfile.mkdtemp(prefix="benchwriteplane-")
    try:
        # Single-writer delta-store reference over the same
        # micro-batches: the byte gate every cell must clear.
        ref_root = os.path.join(tmpdir, "ref")
        config = BatchJobConfig(detail_zoom=11, min_detail_zoom=5,
                                result_delta=3, pad_bucketing="pow2",
                                pad_bucket_min=1 << 8)
        for batch in open_source(spec).batches(args.micro_batch):
            delta.apply_batch(ref_root, delta.ColumnsSource(batch), config)
        ref_docs = _collect_docs(TileStore(f"delta:{ref_root}"))

        results = []
        for n_writers in counts:  # 1 first: warm cache favors the ref
            row = bench_cell(spec, n_writers, args.micro_batch, tmpdir,
                             ref_docs)
            print(json.dumps({k: row[k] for k in
                              ("writers", "pts_per_s", "lag_s",
                               "byte_identical")}), flush=True)
            results.append(row)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)

    doc = {
        "bench": "writeplane",
        "points": args.points,
        "micro_batch": args.micro_batch,
        "tiles": len(ref_docs),
        "results": results,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {args.out}", file=sys.stderr)
    return 0 if all(r["byte_identical"] for r in results) else 1


if __name__ == "__main__":
    sys.exit(main())
