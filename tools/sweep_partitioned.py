#!/usr/bin/env python
"""On-chip sweep of the sort-partitioned binning kernel's tunables.

Sweeps block_cells (output-block size: VPU/MXU cost vs good-chunk rate)
and chunk (points per grid step) on the headline bench workload, plus
the XLA scatter reference. One JSON line per configuration. Run on the
real chip; see PERF_NOTES.md for recorded results.

    python tools/sweep_partitioned.py [--n 25] [--steps 5] [--state FILE]

``--state FILE`` appends each configuration's result as it lands and a
re-run skips configurations already measured — the axon relay dies
mid-run often enough that all-or-nothing sweeps never finish.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def _load_state(path):
    if not path or not os.path.exists(path):
        return {}
    out = {}
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn final line from a killed writer
            if "config" in rec:
                out[rec["config"]] = rec
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=25, help="log2 point count")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--zoom", type=int, default=15)
    ap.add_argument("--state", default=None,
                    help="JSONL checkpoint; measured configs are skipped")
    args = ap.parse_args()
    state = _load_state(args.state)

    import jax
    import jax.numpy as jnp

    from heatmap_tpu.ops import window_from_bounds
    from heatmap_tpu.ops.histogram import bin_rowcol_window
    from heatmap_tpu.ops.partitioned import bin_rowcol_window_partitioned
    from heatmap_tpu.tilemath import mercator

    win = window_from_bounds((44.0, 51.0), (-127.0, -117.0), zoom=args.zoom,
                             align_levels=min(12, args.zoom),
                             pad_multiple=256)
    n = 1 << args.n
    rng = np.random.default_rng(0)
    n_hot = n // 4
    lat = np.concatenate([47.6 + rng.normal(0, 0.5, n - n_hot),
                          47.6 + rng.normal(0, 0.02, n_hot)]).astype(np.float32)
    lon = np.concatenate([-122.3 + rng.normal(0, 0.7, n - n_hot),
                          -122.3 + rng.normal(0, 0.03, n_hot)]).astype(np.float32)
    dla, dlo = jax.device_put(jnp.asarray(lat)), jax.device_put(jnp.asarray(lon))

    def timed(f):
        out = f(dla, dlo)
        int(out.ravel()[0])  # scalar sync through the relay
        t0 = time.perf_counter()
        for _ in range(args.steps):
            out = f(dla, dlo)
            int(out.ravel()[0])
        return (time.perf_counter() - t0) / args.steps

    def report(name, dt, **extra):
        rec = {
            "config": name, "ms": round(dt * 1e3, 1),
            "mpts_per_s": round(n / dt / 1e6, 1), **extra,
        }
        print(json.dumps(rec), flush=True)
        if args.state:
            with open(args.state, "a") as f:
                f.write(json.dumps(rec) + "\n")
                f.flush()
                os.fsync(f.fileno())

    def measured(name):
        if name in state:
            rec = dict(state[name])
            rec["cached"] = True
            print(json.dumps(rec), flush=True)
            return True
        return False

    @jax.jit
    def xla(la, lo):
        r, c, v = mercator.project_points(la, lo, win.zoom, dtype=jnp.float32)
        return bin_rowcol_window(r, c, win, valid=v)

    if not measured("xla-scatter"):
        report("xla-scatter", timed(xla))

    # Sort cost in isolation, stable vs unstable (the idx sort needs no
    # stability: duplicate cell ids are indistinguishable).
    from jax import lax

    for stable in (True, False):

        @jax.jit
        def sort_only(la, lo, st=stable):
            r, c, v = mercator.project_points(la, lo, win.zoom,
                                              dtype=jnp.float32)
            idx = jnp.where(v, r * win.width + c, win.height * win.width)
            return lax.sort(idx, is_stable=st)

        if not measured(f"sort-only stable={stable}"):
            report(f"sort-only stable={stable}", timed(sort_only))

    # Sort-shape probe: k independent row sorts of n/k elements (vmapped
    # along axis -1). If this beats the flat sort meaningfully, a
    # k-stream variant of the partitioned kernel (accumulating output
    # blocks across per-stream visit runs) buys the difference.
    for k in (8, 32, 128):

        @jax.jit
        def sort_rows(la, lo, kk=k):
            r, c, v = mercator.project_points(la, lo, win.zoom,
                                              dtype=jnp.float32)
            idx = jnp.where(v, r * win.width + c, win.height * win.width)
            return lax.sort(idx.reshape(kk, -1), dimension=1,
                            is_stable=False)

        if not measured(f"sort-rows k={k}"):
            report(f"sort-rows k={k}", timed(sort_rows))

    combos = [
        # (block_cells, chunk, bad_frac, streams): block size sweep at
        # the defaults, chunk sweep at the best-guess block, tail-cap
        # sweep (the n/bad_frac scatter tail costs ~8-30 ns/update),
        # then the k-stream batched-row-sort variant.
        (1 << 16, 1024, 8, 1),
        (1 << 14, 1024, 8, 1),
        (1 << 12, 1024, 8, 1),
        (1 << 14, 512, 8, 1),
        (1 << 14, 2048, 8, 1),
        (1 << 16, 1024, 32, 1),
        (1 << 14, 1024, 32, 1),
        (1 << 14, 1024, 128, 1),
        (1 << 16, 1024, 8, 8),
        (1 << 16, 1024, 8, 32),
        (1 << 16, 1024, 8, 128),
        # Round-2 follow-ups: the tail-cap sweep won ~10% at k=1
        # (bf=128: 354.8 ms vs 403.2) and the k-stream variant won 2x
        # (k=8: 197.5 ms); measure whether the two compose at the new
        # streams=8 default.
        (1 << 16, 1024, 32, 8),
        (1 << 16, 1024, 128, 8),
        (1 << 14, 1024, 8, 8),
    ]
    for block_cells, chunk, bad_frac, streams in combos:

        @jax.jit
        def part(la, lo, bc=block_cells, ck=chunk, bf=bad_frac, st=streams):
            r, c, v = mercator.project_points(la, lo, win.zoom,
                                              dtype=jnp.float32)
            return bin_rowcol_window_partitioned(
                r, c, win, valid=v, block_cells=bc, chunk=ck, bad_frac=bf,
                streams=st,
            )

        name = (f"partitioned bc={block_cells} chunk={chunk} "
                f"bf={bad_frac} k={streams}")
        if measured(name):
            continue
        try:
            report(name, timed(part), block_cells=block_cells,
                   chunk=chunk, bad_frac=bad_frac, streams=streams)
        except Exception as e:  # noqa: BLE001 — keep sweeping
            print(json.dumps({
                "config": name,
                "error": f"{type(e).__name__}: {e}"[:200],
            }), flush=True)

    # Weighted binning (BASELINE config 3 shape): the pair-sort +
    # weight-scaled one-hot variant vs the weighted XLA scatter. Decides
    # whether _pick_backend routes weighted large windows to partitioned.
    wts = jnp.asarray(rng.integers(1, 16, n).astype(np.float32))
    dw = jax.device_put(wts)

    @jax.jit
    def xla_weighted(la, lo):
        r, c, v = mercator.project_points(la, lo, win.zoom, dtype=jnp.float32)
        return bin_rowcol_window(r, c, win, weights=dw, valid=v)

    def make_part_weighted(st):
        @jax.jit
        def part_weighted(la, lo):
            r, c, v = mercator.project_points(la, lo, win.zoom,
                                              dtype=jnp.float32)
            return bin_rowcol_window_partitioned(
                r, c, win, weights=dw, valid=v, streams=st)
        return part_weighted

    # "partitioned weighted" (the original k=1 run) measured 56.7
    # M pts/s vs the weighted scatter's 76.3 — the pair sort erases the
    # matmul win at k=1. The k=8 entry decides whether the streams
    # default flips that.
    for name, fn in (("xla-scatter weighted", xla_weighted),
                     ("partitioned weighted", make_part_weighted(1)),
                     ("partitioned weighted k=8", make_part_weighted(8))):
        if measured(name):
            continue
        try:
            report(name, timed(fn))
        except Exception as e:  # noqa: BLE001 — keep sweeping
            print(json.dumps({
                "config": name,
                "error": f"{type(e).__name__}: {e}"[:200],
            }), flush=True)

    # Cascade segment reduction: the per-level unit is 2 scatters over
    # a sorted stream (aggregate_sorted_keys) vs the multi-channel MXU
    # kernel (sparse_partitioned). Decides whether the count cascade
    # routes to pyramid_sparse_morton_partitioned. This section FORCE-
    # ENABLES x64 (the composite keys are int64); it runs LAST so the
    # f32 sections above have already traced and executed — mid-process
    # x64 flips are otherwise unsupported, so never add f32 sections
    # after this point.
    try:
        import jax as _jax

        _jax.config.update("jax_enable_x64", True)
        from heatmap_tpu.ops.sparse import aggregate_sorted_keys
        from heatmap_tpu.ops.sparse_partitioned import (
            aggregate_sorted_keys_partitioned,
        )
        from heatmap_tpu.ops.pyramid import (
            pyramid_sparse_morton,
            pyramid_sparse_morton_partitioned,
        )

        kn = n
        # Cascade-shaped keys: clustered z21-ish composite codes.
        kkeys = np.sort(
            rng.choice(1 << 42, max(kn // 8, 1), replace=False)[
                rng.integers(0, max(kn // 8, 1), kn)
            ].astype(np.int64)
        )
        dkeys = jax.device_put(jnp.asarray(kkeys, jnp.int64))
        ones = jnp.ones(kn, jnp.int32)
        sent = np.iinfo(np.int64).max

        def timed_k(f):
            out = f(dkeys, ones)
            int(jnp.asarray(out[1]).ravel()[0])
            t0 = time.perf_counter()
            for _ in range(args.steps):
                out = f(dkeys, ones)
                int(jnp.asarray(out[1]).ravel()[0])
            return (time.perf_counter() - t0) / args.steps

        # Symmetric jitting: each contender is one compiled dispatch
        # (the repo measured 1.67x just from de-eagering the cascade,
        # so an unjitted side would lose on dispatch latency alone).
        for name, f in (
            ("cascade-level scatter",
             jax.jit(lambda k, o: aggregate_sorted_keys(
                 k, o, kn, sentinel=sent))),
            ("cascade-level partitioned",
             jax.jit(lambda k, o: aggregate_sorted_keys_partitioned(
                 k, kn, sentinel=sent))),
            ("cascade-level partitioned k=4",
             jax.jit(lambda k, o: aggregate_sorted_keys_partitioned(
                 k, kn, sentinel=sent, streams=4))),
            ("cascade-pyramid16 scatter",
             jax.jit(lambda k, o: pyramid_sparse_morton(
                 k, levels=16, capacity=kn)[-1])),
            ("cascade-pyramid16 partitioned",
             jax.jit(lambda k, o: pyramid_sparse_morton_partitioned(
                 k, levels=16, capacity=kn)[-1])),
            # k-stream variant (per-sub-stream output slabs, summed):
            # the window kernel's streams=8 default came from exactly
            # this shape winning 2x; k=4 bounds the extra output
            # buffer at 4 x capacity x 16B.
            ("cascade-pyramid16 partitioned k=4",
             jax.jit(lambda k, o: pyramid_sparse_morton_partitioned(
                 k, levels=16, capacity=kn, streams=4)[-1])),
        ):
            if measured(name):
                continue
            try:
                dt = timed_k(f)
                report(name, dt)
            except Exception as e:  # noqa: BLE001 — keep sweeping
                print(json.dumps({
                    "config": name,
                    "error": f"{type(e).__name__}: {e}"[:200],
                }), flush=True)
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"config": "cascade-suite",
                          "error": f"{type(e).__name__}: {e}"[:200]}),
              flush=True)


if __name__ == "__main__":
    main()
