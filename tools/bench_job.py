#!/usr/bin/env python
"""End-to-end batch-job benchmark: HMPB ingest -> cascade -> egress.

Generates an HMPB file of synthetic GPS points (hot cluster + fringe,
multiple users incl. rt-/x- routing), runs run_job_fast end to end on
the default backend, and prints the tracer's stage balance plus a
points/sec headline. Unlike bench.py (the isolated projection+binning
kernel), this measures the full production job: mmap ingest, group
routing, the z21 composite-key cascade, decode/finalize, and egress.

    PYTHONPATH=.:$PYTHONPATH python tools/bench_job.py [--n 20000000]
        [--egress arrays|json|none] [--runs 1]
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

import numpy as np


def synth_hmpb(path: str, n: int, seed: int = 0) -> str:
    from heatmap_tpu.io.hmpb import write_hmpb

    rng = np.random.default_rng(seed)
    n_hot = n // 4
    lat = np.concatenate([47.6 + rng.normal(0, 0.5, n - n_hot),
                          47.6 + rng.normal(0, 0.02, n_hot)])
    lon = np.concatenate([-122.3 + rng.normal(0, 0.7, n - n_hot),
                          -122.3 + rng.normal(0, 0.03, n_hot)])
    # Routed ids against a names table shaped like production: a few
    # hundred users, one pooled "route" group, x-excluded rows (-1).
    names = ["all"] + [f"user{i}" for i in range(200)] + ["route"]
    routed = rng.integers(1, len(names), n, dtype=np.int32)
    routed[rng.random(n) < 0.05] = -1  # x- excluded
    ts = rng.integers(1_500_000_000_000, 1_700_000_000_000, n, dtype=np.int64)
    background = (rng.random(n) < 0.02).astype(np.uint8)
    return write_hmpb(path, lat, lon, routed, names,
                      timestamp=ts, background=background)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=20_000_000)
    ap.add_argument("--egress", choices=("arrays", "json", "none"),
                    default="arrays")
    ap.add_argument("--runs", type=int, default=1)
    ap.add_argument("--keep", action="store_true",
                    help="keep the generated HMPB file")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (the axon sitecustomize "
                    "overrides JAX_PLATFORMS, so the env var is not enough)")
    ap.add_argument("--cascade-backend", default=None,
                    choices=("scatter", "partitioned", "both"),
                    help="cascade reduction backend; 'both' runs every "
                    "run twice and prints one result line per backend — "
                    "the on-chip A/B that decides the "
                    "BatchJobConfig.cascade_backend default")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)  # int64 composite keys + exact z21

    from heatmap_tpu.io.hmpb import HMPBSource
    from heatmap_tpu.io.sinks import LevelArraysSink, MemorySink
    from heatmap_tpu.pipeline import BatchJobConfig, run_job_fast
    from heatmap_tpu.utils.trace import get_tracer

    tmpdir = tempfile.mkdtemp(prefix="benchjob-")
    try:
        hmpb = os.path.join(tmpdir, "points.hmpb")
        t0 = time.perf_counter()
        synth_hmpb(hmpb, args.n)
        gen_s = time.perf_counter() - t0
        print(json.dumps({"stage": "synth+write_hmpb", "s": round(gen_s, 2),
                          "path": hmpb,
                          "bytes": os.path.getsize(hmpb)}), flush=True)

        backends = (("scatter", "partitioned")
                    if args.cascade_backend == "both"
                    else (args.cascade_backend,))
        tracer = get_tracer()
        for run in range(args.runs):
            for backend in backends:
                config = (BatchJobConfig() if backend is None
                          else BatchJobConfig(cascade_backend=backend))
                tracer.reset()
                if args.egress == "arrays":
                    sink = LevelArraysSink(
                        os.path.join(tmpdir, f"levels{run}-{backend}"))
                elif args.egress == "json":
                    sink = MemorySink()
                else:
                    sink = None
                t0 = time.perf_counter()
                out = run_job_fast(HMPBSource(hmpb), sink=sink, config=config)
                dt = time.perf_counter() - t0
                stages = {
                    name: round(r["total_s"], 3)
                    for name, r in sorted(tracer.report().items())
                }
                print(json.dumps({
                    "run": run,
                    "device": jax.devices()[0].platform,
                    "n_points": args.n,
                    "cascade_backend": backend or "default",
                    "egress": args.egress,
                    "total_s": round(dt, 2),
                    "pts_per_s": round(args.n / dt),
                    "stages": stages,
                    "out": (len(out) if hasattr(out, "__len__")
                            else str(out)[:80]),
                }), flush=True)
    finally:
        if args.keep:
            print(json.dumps({"kept": tmpdir}), flush=True)
        else:
            import shutil

            shutil.rmtree(tmpdir, ignore_errors=True)


if __name__ == "__main__":
    main()
