#!/usr/bin/env python
"""End-to-end batch-job benchmark: HMPB ingest -> cascade -> egress.

Generates an HMPB file of synthetic GPS points (hot cluster + fringe,
multiple users incl. rt-/x- routing), runs run_job_fast end to end, and
prints the tracer's stage balance plus a points/sec headline. Unlike
bench.py (the isolated projection+binning kernel), this measures the
full production job: mmap ingest, group routing, the z21 composite-key
cascade, decode/finalize, and egress.

Each measurement runs in a SUBPROCESS (``--single`` re-exec of this
script): the round-5 A/B died to one
``UNAVAILABLE: TPU worker process crashed or restarted`` raised from
the decode device_get at n=20M, taking both backends' rows with it. A
child crash now costs only that measurement, its stderr lands in
``onchip_state/bj_stderr.log``, and the driver AUTO-BISECTS ``--n``
downward (halving, same regenerated input for both backends at each
size) until a row lands — a smaller measured row beats a dead run.

    PYTHONPATH=.:$PYTHONPATH python tools/bench_job.py [--n 20000000]
        [--egress arrays|json|none] [--runs 1] [--cascade-backend both]
        [--state onchip_state/sweep.jsonl] [--trace-stages]

``--state`` appends one sweep row per on-chip measurement in
tools/sweep_partitioned.py's format — ``cascade-pyramid16 scatter`` /
``cascade-pyramid16 partitioned`` — the rows apply_decisions rule (b)
reads. ``--trace-stages`` adds sort / segment-reduce attribution to the
stage report (runs the cascade eagerly — see utils/trace.py).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

STDERR_LOG = os.path.join("onchip_state", "bj_stderr.log")


def synth_hmpb(path: str, n: int, seed: int = 0) -> str:
    from heatmap_tpu.io.hmpb import write_hmpb

    rng = np.random.default_rng(seed)
    n_hot = n // 4
    lat = np.concatenate([47.6 + rng.normal(0, 0.5, n - n_hot),
                          47.6 + rng.normal(0, 0.02, n_hot)])
    lon = np.concatenate([-122.3 + rng.normal(0, 0.7, n - n_hot),
                          -122.3 + rng.normal(0, 0.03, n_hot)])
    # Routed ids against a names table shaped like production: a few
    # hundred users, one pooled "route" group, x-excluded rows (-1).
    names = ["all"] + [f"user{i}" for i in range(200)] + ["route"]
    routed = rng.integers(1, len(names), n, dtype=np.int32)
    routed[rng.random(n) < 0.05] = -1  # x- excluded
    ts = rng.integers(1_500_000_000_000, 1_700_000_000_000, n, dtype=np.int64)
    background = (rng.random(n) < 0.02).astype(np.uint8)
    return write_hmpb(path, lat, lon, routed, names,
                      timestamp=ts, background=background)


def run_single(args) -> int:
    """One measurement in THIS process: ingest the prepared HMPB, run
    the job once, print the result JSON line. The subprocess unit the
    driver resurrects from."""
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)  # int64 keys + exact z21

    from heatmap_tpu import obs
    from heatmap_tpu.io.hmpb import HMPBSource
    from heatmap_tpu.io.sinks import LevelArraysSink, MemorySink
    from heatmap_tpu.obs import tracing
    from heatmap_tpu.pipeline import BatchJobConfig, run_job_fast
    from heatmap_tpu.utils.trace import enable_stage_tracing, get_tracer

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_analyze

    if args.trace_stages:
        enable_stage_tracing(True)
    # Metrics ride along on every measurement (counters/gauges only —
    # no event log, so no per-span I/O in the timed region); the folded
    # run report lands in the bench record below.
    obs.enable_metrics(True)
    backend = args.cascade_backend
    config = (BatchJobConfig() if backend is None
              else BatchJobConfig(cascade_backend=backend))
    tracer = get_tracer()
    tracer.reset()
    # Span-tree capture rides along (hooks only; no I/O in the timed
    # region) so the record carries critical-path attribution.
    collector = tracing.enable_tracing()
    if args.egress == "arrays":
        sink = LevelArraysSink(os.path.join(
            os.path.dirname(args.hmpb), f"levels{args.run}-{backend}"))
    elif args.egress == "json":
        sink = MemorySink()
    else:
        sink = None
    t0 = time.perf_counter()
    out = run_job_fast(HMPBSource(args.hmpb), sink=sink, config=config)
    dt = time.perf_counter() - t0
    stages = {
        name: round(r["total_s"], 3)
        for name, r in sorted(tracer.report().items())
    }
    obs.sample_device_memory()
    print(json.dumps({
        "run": args.run,
        "device": jax.devices()[0].platform,
        "n_points": args.n,
        "cascade_backend": backend or "default",
        "egress": args.egress,
        "total_s": round(dt, 2),
        "pts_per_s": round(args.n / dt),
        "stages": stages,
        "out": (len(out) if hasattr(out, "__len__") else str(out)[:80]),
        # Full per-stage attribution + io/cascade counters for the
        # decision evaluator: BENCH rows carry the same artifact
        # `cli run --report` writes (obs.report schema).
        "run_report": obs.build_run_report(tracer=tracer,
                                           registry=obs.get_registry()),
        # Span-tree digest: top self-time spans + the slowest trace's
        # critical path (tools/trace_analyze.py).
        "trace": trace_analyze.summarize(collector.to_chrome()),
    }, default=str), flush=True)
    return 0


def _append_sweep_row(state_path: str, rec: dict):
    """One sweep.jsonl row per landed on-chip measurement, in
    tools/sweep_partitioned.py's report format (apply_decisions keys
    rows by "config"; flush+fsync so a later crash cannot tear it)."""
    n, dt = rec["n_points"], rec["total_s"]
    row = {
        "config": f"cascade-pyramid16 {rec['cascade_backend']}",
        "ms": round(dt * 1e3, 1),
        "mpts_per_s": round(n / dt / 1e6, 1) if dt else None,
        "n": n,
        "egress": rec["egress"],
        "device": rec["device"],
        "end_to_end": True,
    }
    os.makedirs(os.path.dirname(state_path) or ".", exist_ok=True)
    with open(state_path, "a") as f:
        f.write(json.dumps(row) + "\n")
        f.flush()
        os.fsync(f.fileno())
    print(json.dumps({"sweep_row": row["config"], "ms": row["ms"]}),
          flush=True)


def _drive_one(args, hmpb: str, n: int, run: int, backend: str | None):
    """Run one measurement in a subprocess; return its result record or
    None. Child stdout passes through (teed for the result line); child
    stderr — where the TPU runtime prints its crash backtraces —
    appends to onchip_state/bj_stderr.log."""
    cmd = [sys.executable, os.path.abspath(__file__), "--single",
           "--hmpb", hmpb, "--n", str(n), "--run", str(run),
           "--egress", args.egress]
    if backend is not None:
        cmd += ["--cascade-backend", backend]
    if args.cpu:
        cmd.append("--cpu")
    if args.trace_stages:
        cmd.append("--trace-stages")
    os.makedirs(os.path.dirname(STDERR_LOG), exist_ok=True)
    with open(STDERR_LOG, "a") as ef:
        ef.write(f"\n===== bench_job attempt at {time.strftime('%F %T')} "
                 f"backend={backend} n={n} =====\n")
        ef.flush()
        try:
            r = subprocess.run(cmd, timeout=args.child_timeout,
                               stdout=subprocess.PIPE, stderr=ef, text=True)
        except subprocess.TimeoutExpired:
            ef.write(f"[driver] child timed out after "
                     f"{args.child_timeout}s\n")
            return None
    rec = None
    for line in r.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            print(line, flush=True)
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "pts_per_s" in parsed:
                rec = parsed
    if r.returncode != 0:
        print(json.dumps({"crashed": True, "rc": r.returncode,
                          "backend": backend, "n": n,
                          "stderr_log": STDERR_LOG}), flush=True)
        return None
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=20_000_000)
    ap.add_argument("--egress", choices=("arrays", "json", "none"),
                    default="arrays")
    ap.add_argument("--runs", type=int, default=1)
    ap.add_argument("--keep", action="store_true",
                    help="keep the generated HMPB file")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (the axon sitecustomize "
                    "overrides JAX_PLATFORMS, so the env var is not enough)")
    ap.add_argument("--cascade-backend", default=None,
                    choices=("scatter", "partitioned", "both"),
                    help="cascade reduction backend; 'both' measures each "
                    "backend on the same input file — the on-chip A/B "
                    "that decides the BatchJobConfig.cascade_backend "
                    "default")
    ap.add_argument("--state", default=None,
                    help="append a sweep.jsonl row per on-chip "
                    "measurement (cascade-pyramid16 <backend>)")
    ap.add_argument("--trace-stages", action="store_true",
                    help="per-stage cascade attribution (sort / "
                    "segment-reduce / decode / host egress) in the "
                    "stage report; runs the cascade eagerly")
    ap.add_argument("--child-timeout", type=float, default=1500.0)
    ap.add_argument("--min-n", type=int, default=None,
                    help="bisect floor (default --n // 16)")
    # --single: internal re-exec mode (one measurement, in-process).
    ap.add_argument("--single", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--hmpb", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--run", type=int, default=0, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.single:
        if args.cascade_backend == "both":
            ap.error("--single takes one backend")
        if not args.hmpb:
            ap.error("--single needs --hmpb")
        return run_single(args)

    backends = (("scatter", "partitioned")
                if args.cascade_backend == "both"
                else (args.cascade_backend,))
    min_n = args.min_n if args.min_n is not None else max(args.n // 16, 1)

    tmpdir = tempfile.mkdtemp(prefix="benchjob-")
    landed = {be: False for be in backends}
    try:
        n = args.n
        hmpb = None
        while n >= min_n:
            if hmpb is None:
                hmpb = os.path.join(tmpdir, f"points-{n}.hmpb")
                t0 = time.perf_counter()
                synth_hmpb(hmpb, n)
                print(json.dumps({
                    "stage": "synth+write_hmpb",
                    "s": round(time.perf_counter() - t0, 2),
                    "path": hmpb, "n": n,
                    "bytes": os.path.getsize(hmpb)}), flush=True)
            for run in range(args.runs):
                for be in backends:
                    if landed[be] and n != args.n:
                        # Bisected sizes only chase the backends that
                        # never landed; a full-size row already beat
                        # anything a smaller rerun could add.
                        continue
                    rec = _drive_one(args, hmpb, n, run, be)
                    if rec is None:
                        continue
                    landed[be] = True
                    if args.state and rec.get("device") != "cpu":
                        _append_sweep_row(args.state, rec)
            if all(landed.values()):
                break
            # Bisect: halve n and retry the backends that never landed
            # (same fresh file for every backend at the new size).
            n //= 2
            hmpb = None
            if n >= min_n:
                print(json.dumps({"bisect": True, "next_n": n,
                                  "pending": [b for b, ok in landed.items()
                                              if not ok]}), flush=True)
    finally:
        if args.keep:
            print(json.dumps({"kept": tmpdir}), flush=True)
        else:
            import shutil

            shutil.rmtree(tmpdir, ignore_errors=True)
    if not all(landed.values()):
        print(json.dumps({"error": "no measurement landed",
                          "pending": [b for b, ok in landed.items()
                                      if not ok],
                          "min_n": min_n}), flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
