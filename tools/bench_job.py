#!/usr/bin/env python
"""End-to-end batch-job benchmark: HMPB ingest -> cascade -> egress.

Generates an HMPB file of synthetic GPS points (hot cluster + fringe,
multiple users incl. rt-/x- routing), runs run_job_fast end to end, and
prints the tracer's stage balance plus a points/sec headline. Unlike
bench.py (the isolated projection+binning kernel), this measures the
full production job: mmap ingest, group routing, the z21 composite-key
cascade, decode/finalize, and egress.

Each measurement runs in a SUBPROCESS (``--single`` re-exec of this
script): the round-5 A/B died to one
``UNAVAILABLE: TPU worker process crashed or restarted`` raised from
the decode device_get at n=20M, taking both backends' rows with it. A
child crash now costs only that measurement, its stderr lands in
``onchip_state/bj_stderr.log``, and the driver AUTO-BISECTS ``--n``
downward (halving, same regenerated input for both backends at each
size) until a row lands — a smaller measured row beats a dead run.

    PYTHONPATH=.:$PYTHONPATH python tools/bench_job.py [--n 20000000]
        [--egress arrays|json|none] [--runs 1] [--cascade-backend both]
        [--state onchip_state/sweep.jsonl] [--trace-stages]

``--state`` appends one sweep row per on-chip measurement in
tools/sweep_partitioned.py's format — ``cascade-pyramid16 scatter`` /
``cascade-pyramid16 partitioned`` — the rows apply_decisions rule (b)
reads. ``--trace-stages`` adds sort / segment-reduce attribution to the
stage report (runs the cascade eagerly — see utils/trace.py).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

STDERR_LOG = os.path.join("onchip_state", "bj_stderr.log")


def synth_hmpb(path: str, n: int, seed: int = 0) -> str:
    from heatmap_tpu.io.hmpb import write_hmpb

    rng = np.random.default_rng(seed)
    n_hot = n // 4
    lat = np.concatenate([47.6 + rng.normal(0, 0.5, n - n_hot),
                          47.6 + rng.normal(0, 0.02, n_hot)])
    lon = np.concatenate([-122.3 + rng.normal(0, 0.7, n - n_hot),
                          -122.3 + rng.normal(0, 0.03, n_hot)])
    # Routed ids against a names table shaped like production: a few
    # hundred users, one pooled "route" group, x-excluded rows (-1).
    names = ["all"] + [f"user{i}" for i in range(200)] + ["route"]
    routed = rng.integers(1, len(names), n, dtype=np.int32)
    routed[rng.random(n) < 0.05] = -1  # x- excluded
    ts = rng.integers(1_500_000_000_000, 1_700_000_000_000, n, dtype=np.int64)
    background = (rng.random(n) < 0.02).astype(np.uint8)
    return write_hmpb(path, lat, lon, routed, names,
                      timestamp=ts, background=background)


def run_single(args) -> int:
    """One measurement in THIS process: ingest the prepared HMPB, run
    the job once, print the result JSON line. The subprocess unit the
    driver resurrects from."""
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)  # int64 keys + exact z21

    from heatmap_tpu import obs
    from heatmap_tpu.io.hmpb import HMPBSource
    from heatmap_tpu.io.sinks import LevelArraysSink, MemorySink
    from heatmap_tpu.obs import tracing
    from heatmap_tpu.pipeline import BatchJobConfig, run_job_fast
    from heatmap_tpu.utils.trace import enable_stage_tracing, get_tracer

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_analyze

    if args.trace_stages:
        enable_stage_tracing(True)
    # Metrics ride along on every measurement (counters/gauges only —
    # no event log, so no per-span I/O in the timed region); the folded
    # run report lands in the bench record below.
    obs.enable_metrics(True)
    backend = args.cascade_backend
    config = (BatchJobConfig() if backend is None
              else BatchJobConfig(cascade_backend=backend))
    tracer = get_tracer()
    tracer.reset()
    # Span-tree capture rides along (hooks only; no I/O in the timed
    # region) so the record carries critical-path attribution.
    collector = tracing.enable_tracing()
    if args.egress == "arrays":
        sink = LevelArraysSink(os.path.join(
            os.path.dirname(args.hmpb), f"levels{args.run}-{backend}"))
    elif args.egress == "json":
        sink = MemorySink()
    else:
        sink = None
    t0 = time.perf_counter()
    out = run_job_fast(HMPBSource(args.hmpb), sink=sink, config=config)
    dt = time.perf_counter() - t0
    stages = {
        name: round(r["total_s"], 3)
        for name, r in sorted(tracer.report().items())
    }
    obs.sample_device_memory()
    print(json.dumps({
        "run": args.run,
        "device": jax.devices()[0].platform,
        "n_points": args.n,
        "cascade_backend": backend or "default",
        "egress": args.egress,
        "total_s": round(dt, 2),
        "pts_per_s": round(args.n / dt),
        "stages": stages,
        "out": (len(out) if hasattr(out, "__len__") else str(out)[:80]),
        # Full per-stage attribution + io/cascade counters for the
        # decision evaluator: BENCH rows carry the same artifact
        # `cli run --report` writes (obs.report schema).
        "run_report": obs.build_run_report(tracer=tracer,
                                           registry=obs.get_registry()),
        # Span-tree digest: top self-time spans + the slowest trace's
        # critical path (tools/trace_analyze.py).
        "trace": trace_analyze.summarize(collector.to_chrome()),
    }, default=str), flush=True)
    return 0


def _append_sweep_row(state_path: str, rec: dict):
    """One sweep.jsonl row per landed on-chip measurement, in
    tools/sweep_partitioned.py's report format (apply_decisions keys
    rows by "config"; flush+fsync so a later crash cannot tear it)."""
    n, dt = rec["n_points"], rec["total_s"]
    row = {
        "config": f"cascade-pyramid16 {rec['cascade_backend']}",
        "ms": round(dt * 1e3, 1),
        "mpts_per_s": round(n / dt / 1e6, 1) if dt else None,
        "n": n,
        "egress": rec["egress"],
        "device": rec["device"],
        "end_to_end": True,
    }
    os.makedirs(os.path.dirname(state_path) or ".", exist_ok=True)
    with open(state_path, "a") as f:
        f.write(json.dumps(row) + "\n")
        f.flush()
        os.fsync(f.fileno())
    print(json.dumps({"sweep_row": row["config"], "ms": row["ms"]}),
          flush=True)


def _drive_one(args, hmpb: str, n: int, run: int, backend: str | None):
    """Run one measurement in a subprocess; return its result record or
    None. Child stdout passes through (teed for the result line); child
    stderr — where the TPU runtime prints its crash backtraces —
    appends to onchip_state/bj_stderr.log."""
    cmd = [sys.executable, os.path.abspath(__file__), "--single",
           "--hmpb", hmpb, "--n", str(n), "--run", str(run),
           "--egress", args.egress]
    if backend is not None:
        cmd += ["--cascade-backend", backend]
    if args.cpu:
        cmd.append("--cpu")
    if args.trace_stages:
        cmd.append("--trace-stages")
    os.makedirs(os.path.dirname(STDERR_LOG), exist_ok=True)
    with open(STDERR_LOG, "a") as ef:
        ef.write(f"\n===== bench_job attempt at {time.strftime('%F %T')} "
                 f"backend={backend} n={n} =====\n")
        ef.flush()
        try:
            r = subprocess.run(cmd, timeout=args.child_timeout,
                               stdout=subprocess.PIPE, stderr=ef, text=True)
        except subprocess.TimeoutExpired:
            ef.write(f"[driver] child timed out after "
                     f"{args.child_timeout}s\n")
            return None
    rec = None
    for line in r.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            print(line, flush=True)
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "pts_per_s" in parsed:
                rec = parsed
    if r.returncode != 0:
        print(json.dumps({"crashed": True, "rc": r.returncode,
                          "backend": backend, "n": n,
                          "stderr_log": STDERR_LOG}), flush=True)
        return None
    return rec


def partition_sweep(args) -> int:
    """Uniform-DP vs Morton-range cascade A/B (ISSUE 13 satellite).

    Two point sets — uniform and a Zipf-clustered mixture whose
    clusters are wide enough to hold distinct detail codes (a single
    heavy code is irreducible mass no planner can split) — each run
    through the sharded cascade with ``partition_splits`` off and on.
    The record carries measured wall seconds, the plan's skew ratio,
    and the MODELED per-pyramid merge volume: uniform DP gathers every
    shard's full per-level partial buffers, the Morton path gathers
    only the boundary-tile buffers (``bcap = min(lcap, 2*n_slots)``
    keys per shard per coarse level, level 0 exchanging nothing) — the
    same arithmetic parallel/sharded.py sizes its buffers with. Bytes
    are 16 per key slot (int64 key + 8-byte accumulator). The byte
    gate rides along: both dispatches must produce identical level
    arrays or the row is marked failed.
    """
    if args.cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8")
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from heatmap_tpu.parallel import make_mesh, route_emissions
    from heatmap_tpu.parallel.partition import plan_partition
    from heatmap_tpu.pipeline.batch import project_detail_codes
    from heatmap_tpu.pipeline.cascade import CascadeConfig, run_cascade

    n = args.sweep_n
    dz, mz = 16, 10
    cfg = CascadeConfig(detail_zoom=dz, min_detail_zoom=mz, result_delta=2)
    levels = cfg.n_levels
    mesh = make_mesh()
    ndev = int(np.prod(list(mesh.shape.values())))
    rng = np.random.default_rng(17)

    def zipf_points(m):
        # 80% of the mass over Zipf-ranked cluster centers, sigma wide
        # enough that a cluster spans thousands of z16 tiles.
        n_c = 32
        ranks = np.arange(1, n_c + 1, dtype=np.float64)
        p = (1.0 / ranks) / np.sum(1.0 / ranks)
        centers_lat = rng.uniform(-55.0, 55.0, n_c)
        centers_lon = rng.uniform(-170.0, 170.0, n_c)
        k = int(m * 0.8)
        c = rng.choice(n_c, size=k, p=p)
        lat = np.concatenate([centers_lat[c] + rng.normal(0, 0.3, k),
                              rng.uniform(-55.0, 55.0, m - k)])
        lon = np.concatenate([centers_lon[c] + rng.normal(0, 0.3, k),
                              rng.uniform(-170.0, 170.0, m - k)])
        return lat, lon

    datasets = {
        "uniform": (rng.uniform(-55.0, 55.0, n),
                    rng.uniform(-170.0, 170.0, n)),
        "zipf": zipf_points(n),
    }

    def levels_equal(a, b):
        for (au, asl, an), (bu, bsl, bn) in zip(a, b):
            m = int(an)
            if m != int(bn):
                return False
            if not (np.array_equal(np.asarray(au)[:m], np.asarray(bu)[:m])
                    and np.array_equal(np.asarray(asl)[:m],
                                       np.asarray(bsl)[:m])):
                return False
        return True

    def timed(fn, reps):
        fn()  # warmup: compile outside the timed region
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(fn())
        return (time.perf_counter() - t0) / reps

    rows = []
    for name, (lat, lon) in datasets.items():
        codes, valid = project_detail_codes(lat, lon, dz,
                                            prefer_device=False)
        codes, valid = np.asarray(codes), np.asarray(valid)
        plan = plan_partition(codes, ndev, detail_zoom=dz, valid=valid,
                              n_levels=levels)
        slots = np.zeros(n, np.int32)
        rc, rs, rv, _, seg = route_emissions(plan, codes, slots,
                                             valid=valid)
        d_codes = jnp.asarray(codes)
        d_valid = jnp.asarray(valid)
        d_rc, d_rs, d_rv = (jnp.asarray(rc), jnp.asarray(rs),
                            jnp.asarray(rv))
        splits = jnp.asarray(plan.splits, jnp.int64)
        d_slots = jnp.zeros(n, jnp.int32)

        def run_off():
            return run_cascade(d_codes, d_slots, cfg, 1, valid=d_valid,
                               capacity=n, mesh=mesh)

        def run_morton():
            return run_cascade(d_rc, d_rs, cfg, 1, valid=d_rv,
                               capacity=n, mesh=mesh,
                               partition_splits=splits)

        identical = levels_equal(run_off(), run_morton())
        wall_off = timed(run_off, args.sweep_reps)
        wall_morton = timed(run_morton, args.sweep_reps)

        # Buffer sizing, mirrored from pyramid_sparse_morton_range_
        # sharded: every shard's per-level partial buffer vs only the
        # boundary-tile buffers (n_slots=1 here).
        routed_n = len(rc)
        local_capacity = max(1, min(n, routed_n // ndev))
        lcaps = [max(1, min(n, local_capacity)) for _ in range(levels + 1)]
        bcaps = [max(1, min(lc, 2 * 1)) for lc in lcaps]
        uniform_bytes = sum(ndev * lc * 16 for lc in lcaps)
        morton_bytes = sum(ndev * bc * 16 for bc in bcaps[1:])
        rows.append({
            "dataset": name,
            "n_points": n,
            "skew_ratio": round(plan.skew_ratio, 4),
            "resplits": plan.resplits,
            "degenerate": plan.degenerate,
            "boundary_tiles": plan.boundary_tiles_total(levels),
            "wall_s": {"off": round(wall_off, 4),
                       "morton": round(wall_morton, 4)},
            "modeled_merge_bytes": {"uniform": uniform_bytes,
                                    "morton": morton_bytes},
            "merge_ratio": round(uniform_bytes / max(morton_bytes, 1), 2),
            "byte_identical": bool(identical),
        })
        print(json.dumps(rows[-1]), flush=True)

    doc = {
        "bench": "partition",
        "device": jax.devices()[0].platform,
        "ndev": ndev,
        "detail_zoom": dz,
        "levels": levels,
        "reps": args.sweep_reps,
        "results": rows,
    }
    with open(args.partition_sweep, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(json.dumps({"wrote": args.partition_sweep}), flush=True)
    return 0 if all(r["byte_identical"] for r in rows) else 1


def dispatch_sweep(args) -> int:
    """gspmd vs shard_map dispatch A/B (device-resident cascade).

    Runs the same point sets END TO END (run_job -> level-array sink)
    under both dispatch programs — the one-program gspmd pjit path and
    the shard_map oracle — for uniform DP (uniform points) and
    Morton-range sharding (Zipf-clustered points). Each leg's
    host-vs-device split comes from the dispatch timer
    (``obs.DISPATCH_OVERHEAD`` + the ``cascade.dispatch.*`` stages):
    ``overhead_pct`` is the host share of one dispatch — the routing,
    padding, and argument-prep work the gspmd program moves on device.
    The byte gate rides along: both dispatches must produce identical
    level-array files or the row is marked failed (bench_gate never
    folds a failed row, and reads the artifact as ``dispatch:*``
    series).
    """
    if args.cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8")
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from heatmap_tpu import obs
    from heatmap_tpu.delta import ColumnsSource
    from heatmap_tpu.io.sinks import LevelArraysSink
    from heatmap_tpu.pipeline import BatchJobConfig, run_job

    n = args.sweep_n
    rng = np.random.default_rng(17)

    def zipf_points(m):
        n_c = 32
        ranks = np.arange(1, n_c + 1, dtype=np.float64)
        p = (1.0 / ranks) / np.sum(1.0 / ranks)
        centers_lat = rng.uniform(-55.0, 55.0, n_c)
        centers_lon = rng.uniform(-170.0, 170.0, n_c)
        k = int(m * 0.8)
        c = rng.choice(n_c, size=k, p=p)
        lat = np.concatenate([centers_lat[c] + rng.normal(0, 0.3, k),
                              rng.uniform(-55.0, 55.0, m - k)])
        lon = np.concatenate([centers_lon[c] + rng.normal(0, 0.3, k),
                              rng.uniform(-170.0, 170.0, m - k)])
        return lat, lon

    # Each row pairs a point shape with the partitioner it exercises:
    # uniform points -> uniform DP, Zipf clusters -> Morton ranges.
    cells = {
        "uniform": ((rng.uniform(-55.0, 55.0, n),
                     rng.uniform(-170.0, 170.0, n)), "off"),
        "morton": (zipf_points(n), "morton"),
    }

    def levels_files(path):
        out = {}
        for name in sorted(os.listdir(path)):
            full = os.path.join(path, name)
            if os.path.isfile(full):
                with open(full, "rb") as f:
                    out[name] = f.read()
        return out

    obs.enable_metrics(True)
    reg = obs.get_registry()
    ndev = len(jax.devices())
    tmpdir = tempfile.mkdtemp(prefix="benchdispatch-")
    rows = []
    try:
        for name, ((lat, lon), partition) in cells.items():
            cols = {"latitude": lat, "longitude": lon,
                    "user_id": ["all"] * n}
            wall, host, dev, pct, n_disp, gate = {}, {}, {}, {}, {}, {}
            for mode in ("shard_map", "gspmd"):
                cfg = BatchJobConfig(detail_zoom=16, min_detail_zoom=10,
                                     result_delta=2, data_parallel=True,
                                     dispatch=mode,
                                     spatial_partition=partition)
                out_dir = os.path.join(tmpdir, f"{name}-{mode}")

                def one_run(d, cfg=cfg, cols=cols):
                    run_job(ColumnsSource(cols), LevelArraysSink(d),
                            config=cfg, batch_size=max(1, n // 4))

                one_run(out_dir)  # warmup compiles + the byte-gate run
                gate[mode] = levels_files(out_dir)
                reg.reset()  # timed reps only in the folded samples
                t0 = time.perf_counter()
                for _ in range(args.sweep_reps):
                    one_run(os.path.join(tmpdir, f"{name}-{mode}-rep"))
                wall[mode] = ((time.perf_counter() - t0)
                              / args.sweep_reps)
                counts, total, count_n = obs.DISPATCH_OVERHEAD.samples()[
                    (mode,)]
                host[mode], n_disp[mode] = total, int(count_n)
                dev[mode] = obs.STAGE_SECONDS.samples()[
                    ("cascade.dispatch.device",)][1]
                pct[mode] = round(
                    100.0 * host[mode] / max(host[mode] + dev[mode],
                                             1e-12), 2)
            identical = (sorted(gate["gspmd"]) == sorted(gate["shard_map"])
                         and all(gate["gspmd"][k] == gate["shard_map"][k]
                                 for k in gate["gspmd"]))
            rows.append({
                "dataset": name,
                "n_points": n,
                "spatial_partition": partition,
                "dispatches_timed": n_disp,
                "wall_s": {m: round(w, 4) for m, w in wall.items()},
                "host_s": {m: round(h, 4) for m, h in host.items()},
                "device_s": {m: round(d, 4) for m, d in dev.items()},
                "overhead_pct": pct,
                "overhead_reduction_pct": round(
                    pct["shard_map"] - pct["gspmd"], 2),
                "byte_identical": bool(identical),
            })
            print(json.dumps(rows[-1]), flush=True)
    finally:
        import shutil

        shutil.rmtree(tmpdir, ignore_errors=True)

    doc = {
        "bench": "dispatch",
        "device": jax.devices()[0].platform,
        "ndev": ndev,
        "detail_zoom": 16,
        "reps": args.sweep_reps,
        "results": rows,
    }
    with open(args.dispatch_sweep, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(json.dumps({"wrote": args.dispatch_sweep}), flush=True)
    return 0 if all(r["byte_identical"] for r in rows) else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=20_000_000)
    ap.add_argument("--egress", choices=("arrays", "json", "none"),
                    default="arrays")
    ap.add_argument("--runs", type=int, default=1)
    ap.add_argument("--keep", action="store_true",
                    help="keep the generated HMPB file")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (the axon sitecustomize "
                    "overrides JAX_PLATFORMS, so the env var is not enough)")
    ap.add_argument("--cascade-backend", default=None,
                    choices=("scatter", "partitioned", "both"),
                    help="cascade reduction backend; 'both' measures each "
                    "backend on the same input file — the on-chip A/B "
                    "that decides the BatchJobConfig.cascade_backend "
                    "default")
    ap.add_argument("--state", default=None,
                    help="append a sweep.jsonl row per on-chip "
                    "measurement (cascade-pyramid16 <backend>)")
    ap.add_argument("--trace-stages", action="store_true",
                    help="per-stage cascade attribution (sort / "
                    "segment-reduce / decode / host egress) in the "
                    "stage report; runs the cascade eagerly")
    ap.add_argument("--child-timeout", type=float, default=1500.0)
    ap.add_argument("--min-n", type=int, default=None,
                    help="bisect floor (default --n // 16)")
    ap.add_argument("--partition-sweep", nargs="?",
                    const="BENCH_partition.json", default=None,
                    metavar="OUT.json",
                    help="uniform-DP vs Morton-range cascade A/B on "
                    "uniform + Zipf-clustered point sets: wall time, "
                    "plan skew, modeled merge bytes, byte gate "
                    "(bench_gate reads the artifact as partition:* "
                    "series)")
    ap.add_argument("--dispatch-sweep", nargs="?",
                    const="BENCH_dispatch.json", default=None,
                    metavar="OUT.json",
                    help="gspmd vs shard_map dispatch A/B, end to end: "
                    "wall time, host/device split per dispatch "
                    "(overhead_pct), byte gate (bench_gate reads the "
                    "artifact as dispatch:* series)")
    ap.add_argument("--sweep-n", type=int, default=1 << 20,
                    help="points per partition/dispatch-sweep dataset")
    ap.add_argument("--sweep-reps", type=int, default=3,
                    help="timed repetitions per partition/dispatch-"
                    "sweep leg")
    # --single: internal re-exec mode (one measurement, in-process).
    ap.add_argument("--single", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--hmpb", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--run", type=int, default=0, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.partition_sweep:
        return partition_sweep(args)

    if args.dispatch_sweep:
        return dispatch_sweep(args)

    if args.single:
        if args.cascade_backend == "both":
            ap.error("--single takes one backend")
        if not args.hmpb:
            ap.error("--single needs --hmpb")
        return run_single(args)

    backends = (("scatter", "partitioned")
                if args.cascade_backend == "both"
                else (args.cascade_backend,))
    min_n = args.min_n if args.min_n is not None else max(args.n // 16, 1)

    tmpdir = tempfile.mkdtemp(prefix="benchjob-")
    landed = {be: False for be in backends}
    try:
        n = args.n
        hmpb = None
        while n >= min_n:
            if hmpb is None:
                hmpb = os.path.join(tmpdir, f"points-{n}.hmpb")
                t0 = time.perf_counter()
                synth_hmpb(hmpb, n)
                print(json.dumps({
                    "stage": "synth+write_hmpb",
                    "s": round(time.perf_counter() - t0, 2),
                    "path": hmpb, "n": n,
                    "bytes": os.path.getsize(hmpb)}), flush=True)
            for run in range(args.runs):
                for be in backends:
                    if landed[be] and n != args.n:
                        # Bisected sizes only chase the backends that
                        # never landed; a full-size row already beat
                        # anything a smaller rerun could add.
                        continue
                    rec = _drive_one(args, hmpb, n, run, be)
                    if rec is None:
                        continue
                    landed[be] = True
                    if args.state and rec.get("device") != "cpu":
                        _append_sweep_row(args.state, rec)
            if all(landed.values()):
                break
            # Bisect: halve n and retry the backends that never landed
            # (same fresh file for every backend at the new size).
            n //= 2
            hmpb = None
            if n >= min_n:
                print(json.dumps({"bisect": True, "next_n": n,
                                  "pending": [b for b, ok in landed.items()
                                              if not ok]}), flush=True)
    finally:
        if args.keep:
            print(json.dumps({"kept": tmpdir}), flush=True)
        else:
            import shutil

            shutil.rmtree(tmpdir, ignore_errors=True)
    if not all(landed.values()):
        print(json.dumps({"error": "no measurement landed",
                          "pending": [b for b, ok in landed.items()
                                      if not ok],
                          "min_n": min_n}), flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
