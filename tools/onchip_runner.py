#!/usr/bin/env python
"""Resilient driver for the on-chip runlist (PERF_NOTES.md).

The axon TPU relay is intermittently available: it answers for minutes,
then wedges (backend init hangs) or drops the compile endpoint mid-run.
This runner makes on-chip evidence collection survivable:

- probes the relay in a SUBPROCESS with a short timeout (a wedged
  backend init can hang the caller forever otherwise), and counts the
  probe good only when the platform is NOT cpu (a silent CPU fallback
  must not count as relay-alive — same rule as bench.probe_tpu);
- when the relay answers, runs the next pending runlist item as a
  subprocess, teeing output to ``onchip_state/<name>.log``;
- an item is done when it exits 0 AND its log passes the item's
  success check (bench.py exits 0 on its own CPU fallback by design;
  that must not be recorded as on-chip evidence);
- a failing item is retried at most ``max_attempts`` times and sent to
  the back of the queue meanwhile, so one deterministic failure cannot
  starve the rest of the runlist;
- state lives in ``onchip_state/done.json`` (written atomically) so
  restarts skip finished items; items that support ``--state``
  checkpoint per-measurement, so a mid-run relay death costs only the
  measurement in flight.

    PYTHONPATH=. python tools/onchip_runner.py [--deadline-min 240]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

STATE_DIR = "onchip_state"


def current_epoch() -> str:
    """Kernel epoch as the verify tool computes it (tools/_epoch.py over
    the kernel sources, plus the verify script itself). Epoch-tagged
    done.json entries are compared against this on restart: a kernel
    edit silently staling every recorded verdict must re-queue
    verification, not skip it as already done (the round-5 relay window
    was lost to exactly that)."""
    import importlib.util

    d = os.path.dirname(os.path.abspath(__file__))
    spec = importlib.util.spec_from_file_location(
        "_epoch", os.path.join(d, "_epoch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.kernel_epoch(
        extra_paths=(os.path.join(d, "verify_partitioned_onchip.py"),))


def build_queue(items, done, epoch):
    """Pending items: never recorded, or recorded under a different
    kernel epoch (epoch-sensitive items only). Permanently-failed
    entries also re-queue on an epoch change — the kernel edit may be
    the fix."""
    out = []
    for it in items:
        entry = done.get(it["name"])
        if not entry:
            out.append(it)
        elif it.get("epoch") and entry.get("epoch") != epoch:
            out.append(it)
    return out


PROBE = (
    "import jax, jax.numpy as jnp;"
    "d = jax.devices();"
    "v = float(jnp.arange(128).sum());"
    "print('PROBE_OK', d[0].platform, v, flush=True)"
)


def _last_json_with(log_path: str, key: str):
    """Last JSON object line in the CURRENT attempt's log section that
    has ``key``, else None. Logs append across attempts; a stale line
    from an earlier attempt must not satisfy the success check."""
    try:
        with open(log_path) as f:
            lines = f.readlines()
    except OSError:
        return None
    for i in range(len(lines) - 1, -1, -1):
        if lines[i].startswith("===== attempt at "):
            lines = lines[i + 1:]
            break
    for line in reversed(lines):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if key in rec:
            return rec
    return None


def _check_bench(log_path: str) -> bool:
    rec = _last_json_with(log_path, "device")
    return (rec is not None and rec.get("device") != "cpu"
            and "error" not in rec and "note" not in rec)


def _check_stream(log_path: str) -> bool:
    """At least one on-chip streaming cell completed this attempt.

    Scans EVERY row of the current attempt (not just the last): the
    backend sweep legitimately ends with an error row where pallas
    does not compile, and that must not fail an attempt whose other
    cells landed their evidence.
    """
    try:
        with open(log_path) as f:
            lines = f.readlines()
    except OSError:
        return False
    for i in range(len(lines) - 1, -1, -1):
        if lines[i].startswith("===== attempt at "):
            lines = lines[i + 1:]
            break
    for line in lines:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if (rec.get("check") == "stream" and rec.get("device") != "cpu"
                and "error" not in rec):
            return True
    return False


def _check_bench_job(log_path: str) -> bool:
    rec = _last_json_with(log_path, "device")
    return rec is not None and rec.get("device") != "cpu"


def runlist():
    # VALUE order, not dependency order: if the relay answers late in
    # a round, the first items to complete are the driver-visible
    # artifacts (a device:"tpu" bench at the shipped default, then the
    # end-to-end cascade A/B), with the sweep remainder and the
    # longer verify matrix behind them.
    py = sys.executable
    return [
        {
            "name": "bench",
            # --no-probe: the runner already probed (in a killable
            # subprocess); bench's own CPU fallback would otherwise turn
            # a mid-run relay death into a "successful" CPU artifact.
            "cmd": [py, "bench.py", "--no-probe"],
            "timeout": 1800,
            "check": _check_bench,
        },
        {
            "name": "bench_job",
            # Both cascade backends in one item: the A/B that decides
            # the BatchJobConfig.cascade_backend default. --state lands
            # the cascade-pyramid16 rows apply_decisions rule (b)
            # reads; bench_job subprocesses each measurement and
            # auto-bisects --n on a TPU-worker crash, so a partial row
            # set survives a mid-run relay death.
            "cmd": [py, "tools/bench_job.py", "--n", "20000000",
                    "--cascade-backend", "both",
                    "--state", f"{STATE_DIR}/sweep.jsonl"],
            "timeout": 3600,
            "check": _check_bench_job,
        },
        {
            "name": "sweep_partitioned",
            "cmd": [py, "tools/sweep_partitioned.py",
                    "--state", f"{STATE_DIR}/sweep.jsonl"],
            "timeout": 3600,
        },
        {
            "name": "verify_partitioned",
            "cmd": [py, "tools/verify_partitioned_onchip.py",
                    "--state", f"{STATE_DIR}/verify.jsonl"],
            "timeout": 2700,
            # rc 3 = every combo settled, none bit-INEXACT, but some
            # recorded deterministic compile errors (e.g. the x64
            # toolchain regression): the run is complete — retrying
            # cannot change it. rc 1 (mismatch) stays a failure, and
            # rc 4 (combos skipped on transient relay failures —
            # UNVERIFIED under the current epoch) deliberately is NOT
            # ok: the item re-queues and the next attempt retries just
            # the unsettled combos via --state.
            "ok_rcs": (0, 3),
            # The done.json entry records the kernel epoch; a kernel
            # edit re-queues this item on the next runner start.
            "epoch": True,
        },
        {
            "name": "bench_stream",
            # BASELINE config 4 on chip: the decayed streaming update
            # step at the headline window, per binning backend — the
            # rows that decide StreamConfig's default backend
            # (PERF_NOTES decision rules).
            "cmd": [py, "tools/bench_stream.py",
                    "--state", f"{STATE_DIR}/sweep.jsonl"],
            "timeout": 1800,
            "check": _check_stream,
        },
    ]


def load_done():
    path = os.path.join(STATE_DIR, "done.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def save_done(done):
    path = os.path.join(STATE_DIR, "done.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(done, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def log(msg):
    print(f"[onchip_runner {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def probe(timeout_s: float = 150.0) -> bool:
    # Generous: a relay recovering from an outage can take >75s for
    # its first backend init (per-call cost varies 2-5x day to day);
    # false-failing the probe then would keep the queue idle exactly
    # when the chip finally answers.
    try:
        r = subprocess.run([sys.executable, "-c", PROBE],
                           timeout=timeout_s, capture_output=True, text=True)
        if r.returncode == 0 and "PROBE_OK" in r.stdout:
            line = r.stdout.split("PROBE_OK", 1)[1].split()
            platform = line[0] if line else "?"
            if platform != "cpu":
                log(f"probe ok: platform={platform}")
                return True
            log("probe answered but on CPU fallback; relay NOT up")
            return False
        tail = (r.stderr or r.stdout).strip().splitlines()[-1:]
        log(f"probe failed rc={r.returncode}: {tail}")
        return False
    except subprocess.TimeoutExpired:
        log(f"probe timed out after {timeout_s:.0f}s (relay wedged)")
        return False


def run_item(item, env) -> int:
    os.makedirs(STATE_DIR, exist_ok=True)
    log_path = os.path.join(STATE_DIR, f"{item['name']}.log")
    log(f"running {item['name']} (log: {log_path})")
    with open(log_path, "a") as lf:
        lf.write(f"\n===== attempt at {time.strftime('%F %T')} =====\n")
        lf.flush()
        try:
            r = subprocess.run(item["cmd"], timeout=item["timeout"],
                               stdout=lf, stderr=subprocess.STDOUT, env=env)
            return r.returncode
        except subprocess.TimeoutExpired:
            lf.write(f"\n[runner] TIMED OUT after {item['timeout']}s\n")
            return -1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-min", type=float, default=240.0)
    ap.add_argument("--poll-s", type=float, default=120.0)
    ap.add_argument("--max-attempts", type=int, default=8)
    args = ap.parse_args()

    os.makedirs(STATE_DIR, exist_ok=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = "." + os.pathsep + env.get("PYTHONPATH", "")

    deadline = time.time() + args.deadline_min * 60
    done = load_done()
    epoch = current_epoch()
    queue = build_queue(runlist(), done, epoch)
    attempts = {it["name"]: 0 for it in queue}
    while time.time() < deadline:
        if not queue:
            log("runlist complete")
            return 0
        if not probe():
            time.sleep(args.poll_s)
            continue
        item = queue[0]
        rc = run_item(item, env)
        log_path = os.path.join(STATE_DIR, f"{item['name']}.log")
        check = item.get("check")
        ok = (rc in item.get("ok_rcs", (0,))
              and (check is None or check(log_path)))
        if ok:
            entry = {"at": time.strftime("%F %T")}
            if item.get("epoch"):
                entry["epoch"] = epoch
            done[item["name"]] = entry
            save_done(done)
            queue.pop(0)
            log(f"{item['name']} DONE")
            continue
        attempts[item["name"]] += 1
        why = f"rc={rc}" if rc != 0 else "success-check failed (cpu?)"
        if attempts[item["name"]] >= args.max_attempts:
            done[item["name"]] = {"failed": why,
                                  "at": time.strftime("%F %T")}
            if item.get("epoch"):
                done[item["name"]]["epoch"] = epoch
            save_done(done)
            queue.pop(0)
            log(f"{item['name']} FAILED permanently ({why})")
        else:
            # Back of the queue: one flaky item must not starve the rest.
            queue.append(queue.pop(0))
            log(f"{item['name']} failed ({why}); requeued "
                f"(attempt {attempts[item['name']]}/{args.max_attempts})")
            time.sleep(args.poll_s / 2)
    pending = ", ".join(it["name"] for it in queue)
    log(f"deadline reached; pending: {pending or 'none'}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
