#!/usr/bin/env python
"""Streaming (BASELINE config 4) on-chip benchmark: the decayed
micro-batch update step at the headline window.

Measures the compiled HeatmapStream update loop — per-batch exponential
time decay + window binning on a donated device raster — and prints one
JSON line per (backend, batch) cell:

    {"check": "stream", "backend": ..., "batch": ..., "window": "z11",
     "pts_per_s": ..., "steps_per_s": ..., "device": ...}

Backends route the shard-local binning (ops.histogram): "xla" and
"partitioned" everywhere, "pallas" where Mosaic compiles. The routing
decision for StreamConfig's default backend follows the same rule as
the batch sweeps (PERF_NOTES decision rules): flip only on measured
on-chip wins.

    PYTHONPATH=.:$PYTHONPATH python tools/bench_stream.py \
        [--state onchip_state/sweep.jsonl] [--cpu]

``--state`` appends one JSONL row per completed cell and skips cells
already present, so a mid-run relay death costs only the cell in
flight (tools/onchip_runner.py contract).
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def _done_cells(state_path: str) -> set:
    done = set()
    if not state_path or not os.path.exists(state_path):
        return done
    with open(state_path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("check") == "stream":
                done.add((rec.get("backend"), rec.get("batch"),
                          rec.get("device")))
    return done


def _append(state_path: str, rec: dict) -> None:
    if not state_path:
        return
    with open(state_path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--zoom", type=int, default=11)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batches", default=None,
                    help="comma list of batch sizes (default 262144)")
    ap.add_argument("--backends", default="auto,xla,partitioned,pallas",
                    help="'auto' measures the routed default so the "
                    "decision rule can compare it against each pinned "
                    "backend")
    ap.add_argument("--state", default=None)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (the axon sitecustomize "
                    "overrides JAX_PLATFORMS, so the env var is not "
                    "enough)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from heatmap_tpu.ops import window_from_bounds
    from heatmap_tpu.streaming import HeatmapStream, StreamConfig

    device = jax.devices()[0].platform
    batches = ([int(b) for b in args.batches.split(",")]
               if args.batches else [1 << 18])
    window = window_from_bounds((35.0, 55.0), (-5.0, 20.0),
                                zoom=args.zoom, align_levels=4)
    print(json.dumps({"stage": "setup", "device": device,
                      "window": list(window.shape)}), flush=True)
    done = _done_cells(args.state)

    rng = np.random.default_rng(7)
    for backend in args.backends.split(","):
        for batch in batches:
            key = (backend, batch, device)
            if key in done:
                print(json.dumps({"skip": "done",
                                  "backend": backend, "batch": batch}),
                      flush=True)
                continue
            cfg = StreamConfig(window=window, half_life_s=600.0,
                               pad_to=batch, backend=backend)
            stream = HeatmapStream(cfg)
            lat = rng.uniform(35.0, 55.0, (args.steps, batch))
            lon = rng.uniform(-5.0, 20.0, (args.steps, batch))
            try:
                # Warm step compiles; excluded from the timed loop.
                stream.update(lat[0], lon[0], t=0.0)
                stream.snapshot()
                t0 = time.perf_counter()
                for i in range(1, args.steps):
                    stream.update(lat[i % args.steps],
                                  lon[i % args.steps], t=float(i))
                np.asarray(stream.snapshot())
                dt = time.perf_counter() - t0
            except Exception as e:  # noqa: BLE001 — report, keep sweeping
                print(json.dumps({"check": "stream", "backend": backend,
                                  "batch": batch, "device": device,
                                  "error": f"{type(e).__name__}: {e}"[:300]}),
                      flush=True)
                continue
            steps = args.steps - 1
            rec = {
                "check": "stream", "backend": backend, "batch": batch,
                "window": f"z{args.zoom}", "device": device,
                "steps_per_s": round(steps / dt, 2),
                "pts_per_s": round(steps * batch / dt, 1),
            }
            print(json.dumps(rec), flush=True)
            _append(args.state, rec)


if __name__ == "__main__":
    main()
