#!/usr/bin/env python
"""Continuous-ingest bench: throughput + ingest->servable latency,
bucketed vs exact padding side by side: BENCH_ingest.json.

For each micro-batch size, drains the same deterministically *jittered*
synthetic stream (batch sizes vary tick to tick, the realistic shape a
standing loop sees) through ``ingest.run_ingest`` twice — once with the
exact-padding cascade (one jit compile per distinct batch size) and
once with the pow2 bucketed compile cache (``pipeline/bucketing.py``,
one compile per bucket) — publishing every tick to a live serve store.

Measured per cell:

- ``pts_per_s``    sustained applied points / loop wall seconds;
- ``lag_ms``       ingest->servable p50/p99: micro-batch enqueued ->
                   tiles invalidated (the ``lag_s`` field of each
                   ``ingest_tick`` event);
- ``tick_ms``      apply+publish p50/p99 (queue wait excluded);
- ``compiles``     distinct cascade jit signatures this run
                   (``bucketing.cache_stats()["misses"]`` — counted for
                   exact mode too, under its own mode label);
- ``feed_overlap_pct``  share of host->device transfer time the
                   double-buffered feeder (pipeline/feeder.py) hid
                   behind tick compute, plus the feeder's queue-depth
                   high-water mark.

The exact cell of each pair runs first so a warm jax cache can only
ever favor exact; bucketed cells still win on jittered sizes because
later ticks land in an already-compiled bucket. The acceptance anchor
(docs/ingest.md): bucketed compile count <= bucket count while exact
pays one compile per distinct size.

    PYTHONPATH=.:$PYTHONPATH python tools/bench_ingest.py \
        [--points 40000] [--micro-batches 512,2048,8192] \
        [--out BENCH_ingest.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time


#: Tick-to-tick batch size multipliers (deterministic jitter cycle).
JITTER = (1.0, 0.62, 0.91, 0.55, 0.84, 0.73)


class JitteredSource:
    """Re-chunk a materialized columnar batch into deterministically
    varying micro-batch sizes: tick k gets ``batch_size * JITTER[k %
    len(JITTER)]`` rows. Same points, same order, every drain."""

    def __init__(self, cols: dict):
        self.cols = cols

    def batches(self, batch_size: int = 1 << 20):
        n = len(self.cols["latitude"])
        i = k = 0
        while i < n:
            take = max(1, min(n - i,
                              int(batch_size * JITTER[k % len(JITTER)])))
            yield {c: v[i:i + take] for c, v in self.cols.items()}
            i += take
            k += 1


def _materialize(spec: str) -> dict:
    """Drain a source spec into one columnar dict."""
    from heatmap_tpu.io import open_source

    cols: dict = {}
    for batch in open_source(spec).batches(1 << 20):
        for c, v in batch.items():
            cols.setdefault(c, []).extend(v)
    return cols


def _pct(sorted_vals: list, q: float) -> float | None:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def bench_cell(cols: dict, micro_batch: int, mode: str,
               tmpdir: str) -> dict:
    from heatmap_tpu import delta, ingest
    from heatmap_tpu.obs import events
    from heatmap_tpu.pipeline import BatchJobConfig, bucketing
    from heatmap_tpu.serve import TileCache, TileStore

    config = BatchJobConfig(detail_zoom=11, min_detail_zoom=5,
                            result_delta=3, pad_bucketing=mode)
    root = os.path.join(tmpdir, f"store-{micro_batch}-{mode}")
    delta.init_store(root)
    store, cache = TileStore(f"delta:{root}"), TileCache()
    events_path = os.path.join(tmpdir, f"events-{micro_batch}-{mode}.jsonl")
    bucketing.reset_cache_stats()
    log = events.EventLog(events_path)
    events.set_event_log(log)
    t0 = time.perf_counter()
    try:
        stats = ingest.run_ingest(
            root, JitteredSource(cols), config, store=store, cache=cache,
            ingest=ingest.IngestConfig(micro_batch=micro_batch,
                                       queue_depth=4, compact_every=0))
    finally:
        events.set_event_log(None)
        log.close()
    wall_s = time.perf_counter() - t0
    ticks = [r for r in events.read_events(events_path)
             if r["event"] == "ingest_tick"]
    lags = sorted(1e3 * float(r["lag_s"]) for r in ticks)
    secs = sorted(1e3 * float(r["seconds"]) for r in ticks)
    cache_stats = bucketing.cache_stats()
    shutil.rmtree(root, ignore_errors=True)
    return {
        "micro_batch": micro_batch,
        "mode": mode,
        "ticks": stats.ticks,
        "points": stats.points,
        "pts_per_s": round(stats.points / wall_s, 1) if wall_s else None,
        "lag_ms": {"p50": _pct(lags, 0.50), "p99": _pct(lags, 0.99)},
        "tick_ms": {"p50": _pct(secs, 0.50), "p99": _pct(secs, 0.99)},
        "compiles": cache_stats["misses"],
        "cache_hits": cache_stats["hits"],
        "keys_invalidated": stats.keys_invalidated,
        "max_queue_depth": stats.max_queue_depth,
        "feed_overlap_pct": round(stats.feed_overlap_pct, 1),
        "feeder_depth_hwm": stats.feeder_depth_hwm,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", type=int, default=40_000)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--micro-batches", default="512,2048,8192",
                    help="comma list of micro-batch sizes")
    ap.add_argument("--out", default="BENCH_ingest.json")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from heatmap_tpu import obs
    from heatmap_tpu.utils.trace import get_tracer

    obs.enable_metrics(True)
    cols = _materialize(f"synthetic:{args.points}:{args.seed}")
    sizes = [int(b) for b in args.micro_batches.split(",") if b.strip()]
    tmpdir = tempfile.mkdtemp(prefix="benchingest-")
    results = []
    try:
        for micro_batch in sizes:
            # exact first: a warm jax cache can only favor exact.
            for mode in ("exact", "pow2"):
                row = bench_cell(cols, micro_batch, mode, tmpdir)
                print(json.dumps({k: row[k] for k in
                                  ("micro_batch", "mode", "pts_per_s",
                                   "lag_ms", "compiles")}), flush=True)
                results.append(row)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)

    record = {
        "bench": "ingest",
        "points": args.points,
        "micro_batches": sizes,
        "results": results,
        "run_report": obs.build_run_report(tracer=get_tracer(),
                                           registry=obs.get_registry()),
    }
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2, default=str)
        f.write("\n")
    print(json.dumps({"wrote": args.out}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
