#!/usr/bin/env python
"""Peak-RSS probe for the bounded path's cross-chunk merge.

Reproduces the PERF_NOTES adversarial shape (normal sigma ~0.5 deg ->
near-unique z21 keys, output ~= input) and measures peak RSS of
``run_job_fast(..., max_points_in_flight=...)`` with the in-RAM merge
vs the disk-spill merge (``merge_spill_dir``), each in a fresh
subprocess so high-water marks don't pollute each other. Sinks to
arrays: egress (the at-scale path). Prints one JSON line per mode:

    {"mode": "ram"|"spill", "peak_rss_gb": ..., "seconds": ...,
     "rows": ..., "n": ..., "chunks": ...}

Usage:
    PYTHONPATH=.:$PYTHONPATH python tools/mem_probe.py \
        [--n 20000000] [--chunk 2000000] [--modes ram,spill]

The probe is CPU-only (forces jax_platforms=cpu): merge behavior is
host-side; no relay needed.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

_CHILD = """
import json, os, resource, sys, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from heatmap_tpu.io.hmpb import HMPBSource
from heatmap_tpu.io.sinks import LevelArraysSink
from heatmap_tpu.pipeline import BatchJobConfig, run_job_fast
from heatmap_tpu.pipeline import batch as batch_mod

hmpb, out_dir, spill_dir, chunk = sys.argv[1:5]
chunk = int(chunk)
if spill_dir == "-":
    # "ram" mode must measure the pure in-RAM fold: disable the
    # AUTO_SPILL_ROWS conversion that is now the production default.
    batch_mod.AUTO_SPILL_ROWS = 1 << 62
cfg = BatchJobConfig()
t0 = time.perf_counter()
stats = run_job_fast(
    HMPBSource(hmpb), LevelArraysSink(out_dir), cfg,
    max_points_in_flight=chunk,
    merge_spill_dir=spill_dir if spill_dir != "-" else None,
)
dt = time.perf_counter() - t0
peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
print(json.dumps({
    "peak_rss_gb": round(peak_kb / (1 << 20), 2),
    "seconds": round(dt, 1),
    "rows": stats.get("rows"),
}), flush=True)
"""


def build_points(path: str, n: int, seed: int = 3) -> None:
    from heatmap_tpu.io.hmpb import write_hmpb

    rng = np.random.default_rng(seed)
    lat = rng.normal(47.6, 0.5, n)
    lon = rng.normal(-122.3, 0.5, n)
    routed = rng.integers(0, 8, n).astype(np.int32)
    write_hmpb(path, lat, lon, routed, [f"u{i}" for i in range(8)])


def run_mode(hmpb: str, mode: str, chunk: int, work: str) -> dict:
    out_dir = os.path.join(work, f"out-{mode}")
    spill = os.path.join(work, "spill") if mode == "spill" else "-"
    env = dict(os.environ)
    env["PYTHONPATH"] = "." + os.pathsep + env.get("PYTHONPATH", "")
    t0 = time.perf_counter()
    r = subprocess.run(
        [sys.executable, "-c", _CHILD, hmpb, out_dir, spill, str(chunk)],
        capture_output=True, text=True, env=env,
    )
    if r.returncode != 0:
        raise SystemExit(f"{mode} child failed:\n{r.stderr[-2000:]}")
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    rec.update(mode=mode, wall_s=round(time.perf_counter() - t0, 1))
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=20_000_000)
    ap.add_argument("--chunk", type=int, default=2_000_000)
    ap.add_argument("--modes", default="ram,spill")
    ap.add_argument("--workdir", default=None,
                    help="default: a fresh temp dir (removed on exit)")
    args = ap.parse_args()

    import shutil

    work = args.workdir or tempfile.mkdtemp(prefix="mem-probe-")
    try:
        hmpb = os.path.join(work, "pts.hmpb")
        build_points(hmpb, args.n)
        for mode in args.modes.split(","):
            rec = run_mode(hmpb, mode.strip(), args.chunk, work)
            rec.update(n=args.n, chunks=-(-args.n // args.chunk))
            print(json.dumps(rec), flush=True)
    finally:
        if args.workdir is None:
            shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    main()
