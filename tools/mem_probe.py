#!/usr/bin/env python
"""Peak-RSS probe for the bounded path's cross-chunk merge.

Reproduces the PERF_NOTES adversarial shape (normal sigma ~0.5 deg ->
near-unique z21 keys, output ~= input) and measures peak RSS of
``run_job_fast(..., max_points_in_flight=...)`` with the in-RAM merge
vs the disk-spill merge (``merge_spill_dir``), each in a fresh
subprocess so high-water marks don't pollute each other. Sinks to
arrays: egress (the at-scale path). Prints one JSON line per mode:

    {"mode": "ram"|"spill", "peak_rss_gb": ..., "seconds": ...,
     "rows": ..., "n": ..., "chunks": ...}

Usage:
    PYTHONPATH=.:$PYTHONPATH python tools/mem_probe.py \
        [--n 20000000] [--chunk 2000000] [--modes ram,spill]

The probe is CPU-only (forces jax_platforms=cpu): merge behavior is
host-side; no relay needed.

``--fleet-rss SPEC`` switches to the serve-fleet memory probe
(heatmap_tpu.tilefs): spawn N real backend processes over the spec,
sweep the tile universe against every backend so store pages actually
fault in, and report the fleet's total Pss from
``/proc/<pid>/smaps_rollup`` — Pss, not Rss, because the mmap'd tilefs
store's whole point is that N backends *share* the page-cache copy of
the level arrays, and Pss divides shared pages by their mapper count
while Rss would charge every backend the full store. Pass
``--fleet-rss-heap SPEC`` too and the probe prints both legs plus the
mapped/heap ratio (sub-linear fleet memory is the tilefs acceptance
claim; tools/load_gen.py --cold-vs-warm embeds the same measurement in
BENCH_serve.json as ``serve:fleet_rss_ratio``).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

_CHILD = """
import json, os, resource, sys, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from heatmap_tpu.io.hmpb import HMPBSource
from heatmap_tpu.io.sinks import LevelArraysSink
from heatmap_tpu.pipeline import BatchJobConfig, run_job_fast
from heatmap_tpu.pipeline import batch as batch_mod

hmpb, out_dir, spill_dir, chunk = sys.argv[1:5]
chunk = int(chunk)
if spill_dir == "-":
    # "ram" mode must measure the pure in-RAM fold: disable the
    # AUTO_SPILL_ROWS conversion that is now the production default.
    batch_mod.AUTO_SPILL_ROWS = 1 << 62
cfg = BatchJobConfig()
t0 = time.perf_counter()
stats = run_job_fast(
    HMPBSource(hmpb), LevelArraysSink(out_dir), cfg,
    max_points_in_flight=chunk,
    merge_spill_dir=spill_dir if spill_dir != "-" else None,
)
dt = time.perf_counter() - t0
peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
print(json.dumps({
    "peak_rss_gb": round(peak_kb / (1 << 20), 2),
    "seconds": round(dt, 1),
    "rows": stats.get("rows"),
}), flush=True)
"""


def build_points(path: str, n: int, seed: int = 3) -> None:
    from heatmap_tpu.io.hmpb import write_hmpb

    rng = np.random.default_rng(seed)
    lat = rng.normal(47.6, 0.5, n)
    lon = rng.normal(-122.3, 0.5, n)
    routed = rng.integers(0, 8, n).astype(np.int32)
    write_hmpb(path, lat, lon, routed, [f"u{i}" for i in range(8)])


def run_mode(hmpb: str, mode: str, chunk: int, work: str) -> dict:
    out_dir = os.path.join(work, f"out-{mode}")
    spill = os.path.join(work, "spill") if mode == "spill" else "-"
    env = dict(os.environ)
    env["PYTHONPATH"] = "." + os.pathsep + env.get("PYTHONPATH", "")
    t0 = time.perf_counter()
    r = subprocess.run(
        [sys.executable, "-c", _CHILD, hmpb, out_dir, spill, str(chunk)],
        capture_output=True, text=True, env=env,
    )
    if r.returncode != 0:
        raise SystemExit(f"{mode} child failed:\n{r.stderr[-2000:]}")
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    rec.update(mode=mode, wall_s=round(time.perf_counter() - t0, 1))
    return rec


def pss_kb(pid: int) -> tuple:
    """``(kilobytes, source)`` for one process: Pss from smaps_rollup
    (shared file pages split across their mappers — the honest number
    for an mmap'd fleet), falling back to VmRSS where the kernel lacks
    the rollup file, ``(None, "unavailable")`` off-Linux."""
    try:
        with open(f"/proc/{pid}/smaps_rollup") as f:
            for line in f:
                if line.startswith("Pss:"):
                    return int(line.split()[1]), "pss"
    except OSError:
        pass
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]), "rss"
    except OSError:
        pass
    return None, "unavailable"


def measure_fleet_pss(spec: str, n: int, paths, *,
                      cache_bytes: int = 8 << 20) -> dict:
    """Total proportional RSS of ``n`` real backend processes serving
    ``spec`` after each has answered every path in ``paths``.

    Sweeps every backend *directly* (not through the router) so all of
    them fault the same store pages in — least-load routing would leave
    the measurement at the mercy of which backend won each request. The
    heap tile cache is kept small on purpose: the probe measures the
    store's memory, not cached render bytes.
    """
    from heatmap_tpu.serve.fleet import FleetSupervisor

    import http.client as http_client

    rows = []
    with FleetSupervisor(spec, n, cache_bytes=cache_bytes,
                         probe_interval_s=0.25) as sup:
        sup.start()
        for bid in sorted(sup.router.backends):
            client = sup.router.backends[bid]
            host, port = client.address.rsplit(":", 1)
            conn = http_client.HTTPConnection(host, int(port), timeout=30)
            for p in paths:
                conn.request("GET", p)
                conn.getresponse().read()
            conn.close()
        for bid in sorted(sup._handles):
            proc = getattr(sup._handles[bid], "proc", None)
            if proc is None:  # thread-mode fleet: nothing to attribute
                continue
            kb, source = pss_kb(proc.pid)
            rows.append({"backend": bid, "pid": proc.pid,
                         "kb": kb, "source": source})
    measured = [r for r in rows if r["kb"] is not None]
    total_kb = sum(r["kb"] for r in measured)
    return {
        "spec": spec, "n": n, "paths": len(paths),
        "total_mb": round(total_kb / 1024, 1) if measured else None,
        "per_backend_mb": [round(r["kb"] / 1024, 1) for r in measured],
        "source": measured[0]["source"] if measured else "unavailable",
    }


def fleet_rss_mode(args) -> int:
    """``--fleet-rss``: mapped (and optionally heap) fleet Pss legs."""
    import sys as _sys

    _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from load_gen import tile_universe

    from heatmap_tpu.serve import TileStore

    universe = tile_universe(TileStore(args.fleet_rss), args.fleet_tiles)
    paths = [f"/tiles/{layer}/{z}/{x}/{y}.{fmt}"
             for layer, z, x, y, fmt in universe]
    mapped = measure_fleet_pss(args.fleet_rss, args.fleet_n, paths)
    print(json.dumps({"leg": "mapped", **mapped}), flush=True)
    if args.fleet_rss_heap:
        heap = measure_fleet_pss(args.fleet_rss_heap, args.fleet_n, paths)
        print(json.dumps({"leg": "heap", **heap}), flush=True)
        ratio = (round(mapped["total_mb"] / heap["total_mb"], 4)
                 if mapped["total_mb"] and heap["total_mb"] else None)
        print(json.dumps({"pss_ratio": ratio, "n": args.fleet_n}),
              flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=20_000_000)
    ap.add_argument("--chunk", type=int, default=2_000_000)
    ap.add_argument("--modes", default="ram,spill")
    ap.add_argument("--workdir", default=None,
                    help="default: a fresh temp dir (removed on exit)")
    ap.add_argument("--fleet-rss", default=None, metavar="SPEC",
                    help="serve-fleet Pss probe over this store spec "
                    "(e.g. tilefs:levels/) instead of the merge probe")
    ap.add_argument("--fleet-rss-heap", default=None, metavar="SPEC",
                    help="heap comparison leg (e.g. arrays:levels/); "
                    "with --fleet-rss, also prints the Pss ratio")
    ap.add_argument("--fleet-n", type=int, default=3,
                    help="backends per fleet leg")
    ap.add_argument("--fleet-tiles", type=int, default=128,
                    help="tile universe size swept per backend")
    args = ap.parse_args()

    if args.fleet_rss:
        return fleet_rss_mode(args)

    import shutil

    work = args.workdir or tempfile.mkdtemp(prefix="mem-probe-")
    try:
        hmpb = os.path.join(work, "pts.hmpb")
        build_points(hmpb, args.n)
        for mode in args.modes.split(","):
            rec = run_mode(hmpb, mode.strip(), args.chunk, work)
            rec.update(n=args.n, chunks=-(-args.n // args.chunk))
            print(json.dumps(rec), flush=True)
    finally:
        if args.workdir is None:
            shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    main()
