#!/usr/bin/env python
"""Convert any existing store artifact to the zero-copy tilefs format.

Usage:
    python tools/tilefs_convert.py STORE_SPEC [--out DIR] [--no-levels]
                                   [--verify]

- ``arrays:DIR`` (or a bare npz dir, including multihost ``host*/``
  shards): writes ``tilefs-z*.bin`` mirrors alongside the existing
  levels (in place by default, or into ``--out``). The npz levels stay
  — they are the per-zoom fallback when a tilefs file is torn.
- ``delta:ROOT``: writes the mirrors into the CURRENT base directory,
  so the store serves zero-copy immediately (``TileStore`` sniffs the
  converted base) and live deltas keep overlaying in heap; the next
  compaction rebuilds the mirrors automatically (the staged base
  inherits the tilefs flag).
- ``jsonl:PATH`` / ``dir:PATH`` blob stores: require ``--out`` — the
  blob documents are materialized into columnar levels first
  (npz + tilefs), after which serving renders docs in stored Morton
  order like every other columnar store.

``--verify`` deep-checks every written file (heatmap_tpu.tilefs
verify_tilefs: header/footer/trailer + payload crcs) before reporting.
Writes are atomic (tmp + rename), so a crashed conversion never leaves
a half-written mirror a server could open.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from heatmap_tpu.io.sinks import LevelArraysSink  # noqa: E402
from heatmap_tpu.serve.store import (TileStore, _load_levels,  # noqa: E402
                                     _parse_store_spec)
from heatmap_tpu.tilefs import format as tilefs_format  # noqa: E402
from heatmap_tpu.tilemath.morton import morton_decode_np  # noqa: E402


def _store_to_loaded(store: TileStore) -> dict:
    """Blob-store layers -> loaded-column levels ({zoom: cols})."""
    staged: dict[int, dict[str, list]] = {}
    seen = set()
    for layer in store.layers.values():
        if (layer.user, layer.timespan) in seen:
            continue  # the "default" alias shares the all|alltime layer
        seen.add((layer.user, layer.timespan))
        for zoom, lvl in layer.levels.items():
            rows, cols = morton_decode_np(lvl.codes)
            cz = zoom - (layer.result_delta or 0)
            dst = staged.setdefault(int(zoom), {
                "row": [], "col": [], "value": [], "user": [],
                "timespan": [], "coarse_row": [], "coarse_col": [],
                "zoom": int(zoom), "coarse_zoom": int(cz)})
            dst["row"].append(rows)
            dst["col"].append(cols)
            dst["value"].append(np.asarray(lvl.values, np.float64))
            n = len(lvl.codes)
            dst["user"].append(np.full(n, layer.user, dtype=object))
            dst["timespan"].append(np.full(n, layer.timespan,
                                           dtype=object))
            dst["coarse_row"].append(rows >> (layer.result_delta or 0))
            dst["coarse_col"].append(cols >> (layer.result_delta or 0))
    out = {}
    for zoom, cols in staged.items():
        merged = {"zoom": np.asarray(cols["zoom"]),
                  "coarse_zoom": np.asarray(cols["coarse_zoom"])}
        for k in ("row", "col", "value", "coarse_row", "coarse_col"):
            merged[k] = np.concatenate(cols[k]) if cols[k] else np.array([])
        for k in ("user", "timespan"):
            merged[k] = np.concatenate(cols[k]).astype(str)
        out[zoom] = merged
    return out


def _loaded_to_finalized(levels: dict) -> list:
    """Loaded columns -> finalized dicts (write_levels input)."""
    out = []
    for zoom in sorted(levels):
        cols = dict(levels[zoom])
        for name in ("user", "timespan"):
            vals = np.asarray(cols.pop(name), str)
            names, idx = np.unique(vals, return_inverse=True)
            cols[f"{name}_idx"] = idx.astype(np.int32)
            cols[f"{name}_names"] = names
        cols["zoom"] = int(np.asarray(cols["zoom"]))
        cols["coarse_zoom"] = int(np.asarray(cols["coarse_zoom"]))
        for k in ("row", "col", "coarse_row", "coarse_col"):
            cols[k] = np.asarray(cols[k], np.int64)
        cols["value"] = np.asarray(cols["value"], np.float64)
        out.append(cols)
    return out


def convert(spec: str, out: str | None = None, *,
            write_levels: bool = True) -> dict:
    """Convert ``spec``; returns a summary dict (the CLI prints it)."""
    kind, path = _parse_store_spec(spec)
    written: list[str] = []
    if kind in ("arrays", "tilefs"):
        levels = _load_levels(path)
        dest = out or path
        if out and os.path.abspath(out) != os.path.abspath(path):
            os.makedirs(out, exist_ok=True)
            if write_levels:
                LevelArraysSink(out).write_levels(
                    _loaded_to_finalized(levels))
        written = tilefs_format.write_tilefs_from_loaded(dest, levels)
    elif kind == "delta":
        from heatmap_tpu.delta.compact import read_current

        if out:
            raise SystemExit("--out is not supported for delta stores: "
                             "mirrors go into the CURRENT base")
        cur = read_current(path)
        if not cur.get("base"):
            raise SystemExit(f"{spec}: empty delta store (no base); "
                             "apply a batch or compact first")
        base = os.path.join(path, cur["base"])
        dest = base
        written = tilefs_format.write_tilefs_from_loaded(
            base, LevelArraysSink.load(base))
    else:  # jsonl / dir blob stores
        if not out:
            raise SystemExit(f"{spec}: blob stores need --out DIR (the "
                             "columnar materialization target)")
        dest = out
        store = TileStore(spec)
        levels = _store_to_loaded(store)
        os.makedirs(out, exist_ok=True)
        if write_levels:
            LevelArraysSink(out).write_levels(_loaded_to_finalized(levels))
        written = tilefs_format.write_tilefs_from_loaded(out, levels)
    return {"spec": spec, "kind": kind, "dest": dest,
            "files": [os.path.basename(p) for p in written],
            "bytes": int(sum(os.path.getsize(p) for p in written))}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="convert a store artifact to the zero-copy tilefs "
                    "format (heatmap_tpu.tilefs; see docs/tilefs.md)")
    ap.add_argument("spec", help="store spec: arrays:DIR, delta:ROOT, "
                                 "jsonl:PATH, dir:PATH, or a bare path")
    ap.add_argument("--out", default=None, metavar="DIR",
                    help="write the converted store here instead of in "
                         "place (required for blob stores)")
    ap.add_argument("--no-levels", action="store_true",
                    help="skip the npz level mirror when materializing "
                         "to --out (tilefs only: no torn-file fallback)")
    ap.add_argument("--verify", action="store_true",
                    help="deep-verify every written file (payload crcs)")
    args = ap.parse_args(argv)

    summary = convert(args.spec, args.out,
                      write_levels=not args.no_levels)
    if args.verify:
        bad = {}
        for name in summary["files"]:
            full = os.path.join(summary["dest"], name)
            reason = tilefs_format.verify_tilefs(full)
            if reason is not None:
                bad[name] = reason
        summary["verified"] = not bad
        if bad:
            summary["corrupt"] = bad
    print(json.dumps(summary, indent=2))
    return 1 if summary.get("corrupt") else 0


if __name__ == "__main__":
    sys.exit(main())
