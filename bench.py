#!/usr/bin/env python
"""Headline benchmark: points/sec binned into a z0-z15 tile pyramid.

Runs the fused projection -> window-raster scatter-add -> full pyramid
step (the BASELINE.md primary metric) on the default JAX backend (the
real TPU chip under the driver; CPU with --cpu), and prints ONE JSON
line:

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

``vs_baseline`` is the speedup over a vectorized numpy CPU
implementation of the same workload measured in-process (the reference
publishes no numbers — BASELINE.md — so the baseline proxy is the
strongest single-core CPU formulation of the reference's hot path:
vectorized projection + np.add.at scatter + reshape-sum pyramid, far
faster than the reference's per-record Python mappers).
"""

from __future__ import annotations

import argparse
import json
import math
import time

import numpy as np


def _make_points(n, seed=0):
    """Clustered synthetic GPS points (hot-spot mixture over a metro area),
    the access pattern heatmaps actually see."""
    rng = np.random.default_rng(seed)
    n_hot = n // 4
    base_lat, base_lon = 47.6, -122.3
    lat = np.concatenate(
        [
            base_lat + rng.normal(0, 0.5, n - n_hot),
            base_lat + rng.normal(0, 0.02, n_hot),
        ]
    )
    lon = np.concatenate(
        [
            base_lon + rng.normal(0, 0.7, n - n_hot),
            base_lon + rng.normal(0, 0.03, n_hot),
        ]
    )
    return lat.astype(np.float32), lon.astype(np.float32)


def _numpy_baseline(lat64, lon64, window, levels):
    """Single-core vectorized numpy version of the same step."""
    n = 1 << window.zoom
    phi = lat64 * math.pi / 180
    y = (1 - np.log(np.tan(phi) + 1 / np.cos(phi)) / math.pi) / 2
    row = np.floor(y * n).astype(np.int64) - window.row0
    col = np.floor((lon64 + 180.0) / 360.0 * n).astype(np.int64) - window.col0
    ok = (row >= 0) & (row < window.height) & (col >= 0) & (col < window.width)
    raster = np.zeros((window.height, window.width), np.int32)
    np.add.at(raster, (row[ok], col[ok]), 1)
    out = raster
    for _ in range(levels):
        h, w = out.shape
        if h < 2 or w < 2:
            break
        out = out.reshape(h // 2, 2, w // 2, 2).sum(axis=(1, 3))
    return raster.sum()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1 << 25, help="points per step")
    ap.add_argument("--zoom", type=int, default=15)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--baseline-n", type=int, default=1 << 20)
    ap.add_argument("--cpu", action="store_true", help="run on CPU instead of TPU")
    ap.add_argument("--bin-backend", default="xla",
                    choices=("xla", "partitioned"),
                    help="binning path: xla scatter (default) or the "
                    "sort-partitioned MXU kernel (ops/partitioned.py)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from heatmap_tpu.ops import bin_points_window, pyramid_from_raster, window_from_bounds

    levels = args.zoom  # roll all the way to z0 (window shrinks to 1x1 early)
    window = window_from_bounds(
        (44.0, 51.0), (-127.0, -117.0), zoom=args.zoom,
        align_levels=min(12, args.zoom), pad_multiple=256,
    )

    lat, lon = _make_points(args.n)
    d_lat = jax.device_put(jnp.asarray(lat))
    d_lon = jax.device_put(jnp.asarray(lon))

    @jax.jit
    def step(la, lo):
        raster = bin_points_window(
            la, lo, window, proj_dtype=jnp.float32,
            backend=args.bin_backend,
        )
        pyr = pyramid_from_raster_capped(raster)
        # Return the top so the whole pyramid materializes.
        return pyr[-1].sum(), raster

    def pyramid_from_raster_capped(raster):
        out = [raster]
        r = raster
        for _ in range(levels):
            if r.shape[0] < 2 or r.shape[1] < 2:
                break
            h, w = r.shape
            r = r.reshape(h // 2, 2, w // 2, 2).sum(axis=(1, 3))
            out.append(r)
        return out

    # Warmup/compile. NOTE: timing forces a scalar device->host transfer
    # per step — block_until_ready alone does not reliably block on the
    # axon relay backend, and async dispatch would otherwise make the
    # numbers fictional.
    total, _ = step(d_lat, d_lon)
    int(total)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        total, raster = step(d_lat, d_lon)
        int(total)
    dt = (time.perf_counter() - t0) / args.steps
    pts_per_sec = args.n / dt

    # CPU baseline on a smaller sample, scaled linearly.
    bl_lat, bl_lon = _make_points(args.baseline_n, seed=1)
    t0 = time.perf_counter()
    _numpy_baseline(bl_lat.astype(np.float64), bl_lon.astype(np.float64), window, levels)
    bl_dt = time.perf_counter() - t0
    bl_pts_per_sec = args.baseline_n / bl_dt

    print(
        json.dumps(
            {
                "metric": f"points/sec binned into z0-z{args.zoom} tile pyramid",
                "value": round(pts_per_sec),
                "unit": "points/sec",
                "vs_baseline": round(pts_per_sec / bl_pts_per_sec, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
