#!/usr/bin/env python
"""Headline benchmark: points/sec binned into a z0-z15 tile pyramid.

Runs the fused projection -> window-raster scatter-add -> full pyramid
step (the BASELINE.md primary metric) on the default JAX backend (the
real TPU chip under the driver; CPU with --cpu), and prints ONE JSON
line:

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

``vs_baseline`` is the speedup over a vectorized numpy CPU
implementation of the same workload measured in-process (the reference
publishes no numbers — BASELINE.md — so the baseline proxy is the
strongest single-core CPU formulation of the reference's hot path:
vectorized projection + np.add.at scatter + reshape-sum pyramid, far
faster than the reference's per-record Python mappers).
"""

from __future__ import annotations

import argparse
import json
import math
import subprocess
import sys
import time

import numpy as np

#: One tiny jit through the default backend, run in a THROWAWAY
#: subprocess: the TPU is reached via a relay that can hang for minutes
#: (round 1 lost both driver artifacts to exactly that), so the probe
#: must be killable from outside the process.
_PROBE_CODE = (
    "import jax, jax.numpy as jnp; "
    "v = int(jax.jit(lambda x: x + 1)(jnp.zeros((), jnp.int32))); "
    "print('probe-ok', jax.devices()[0].platform, v)"
)


def probe_tpu(timeout_s: float = 120.0, attempts: int = 3,
              backoff_s: float = 20.0) -> bool:
    """True iff the default backend answers a tiny jit in time AND is an
    accelerator (the chip shows up as platform "axon" here; a probe that
    silently fell back to CPU must not count as TPU-alive)."""
    for i in range(attempts):
        try:
            res = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if res.returncode == 0 and "probe-ok" in res.stdout:
                platform = res.stdout.split("probe-ok", 1)[1].split()[0]
                return platform != "cpu"
        except subprocess.TimeoutExpired:
            pass
        if i + 1 < attempts:
            time.sleep(backoff_s * (i + 1))
    return False


_LAST_TPU_PATH = "onchip_state/last_bench_tpu.json"


def _load_last_tpu():
    """Last persisted on-chip result of this benchmark, or None."""
    try:
        with open(_LAST_TPU_PATH) as f:
            rec = json.load(f)
        return rec if rec.get("unit") == "points/sec" else None
    except (OSError, ValueError):
        return None


def last_tpu_measurement():
    """What a CPU-fallback artifact reports as the most recent on-chip
    result: the file-backed record (written ONLY by an actual TPU run
    of this benchmark, ``_save_last_tpu``) or an explicit "never" —
    there is no hand-typed number here by design (VERDICT r4 #8), so
    no stale literal can masquerade as measured evidence. Prior-session
    prose figures live in PERF_NOTES.md, clearly labeled as prose.
    Pinned by tests/test_bench_artifact.py."""
    return _load_last_tpu() or {
        "value": None,
        "unit": "points/sec",
        "measured": "never (no on-chip run of bench.py has completed; "
                    "see PERF_NOTES.md for prior-session prose figures)",
    }


def _save_last_tpu(out):
    """Persist a TPU run's result (best effort; artifact printing must
    never fail on a read-only or missing state dir)."""
    try:
        import os

        os.makedirs("onchip_state", exist_ok=True)
        rec = dict(out)
        rec["measured"] = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
        with open(_LAST_TPU_PATH, "w") as f:
            json.dump(rec, f, indent=1)
    except OSError:
        pass


def _make_points(n, seed=0):
    """Clustered synthetic GPS points (hot-spot mixture over a metro area),
    the access pattern heatmaps actually see."""
    rng = np.random.default_rng(seed)
    n_hot = n // 4
    base_lat, base_lon = 47.6, -122.3
    lat = np.concatenate(
        [
            base_lat + rng.normal(0, 0.5, n - n_hot),
            base_lat + rng.normal(0, 0.02, n_hot),
        ]
    )
    lon = np.concatenate(
        [
            base_lon + rng.normal(0, 0.7, n - n_hot),
            base_lon + rng.normal(0, 0.03, n_hot),
        ]
    )
    return lat.astype(np.float32), lon.astype(np.float32)


def _numpy_baseline(lat64, lon64, window, levels):
    """Single-core vectorized numpy version of the same step."""
    n = 1 << window.zoom
    phi = lat64 * math.pi / 180
    y = (1 - np.log(np.tan(phi) + 1 / np.cos(phi)) / math.pi) / 2
    row = np.floor(y * n).astype(np.int64) - window.row0
    col = np.floor((lon64 + 180.0) / 360.0 * n).astype(np.int64) - window.col0
    ok = (row >= 0) & (row < window.height) & (col >= 0) & (col < window.width)
    raster = np.zeros((window.height, window.width), np.int32)
    np.add.at(raster, (row[ok], col[ok]), 1)
    out = raster
    for _ in range(levels):
        h, w = out.shape
        if h < 2 or w < 2:
            break
        out = out.reshape(h // 2, 2, w // 2, 2).sum(axis=(1, 3))
    return raster.sum()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1 << 25, help="points per step")
    ap.add_argument("--zoom", type=int, default=15)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--baseline-n", type=int, default=1 << 20)
    ap.add_argument("--cpu", action="store_true", help="run on CPU instead of TPU")
    ap.add_argument("--no-probe", action="store_true",
                    help="skip the TPU liveness probe (assume reachable)")
    ap.add_argument("--probe-timeout", type=float, default=120.0)
    ap.add_argument("--bin-backend", default="auto",
                    choices=("auto", "xla", "pallas", "partitioned"),
                    help="binning path: auto (measured per-window routing), "
                    "xla scatter, pallas MXU kernel, or the sort-partitioned "
                    "MXU kernel (ops/partitioned.py)")
    args = ap.parse_args()

    device = "cpu" if args.cpu else "tpu"
    note = None
    if not args.cpu and not args.no_probe:
        if not probe_tpu(timeout_s=args.probe_timeout):
            # A flaky relay must degrade to an honest CPU number, never
            # zero out the round's artifact with a hang/stack trace.
            device = "cpu"
            note = "tpu-unavailable; cpu fallback"

    LAST_TPU_MEASUREMENT = last_tpu_measurement()

    import jax

    if device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from heatmap_tpu.ops import bin_points_window, window_from_bounds
    from heatmap_tpu.ops.histogram import _pick_backend

    levels = args.zoom  # roll all the way to z0 (window shrinks to 1x1 early)
    window = window_from_bounds(
        (44.0, 51.0), (-127.0, -117.0), zoom=args.zoom,
        align_levels=min(12, args.zoom), pad_multiple=256,
    )

    lat, lon = _make_points(args.n)
    d_lat = jax.device_put(jnp.asarray(lat))
    d_lon = jax.device_put(jnp.asarray(lon))

    def make_step(backend):
        @jax.jit
        def step(la, lo):
            raster = bin_points_window(
                la, lo, window, proj_dtype=jnp.float32, backend=backend,
            )
            pyr = pyramid_from_raster_capped(raster)
            # Return the top so the whole pyramid materializes.
            return pyr[-1].sum(), raster

        return step

    def pyramid_from_raster_capped(raster):
        out = [raster]
        r = raster
        for _ in range(levels):
            if r.shape[0] < 2 or r.shape[1] < 2:
                break
            h, w = r.shape
            r = r.reshape(h // 2, 2, w // 2, 2).sum(axis=(1, 3))
            out.append(r)
        return out

    # Warmup/compile. NOTE: timing forces a scalar device->host transfer
    # per step — block_until_ready alone does not reliably block on the
    # axon relay backend, and async dispatch would otherwise make the
    # numbers fictional.
    resolved = _pick_backend(args.bin_backend, window)
    step = make_step(args.bin_backend)
    note2 = None
    try:
        total, _ = step(d_lat, d_lon)
        int(total)
    except Exception as e:  # noqa: BLE001
        # A kernel backend that fails to compile/run on THIS chip must
        # degrade to the scatter path, not zero out the artifact.
        if args.bin_backend == "xla":
            raise
        note2 = (f"{resolved} backend failed "
                 f"({type(e).__name__}); xla fallback")
        resolved = "xla"
        step = make_step("xla")
        total, _ = step(d_lat, d_lon)
        int(total)

    # Median over per-step times: the axon relay's per-call sync cost
    # spikes unpredictably (PERF_NOTES.md), and one stalled step must
    # not halve the round's recorded number.
    times = []
    for _ in range(args.steps):
        t0 = time.perf_counter()
        total, raster = step(d_lat, d_lon)
        int(total)
        times.append(time.perf_counter() - t0)
    times.sort()
    dt = times[len(times) // 2]
    pts_per_sec = args.n / dt

    # CPU baseline on a smaller sample, scaled linearly.
    bl_lat, bl_lon = _make_points(args.baseline_n, seed=1)
    t0 = time.perf_counter()
    _numpy_baseline(bl_lat.astype(np.float64), bl_lon.astype(np.float64), window, levels)
    bl_dt = time.perf_counter() - t0
    bl_pts_per_sec = args.baseline_n / bl_dt

    # Record what ACTUALLY ran, not what was requested: with --no-probe
    # a missing TPU silently falls back to CPU inside JAX, and a CPU
    # number labeled "tpu" would both corrupt the round artifact and
    # overwrite real on-chip evidence in last_bench_tpu.json.
    actual_platform = jax.devices()[0].platform
    if device != "cpu" and actual_platform == "cpu":
        device = "cpu"
        fallback = "requested tpu; jax resolved cpu"
        note = f"{note}; {fallback}" if note else fallback

    out = {
        "metric": f"points/sec binned into z0-z{args.zoom} tile pyramid",
        "value": round(pts_per_sec),
        "unit": "points/sec",
        "vs_baseline": round(pts_per_sec / bl_pts_per_sec, 2),
        "device": device,
        "bin_backend": args.bin_backend,
        # "auto" resolves per window/platform — record what actually ran
        # so artifacts from different rounds stay comparable.
        "bin_backend_resolved": resolved,
    }
    if note:
        out["note"] = note
        out["last_tpu_measurement"] = LAST_TPU_MEASUREMENT
    if note2:
        out["note_backend"] = note2
    if device != "cpu":
        _save_last_tpu(out)
    print(json.dumps(out))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — the artifact must be JSON
        print(json.dumps({
            "metric": "points/sec binned into tile pyramid",
            "value": 0,
            "unit": "points/sec",
            "vs_baseline": 0,
            "error": f"{type(e).__name__}: {e}"[:500],
        }))
        sys.exit(0)
