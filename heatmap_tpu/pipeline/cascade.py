"""The zoom cascade: one sorted composite key, sixteen levels, zero shuffles.

The reference runs 16 Spark stages, each re-projecting every aggregate's
tile center and shuffling twice (reference heatmap.py:107-118;
SURVEY.md §3.3: 32 shuffles). Here the whole cascade is ONE device-side
sparse pyramid over composite integer keys:

    key = slot * 4^detail_zoom + morton_code,  slot = timespan*G + group

Because the slot multiplier is a power of four, ``key >> 2`` coarsens
the Morton part one zoom while leaving the (timespan, group) slot
intact, and preserves sort order — so every cascade level is a plain
segment-sum over the order established by a single sort
(ops/pyramid.pyramid_sparse_morton).

Blob regrouping (reference map_to_resultset + groupByKey,
heatmap.py:79-90,112) happens host-side at egress: the blob id is just
``key >> 2*result_delta``, no second shuffle.

The reference's '`all`'-amplification quirk (SURVEY.md §8.1:
``all_z = 2*all_{z+1} + sum_users user_{z+1}``) is reproduced on demand
by ``amplify_all=True`` as a host-side post-pass over the correct
per-level aggregates; per-user counts are identical in both modes, as
they are in the reference.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from heatmap_tpu.ops import pyramid as pyramid_ops
from heatmap_tpu.pipeline.groups import ALL_GROUP
from heatmap_tpu.tilemath import keys as keys_mod
from heatmap_tpu.tilemath.morton import morton_decode_np


@dataclasses.dataclass(frozen=True)
class CascadeConfig:
    """Static cascade parameters (reference constants, heatmap.py:16-17).

    Levels run at detail zooms ``detail_zoom`` down to
    ``min_detail_zoom + 1`` inclusive (reference range(21, 5, -1) ->
    z21..z6); each level's blobs are keyed by the tile ``result_delta``
    zooms coarser (z16..z1).
    """

    detail_zoom: int = 21
    min_detail_zoom: int = 5
    result_delta: int = 5
    amplify_all: bool = False

    @property
    def n_levels(self) -> int:
        return self.detail_zoom - self.min_detail_zoom - 1

    def __post_init__(self):
        if self.min_detail_zoom + 1 > self.detail_zoom:
            raise ValueError(f"empty cascade: {self}")
        if self.detail_zoom - self.n_levels - self.result_delta < 0:
            raise ValueError(
                f"result tiles would go below zoom 0: {self} "
                f"(min detail zoom {self.min_detail_zoom + 1} needs "
                f"result_delta <= {self.min_detail_zoom + 1})"
            )


def composite_keys(codes, slots, detail_zoom: int, n_slots: int):
    """Pack (slot, morton_code) into one sortable, shiftable int64 key."""
    import jax

    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "the composite-key cascade needs int64 keys; enable x64 "
            "(jax.config.update('jax_enable_x64', True)) first"
        )
    code_bits = 2 * detail_zoom
    if code_bits + max(1, int(np.ceil(np.log2(max(n_slots, 2))))) >= 63:
        raise ValueError(
            f"composite keys overflow int64: zoom {detail_zoom} with {n_slots} slots"
        )
    codes = jnp.asarray(codes, jnp.int64)
    slots = jnp.asarray(slots, jnp.int64)
    return (slots << code_bits) | codes


def decode_level_keys(level_keys: np.ndarray, detail_zoom: int, level: int):
    """Host-side inverse at pyramid ``level``: -> (slot, morton_code)."""
    code_bits = 2 * (detail_zoom - level)
    k = np.asarray(level_keys, np.int64)
    return k >> code_bits, k & ((1 << code_bits) - 1)


def build_cascade(codes, slots, config: CascadeConfig, n_slots: int,
                  weights=None, valid=None, capacity=None):
    """Device-side cascade: per-level (composite key, sum) aggregates.

    Args:
      codes: detail-zoom Morton codes per emission.
      slots: (timespan*G + group) slot id per emission.
      weights/valid/capacity: as in ops.pyramid.pyramid_sparse_morton.

    Returns the list of per-level (keys, sums, n_unique) — level i at
    detail zoom ``config.detail_zoom - i``.
    """
    ck = composite_keys(codes, slots, config.detail_zoom, n_slots)
    return pyramid_ops.pyramid_sparse_morton(
        ck,
        weights=weights,
        valid=valid,
        levels=config.n_levels,
        capacity=capacity,
    )


def emit_blobs(level_data, config: CascadeConfig, slot_names):
    """Host-side egress: per-level aggregates -> reference-format blobs.

    ``level_data``: list of (keys, sums, n_unique) numpy-able arrays
    from :func:`build_cascade`. ``slot_names``: slot id ->
    (user_name, timespan_label).

    Returns {"user|timespan|coarseTileId": {detailTileId: float count}}
    exactly like the reference write path (reference heatmap.py:54-55,
    79-90,128-129 — including float counts, SURVEY.md §8.8).
    """
    blobs: dict[str, dict[str, float]] = {}
    sep = "|"  # reference KEY_SEPERATOR [sic], heatmap.py:18

    amplified = _amplified_all(level_data, config, slot_names) if config.amplify_all else None

    for level in range(config.n_levels + 1):
        keys_arr, sums, n = (np.asarray(x) for x in level_data[level])
        n = int(n)
        if n > keys_arr.shape[0]:
            raise ValueError(
                f"cascade level {level} overflowed capacity "
                f"({n} uniques > {keys_arr.shape[0]}); raise `capacity`"
            )
        keys_arr, sums = keys_arr[:n], sums[:n]
        zoom = config.detail_zoom - level
        slot_ids, codes = decode_level_keys(keys_arr, config.detail_zoom, level)
        rows, cols = morton_decode_np(codes)
        c_rows, c_cols = rows >> config.result_delta, cols >> config.result_delta
        coarse_zoom = zoom - config.result_delta

        values = sums.astype(np.float64)

        for i in range(len(keys_arr)):
            user, ts = slot_names[int(slot_ids[i])]
            value = float(values[i])
            if amplified is not None and user == "all":
                value = amplified.values[level].get((ts, int(codes[i])), value)
            blob_id = (
                f"{user}{sep}{ts}{sep}"
                f"{keys_mod.tile_id_string(coarse_zoom, c_rows[i], c_cols[i])}"
            )
            detail_id = keys_mod.tile_id_string(zoom, rows[i], cols[i])
            blobs.setdefault(blob_id, {})[detail_id] = value
    return blobs


class _amplified_all:
    """Reference-compat 'all' counts via the SURVEY.md §8.1 recurrence.

    A_0 = all_0 (correct);  A_L = 2 * rollup(A_{L-1}) + sum_users user_L.
    Per-user counts are untouched. Computed per (timespan, tile) on the
    host from the correct level aggregates.
    """

    def __init__(self, level_data, config: CascadeConfig, slot_names):
        self.values: list[dict] = []  # level -> {(ts, code): amplified}
        prev: dict = {}
        for level in range(config.n_levels + 1):
            keys_arr, sums, n = (np.asarray(x) for x in level_data[level])
            keys_arr, sums = keys_arr[: int(n)], sums[: int(n)]
            slot_ids, codes = decode_level_keys(keys_arr, config.detail_zoom, level)
            cur: dict = {}
            user_total: dict = {}
            all_correct: dict = {}
            for s, code, v in zip(slot_ids, codes, sums.astype(np.float64)):
                user, ts = slot_names[int(s)]
                key = (ts, int(code))
                if user == "all":
                    all_correct[key] = v
                else:
                    user_total[key] = user_total.get(key, 0.0) + v
            if level == 0:
                cur = dict(all_correct)
            else:
                rolled: dict = {}
                for (ts, code), v in prev.items():
                    pk = (ts, code >> 2)
                    rolled[pk] = rolled.get(pk, 0.0) + v
                for key in all_correct:
                    cur[key] = 2.0 * rolled.get(key, 0.0) + user_total.get(key, 0.0)
            self.values.append(cur)
            prev = cur
