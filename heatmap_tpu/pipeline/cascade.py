"""The zoom cascade: one sorted composite key, sixteen levels, zero shuffles.

The reference runs 16 Spark stages, each re-projecting every aggregate's
tile center and shuffling twice (reference heatmap.py:107-118;
SURVEY.md §3.3: 32 shuffles). Here the whole cascade is ONE device-side
sparse pyramid over composite integer keys:

    key = slot * 4^detail_zoom + morton_code,  slot = timespan*G + group

Because the slot multiplier is a power of four, ``key >> 2`` coarsens
the Morton part one zoom while leaving the (timespan, group) slot
intact, and preserves sort order — so every cascade level is a plain
segment-sum over the order established by a single sort
(ops/pyramid.pyramid_sparse_morton).

Blob regrouping (reference map_to_resultset + groupByKey,
heatmap.py:79-90,112) happens host-side at egress: the blob id is just
``key >> 2*result_delta``, no second shuffle.

The reference's '`all`'-amplification quirk (SURVEY.md §8.1:
``all_z = 2*all_{z+1} + sum_users user_{z+1}``) is reproduced on demand
by ``amplify_all=True`` as a host-side post-pass over the correct
per-level aggregates; per-user counts are identical in both modes, as
they are in the reference.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from heatmap_tpu.obs import events as obs_events
from heatmap_tpu.obs import tracing
from heatmap_tpu.ops import pyramid as pyramid_ops
from heatmap_tpu.tilemath.morton import morton_decode_np


@dataclasses.dataclass(frozen=True)
class CascadeConfig:
    """Static cascade parameters (reference constants, heatmap.py:16-17).

    Levels run at detail zooms ``detail_zoom`` down to
    ``min_detail_zoom + 1`` inclusive (reference range(21, 5, -1) ->
    z21..z6); each level's blobs are keyed by the tile ``result_delta``
    zooms coarser (z16..z1).
    """

    detail_zoom: int = 21
    min_detail_zoom: int = 5
    result_delta: int = 5
    amplify_all: bool = False

    @property
    def n_levels(self) -> int:
        return self.detail_zoom - self.min_detail_zoom - 1

    def __post_init__(self):
        if self.min_detail_zoom + 1 > self.detail_zoom:
            raise ValueError(f"empty cascade: {self}")
        if self.detail_zoom - self.n_levels - self.result_delta < 0:
            raise ValueError(
                f"result tiles would go below zoom 0: {self} "
                f"(min detail zoom {self.min_detail_zoom + 1} needs "
                f"result_delta <= {self.min_detail_zoom + 1})"
            )


def composite_keys(codes, slots, detail_zoom: int, n_slots: int):
    """Pack (slot, morton_code) into one sortable, shiftable int64 key."""
    import jax

    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "the composite-key cascade needs int64 keys; enable x64 "
            "(jax.config.update('jax_enable_x64', True)) first"
        )
    code_bits = 2 * detail_zoom
    if code_bits + max(1, int(np.ceil(np.log2(max(n_slots, 2))))) >= 63:
        raise ValueError(
            f"composite keys overflow int64: zoom {detail_zoom} with {n_slots} slots"
        )
    codes = jnp.asarray(codes, jnp.int64)
    slots = jnp.asarray(slots, jnp.int64)
    return (slots << code_bits) | codes


def decode_level_keys(level_keys: np.ndarray, detail_zoom: int, level: int):
    """Host-side inverse at pyramid ``level``: -> (slot, morton_code)."""
    code_bits = 2 * (detail_zoom - level)
    k = np.asarray(level_keys, np.int64)
    return k >> code_bits, k & ((1 << code_bits) - 1)


def build_cascade(codes, slots, config: CascadeConfig, n_slots: int,
                  weights=None, valid=None, capacity=None, acc_dtype=None,
                  adaptive: bool = False, backend: str = "scatter",
                  mesh=None, merge: str = "replicated",
                  weight_bound: int | None = None,
                  partition_splits=None, dispatch: str = "shard_map"):
    """Device-side cascade: per-level (composite key, sum) aggregates.

    Args:
      codes: detail-zoom Morton codes per emission.
      slots: (timespan*G + group) slot id per emission.
      weights/valid/capacity/acc_dtype/adaptive: as in
        ops.pyramid.pyramid_sparse_morton (weighted jobs pass f64
        weights + acc_dtype=f64 for exact-at-scale sums; the eager job
        paths pass adaptive=True only when
        BatchJobConfig.adaptive_capacity opts in — deep levels then
        shrink to the real unique counts at the cost of per-shape
        recompiles, see PERF_NOTES.md).

    ``backend``: "scatter" (aggregate_sorted_keys) or "partitioned"
    (multi-channel MXU segment reduction, ops/sparse_partitioned.py —
    measured 1.8x the scatter cascade on chip, 12/12 verify combos
    bit-exact; weighted jobs only under the bounded-integer
    ``weight_bound`` contract). The production default is routed by
    BatchJobConfig.resolved_cascade_backend.

    ``mesh``: a jax.sharding.Mesh to data-parallelize the detail-level
    reduction over (parallel.sharded.pyramid_sparse_morton_sharded):
    emissions are padded to the shard count and reduced per device,
    one all_gather merges the compact per-device aggregates, and the
    rollup runs replicated — composite keys shift exactly like plain
    Morton codes (the slot bits ride above the code bits), so the
    shift-preserves-sort property the single-device cascade relies on
    holds per level unchanged. Counts and integer-valued weighted sums
    are BIT-IDENTICAL to the single-device cascade (same sorted unique
    keys, exact integer addition in any order); fractional weighted
    sums agree up to f64 summation-order rounding — the same contract
    as the bounded path's cross-chunk merge (pipeline/batch.py
    run_job). Composes with BOTH backends — "partitioned" swaps the
    per-device detail reduction for the MXU segment kernel inside the
    shard_map body, same compact (keys, sums, count) contract, so the
    merge and rollup are untouched and blobs stay byte-equal.
    ``adaptive`` reads concrete counts and does not compose.

    ``merge`` selects the mesh path's cross-device merge:
    "replicated" (default — all_gather the compact partials, re-reduce
    and roll up on every device; O(global uniques) replicated) or
    "prefix" (coarse-prefix all_to_all regroup — each device merges
    and rolls up only its keyspace range, O(uniques/k) per stage;
    parallel.sharded.pyramid_sparse_morton_prefix_sharded). Same
    results either way (counts/integer weights bit-identical,
    fractional weighted to f64 summation order).

    ``partition_splits``: a TRACED ``(n_shards - 1,)`` int array of
    detail-zoom Morton split codes from a parallel.partition plan.
    Requires a mesh and emissions PRE-ROUTED host-side into per-shard
    contiguous range segments (partition.route_emissions); the mesh
    path then runs the range-sharded pyramid whose cross-chip exchange
    is boundary tiles only (parallel.sharded.
    pyramid_sparse_morton_range_sharded) instead of the full-pyramid
    replicated/prefix merge. Traced — every plan shares one compile.

    ``dispatch`` selects the mesh path's formulation: "shard_map" (the
    parallel/sharded.py kernels — host-routed range segments, the
    differential-testing oracle) or "gspmd" (parallel/gspmd.py —
    global-view NamedSharding programs; ``partition_splits`` then
    routes ON-DEVICE, so emissions arrive UNROUTED, and
    ``adaptive`` composes with the mesh). Byte-identical outputs
    either way (tests/test_gspmd.py).
    """
    if dispatch not in ("shard_map", "gspmd"):
        raise ValueError(
            f"unknown cascade dispatch {dispatch!r} "
            "(valid: shard_map, gspmd)")
    if merge not in ("replicated", "prefix"):
        raise ValueError(
            f"unknown mesh merge {merge!r} (valid: replicated, prefix)"
        )
    if partition_splits is not None and mesh is None:
        raise ValueError(
            "partition_splits is the mesh path's range plan; it needs "
            "a mesh — plan routing happens in pipeline/batch.py"
        )
    if mesh is not None and adaptive and dispatch != "gspmd":
        raise ValueError(
            "shard_map mesh cascade is shape-static; "
            "adaptive_capacity reads concrete per-level counts and "
            "does not compose — disable one of them, or use "
            "dispatch='gspmd' (its traced router and global-view "
            "rollup accept adaptive shrinking)"
        )
    if mesh is not None and dispatch == "gspmd" and merge == "prefix":
        raise ValueError(
            "the gspmd dispatch has no prefix-merge program yet; use "
            "dispatch='shard_map' for dp_merge='prefix'"
        )
    if backend == "partitioned":
        # These hold on the mesh path too: every shard runs the same
        # kernel on the same key layout, so the single-device
        # contracts gate the data-parallel route identically.
        slot_bits = max(1, int(np.ceil(np.log2(max(n_slots, 2)))))
        if 2 * config.detail_zoom + slot_bits > 60:
            raise ValueError(
                f"cascade backend 'partitioned' reconstructs keys from "
                f"three 20-bit channels (60-bit limit); zoom "
                f"{config.detail_zoom} with {n_slots} slots needs "
                f"{2 * config.detail_zoom + slot_bits} bits — use the "
                "scatter backend"
            )
        if weights is not None and weight_bound is None:
            raise ValueError(
                "cascade backend 'partitioned' takes weighted jobs "
                "only under the bounded-integer contract (weights "
                "integer in [0, weight_bound]; exactness slab = "
                "2^24 // bound — ops/sparse_partitioned.py): pass "
                "weight_bound, or use the scatter backend (required "
                "for fractional weights)"
            )
        if adaptive:
            raise ValueError(
                "cascade backend 'partitioned' reduces every level from "
                "the full stream; adaptive capacities do not apply"
            )
    elif backend != "scatter":
        raise ValueError(f"unknown cascade backend {backend!r}")
    ck = composite_keys(codes, slots, config.detail_zoom, n_slots)
    # Zoom-clamped per-level capacities: level l's key space is at most
    # n_slots * 4^(detail_zoom - l) — a STATIC bound that no data can
    # exceed — so coarse levels get small arrays instead of n-sized
    # padding. On the scatter backend (which feeds each level from the
    # previous level's capacity-sized aggregates) this shrinks the deep
    # half of the cascade's compute outright; on the partitioned
    # backend it shrinks the per-level output buffers. Unlike
    # adaptive_capacity this costs no extra compiles and no device
    # syncs (everything stays shape-static). Callers passing an
    # explicit per-level LIST keep full control.
    if capacity is None or isinstance(capacity, int):
        base = capacity or max(int(codes.shape[0]), 1)
        capacity = [
            min(base, n_slots << (2 * (config.detail_zoom - lvl)))
            for lvl in range(config.n_levels + 1)
        ]
    if mesh is not None:
        return _build_cascade_sharded(
            ck, config, mesh, weights=weights, valid=valid,
            capacity=capacity, acc_dtype=acc_dtype, merge=merge,
            backend=backend,
            weight_bound=weight_bound if weights is not None else None,
            partition_splits=partition_splits, n_slots=n_slots,
            dispatch=dispatch, adaptive=adaptive,
        )
    if backend == "partitioned":
        return pyramid_ops.pyramid_sparse_morton_partitioned(
            ck,
            valid=valid,
            levels=config.n_levels,
            capacity=capacity,
            weights=weights,
            weight_bound=weight_bound if weights is not None else None,
        )
    return pyramid_ops.pyramid_sparse_morton(
        ck,
        weights=weights,
        valid=valid,
        levels=config.n_levels,
        capacity=capacity,
        acc_dtype=acc_dtype,
        adaptive=adaptive,
    )


def _build_cascade_sharded(ck, config: CascadeConfig, mesh,
                           weights=None, valid=None, capacity=None,
                           acc_dtype=None, merge: str = "replicated",
                           backend: str = "scatter",
                           weight_bound: int | None = None,
                           partition_splits=None, n_slots: int = 1,
                           dispatch: str = "shard_map",
                           adaptive: bool = False):
    """Pad composite keys to the mesh shard count and run the sharded
    pyramid (see build_cascade's ``mesh`` doc). Pad lanes carry
    valid=False, the masking path every kernel already drops.

    ``dispatch="gspmd"`` swaps each shard_map kernel for its
    global-view NamedSharding twin (parallel/gspmd.py): same padding,
    byte-identical outputs; with ``partition_splits`` the emissions
    arrive UNROUTED and are routed on-device, so no segment-divisibility
    requirement applies there.
    """
    # Lazy import: parallel.sharded pulls in the pallas histogram stack,
    # which cascade-only consumers (spark_adapter, tools) never need.
    from heatmap_tpu.parallel import sharded as sharded_kernels

    _, ndev = sharded_kernels._shard_axes(mesh)
    n = int(ck.shape[0])
    if n == 0:
        # Zero-row shards would size the per-device stage at zero
        # capacity; the replicated pyramid handles empty inputs already
        # and there is nothing to parallelize.
        return pyramid_ops.pyramid_sparse_morton(
            ck, weights=weights, valid=valid, levels=config.n_levels,
            capacity=capacity, acc_dtype=acc_dtype,
        )
    if partition_splits is not None:
        if dispatch == "gspmd":
            from heatmap_tpu.parallel import gspmd as gspmd_kernels

            # UNROUTED emissions + traced splits: routing happens
            # inside the program (route_on_device), replacing the host
            # scatter of partition.route_emissions.
            return gspmd_kernels.pyramid_gspmd_range(
                ck, mesh, partition_splits,
                code_bits=2 * config.detail_zoom, slot_bound=n_slots,
                weights=weights, valid=valid, levels=config.n_levels,
                capacity=capacity, acc_dtype=acc_dtype, backend=backend,
                weight_bound=weight_bound, adaptive=adaptive,
            )
        # Emissions arrive pre-routed into per-shard contiguous range
        # segments of equal length (partition.route_emissions) — no
        # tail pad here, a pad would shift lanes across segment
        # boundaries and break the range invariant.
        return sharded_kernels.pyramid_sparse_morton_range_sharded(
            ck, mesh, partition_splits,
            code_bits=2 * config.detail_zoom, slot_bound=n_slots,
            weights=weights, valid=valid, levels=config.n_levels,
            capacity=capacity, acc_dtype=acc_dtype, backend=backend,
            weight_bound=weight_bound,
        )
    pad = (-n) % ndev
    v = (jnp.ones((n,), bool) if valid is None
         else jnp.asarray(valid, bool))
    if pad:
        ck = jnp.concatenate([ck, jnp.zeros((pad,), ck.dtype)])
        v = jnp.concatenate([v, jnp.zeros((pad,), bool)])
        if weights is not None:
            weights = jnp.asarray(weights)
            weights = jnp.concatenate(
                [weights, jnp.zeros((pad,), weights.dtype)]
            )
    if dispatch == "gspmd":
        from heatmap_tpu.parallel import gspmd as gspmd_kernels

        return gspmd_kernels.pyramid_gspmd_uniform(
            ck, mesh, weights=weights, valid=v, levels=config.n_levels,
            capacity=capacity, acc_dtype=acc_dtype, backend=backend,
            weight_bound=weight_bound, adaptive=adaptive,
        )
    kernel = (sharded_kernels.pyramid_sparse_morton_prefix_sharded
              if merge == "prefix"
              else sharded_kernels.pyramid_sparse_morton_sharded)
    return kernel(
        ck, mesh, weights=weights, valid=v, levels=config.n_levels,
        capacity=capacity, acc_dtype=acc_dtype, backend=backend,
        weight_bound=weight_bound,
    )


#: build_cascade under one jit: a single dispatch instead of ~130
#: eager op dispatches (each paying relay latency on the axon backend)
#: and cross-level XLA fusion of the shift/compare/cumsum chains —
#: measured 1.67x on the CPU cascade stage (PERF_NOTES.md). Static
#: args recompile per (config, n_slots, capacity, acc_dtype), i.e.
#: once per job shape.
_build_cascade_jit = functools.partial(
    jax.jit,
    static_argnames=("config", "n_slots", "capacity", "acc_dtype",
                     "backend", "mesh", "merge", "weight_bound",
                     "dispatch"),
)(build_cascade)

#: Lazily-built donating twin of _build_cascade_jit for the gspmd
#: dispatch: the routed-emission buffers (codes/slots/weights/valid)
#: are donated to the program, letting XLA reuse their device memory
#: for the pyramid accumulators in-place on TPU/GPU. Built on first
#: use because donation support depends on the initialized backend
#: (parallel/gspmd.py donating_jit drops donation on CPU but keeps the
#: ledger guard, so re-feeding a consumed buffer is a typed error on
#: every platform).
_donating_cascade_jit = None


def _get_donating_cascade_jit():
    global _donating_cascade_jit
    if _donating_cascade_jit is None:
        from heatmap_tpu.parallel import gspmd as gspmd_kernels

        _donating_cascade_jit = gspmd_kernels.donating_jit(
            build_cascade,
            donate_argnums=(0, 1),  # codes, slots
            donate_argnames=("weights", "valid"),
            static_argnames=("config", "n_slots", "capacity",
                             "acc_dtype", "backend", "mesh", "merge",
                             "weight_bound", "dispatch"),
        )
    return _donating_cascade_jit


def run_cascade(codes, slots, config: CascadeConfig, n_slots: int,
                weights=None, valid=None, capacity=None, acc_dtype=None,
                adaptive: bool = False, jit: bool = True,
                backend: str = "scatter", mesh=None,
                merge: str = "replicated",
                weight_bound: int | None = None,
                partition_splits=None, dispatch: str = "shard_map"):
    """The production cascade entry: jitted whole, unless ``adaptive``
    (which must read concrete per-level unique counts and therefore
    runs eagerly — see ops.pyramid.pyramid_sparse_morton) or
    ``jit=False`` (callers whose input shapes vary call to call — e.g.
    the bounded chunked path — would recompile the whole graph per
    call and should stay eager). ``mesh`` (hashable, a valid static
    arg) routes the detail reduction through the data-parallel sharded
    pyramid — see build_cascade."""
    # Tree-only span around the dispatch: the cascade_dispatch event is
    # emitted inside it, so the audit record carries this span's
    # trace_id/span_id (events.py stamps _TRACE_STAMPED types).
    tsp = tracing.begin_span("cascade.dispatch", {"backend": backend})
    try:
        if obs_events._current is not None:
            # Audit every dispatch: what the cascade actually executed
            # (shape info is static even on tracers, so this is safe in
            # eager AND pre-jit contexts). backend_resolved in batch.py
            # records the routing *decision*; this records each execution.
            extra = {"dispatch": dispatch} if mesh is not None else {}
            obs_events.emit(
                "cascade_dispatch", backend=backend,
                jit=bool(jit and not adaptive), mesh=mesh is not None,
                merge=merge, n_emissions=int(codes.shape[0]),
                n_slots=int(n_slots),
                partition=partition_splits is not None, **extra)
        if adaptive or not jit:
            return build_cascade(
                codes, slots, config, n_slots, weights=weights, valid=valid,
                capacity=capacity, acc_dtype=acc_dtype, adaptive=adaptive,
                backend=backend, mesh=mesh, merge=merge,
                weight_bound=weight_bound,
                partition_splits=partition_splits, dispatch=dispatch,
            )
        if isinstance(capacity, list):
            capacity = tuple(capacity)  # static args must be hashable
        # Donation engages only when the emission buffers are already
        # device-resident jax Arrays (the feeder's put, or an upstream
        # jnp producer): donating host numpy inputs would be a silent
        # no-op on TPU plus a "donated buffer not usable" warning.
        jit_entry = _build_cascade_jit
        if (dispatch == "gspmd" and mesh is not None
                and isinstance(codes, jax.Array)):
            jit_entry = _get_donating_cascade_jit()
        return jit_entry(
            codes, slots, config=config, n_slots=n_slots, weights=weights,
            valid=valid, capacity=capacity, acc_dtype=acc_dtype,
            backend=backend, mesh=mesh, merge=merge,
            weight_bound=weight_bound,
            partition_splits=partition_splits, dispatch=dispatch,
        )
    finally:
        tracing.end_span(tsp)


def _on_accelerator(x) -> bool:
    """True when ``x`` is a jax array living on a non-CPU device."""
    try:
        return any(d.platform != "cpu" for d in x.devices())
    except AttributeError:
        return False  # plain numpy


def decode_levels(level_data, config: CascadeConfig):
    """One decode pass shared by all egress consumers.

    Returns per-level dicts of numpy arrays:
    {slot, code, row, col, zoom, value} — values float64 (reference
    emits float counts, SURVEY.md §8.8). Raises on capacity overflow.
    """
    # Device->host in one batched device_get: on accelerators the
    # arrays are first truncated to their real row counts ON DEVICE
    # (they are padded to full capacity — 16 levels x capacity x 16B
    # of mostly-pad otherwise crosses the link), and the single
    # device_get moves every level in one round trip instead of 32+
    # serial np.asarray transfers (the relay adds per-call latency).
    # On CPU the transfer is free and a device slice would only add a
    # copy, so slice host-side there.
    n_lvls = config.n_levels + 1
    if any(_on_accelerator(level_data[lvl][2]) for lvl in range(n_lvls)):
        import jax

        # Batch the count scalars too: int() per level would block on
        # one relay round trip each before the main transfer.
        counts = [int(c) for c in jax.device_get(
            [level_data[lvl][2] for lvl in range(n_lvls)]
        )]
    else:
        counts = [int(level_data[lvl][2]) for lvl in range(n_lvls)]
    for level, n in enumerate(counts):
        if n > level_data[level][0].shape[0]:
            raise ValueError(
                f"cascade level {level} overflowed capacity "
                f"({n} uniques > {level_data[level][0].shape[0]}); "
                f"raise `capacity`"
            )
    if any(_on_accelerator(level_data[lvl][0])
           for lvl in range(config.n_levels + 1)):
        import jax

        host = jax.device_get(
            [(level_data[lvl][0][:n], level_data[lvl][1][:n])
             for lvl, n in enumerate(counts)]
        )
    else:
        host = [
            (np.asarray(level_data[lvl][0])[:n],
             np.asarray(level_data[lvl][1])[:n])
            for lvl, n in enumerate(counts)
        ]

    out = []
    for level, (keys_arr, sums) in enumerate(host):
        # Lazy import (native asserts against pipeline.timespan at
        # load; module-level would be circular). One threaded C pass
        # replaces the ~8 single-threaded numpy passes when available.
        from heatmap_tpu import native as _native

        code_bits = 2 * (config.detail_zoom - level)
        # The native decoder returns int32 slots. Slot ids are bounded
        # by key >> code_bits; with code_bits >= 33 they fit int32 by
        # construction, below that check the actual max (one cheap
        # pass) and fall back to the int64 numpy path if they don't.
        native_ok = _native.decode_keys is not None and (
            code_bits >= 33
            or keys_arr.size == 0
            or int(keys_arr.max()) >> code_bits < 2**31
        )
        if native_ok:
            slot_ids, codes, rows, cols = _native.decode_keys(
                keys_arr, code_bits
            )
        else:
            slot_ids, codes = decode_level_keys(
                keys_arr, config.detail_zoom, level
            )
            rows, cols = morton_decode_np(codes)
        out.append(
            {
                "zoom": config.detail_zoom - level,
                "slot": slot_ids,
                "code": codes,
                "row": rows,
                "col": cols,
                "value": sums.astype(np.float64),
            }
        )
    return out


def emit_level_arrays(level_data, config: CascadeConfig, slot_names):
    """Columnar egress (the production path): per-level numpy arrays.

    Adds coarse (blob) tile coordinates and resolves slot names to
    (user, timespan) index arrays; sinks can write these columns
    directly (files/Arrow/Cassandra batches) without any per-element
    Python. Applies the amplify_all compat patch when configured.
    """
    return finalize_level_arrays(
        decode_levels(level_data, config), config, slot_names
    )


def finalize_level_arrays(levels, config: CascadeConfig, slot_names):
    """Second half of :func:`emit_level_arrays`, for callers that build
    decoded levels themselves (e.g. the bounded-memory chunk merge in
    pipeline.batch): resolve slot names, add coarse tile coordinates,
    apply the amplify_all compat patch.

    User/timespan columns are DICTIONARY-ENCODED: per-row int32
    ``user_idx``/``timespan_idx`` into the small ``user_names``/
    ``timespan_names`` tables. Materializing per-row unicode columns
    (the previous contract) cost more host wall-clock than the entire
    device cascade at 25M aggregates — and every consumer either wants
    columns (sinks: dictionary encoding is smaller and faster) or only
    touches blob-run starts (JSON egress). Use :func:`level_strings`
    where full string columns are genuinely needed.
    """
    if config.amplify_all:
        _patch_amplified(levels, slot_names)
    n_slots = max(slot_names) + 1
    users = np.array([slot_names.get(s, ("?", "?"))[0] for s in range(n_slots)])
    tss = np.array([slot_names.get(s, ("?", "?"))[1] for s in range(n_slots)])
    # Unique name tables + per-slot index maps (tiny: O(n_slots)).
    user_names, slot_to_uidx = np.unique(users, return_inverse=True)
    ts_names, slot_to_tidx = np.unique(tss, return_inverse=True)
    slot_to_uidx = slot_to_uidx.astype(np.int32)
    slot_to_tidx = slot_to_tidx.astype(np.int32)
    for lvl in levels:
        lvl["user_idx"] = slot_to_uidx[lvl["slot"]]
        lvl["timespan_idx"] = slot_to_tidx[lvl["slot"]]
        lvl["user_names"] = user_names
        lvl["timespan_names"] = ts_names
        lvl["coarse_zoom"] = lvl["zoom"] - config.result_delta
        lvl["coarse_row"] = lvl["row"] >> config.result_delta
        lvl["coarse_col"] = lvl["col"] >> config.result_delta
    return levels


def level_strings(lvl, sel=None):
    """(user, timespan) string arrays for a finalized level — full
    columns, or only rows ``sel`` (any numpy index)."""
    ui, ti = lvl["user_idx"], lvl["timespan_idx"]
    if sel is not None:
        ui, ti = ui[sel], ti[sel]
    return lvl["user_names"][ui], lvl["timespan_names"][ti]


def emit_blobs(level_data, config: CascadeConfig, slot_names):
    """Reference-format blob egress.

    Returns {"user|timespan|coarseTileId": {detailTileId: float count}}
    exactly like the reference write path (reference heatmap.py:54-55,
    79-90,128-129). String/dict building is vectorized with np.char;
    the per-blob dict assembly is inherently Python-object bound — use
    :func:`emit_level_arrays` for bulk sinks.
    """
    return blobs_from_level_arrays(
        emit_level_arrays(level_data, config, slot_names)
    )


def _level_blob_columns(lvl):
    """(blob_ids, detail_ids, values) string/float columns for a level."""
    sep = "|"  # reference KEY_SEPERATOR [sic], heatmap.py:18
    users, tss = level_strings(lvl)
    blob_ids = np.char.add(
        np.char.add(users, sep + tss + sep),
        _tile_id_strings(lvl["coarse_zoom"], lvl["coarse_row"], lvl["coarse_col"]),
    )
    detail_ids = _tile_id_strings(lvl["zoom"], lvl["row"], lvl["col"])
    return blob_ids, detail_ids, lvl["value"]


def blobs_from_level_arrays(levels):
    """Reference-format blobs from finalized level arrays
    (:func:`finalize_level_arrays` output)."""
    blobs: dict[str, dict[str, float]] = {}
    for lvl in levels:
        if len(lvl["slot"]) == 0:
            continue
        blob_ids, detail_ids, values = _level_blob_columns(lvl)
        # Group by blob id: sort once, slice runs.
        order = np.argsort(blob_ids, kind="stable")
        sorted_ids = blob_ids[order]
        starts = np.flatnonzero(
            np.concatenate([[True], sorted_ids[1:] != sorted_ids[:-1]])
        )
        bounds = np.append(starts, len(sorted_ids))
        for k, s in enumerate(starts):
            e = bounds[k + 1]
            idx = order[s:e]
            blobs.setdefault(str(sorted_ids[s]), {}).update(
                zip(detail_ids[idx].tolist(), values[idx].tolist())
            )
    return blobs


def json_blobs_from_level_arrays(levels):
    """{blob_id: json_string} egress without per-aggregate Python.

    Produces a dict EQUAL to ``{k: json.dumps(v) for k, v in
    blobs_from_level_arrays(levels).items()}`` — same keys, and each
    value byte-identical (numpy's shortest-roundtrip repr matches
    json.dumps for doubles; within-blob entry order is preserved).
    Key INSERTION order differs: composite-key order here vs
    string-sorted there, so sequential sink output is not diffable
    byte-for-byte against the old path. Per level, the JSON fragments are
    assembled with vectorized string ops, concatenated into ONE Python
    string with NUL markers at blob starts, and split back into
    per-blob documents — the only O(blobs) Python work left is the
    final dict construction. Measured ~1.5x the dict+json.dumps path
    at 3.5M blobs / ~60M aggregates (the remaining floor is numpy's
    per-aggregate int/float-to-string formatting, ~8 passes over every
    aggregate). Jobs at that scale should prefer the columnar
    LevelArraysSink, which skips string egress entirely.

    Blob ids never collide across levels (the coarse zoom is part of
    the id), so per-level construction is complete — the dict-merge in
    blobs_from_level_arrays exists only for generic robustness.
    """
    sep = "|"  # reference KEY_SEPERATOR [sic], heatmap.py:18
    out: dict[str, str] = {}
    for lvl in levels:
        if len(lvl["slot"]) == 0:
            continue
        # Level arrays arrive sorted by (slot, code), so blob runs —
        # same slot, same coarse tile — are already CONTIGUOUS: no
        # string sort needed, and blob-id strings (the widest in play)
        # are built only at run starts, #blobs not #aggregates.
        slots = lvl["slot"]
        is_start = np.concatenate([[True], (
            (slots[1:] != slots[:-1])
            | (lvl["coarse_row"][1:] != lvl["coarse_row"][:-1])
            | (lvl["coarse_col"][1:] != lvl["coarse_col"][:-1])
        )])
        sidx = np.flatnonzero(is_start)
        from heatmap_tpu import native as _native

        if _native.format_blob_ids is not None:
            ids = _native.format_blob_ids(
                lvl["user_idx"][sidx], lvl["timespan_idx"][sidx],
                lvl["coarse_row"][sidx], lvl["coarse_col"][sidx],
                int(lvl["coarse_zoom"]),
                lvl["user_names"], lvl["timespan_names"],
            )
        else:
            users, tss = level_strings(lvl, sidx)
            ids = np.char.add(
                np.char.add(users, sep + tss + sep),
                _tile_id_strings(lvl["coarse_zoom"],
                                 lvl["coarse_row"][sidx],
                                 lvl["coarse_col"][sidx]),
            ).tolist()
        out.update(zip(ids, _blob_bodies(lvl, is_start)))
    return out


def _blob_bodies(lvl, is_start):
    """Per-blob '{...}' JSON documents for one level, in order.

    The multithreaded native formatter handles the common case —
    integral values, i.e. every count job and any weighted job whose
    sums happen to be whole numbers — at C speed; the numpy join/split
    path formats fractional weighted sums and doubles as the formatting
    oracle (tested equal byte-for-byte on integral inputs).
    """
    values = lvl["value"]
    # Lazy import: native asserts against pipeline.timespan at load, so
    # a module-level import here would be circular.
    from heatmap_tpu import native as _native

    if _native.format_blob_bodies is not None and bool(
        np.all((values == np.floor(values)) & (np.abs(values) < 1e15))
    ):
        return _native.format_blob_bodies(
            lvl["row"], lvl["col"], values, is_start, int(lvl["zoom"])
        )
    # '"<detail>": <value>' fragments, json.dumps separators.
    frag = np.char.add(
        np.char.add(
            np.char.add(
                '"', _tile_id_strings(lvl["zoom"], lvl["row"], lvl["col"])
            ),
            '": ',
        ),
        values.astype(str),
    )
    # Run-start fragments open a new document ('}\x00{' closes the
    # previous one); the rest continue with ', '. One join, one split,
    # zero per-blob concatenation.
    parts = np.char.add(np.where(is_start, "}\x00{", ", "), frag)
    big = "".join(parts.tolist()) + "}"
    return big.split("\x00")[1:]  # [0] is the artifact '}' head


def _tile_id_strings(zoom, rows, cols):
    """Vectorized reference tile-id strings "zoom_row_col"."""
    z = np.char.add(np.asarray(zoom).astype(str), "_")
    return np.char.add(
        np.char.add(np.char.add(z, rows.astype(str)), "_"), cols.astype(str)
    )


def _sorted_lookup(sorted_keys, sorted_vals, queries):
    """Value per query from a sorted (keys, vals) table; 0.0 on miss."""
    if len(sorted_keys) == 0 or len(queries) == 0:
        return np.zeros(len(queries), np.float64)
    pos = np.clip(np.searchsorted(sorted_keys, queries), 0,
                  len(sorted_keys) - 1)
    return np.where(sorted_keys[pos] == queries, sorted_vals[pos], 0.0)


def _patch_amplified(levels, slot_names):
    """In-place 'all' amplification (SURVEY.md §8.1 recurrence):

    A_0 = all_0 (correct);  A_L = 2 * rollup(A_{L-1}) + sum_users user_L.
    Per-user counts untouched, exactly as in the reference. Fully
    vectorized: every step works on packed ``(slot << code_bits) |
    code`` int64 keys (sorted, since level arrays arrive in ascending
    composite-key order) via unique/bincount folds and searchsorted
    lookups — no per-aggregate Python.
    """
    is_all_slot = np.array(
        [slot_names.get(s, ("?",))[0] == "all" for s in range(max(slot_names) + 1)]
    )
    prev_s = prev_c = np.empty(0, np.int64)
    prev_v = np.empty(0, np.float64)
    for level, lvl in enumerate(levels):
        slots = np.asarray(lvl["slot"], np.int64)
        codes = np.asarray(lvl["code"], np.int64)
        vals = np.asarray(lvl["value"], np.float64)
        cb = 2 * lvl["zoom"]  # codes at this level are < 4**zoom
        all_mask = (
            is_all_slot[slots] if len(slots) else np.zeros(0, bool)
        )
        a_s, a_c = slots[all_mask], codes[all_mask]
        if level == 0:
            new_all = vals[all_mask]
        else:
            # rollup(A_{L-1}): parent key folds the 4 children.
            rk = (prev_s << cb) | (prev_c >> 2)
            ruk, rinv = np.unique(rk, return_inverse=True)
            rv = (
                np.bincount(rinv, weights=prev_v)
                if len(rk) else np.empty(0, np.float64)
            )
            # sum over non-all slots, keyed by the all-slot of their
            # timespan (slot = ts*G + g with g=0 the all group).
            um = ~all_mask
            if um.any():
                utk = (_all_slot_of(slots[um], is_all_slot) << cb) | codes[um]
                uuk, uinv = np.unique(utk, return_inverse=True)
                uv = np.bincount(uinv, weights=vals[um])
            else:
                uuk = np.empty(0, np.int64)
                uv = np.empty(0, np.float64)
            ak = (a_s << cb) | a_c
            new_all = (
                2.0 * _sorted_lookup(ruk, rv, ak)
                + _sorted_lookup(uuk, uv, ak)
            )
        if len(slots):
            patched = vals.copy()
            patched[all_mask] = new_all
            lvl["value"] = patched
        prev_s, prev_c, prev_v = a_s, a_c, new_all


def _all_slot_of(slots, is_all_slot):
    """Map each slot to the 'all' slot of its timespan block.

    Slots are ts*G + g with g=0 the 'all' group, so the all-slot is the
    largest all-slot <= slot: computed via searchsorted over the sorted
    all-slot ids.
    """
    all_ids = np.flatnonzero(is_all_slot)
    pos = np.searchsorted(all_ids, slots, side="right") - 1
    return all_ids[pos]
