"""Bucketed-padding compile cache for the cascade.

The delta engine's apply floor is jit re-compilation: the cascade is
jitted with static ``(config, n_slots, capacity, ...)`` args and traced
per input SHAPE, so every distinct emission count — i.e. every distinct
micro-batch size — compiles a fresh executable (ROADMAP.md;
BENCH_delta.json shows incremental apply only ~1.3-1.75x over full
recompute because compile time dominates small batches).

This module rounds the padded shapes UP to a small set of buckets so
arbitrary batch sizes reuse one compilation per bucket:

- emission arrays are padded to the bucket length with ``valid=False``
  pad lanes — the masking path every cascade kernel already drops
  (the exact mechanism ``_build_cascade_sharded`` uses to pad to the
  shard count);
- ``n_slots`` is rounded up to a power of two — it only feeds overflow
  checks and the zoom-clamped capacity bound (``n_slots << 2*(dz-l)``),
  never slot *names* (those come from the vocabs), so a larger value is
  byte-neutral and stops per-batch vocab growth from forcing compiles;
- the derived default capacity keys off the PADDED length, so the
  per-level capacity tuple (a static jit arg) is a pure function of the
  bucket, not the batch.

Byte equality with exact padding holds because ``decode_levels``
truncates every level to its real unique count before any host egress:
pad lanes are masked out on device and never reach a blob.

Cost model (docs/ingest.md): pow2 buckets waste < 2x emissions worst
case (amortized ~1.33x) for a compile count bounded by
``log2(max_batch)``; the 1.25x-geometric ladder tightens waste to
< 1.25x at ~3.1x the bucket count. Both collapse a continuous-ingest
loop's compile count from O(distinct batch sizes) to O(log max size).

The module also mirrors the jit cache's hit/miss behaviour: every
jitted cascade dispatch from ``_run_grouped`` registers its would-be
compilation signature here, so ``cascade_bucket_hits_total`` /
``cascade_bucket_misses_total`` (and :func:`cache_stats`) count cache
hits and compiles without touching jax internals — misses == fresh XLA
compiles as long as the process-wide jit cache is not evicting (it
holds thousands of entries; tests assert on exactly this mirror).
"""

from __future__ import annotations

import math
import threading

import numpy as np

from heatmap_tpu.obs import get_registry

#: Valid BatchJobConfig.pad_bucketing values. "exact" = no bucketing
#: (the historical behaviour: shapes follow the input exactly).
BUCKETING_MODES = ("exact", "pow2", "geometric")

#: Growth factor of the "geometric" ladder (ROADMAP names 1.25x).
GEOMETRIC_RATIO = 1.25

#: Floor for every bucket: batches below this pad up to it, so the
#: whole small-batch tail shares ONE compilation. 4096 emissions is
#: ~1ms of cascade work on CPU — far below compile cost either way.
DEFAULT_MIN_BUCKET = 1 << 12

_registry = get_registry()

CASCADE_BUCKET_HITS = _registry.counter(
    "cascade_bucket_hits_total",
    "Jitted cascade dispatches that reused a compiled bucket",
    labelnames=("mode",))
CASCADE_BUCKET_MISSES = _registry.counter(
    "cascade_bucket_misses_total",
    "Jitted cascade dispatches that compiled a new bucket signature",
    labelnames=("mode",))
CASCADE_PAD_EMISSIONS = _registry.counter(
    "cascade_pad_emissions_total",
    "Masked pad lanes added by bucketed padding (waste accounting)")

# Signature mirror of the process-wide jit cache (jax caches per
# (shapes, static args) — so do we). Guarded: run_job may be driven
# from producer/consumer threads.
_lock = threading.Lock()
_seen: set = set()
_stats = {"hits": 0, "misses": 0}


def bucket_size(n: int, mode: str,
                min_bucket: int = DEFAULT_MIN_BUCKET) -> int:
    """Bucket length for ``n`` emissions under ``mode``.

    exact -> n unchanged; pow2 -> next power of two >= max(n,
    min_bucket); geometric -> the smallest rung of the
    ``min_bucket * 1.25^k`` ladder >= n. n == 0 stays 0 (an empty
    batch compiles its own trivial shape either way).
    """
    if mode not in BUCKETING_MODES:
        raise ValueError(
            f"unknown pad_bucketing {mode!r} (valid: "
            f"{', '.join(BUCKETING_MODES)})")
    if mode == "exact" or n <= 0:
        return max(int(n), 0)
    n = int(n)
    if n <= min_bucket:
        return int(min_bucket)
    if mode == "pow2":
        return 1 << (n - 1).bit_length()
    # geometric: ceil rung of min_bucket * ratio^k. Computed by log,
    # then corrected for float edge cases so the rung always covers n
    # and the rung index is minimal.
    k = math.ceil(math.log(n / min_bucket) / math.log(GEOMETRIC_RATIO))
    size = int(math.ceil(min_bucket * GEOMETRIC_RATIO ** k))
    while size < n:  # float log undershoot
        k += 1
        size = int(math.ceil(min_bucket * GEOMETRIC_RATIO ** k))
    while k > 0:
        prev = int(math.ceil(min_bucket * GEOMETRIC_RATIO ** (k - 1)))
        if prev < n:
            break
        k, size = k - 1, prev
    return size


def bucket_slots(n_slots: int) -> int:
    """Round the slot count up to a power of two (>= 2).

    ``n_slots`` reaches the cascade only as a static overflow bound and
    the zoom-clamped capacity multiplier — never as data — so a larger
    value cannot change any emitted byte, but a per-batch exact value
    (every new user grows the vocab) would force a recompile per tick.
    """
    n = max(int(n_slots), 2)
    return 1 << (n - 1).bit_length()


def pad_emissions(e_codes, e_slots, e_valid, e_weights, target: int):
    """Pad emission arrays to ``target`` lanes with ``valid=False``.

    Works on numpy and device (jnp) arrays alike — the x64 ingest path
    keeps codes device-resident, and a host round-trip here would undo
    that win. Pad codes/slots are zeros (any in-range value works: the
    valid mask drops them in every kernel), pad weights 0.0.
    """
    n = int(e_codes.shape[0])
    pad = target - n
    if pad <= 0:
        return e_codes, e_slots, e_valid, e_weights
    if isinstance(e_codes, np.ndarray):
        xp = np
    else:
        import jax.numpy as xp
    e_codes = xp.concatenate([e_codes, xp.zeros((pad,), e_codes.dtype)])
    e_slots = xp.concatenate([e_slots, xp.zeros((pad,), e_slots.dtype)])
    if e_valid is None:
        e_valid = xp.arange(target) < n
    else:
        e_valid = xp.concatenate(
            [xp.asarray(e_valid, bool), xp.zeros((pad,), bool)])
    if e_weights is not None:
        e_weights = xp.concatenate(
            [e_weights, xp.zeros((pad,), e_weights.dtype)])
    if _registry.enabled:
        CASCADE_PAD_EMISSIONS.inc(pad)
    return e_codes, e_slots, e_valid, e_weights


def note_dispatch(signature: tuple, mode: str) -> bool:
    """Record one jitted cascade dispatch; True if its compilation
    signature was already seen (a compile-cache hit).

    ``signature`` must contain everything jax keys the compiled
    executable on: input shapes/dtypes plus every static arg
    (pipeline.batch builds it next to the run_cascade call so the two
    cannot drift silently).
    """
    with _lock:
        hit = signature in _seen
        if hit:
            _stats["hits"] += 1
        else:
            _seen.add(signature)
            _stats["misses"] += 1
    if _registry.enabled:
        (CASCADE_BUCKET_HITS if hit else CASCADE_BUCKET_MISSES).inc(
            mode=mode)
    return hit


def cache_stats() -> dict:
    """{"hits": n, "misses": n, "signatures": n} — misses mirror fresh
    XLA compiles of the jitted cascade (see module docstring)."""
    with _lock:
        return {**_stats, "signatures": len(_seen)}


def reset_cache_stats():
    """Forget seen signatures + counters (tests and benches only; the
    real jit cache is NOT cleared — after a reset the first dispatch of
    an already-compiled signature counts as a miss again)."""
    with _lock:
        _seen.clear()
        _stats["hits"] = 0
        _stats["misses"] = 0
