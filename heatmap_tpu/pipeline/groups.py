"""User-group routing: the reference's per-user heatmap rules.

Reference heatmap.py:64-70 semantics, reproduced exactly:

- every point counts toward the ``'all'`` group;
- user ids starting with ``'x'`` are excluded from per-user heatmaps
  (they still count in ``'all'``);
- user ids starting with ``'rt-'`` are pooled under ``"route"``;
- everyone else gets their own per-user group.

Strings stay on the host; devices see dense int32 group ids
(``ALL_GROUP == 0``; excluded points get ``EXCLUDED``).
"""

from __future__ import annotations

import numpy as np

ALL_GROUP = 0
EXCLUDED = -1

ALL_NAME = "all"
ROUTE_NAME = "route"


def route_user(user_id: str):
    """Routed per-user group name, or None if excluded (x-prefix).

    Mirrors reference heatmap.py:65-70 (prefix tests via slicing, so a
    bare ``"x"`` or ``"rt-"`` id behaves identically to the reference).
    """
    if user_id[:1] == "x":
        return None
    if user_id[:3] == "rt-":
        return ROUTE_NAME
    return user_id


class UserVocab:
    """Host-side bidirectional map: routed group name <-> dense int id.

    Group 0 is always ``'all'``. Built incrementally so streaming
    micro-batches can extend it.
    """

    def __init__(self):
        self._names = [ALL_NAME]
        self._ids = {ALL_NAME: ALL_GROUP}

    def __len__(self):
        return len(self._names)

    @property
    def names(self):
        return tuple(self._names)

    def id_for(self, group_name: str) -> int:
        gid = self._ids.get(group_name)
        if gid is None:
            gid = len(self._names)
            self._names.append(group_name)
            self._ids[group_name] = gid
        return gid

    def name_for(self, gid: int) -> str:
        return self._names[gid]

    def group_ids(self, user_ids) -> np.ndarray:
        """Vectorize: per-point routed group id (EXCLUDED for x-users)."""
        out = np.empty(len(user_ids), np.int32)
        for i, uid in enumerate(user_ids):
            name = route_user(uid)
            out[i] = EXCLUDED if name is None else self.id_for(name)
        return out
