"""User-group routing: the reference's per-user heatmap rules.

Reference heatmap.py:64-70 semantics, reproduced exactly:

- every point counts toward the ``'all'`` group;
- user ids starting with ``'x'`` are excluded from per-user heatmaps
  (they still count in ``'all'``);
- user ids starting with ``'rt-'`` are pooled under ``"route"``;
- everyone else gets their own per-user group.

Strings stay on the host; devices see dense int32 group ids
(``ALL_GROUP == 0``; excluded points get ``EXCLUDED``).
"""

from __future__ import annotations

import numpy as np

ALL_GROUP = 0
EXCLUDED = -1

ALL_NAME = "all"
ROUTE_NAME = "route"


def route_user(user_id: str):
    """Routed per-user group name, or None if excluded (x-prefix).

    Mirrors reference heatmap.py:65-70 (prefix tests via slicing, so a
    bare ``"x"`` or ``"rt-"`` id behaves identically to the reference).
    """
    if user_id[:1] == "x":
        return None
    if user_id[:3] == "rt-":
        return ROUTE_NAME
    return user_id


class UserVocab:
    """Host-side bidirectional map: routed group name <-> dense int id.

    Group 0 is always ``'all'``. Built incrementally so streaming
    micro-batches can extend it.
    """

    def __init__(self):
        self._names = [ALL_NAME]
        self._ids = {ALL_NAME: ALL_GROUP}

    def __len__(self):
        return len(self._names)

    @property
    def names(self):
        return tuple(self._names)

    def id_for(self, group_name: str) -> int:
        gid = self._ids.get(group_name)
        if gid is None:
            gid = len(self._names)
            self._names.append(group_name)
            self._ids[group_name] = gid
        return gid

    def name_for(self, gid: int) -> str:
        return self._names[gid]

    def group_ids(self, user_ids) -> np.ndarray:
        """Per-point routed group id (EXCLUDED for x-users).

        Factorize-then-route-unique: one hash factorize over the id
        column, then Python routing only per DISTINCT user — instead of
        the reference's per-record mapper cost (heatmap.py:64-70) on
        every row (measured ~4x on 10M rows). Factorize preserves
        first-appearance order, so vocab ids are assigned in first-use
        row order — identical to the per-row loop (and to
        run_job_fast's reader-table mapping, which mirrors that order).
        """
        n = len(user_ids)
        if n == 0:
            return np.empty(0, np.int32)
        codes = uniques = None
        try:
            import pandas as pd

            codes, uniques = pd.factorize(
                np.asarray(user_ids, dtype=object), use_na_sentinel=False
            )
        except (ImportError, TypeError):
            # No pandas, or pandas < 1.5 (kwarg spelled na_sentinel) —
            # degrade to the loop rather than fail the whole ingest.
            # Only the factorize call sits in this try: routing errors
            # below (e.g. a None user id) must stay loud.
            pass
        if codes is not None:
            mapped = np.empty(len(uniques), np.int32)
            for j, uid in enumerate(uniques):
                # Route the ORIGINAL object: None/int ids must fail as
                # loudly as they do in the per-row loop, not be
                # stringified into a bogus 'nan'/'123' group.
                name = route_user(uid)
                mapped[j] = EXCLUDED if name is None else self.id_for(name)
            return mapped[codes].astype(np.int32)
        # Dict-cache loop: one hash lookup per row, routing only on
        # first sight of each id.
        cache: dict = {}
        out = np.empty(n, np.int32)
        for i, uid in enumerate(user_ids):
            gid = cache.get(uid)
            if gid is None:
                name = route_user(uid)
                gid = EXCLUDED if name is None else self.id_for(name)
                cache[uid] = gid
            out[i] = gid
        return out
