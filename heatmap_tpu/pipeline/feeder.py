"""Async double-buffered host->device feeder.

The one-program gspmd dispatch (parallel/gspmd.py) removes the host
round-trips *between* cascade stages; what remains on the host path is
the transfer *into* each dispatch — ``jax.device_put`` of the next
batch's numeric columns. This module overlaps that transfer with the
current batch's compute: a worker thread feeds batch k+1 onto the
device (optionally with a target ``NamedSharding``) while batch k runs,
through a bounded queue so at most ``depth`` fed batches are resident
ahead of the consumer.

Used by both standing consumers of the bucketed compile cache:

- ``pipeline/batch.py`` ``_run_job_bounded`` feeds chunk k+1's
  latitude/longitude/weights columns while chunk k's cascade runs
  (replacing the host-only prefetch queue — same overlap semantics,
  plus the H2D copy now rides the worker thread);
- ``ingest/loop.py`` ``run_ingest`` feeds micro-batch columns ahead of
  the tick that journals and applies them.

Byte identity: the feeder moves buffers, never values. ``device_put``
canonicalizes dtypes when x64 is off (float64 -> float32), which WOULD
change results, so :func:`device_put_columns` passes everything through
untouched unless ``jax_enable_x64`` is on (the composite-key cascade
requires x64 anyway, so in practice the guard only disarms the feeder
in configurations that could not run the cascade at all). Fed order is
the source order — the queue is FIFO and the single worker feeds
sequentially — so vocab ids, journal epochs, and merge results are
identical to the unfed path (pinned in tests/test_gspmd.py).

Fault plane: every put runs under the ``feeder.put`` site via
``faults.retry_call`` — a transient (or injected) failure re-feeds the
same batch, which is idempotent (device_put again; nothing downstream
has seen it). A terminal failure propagates to the consumer, and on the
ingest path the journal's content hashes make the re-fed batch
exactly-once after restart (the chaos ``dispatch`` phase pins this).

Telemetry: ``feeder_depth`` gauge (batches resident ahead of the
consumer at each dequeue) and :class:`FeederStats` — ``feed_s`` (worker
time spent transferring), ``wait_s`` (consumer time blocked on the
queue), and ``overlap_pct`` = the share of transfer time hidden behind
compute, the ``ingest:feed_overlap_pct`` bench series.
"""

from __future__ import annotations

import dataclasses
import queue as queue_mod
import threading
import time

from heatmap_tpu import faults, obs

_DONE = object()   # worker -> consumer end-of-stream sentinel
_POLL_S = 0.05     # bounded put/get poll interval (not a sleep loop)

#: Default bound on fed batches resident ahead of the consumer.
#: 1 = classic double buffering (next batch transfers while the
#: current one computes); deeper only helps when feed times are spiky.
DEFAULT_DEPTH = 1


@dataclasses.dataclass
class FeederStats:
    """Outcome of one feeder drain (shared with the consumer live)."""

    batches: int = 0     #: batches fed through
    feed_s: float = 0.0  #: worker seconds spent in transfer (sum)
    wait_s: float = 0.0  #: consumer seconds blocked on the queue (sum)
    depth_hwm: int = 0   #: max batches resident ahead of the consumer

    @property
    def overlap_pct(self) -> float:
        """Share of transfer time hidden behind compute, in percent.

        100 means the consumer never waited (every transfer fully
        overlapped); 0 means every transfer second was paid for in
        consumer wait time (no overlap at all).
        """
        if self.feed_s <= 0.0:
            return 100.0
        return 100.0 * max(0.0, 1.0 - self.wait_s / self.feed_s)


def device_put_columns(cols, *, sharding=None, columns=("latitude",
                                                        "longitude",
                                                        "value")):
    """Device-put the numeric columns of one batch dict.

    Only ndarray-valued float/int columns in ``columns`` move (the
    cascade consumes exactly those on device); string/object columns
    and host-labeled ones (``timestamp`` feeds the host-side timespan
    labeler) stay put. With x64 off everything passes through untouched
    — see the module docstring's byte-identity contract.
    """
    import jax
    import numpy as np

    if not jax.config.jax_enable_x64:
        return cols
    out = dict(cols)
    for name in columns:
        val = out.get(name)
        if isinstance(val, np.ndarray) and val.dtype.kind in "fiu":
            out[name] = jax.device_put(val, sharding)
    return out


def feed(items, transfer, *, depth: int = DEFAULT_DEPTH,
         stats: FeederStats | None = None, thread_name: str = "feeder"):
    """Yield ``transfer(item)`` for each item, transferring up to
    ``depth`` items ahead of the consumer on a worker thread.

    ``transfer`` runs under the ``feeder.put`` fault site (retried per
    its policy; must be idempotent — ``device_put`` is). Items yield in
    source order. A worker exception (source or transfer, retries
    exhausted) re-raises here after in-flight items drain; a consumer
    exception stops the worker before propagating. The worker is
    trace-context bound so transfer-side spans parent under the ambient
    job span.

    Returns a generator; pass a :class:`FeederStats` to read overlap
    numbers during/after the drain.
    """
    if depth < 1:
        raise ValueError(f"feeder depth must be >= 1, got {depth}")
    st = stats if stats is not None else FeederStats()
    q: queue_mod.Queue = queue_mod.Queue(maxsize=depth)
    abort = threading.Event()
    worker_error: list = []

    def _put(payload) -> bool:
        while not abort.is_set():
            try:
                q.put(payload, timeout=_POLL_S)
                return True
            except queue_mod.Full:
                continue
        return False

    def _work():
        try:
            for index, item in enumerate(items):
                t0 = time.monotonic()
                fed = faults.retry_call(
                    transfer, item, site="feeder.put", key=index)
                st.feed_s += time.monotonic() - t0
                if not _put(fed):
                    return
            _put(_DONE)
        except BaseException as e:  # re-raised in the consumer
            worker_error.append(e)
            abort.set()

    from heatmap_tpu.obs import tracing

    worker = threading.Thread(target=tracing.context_bound(_work),
                              name=thread_name, daemon=True)
    worker.start()

    def _drain():
        metrics_on = obs.metrics_enabled()
        try:
            while True:
                t0 = time.monotonic()
                try:
                    got = q.get(timeout=_POLL_S)
                except queue_mod.Empty:
                    if abort.is_set():
                        break
                    st.wait_s += time.monotonic() - t0
                    continue
                st.wait_s += time.monotonic() - t0
                if got is _DONE:
                    break
                resident = q.qsize() + 1  # this item + still queued
                st.depth_hwm = max(st.depth_hwm, resident)
                if metrics_on:
                    obs.FEEDER_DEPTH.set(q.qsize())
                st.batches += 1
                yield got
        finally:
            abort.set()
            worker.join(timeout=5.0)
        if worker_error:
            raise worker_error[0]

    return _drain()
