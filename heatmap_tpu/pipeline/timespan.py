"""Timespan labels: alltime / year / month / day buckets.

The reference formats these in ``build_timespan_label`` (reference
heatmap.py:38-52) but the call site is commented out and an early
``return`` inside the timespan loop means only the first timespan could
ever emit (reference heatmap.py:62-76, SURVEY.md §8.2/§8.3 quirks).
Here the feature is implemented *correctly* — every requested timespan
emits — with labels matching the reference's formatting exactly;
"alltime"-only remains the default for output parity.
"""

from __future__ import annotations

import datetime as _dt

import numpy as np

ALLTIME = "alltime"
VALID_TYPES = ("alltime", "year", "month", "day")

#: Canonical missing-timestamp sentinel for integer epoch-ms columns.
#: io.hmpb re-exports this as part of its on-disk format contract, and
#: the native decoder asserts its C definition matches.
TS_MISSING = np.iinfo(np.int64).min


def timespan_label(timespan_type: str, local_date) -> str:
    """Label for one timespan bucket; formatting per reference
    heatmap.py:38-52 (zero-padded month/day)."""
    if timespan_type == "alltime":
        return ALLTIME
    if timespan_type == "year":
        return str(local_date.year)
    if timespan_type == "month":
        return f"{local_date.year}-{local_date.month:02d}"
    if timespan_type == "day":
        return f"{local_date.year}-{local_date.month:02d}-{local_date.day:02d}"
    raise ValueError(f"unknown timespan type {timespan_type!r}; use {VALID_TYPES}")


def _to_date(ts):
    if ts is None:
        raise ValueError(
            "dated timespans (year/month/day) need a timestamp column; "
            "got a row with timestamp=None — use --timespans alltime for "
            "timestamp-less sources"
        )
    if isinstance(ts, _dt.datetime):
        return ts.date()
    if isinstance(ts, _dt.date):
        return ts
    # Epoch milliseconds, the shape the reference's commented ingest
    # produced (reference heatmap.py:26).
    return _dt.datetime.fromtimestamp(float(ts) / 1000.0, _dt.timezone.utc).date()


class TimespanVocab:
    """Host-side label <-> dense int id map (id 0 is always 'alltime')."""

    def __init__(self):
        self._labels = [ALLTIME]
        self._ids = {ALLTIME: 0}

    def __len__(self):
        return len(self._labels)

    @property
    def labels(self):
        return tuple(self._labels)

    def id_for(self, label: str) -> int:
        tid = self._ids.get(label)
        if tid is None:
            tid = len(self._labels)
            self._labels.append(label)
            self._ids[label] = tid
        return tid

    def label_for(self, tid: int) -> str:
        return self._labels[tid]

    def label_ids(self, timespan_type: str, timestamps) -> np.ndarray:
        """Per-point label ids for one timespan type.

        'alltime' ignores timestamps entirely (and tolerates None, like
        the reference whose timestamps are carried but unused,
        SURVEY.md §8.7). Numeric epoch-ms columns are factorized to
        unique UTC days first, so Python label formatting runs per
        distinct day, not per row.
        """
        n = len(timestamps)
        if timespan_type == "alltime":
            return np.zeros(n, np.int32)
        arr = np.asarray(timestamps)
        if arr.dtype.kind == "M" and n:
            # datetime64 columns (Parquet/Arrow): epoch ms. NaT casts
            # to INT64_MIN == TS_MISSING, so missing values flow into
            # the sentinel check below for free.
            arr = arr.astype("datetime64[ms]").astype(np.int64)
        if arr.dtype.kind in "iuf" and n:
            # Missing rows (sentinel / NaN) fail like the object path's
            # timestamp=None does — a dated bucket can't be invented.
            missing = (
                np.isnan(arr) if arr.dtype.kind == "f" else arr == TS_MISSING
            )
            if missing.any():
                _to_date(None)  # raises with the canonical guidance
            # Epoch ms -> UTC day ordinal; floor (not truncation)
            # matches fromtimestamp(ms/1000, UTC).date() for negatives.
            if arr.dtype.kind == "f":
                days = np.floor(arr / 86_400_000.0).astype(np.int64)
            else:
                days = np.floor_divide(arr.astype(np.int64), 86_400_000)
            uniq, inv = np.unique(days, return_inverse=True)
            per_day = np.empty(len(uniq), np.int32)
            for j, d in enumerate(uniq):
                date = _dt.datetime.fromtimestamp(
                    int(d) * 86_400, _dt.timezone.utc
                ).date()
                per_day[j] = self.id_for(timespan_label(timespan_type, date))
            return per_day[inv.reshape(-1)].astype(np.int32)
        out = np.empty(n, np.int32)
        for i, ts in enumerate(timestamps):
            out[i] = self.id_for(timespan_label(timespan_type, _to_date(ts)))
        return out
