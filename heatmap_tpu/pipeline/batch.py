"""Batch job orchestration: the TPU-native ``batchMain``.

End-to-end equivalent of reference heatmap.py:152-158:

    rows -> dataframe_loader -> build_heatmaps -> heatmap_to_json -> sink

with the Spark RDD program replaced by: host-side ingest filtering +
vocab building (strings never reach the device), one f64 projection to
detail-zoom Morton codes, the single-sort composite-key cascade on
device (cascade.py), and host-side blob egress.
"""

from __future__ import annotations

import dataclasses
import functools
import os

import jax
import numpy as np

from heatmap_tpu import obs
from heatmap_tpu.pipeline import bucketing as bucketing_mod
from heatmap_tpu.pipeline import cascade as cascade_mod
from heatmap_tpu.tilemath import mercator, morton
from heatmap_tpu.pipeline.groups import ALL_GROUP, EXCLUDED, UserVocab
from heatmap_tpu.pipeline.timespan import TS_MISSING, TimespanVocab

BACKGROUND_SOURCE = "background"  # dropped at ingest, reference heatmap.py:28-29


@dataclasses.dataclass(frozen=True)
class BatchJobConfig:
    """Flags replacing the reference's hard-coded constants
    (reference heatmap.py:16-23; SURVEY.md §5 "config system")."""

    detail_zoom: int = 21
    min_detail_zoom: int = 5
    result_delta: int = 5
    timespans: tuple = ("alltime",)
    # Reference-compat quirks (SURVEY.md §8.1, §8.2), off by default:
    amplify_all: bool = False
    first_timespan_only: bool = False
    capacity: int | None = None
    #: Sum the source's per-point 'value' column instead of counting
    #: (the cascade accumulates in f64; blob values become the sums).
    #: The reference counts 1.0 per row (heatmap.py:35) — weighted jobs
    #: are a capability extension, not a parity surface.
    weighted: bool = False
    #: Cascade reduction backend: "auto" (default), "scatter", or
    #: "partitioned" (multi-channel MXU segment reduction — measured
    #: 1.8x the scatter kernel at cascade level on v5e-1, 12.2 vs
    #: 6.9 M pts/s, and 12/12 verify combos bit-exact under Mosaic;
    #: PERF_NOTES.md round 5). "auto" routes COUNT jobs on TPU to the
    #: partitioned kernel and weighted jobs to scatter (the weighted
    #: cascade stays opt-in: partitioned takes weighted jobs only
    #: under the bounded-integer contract ``weight_bound``, and only
    #: when requested explicitly). "scatter" is the escape hatch that
    #: pins the old kernel everywhere.
    cascade_backend: str = "auto"
    #: Bounded-integer weight contract for weighted partitioned jobs:
    #: every 'value' is an integer in [0, weight_bound]. Lifts the
    #: weighted lockout on the partitioned backend (the exactness slab
    #: shrinks to 2^24 // bound; violations are detected on device and
    #: surface as capacity overflow — ops/sparse_partitioned.py).
    #: Fractional weights CANNOT take this contract (f32 products
    #: round before accumulation; no slab restores exactness) — they
    #: stay on the scatter backend.
    weight_bound: int | None = None
    #: Shrink deep cascade levels to the real unique counts (one scalar
    #: sync per level; identical results — see
    #: ops.pyramid.pyramid_sparse_morton). Measured on CPU: ~1.1x warm,
    #: but the data-dependent level shapes cost ~16 extra XLA compiles
    #: cold (6x slower first run at 500k pts) — so OFF by default until
    #: the on-chip stage balance shows the per-level scatters dominating
    #: enough to pay for the compiles (PERF_NOTES pending item 4).
    adaptive_capacity: bool = False
    #: Data-parallelize the cascade over the process's LOCAL devices
    #: (reference scale-out analog: Spark's elastic executors,
    #: submit-heatmap:10-13). None (default) auto-enables when
    #: ``jax.local_device_count() > 1`` AND the call's emission count
    #: reaches AUTO_DP_MIN_EMISSIONS — a single-process v5e-8 host
    #: drives all 8 chips from the same ``run_job`` call on real
    #: workloads, while tiny inputs (and single chips) skip the mesh
    #: dispatch they'd only lose to. True forces the mesh path at any
    #: size and device count (the sharded kernels are exercised,
    #: results unchanged); False pins the single-device cascade. Counts and integer-valued weighted
    #: sums are bit-identical either way; fractional weighted sums
    #: agree up to f64 summation-order rounding (see
    #: cascade.build_cascade ``mesh``). Composes with multi-process
    #: runs (run_job_multihost): each process data-parallelizes its
    #: slice over its own local devices.
    data_parallel: bool | None = None
    #: Cross-device merge for the data-parallel cascade: "replicated"
    #: (default — all_gather compact partials, merge + roll up on every
    #: device; O(global uniques) replicated, measured fine for
    #: clustered data) or "prefix" (coarse-prefix all_to_all regroup —
    #: each device merges and rolls up only its keyspan range,
    #: O(uniques/k) per stage; the scaling shape for unique-heavy data
    #: — docs/DESIGN.md §4, reference heatmap.py:112's hash-partitioned
    #: reducers). Blobs identical either way (counts and integer
    #: weighted sums bit-exact; fractional weighted to f64 summation
    #: order). Ignored off the mesh path.
    dp_merge: str = "replicated"
    #: Auto-DP engagement threshold override (emission count at which
    #: ``data_parallel=None`` engages the mesh). None uses the module
    #: default ``AUTO_DP_MIN_EMISSIONS``, which is calibrated from a
    #: CPU-mesh data point only — a v5e-8 operator should measure the
    #: real crossover (docs/OPERATIONS.md "Calibrating auto-DP") and
    #: set this (CLI ``--dp-min-emissions``). Meaningful for auto mode
    #: only; explicit True/False ignore the threshold, so combining is
    #: rejected at config time.
    dp_min_emissions: int | None = None
    #: Bucketed-padding compile cache (pipeline/bucketing.py): "exact"
    #: (default — shapes follow the input, every distinct batch size
    #: compiles fresh), "pow2" or "geometric" (pad emissions up to a
    #: power-of-two / 1.25x-geometric bucket with masked pad lanes, so
    #: arbitrary-size applies and streaming ticks reuse one compilation
    #: per bucket). Byte-neutral: decode truncates to real unique
    #: counts, pinned in tests/test_ingest.py. This knob is runtime
    #: tuning, NOT data semantics — delta/compact.CONFIG_FIELDS
    #: deliberately excludes it, so stores accept mixed settings.
    pad_bucketing: str = "exact"
    #: Bucket floor for pad_bucketing != "exact": batches below this
    #: many emissions share one compilation (bucketing.bucket_size).
    pad_bucket_min: int = 1 << 12
    #: Morton-range spatial sharding of the data-parallel cascade
    #: (parallel/partition.py): "auto" (default — when the mesh path
    #: engages AND the emission count reaches the auto-DP threshold,
    #: plan P-1 split codes from sampled quantiles, route each shard a
    #: contiguous Z-order range host-side, and shrink the cross-chip
    #: merge to boundary tiles only), "morton" (force range sharding
    #: whenever a mesh engages, any size), "off" (uniform round-robin
    #: DP, the historical path). Byte-neutral: counts and
    #: integer-valued weighted sums are bit-identical to "off"
    #: (tests/test_partition.py pins blobs across backends); a
    #: degenerate plan (one range holds ~all sampled mass) falls back
    #: to uniform DP with a backend_resolved audit event
    #: (_dp_mesh_for). Composes with pad_bucketing: per-range segments
    #: pad to bucketed lengths so routed shapes hit the same compile
    #: cache.
    spatial_partition: str = "auto"
    #: Mesh-cascade dispatch formulation: "auto" (default — "gspmd"
    #: wherever its programs exist, which today is every mesh shape
    #: except dp_merge="prefix"), "gspmd" (parallel/gspmd.py — the
    #: whole cascade as ONE global-view NamedSharding program:
    #: on-device emission routing against traced splits, range-local
    #: rollup, boundary merge, and canonical egress ordering, with no
    #: host round-trips between stages), or "shard_map" (the
    #: parallel/sharded.py kernels with host-side range routing — kept
    #: selectable for one release as the differential-testing oracle).
    #: Byte-identical outputs either way (tests/test_gspmd.py pins
    #: levels AND served blobs). Ignored off the mesh path. Only
    #: "gspmd" composes with adaptive_capacity: its traced router and
    #: global-view rollup accept concrete-count shrinking that the
    #: shape-static shard_map bodies cannot.
    dispatch: str = "auto"

    def __post_init__(self):
        from heatmap_tpu.pipeline.bucketing import BUCKETING_MODES

        if self.pad_bucketing not in BUCKETING_MODES:
            raise ValueError(
                f"unknown pad_bucketing {self.pad_bucketing!r} (valid: "
                f"{', '.join(BUCKETING_MODES)}) — rejected at config "
                "time so a typo fails before a multi-hour ingest"
            )
        if self.pad_bucket_min < 1:
            raise ValueError(
                f"pad_bucket_min must be >= 1, got {self.pad_bucket_min}"
            )
        if self.dp_merge not in ("replicated", "prefix"):
            raise ValueError(
                f"unknown dp_merge {self.dp_merge!r} (valid: "
                "replicated, prefix) — rejected at config time so a "
                "typo fails before a multi-hour ingest"
            )
        if self.dp_min_emissions is not None:
            if self.data_parallel is not None:
                raise ValueError(
                    "dp_min_emissions tunes AUTO data-parallel routing "
                    "only; data_parallel=True/False ignore the "
                    "threshold — rejected at config time so a "
                    "calibration flag that silently does nothing "
                    "cannot ship"
                )
            if self.dp_min_emissions < 0:
                raise ValueError(
                    f"dp_min_emissions must be >= 0, got "
                    f"{self.dp_min_emissions}"
                )
        if self.cascade_backend not in ("auto", "scatter", "partitioned"):
            raise ValueError(
                f"unknown cascade backend {self.cascade_backend!r} "
                "(valid: auto, scatter, partitioned) — rejected at "
                "config time so a typo fails before a multi-hour ingest"
            )
        if (self.weighted and self.cascade_backend == "partitioned"
                and self.weight_bound is None):
            raise ValueError(
                "cascade backend 'partitioned' takes weighted jobs "
                "only under the bounded-integer contract: set "
                "weight_bound (every 'value' an integer in "
                "[0, weight_bound]); fractional weights use the "
                "scatter backend — rejected at config time so the "
                "combination fails before ingest"
            )
        if self.weight_bound is not None:
            if not self.weighted:
                raise ValueError(
                    "weight_bound declares the weighted integer "
                    "contract and needs weighted=True — rejected at "
                    "config time so a silently ignored bound cannot "
                    "ship"
                )
            if self.weight_bound < 1:
                raise ValueError(
                    f"weight_bound must be >= 1, got {self.weight_bound}"
                )
            # The partitioned cascade runs at the kernel's default
            # geometry (chunk=1024, streams=1), where the f32
            # exactness slab 2^24 // bound must hold at least one
            # chunk row — beyond that NO slab size keeps weighted
            # sums exact (ops/sparse_partitioned.py refuses too, but
            # a config-time rejection beats a mid-job one).
            max_bound = (1 << 24) // 1024
            if (self.cascade_backend == "partitioned"
                    and self.weight_bound > max_bound):
                raise ValueError(
                    f"weight_bound {self.weight_bound} exceeds the "
                    f"partitioned backend's exactness limit "
                    f"{max_bound} (slab 2^24 // bound must hold one "
                    "1024-element chunk) — use the scatter backend "
                    "for larger weights"
                )
        if self.dispatch not in ("auto", "gspmd", "shard_map"):
            raise ValueError(
                f"unknown dispatch {self.dispatch!r} (valid: auto, "
                "gspmd, shard_map) — rejected at config time so a typo "
                "fails before a multi-hour ingest"
            )
        if self.dispatch == "gspmd" and self.dp_merge == "prefix":
            raise ValueError(
                "dispatch='gspmd' has no prefix-merge program yet; "
                "dp_merge='prefix' needs dispatch='shard_map' (or "
                "'auto', which resolves it there)"
            )
        if (self.data_parallel and self.adaptive_capacity
                and self.resolved_dispatch != "gspmd"):
            raise ValueError(
                "the shard_map mesh cascade is shape-static; "
                "adaptive_capacity reads concrete per-level counts "
                "and does not compose — disable one of them, or use "
                "dispatch='gspmd' (its global-view rollup accepts "
                "adaptive shrinking)"
            )
        if self.spatial_partition not in ("auto", "morton", "off"):
            raise ValueError(
                f"unknown spatial_partition {self.spatial_partition!r} "
                "(valid: auto, morton, off) — rejected at config time "
                "so a typo fails before a multi-hour ingest"
            )
        if self.spatial_partition == "morton":
            if self.data_parallel is False:
                raise ValueError(
                    "spatial_partition='morton' range-shards the "
                    "data-parallel cascade; data_parallel=False pins "
                    "the single-device path — rejected at config time "
                    "so a silently ignored partition cannot ship"
                )
            if (self.adaptive_capacity
                    and self.resolved_dispatch != "gspmd"):
                # The host router (route_emissions) is shape-static, so
                # morton + adaptive only composes when routing happens
                # on-device — the gspmd dispatch. "auto" resolves to
                # gspmd precisely so this combination Just Works.
                raise ValueError(
                    "spatial_partition='morton' with "
                    "dispatch='shard_map' rides the host-routed "
                    "shape-static mesh path; adaptive_capacity does "
                    "not compose there — use dispatch='gspmd' (or "
                    "'auto'), whose on-device routing accepts it"
                )

    @property
    def resolved_dispatch(self) -> str:
        """The mesh-dispatch formulation the cascade actually runs:
        "auto" resolves to the one-program gspmd dispatch wherever its
        programs exist — today everything except dp_merge="prefix",
        which keeps the shard_map prefix kernel. Explicit requests are
        honored as-is (gspmd + prefix is rejected at config time)."""
        if self.dispatch != "auto":
            return self.dispatch
        return "shard_map" if self.dp_merge == "prefix" else "gspmd"

    @property
    def resolved_cascade_backend(self) -> str:
        """The backend the cascade actually runs: on TPU, "auto"
        resolves to the partitioned MXU kernel for count jobs (the
        measured 1.8x cascade win, bit-identical blobs) and to scatter
        for weighted jobs — the weighted partitioned route needs the
        bounded-integer contract and stays an explicit request. Off
        TPU "auto" stays on scatter: the pallas kernel only runs in
        interpret mode there (orders slower than the native XLA
        scatter), the same platform gate ops/histogram._pick_backend
        applies. An explicit "partitioned" is honored anywhere."""
        if self.cascade_backend != "auto":
            return self.cascade_backend
        if self.weighted:
            return "scatter"
        import jax

        on_tpu = jax.devices()[0].platform in ("tpu", "axon")
        return "partitioned" if on_tpu else "scatter"

    def cascade_config(self) -> cascade_mod.CascadeConfig:
        return cascade_mod.CascadeConfig(
            detail_zoom=self.detail_zoom,
            min_detail_zoom=self.min_detail_zoom,
            result_delta=self.result_delta,
            amplify_all=self.amplify_all,
        )


def _row_get(row, key, default=None):
    """Mapping-style ``.get`` for dicts AND pyspark-Row-shaped rows.

    ``pyspark.sql.Row`` is a tuple subclass with ``__getitem__`` by
    field name but NO ``.get`` method — the exact objects a
    ``df.rdd.mapPartitions`` body receives (the Spark-boundary
    contract of spark_adapter.py; the reference's mappers indexed Rows
    by name the same way, reference heatmap.py:27-35). Missing fields
    raise ValueError there, KeyError on mappings — both mean
    ``default``.
    """
    getter = getattr(row, "get", None)
    if getter is not None:
        return getter(key, default)
    try:
        return row[key]
    except (KeyError, ValueError, IndexError, TypeError):
        return default


def load_rows(rows):
    """Ingest filter + column extraction (reference dataframe_loader,
    heatmap.py:25-36): drops ``source == "background"`` rows, keeps
    (latitude, longitude, user_id, timestamp) and, when any row
    carries one, the optional ``value`` weight column (absent values
    default 1.0 — the reference counts 1.0 per row, heatmap.py:35).

    ``rows``: iterable of dicts OR pyspark-Row-shaped objects with the
    reference's column names. Returns dict of host arrays/lists.
    """
    lats, lons, users, stamps, vals = [], [], [], [], []
    _missing = object()
    any_value = False
    for row in rows:
        if _row_get(row, "source") == BACKGROUND_SOURCE:
            continue
        lats.append(row["latitude"])
        lons.append(row["longitude"])
        users.append(row["user_id"])
        stamps.append(_row_get(row, "timestamp"))
        # Keyed on field PRESENCE, not non-None values: a partition
        # whose rows all carry value=None must still emit the column
        # (nulls default 1.0) — otherwise the same weighted job fails
        # or succeeds depending on partition placement.
        v = _row_get(row, "value", _missing)
        any_value = any_value or v is not _missing
        vals.append(None if v is _missing else v)
    out = {
        "latitude": np.asarray(lats, np.float64),
        "longitude": np.asarray(lons, np.float64),
        "user_id": users,
        "timestamp": stamps,
    }
    if any_value:
        out["value"] = np.asarray(
            [1.0 if v is None else float(v) for v in vals], np.float64
        )
    return out


def project_detail_codes(lat: np.ndarray, lon: np.ndarray, detail_zoom: int,
                         prefer_device: bool = True):
    """f64 projection to detail-zoom Morton codes + validity.

    When x64 is enabled the projection and bit-interleave run ON DEVICE
    in float64/int64 — measured bit-identical to the CPython-double
    oracle at z21 and ~84x the host numpy rate on v5e (PERF_NOTES.md
    round 2: 0.31 B pts/s vs 3.7 M pts/s for numpy project+interleave,
    which would otherwise bottleneck every job's ingest). Both
    implementations follow the same IEEE-double op order (reference
    tile.py:17,21), so the paths agree bit-for-bit and are
    cross-checked in tests. Without x64 (or with
    ``prefer_device=False``) the host numpy path is used — device f32
    cannot place z21 points.
    """
    import jax

    if prefer_device and jax.config.jax_enable_x64:
        import jax.numpy as jnp

        codes, valid = _project_codes_jit(
            jnp.asarray(lat, jnp.float64), jnp.asarray(lon, jnp.float64),
            detail_zoom,
        )
        return np.asarray(codes), np.asarray(valid)
    row, col, valid = mercator.project_points_np(lat, lon, detail_zoom)
    return morton.morton_encode_np(row, col), valid


@functools.partial(jax.jit, static_argnames=("zoom",))
def _project_codes_jit(lat, lon, zoom):
    import jax.numpy as jnp

    row, col, valid = mercator.project_points(lat, lon, zoom,
                                              dtype=jnp.float64)
    return morton.morton_encode(row, col, dtype=jnp.int64, zoom=zoom), valid


#: Auto data-parallel engages only past this many emissions (explicit
#: ``data_parallel=True`` always engages). Below it the per-device
#: slices are too small for the shard_map dispatch + all_gather merge
#: to pay for themselves on ANY backend — measured 9x SLOWER on the
#: 8-device CPU mesh with 150-point bounded chunks (eager per-chunk
#: dispatch), and a real chip gains nothing from sharding a few
#: thousand rows eight ways. Auto-routing must never slow down jobs
#: that were fine (the _auto_points_in_flight rule applied to DP).
AUTO_DP_MIN_EMISSIONS = 1 << 18


def _dp_mesh(config: BatchJobConfig):
    """Mesh over the process's local devices for the cascade's
    data-parallel route, or None for the single-device cascade.

    Capability gate only — the per-call size gate is
    :func:`_dp_mesh_for`. Auto (``data_parallel=None``) engages only
    past one local device: the mesh path is bit-identical but adds
    shard_map dispatch that a single chip gains nothing from. Both
    cascade backends compose with the mesh (the partitioned segment
    reduction runs inside the shard_map body — parallel/sharded.py);
    under the shard_map dispatch adaptive capacities route
    single-device (True + adaptive rejected at config time there),
    while the gspmd dispatch takes them onto the mesh — its
    global-view rollup reads concrete counts eagerly.
    """
    if config.data_parallel is False:
        return None
    if config.adaptive_capacity and config.resolved_dispatch != "gspmd":
        return None
    if config.data_parallel is None and jax.local_device_count() < 2:
        return None
    from heatmap_tpu.parallel.mesh import make_mesh

    return make_mesh(devices=jax.local_devices())


def _dp_mesh_for(mesh, config: BatchJobConfig, n_emissions: int,
                 plan=None):
    """The mesh to pass this cascade call, or None: auto engages only
    at AUTO_DP_MIN_EMISSIONS and up; explicit True always engages.

    ``plan`` makes the decision plan-aware rather than a function of
    ``n_emissions`` alone: a proposed Morton partition plan
    (parallel.partition.PartitionPlan) whose sampled mass is degenerate
    — effectively one non-empty range — must NOT ride the range-sharded
    path, because routing all mass to one shard serializes the cascade
    (strictly worse than the uniform-DP mesh this threshold was
    calibrated for). The call then keeps the uniform mesh and records
    the fallback as a ``backend_resolved`` event
    (reason="degenerate partition plan...") so the routing decision
    stays auditable; callers must drop the plan when it is degenerate
    (_run_grouped and the elastic planner do).
    """
    if mesh is None:
        return None
    threshold = (AUTO_DP_MIN_EMISSIONS if config.dp_min_emissions is None
                 else config.dp_min_emissions)
    if config.data_parallel is None and n_emissions < threshold:
        return None
    if plan is not None and plan.degenerate and obs.telemetry_enabled():
        obs.emit(
            "backend_resolved",
            requested=f"spatial_partition={config.spatial_partition}",
            resolved="uniform-dp",
            reason=("degenerate partition plan (max shard mass "
                    f"{max(plan.shard_mass or [0.0]):.3f}) would "
                    "serialize the cascade on one shard — falling "
                    "back to uniform DP"),
            spatial_partition=config.spatial_partition,
            n_emissions=int(n_emissions),
        )
    return mesh


def _cascade_codes(lat, lon, detail_zoom):
    """Codes + validity feeding the cascade: DEVICE-RESIDENT under x64
    (projection through emission assembly to the cascade sort never
    round-trips the big code column through the host), host numpy
    otherwise."""
    if jax.config.jax_enable_x64:
        import jax.numpy as jnp

        return _project_codes_jit(
            jnp.asarray(lat, jnp.float64), jnp.asarray(lon, jnp.float64),
            detail_zoom,
        )
    return project_detail_codes(lat, lon, detail_zoom, prefer_device=False)


def build_emissions(codes, valid, group_ids, timestamps,
                    config: BatchJobConfig, ts_vocab: TimespanVocab | None = None,
                    weights=None):
    """Expand points into (code, slot) emissions + slot name table.

    Mirrors the reference mapper's group expansion (heatmap.py:64-75):
    each point emits once for 'all' and once for its routed group (if
    not excluded), for each requested timespan. With
    ``first_timespan_only`` (reference early-return quirk, SURVEY.md
    §8.2) only the first timespan emits. Pass a shared ``ts_vocab`` to
    keep timespan ids consistent across chunked calls.

    ``codes``/``valid`` may be device arrays (the x64 ingest path keeps
    them device-resident from projection to cascade — no host
    round-trip of the big code column). In that case the slot ids are
    assembled on device as well, from int32 uploads of the host-vocab
    id columns (half the transfer of pre-built int64 slots, no host
    concatenation). ``group_ids`` must be numpy.

    ``weights`` (per-point values, weighted jobs) expand exactly like
    the codes — each emission carries its point's weight; the returned
    weights entry is None when not given.
    """
    ts_vocab = ts_vocab if ts_vocab is not None else TimespanVocab()
    timespans = (
        config.timespans[:1] if config.first_timespan_only else config.timespans
    )
    per_ts_ids = [ts_vocab.label_ids(t, timestamps) for t in timespans]
    n_groups = int(group_ids.max(initial=ALL_GROUP)) + 1
    on_device = not isinstance(codes, np.ndarray)
    if on_device:
        import jax.numpy as jnp
    xp = jnp if on_device else np
    keep = group_ids != EXCLUDED
    keep_x = xp.asarray(keep)
    routed = np.where(keep, group_ids, 0).astype(np.int32)
    routed_x = xp.asarray(routed)
    weights_x = None if weights is None else xp.asarray(weights)
    emit_codes, emit_slots, emit_valid = [], [], []
    for ts_ids in per_ts_ids:
        ts_x = xp.asarray(ts_ids.astype(np.int32))
        ts64 = ts_x.astype(xp.int64)
        # 'all' emission for every point.
        emit_codes.append(codes)
        emit_slots.append(ts64 * n_groups + ALL_GROUP)
        emit_valid.append(valid)
        # per-user emission for non-excluded points.
        emit_codes.append(codes)
        emit_slots.append(ts64 * n_groups + routed_x)
        emit_valid.append(valid & keep_x)
    n_copies = 2 * len(per_ts_ids)
    e_weights = (
        None if weights_x is None
        else xp.concatenate([weights_x] * n_copies)
    )
    return (
        xp.concatenate(emit_codes),
        xp.concatenate(emit_slots),
        xp.concatenate(emit_valid),
        ts_vocab,
        n_groups,
        e_weights,
    )


def load_columns(batch):
    """Vectorized ingest filter over a columnar source batch
    (heatmap_tpu.io.sources layout): drops ``source == "background"``
    rows (reference heatmap.py:28-29) without touching per-row Python.
    """
    src = batch.get("source")
    lat = np.asarray(batch["latitude"], np.float64)
    lon = np.asarray(batch["longitude"], np.float64)
    users = batch["user_id"]
    stamps = batch.get("timestamp")
    values = batch.get("value")  # optional per-point weight (config 3)
    if values is not None:
        values = np.asarray(values, np.float64)
    if stamps is None or len(stamps) == 0:
        stamps = [None] * len(lat)
    if src is not None and len(src):
        keep = np.asarray(src, object) != BACKGROUND_SOURCE
        if not keep.all():
            idx = np.flatnonzero(keep)
            lat, lon = lat[idx], lon[idx]
            users = [users[i] for i in idx]
            stamps = [stamps[i] for i in idx]
            if values is not None:
                values = values[idx]
    out = {
        "latitude": lat,
        "longitude": lon,
        "user_id": list(users),
        "timestamp": list(stamps),
    }
    if values is not None:
        out["value"] = values
    return out


def run_job(source, sink=None, config: BatchJobConfig | None = None,
            batch_size: int = 1 << 20,
            max_points_in_flight: int | None = None,
            overlap_ingest: bool = True,
            merge_spill_dir: str | None = None):
    """Source-to-sink job over columnar batches (the production entry;
    reference batchMain shape with get_rows/write_heatmap_dataframes
    replaced by heatmap_tpu.io sources/sinks, heatmap.py:152-158).

    Accumulates host columns across source batches, runs the cascade
    once on device, writes blobs to ``sink`` (upsert-by-id). Returns
    the blob dict; if ``sink`` is given also writes into it.

    ``max_points_in_flight`` bounds peak memory for sources larger than
    host RAM (BASELINE.md config 5 shape): the cascade runs per chunk of
    at most that many points and per-level aggregates merge on the host
    — exact, because every level is a linear (key, sum) reduction, the
    same property the Spark adapter's partition merge relies on
    (spark_adapter.merge_heatmaps). (Counts and integer-valued weights
    are bit-identical to the unchunked path; fractional weighted sums
    agree up to f64 summation-order rounding.) Peak footprint is then
    O(chunk + unique aggregate keys) instead of O(total points).
    ``overlap_ingest`` double-buffers the bounded path: a prefetch
    thread parses chunk N+1 while the device cascades chunk N (see
    _run_job_bounded; identical results, up to 3 chunks resident).

    ``max_points_in_flight=None`` (default) AUTO-ROUTES: when the
    source's estimated point count would not fit host RAM single-shot
    (_auto_points_in_flight heuristic — declared/estimated source rows
    vs MemAvailable), the job takes the bounded path with a RAM-derived
    chunk size instead of requiring the operator to know the knob
    (VERDICT r2 weak #5: the default run on a bigger-than-RAM CSV must
    not OOM). Pass ``0`` to force the single-shot path, or an explicit
    point count to pick the chunk size yourself. The bounded path's
    in-RAM cross-chunk merge is O(unique output keys) (PERF_NOTES
    memory model); ``merge_spill_dir`` lifts that too, spilling
    per-chunk aggregates to disk and merging one level at a time at
    egress (_SpillMerge) — for near-unique-output shapes whose merge
    table outgrows RAM.
    """
    from heatmap_tpu.obs import tracing
    from heatmap_tpu.utils.trace import get_tracer

    config = config or BatchJobConfig()
    if max_points_in_flight is None:
        max_points_in_flight = _auto_points_in_flight(source)
    if merge_spill_dir is not None and not max_points_in_flight:
        raise ValueError(
            "merge_spill_dir lives on the bounded path, but this job "
            "routed single-shot (source fits host RAM, is unsizeable, "
            "or bounding was disabled with 0); pass "
            "max_points_in_flight > 0 to chunk — silently ignoring the "
            "spill request would run the in-RAM merge it exists to avoid"
        )
    # Tree-only span (no aggregate entry): a bare run_job call under
    # tracing yields ONE connected tree whose ingest/cascade/egress
    # tracer spans all parent here (root-on-demand when no CLI root).
    with tracing.span("run_job", bounded=bool(max_points_in_flight)):
        if max_points_in_flight:  # 0/None -> single-shot
            return _run_job_bounded(
                source, sink, config, batch_size, max_points_in_flight,
                overlap_ingest=overlap_ingest, spill_dir=merge_spill_dir,
            )
        tracer = get_tracer()
        data = ingest_columns(source.batches(batch_size), config)
        if data is None:
            return {}
        with tracer.span("cascade", items=len(data["latitude"])):
            blobs = _run_loaded(data, config, as_json=True, sink=sink)
        return blobs


#: Rough host bytes per point on the string ingest path: two f64
#: coords (16) + a user-id pointer/str share (~60) + a timestamp list
#: slot (~40) + concatenate/emission slack. Deliberately conservative —
#: the cost of underestimating is an OOM, of overestimating a slightly
#: smaller chunk.
_HOST_BYTES_PER_POINT = 160

#: Text-source row-size floor (bytes) for estimating points from file
#: size: a minimal "lat,lon,user" CSV row. Underestimating bytes/row
#: overestimates points, which errs toward bounding — the safe side.
_MIN_TEXT_ROW_BYTES = 32

#: Bounded path: convert the in-RAM cross-chunk merge table to the
#: disk-spill merge once it exceeds this many aggregate rows (~200 MB
#: of columns; the spilled runs are 24 B/row in the system temp dir).
#: Past this size the iterative fold's per-chunk re-scan of the whole
#: table loses to one egress-time sort per level — measured 2.8x slower
#: and +3.4 GB at 131M rows (PERF_NOTES round 3). Small-output jobs
#: never cross it and never touch disk.
AUTO_SPILL_ROWS = 8_000_000

#: Directory for AUTOMATIC spill (None -> tempfile.gettempdir()).
#: Set this (or pass merge_spill_dir explicitly) to redirect; the
#: TMPDIR env var works too, via gettempdir().
AUTO_SPILL_DIR: str | None = None


def _mount_fstype(path: str, mounts_file: str = "/proc/mounts") -> str | None:
    """Filesystem type of the longest mount-point prefix of ``path``
    (Linux), or None when undeterminable (non-Linux: best effort)."""
    try:
        real = os.path.realpath(path)
        best, fstype = "", None
        with open(mounts_file) as f:
            for line in f:
                parts = line.split()
                if len(parts) < 3:
                    continue
                mnt, typ = parts[1], parts[2]
                if real == mnt or real.startswith(mnt.rstrip("/") + "/") \
                        or mnt == "/":
                    if len(mnt) > len(best):
                        best, fstype = mnt, typ
        return fstype
    except OSError:
        return None


def _free_disk_bytes(path: str) -> int | None:
    """Free bytes available to this process on ``path``'s filesystem,
    or None when unknowable (the caller keeps its measured default)."""
    try:
        st = os.statvfs(path)
        return st.f_bavail * st.f_frsize
    except (OSError, AttributeError):
        return None


def _auto_spill_projection_fits(spill_dir: str, table_rows: int,
                                chunks_done: int,
                                total_chunks_est: int | None,
                                max_chunk_rows: int) -> bool:
    """Will the projected spill volume fit the target filesystem?

    Auto-spill must never convert a job that was finishing fine in RAM
    into an ENOSPC failure on a small disk-backed temp dir (tmpfs is
    already refused by _auto_spill_target; SIZE was not checked before
    this). Projection: the accumulated table spills immediately
    (24 B/row) and each remaining chunk adds at most the largest
    chunk's output seen so far; when the source size is unknowable,
    assume as many chunks remain as have run. 25% headroom — the
    projection errs conservative, and the write-failure fallback below
    still catches a filesystem that fills anyway.
    """
    free = _free_disk_bytes(spill_dir)
    if free is None:
        return True
    remaining = (chunks_done if total_chunks_est is None
                 else max(total_chunks_est - chunks_done, 0))
    projected = 24 * (table_rows + remaining * max_chunk_rows)
    return projected + projected // 4 <= free


def _auto_spill_target() -> str | None:
    """Directory for automatic spill, or None to stay in-RAM.

    RAM-backed candidates (tmpfs/ramfs — /tmp on many distros) are
    refused: spilling there moves pages from process RSS into tmpfs,
    which the OOM killer counts all the same, and a size-capped tmpfs
    would ENOSPC a job the in-RAM fold finishes. Explicit
    ``merge_spill_dir`` is never second-guessed.
    """
    import tempfile

    cand = AUTO_SPILL_DIR or tempfile.gettempdir()
    if _mount_fstype(cand) in ("tmpfs", "ramfs"):
        return None
    return cand


def _available_ram_bytes() -> int | None:
    """MemAvailable from /proc/meminfo (Linux), else total RAM via
    sysconf, else None (no auto-routing without a signal)."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import os as _os

        return _os.sysconf("SC_PAGE_SIZE") * _os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError, AttributeError):
        return None


def _estimate_source_points(source) -> int | None:
    """Best-effort source row count: a declared ``n`` (Synthetic, HMPB)
    beats a file-size estimate (text sources); None when unknowable
    (generators, network sources — those scale via multihost range
    sharding instead)."""
    import os as _os

    n = getattr(source, "n", None)
    if n is not None:
        return int(n)
    path = source if isinstance(source, str) else getattr(source, "path", None)
    if isinstance(path, str):
        try:
            if _os.path.isdir(path):
                size = sum(
                    e.stat().st_size for e in _os.scandir(path) if e.is_file()
                )
            else:
                size = _os.path.getsize(path)
        except OSError:
            return None
        return size // _MIN_TEXT_ROW_BYTES
    return None


def _auto_points_in_flight(source, ram_budget: int | None = None,
                           shard_count: int = 1,
                           fast: bool = False,
                           n_timespans: int = 1,
                           weighted: bool = False) -> int | None:
    """Bounded-path chunk size when the source won't fit RAM, else None.

    Half of MemAvailable is the working budget; a source whose
    estimated host columns exceed it routes to the bounded path with a
    chunk of a quarter of what fits (cascade state + double-buffered
    ingest + device arrays share the budget). Sources that fit keep
    the faster single-shot path — auto-routing must never slow down
    jobs that were fine.

    ``shard_count``: divide the estimate by the number of processes
    sharing the source (run_job_multihost ingests ~1/k of the rows per
    host, so the fit decision is about the slice, not the whole file).

    ``fast`` (run_job_fast's auto call): consult the source's
    ``fast_host_bytes_per_point`` — HMPB mmap ingest is near-zero-copy
    (~30 B/point of materialized routed columns vs 160 B of string
    ingest), so a large HMPB file that fits single-shot must not be
    silently demoted to the chunked path by the string-path constant
    (ADVICE r3). ``n_timespans`` scales the per-emission share added
    on top of the declared rate (each timespan doubles the emission
    arrays). The string path never reads the attribute: the same
    source consumed through ``batches()`` materializes Python strings
    at the conservative rate.
    """
    est = _estimate_source_points(source)
    if est is None:
        return None
    est = -(-est // max(shard_count, 1))
    if ram_budget is None:
        avail = _available_ram_bytes()
        if avail is None:
            return None
        ram_budget = avail // 2
    bytes_per_point = _HOST_BYTES_PER_POINT
    if fast:
        declared = getattr(source, "fast_host_bytes_per_point", None)
        if declared is not None:
            # The declared rate covers resident ingest columns only;
            # the emission/sort arrays (i64 code + i64 slot + valid,
            # ~2x transiently under the cascade sort ≈ 32 B/emission,
            # 2 emissions per timespan per point) share the same
            # budget — on host-memory backends they ARE host RAM, so
            # the fit check must include them or a "fitting" file can
            # materialize several times the budget single-shot.
            bytes_per_point = declared + 64 * max(n_timespans, 1)
            if weighted:
                # Weighted jobs carry an f64 value column (+8 B/pt)
                # and expand f64 e_weights per emission with the same
                # 2x transient factor (+32 B/timespan/pt).
                bytes_per_point += 8 + 32 * max(n_timespans, 1)
    fits = ram_budget // bytes_per_point
    if est <= fits:
        return None
    # A quarter of what fits (up to 3 chunks resident under
    # overlap_ingest, plus merge state), floored at 64k points so tiny
    # hosts still get device-worthy batches — the floor must stay well
    # UNDER the budget or auto-bounding would itself overrun the RAM it
    # exists to protect.
    return max(1 << 16, fits // 4)


def ingest_columns(batches, config: BatchJobConfig):
    """Accumulate source batches into the ``_run_loaded`` data dict.

    Shared by run_job and the multi-process run_job_multihost ingest so
    weighted-column validation and assembly can't drift between them.
    Returns None when the batches carried no rows.
    """
    from heatmap_tpu.utils.trace import get_tracer

    tracer = get_tracer()
    lats, lons, users, stamps, vals = [], [], [], [], []
    for batch in batches:
        with tracer.span("ingest.batch"):
            cols = load_columns(batch)
            lats.append(cols["latitude"])
            lons.append(cols["longitude"])
            users.extend(cols["user_id"])
            stamps.extend(cols["timestamp"])
            if config.weighted:
                _require_value_column(cols)
                vals.append(cols["value"])
        tracer.add_items("ingest.batch", len(cols["latitude"]))
    if not lats or sum(len(a) for a in lats) == 0:
        return None
    data = {
        "latitude": np.concatenate(lats),
        "longitude": np.concatenate(lons),
        "user_id": users,
        "timestamp": stamps,
    }
    if config.weighted:
        data["value"] = np.concatenate(vals)
    return data


class _FastRouter:
    """Maps fast-batch reader group ids into a shared UserVocab.

    Fast batches carry ``routed`` ids into a reader-side ``names``
    table that grows via ``new_group_names``; vocab ids are assigned in
    first-use order of KEPT rows so they match the string path's
    assignment order exactly (run_job_fast and the fast bounded path
    share this logic — divergence here would silently shuffle user
    attribution between paths).
    """

    def __init__(self, vocab: UserVocab):
        self.vocab = vocab
        self.names: list = []
        self._map = np.full(1024, -2, np.int32)  # -2 = not yet mapped

    def observe(self, batch):
        """Grow the reader name table (REQUIRED for every batch, even
        ones whose rows are skipped — later batches reference ids first
        named earlier)."""
        self.names.extend(batch["new_group_names"])

    def route(self, batch):
        """-> (lat, lon, gids, ts_i64, values_or_None), background rows
        dropped. ``values`` comes through when the fast batch carries a
        'value' column (HMPB with a value section), filtered by the
        same keep mask."""
        if len(self.names) > len(self._map):
            grown = np.full(max(len(self.names), 2 * len(self._map)),
                            -2, np.int32)
            grown[: len(self._map)] = self._map
            self._map = grown
        keep = ~batch["background"]
        routed = batch["routed"][keep]
        ref_ids = routed[routed >= 0]
        unmapped = self._map[ref_ids] == -2
        if unmapped.any():
            first_use = ref_ids[unmapped]
            _, order = np.unique(first_use, return_index=True)
            for rid in first_use[np.sort(order)]:
                if self._map[rid] == -2:
                    self._map[rid] = self.vocab.id_for(self.names[rid])
        gids = np.where(
            routed >= 0, self._map[np.maximum(routed, 0)], EXCLUDED
        ).astype(np.int32)
        ts = batch.get("timestamp")
        ts64 = (
            np.full(int(keep.sum()), TS_MISSING, np.int64)
            if ts is None else np.asarray(ts, np.int64)[keep]
        )
        vals = batch.get("value")
        if vals is not None:
            vals = np.asarray(vals, np.float64)[keep]
        return (batch["latitude"][keep], batch["longitude"][keep], gids,
                ts64, vals)


def _check_checkpoint_weighted(meta, config: BatchJobConfig,
                               checkpoint_dir: str):
    """Refuse to resume a checkpoint under the other ingest mode —
    mixing counted and weighted rows in one accumulation would corrupt
    every blob. Checkpoints without the key are counted: they predate
    weighted checkpointing, which refused weighted+checkpoint outright,
    so treating the absence as counted=True keeps the refusal message
    (instead of a bare KeyError on the missing values array)."""
    ck = bool(meta.get("weighted", False))
    if ck != bool(config.weighted):
        raise RuntimeError(
            f"checkpoint at {checkpoint_dir!r} was written by a "
            f"{'weighted' if ck else 'counted'} job; resume with the "
            f"matching weighted setting or a fresh checkpoint dir"
        )


def _require_value_column(cols):
    """Shared guard for weighted string-path ingest: the source batch
    must carry a 'value' column."""
    if "value" not in cols:
        raise ValueError(
            "weighted job needs a 'value' column in the source "
            "(CSV/JSONL/Parquet column named 'value')"
        )


def _require_fast_weights(values):
    """Shared guard for weighted fast ingest: fast batches must carry a
    'value' column (HMPB with a value section)."""
    if values is None:
        raise ValueError(
            "weighted fast job needs a 'value' column in the fast "
            "batches (convert the source to HMPB from an input with a "
            "'value' column)"
        )


def _fast_batches_for(source, batch_size, checkpointing=False):
    """The run_job_fast input contract: CSV path -> native decoder,
    else an object with ``fast_batches``."""
    if isinstance(source, str):
        try:
            from heatmap_tpu.native import parse_csv_batches
        except ImportError as e:
            raise RuntimeError(
                "run_job_fast on a CSV path needs the native decoder "
                "(native/ build failed or disabled); use "
                "run_job(CSVSource(path)) instead"
            ) from e
        return parse_csv_batches(
            source, batch_size, fast=True,
            n_workers=1 if checkpointing else None,
        )
    if hasattr(source, "fast_batches"):
        return source.fast_batches(batch_size)
    raise TypeError(
        f"run_job_fast needs a CSV path or a fast-batch source "
        f"(got {type(source).__name__}); use run_job for generic "
        f"sources"
    )


def _resolve_backend(config: BatchJobConfig, n_emissions: int | None = None,
                     data_parallel: bool = False) -> str:
    """Resolve the cascade backend once per job and leave an audit
    trail: a ``backend_resolved`` event recording how ``"auto"`` routed
    (and why), plus the ``points_binned_total`` ingress counter when the
    emission count is known at resolution time. Pure pass-through of
    ``config.resolved_cascade_backend`` when telemetry is off.

    When the mesh engages (``data_parallel``), the event also carries
    ``dispatch`` — how the formulation knob resolved ("gspmd" vs
    "shard_map"), so dispatcher routing stays auditable alongside the
    kernel-backend decision.
    """
    resolved = config.resolved_cascade_backend
    if not obs.telemetry_enabled():
        return resolved
    if config.cascade_backend != "auto":
        reason = "explicit request"
    elif config.weighted:
        reason = ("weighted jobs stay on scatter (the bounded-integer "
                  "partitioned contract is an explicit opt-in)")
    elif resolved == "partitioned":
        reason = "count job on tpu -> partitioned MXU kernel"
    else:
        reason = "non-tpu platform -> xla scatter"
    if n_emissions is not None:
        obs.POINTS_BINNED.inc(int(n_emissions), backend=resolved)
    fields = {"requested": config.cascade_backend, "resolved": resolved,
              "reason": reason, "weighted": bool(config.weighted),
              "data_parallel": bool(data_parallel)}
    if data_parallel:
        fields["dispatch"] = config.resolved_dispatch
    if n_emissions is not None:
        fields["n_emissions"] = int(n_emissions)
    obs.emit("backend_resolved", **fields)
    return resolved


def _run_job_bounded(source, sink, config: BatchJobConfig,
                     batch_size: int, max_points: int,
                     overlap_ingest: bool = True, fast: bool = False,
                     spill_dir: str | None = None):
    """Chunked cascade with host-side per-level aggregate merge.

    Spark streams partitions through executors (reference
    heatmap.py:111-117); the analog here: chunks of at most
    ``max_points`` points run the full device cascade, and the decoded
    per-level (timespan, group, code) -> sum aggregates fold into one
    running table per level. UserVocab / TimespanVocab are shared
    across chunks so ids stay consistent; slot packing is re-derived
    from the FINAL vocab sizes at egress (per-chunk packing uses the
    chunk-local group count, which decode inverts exactly).

    ``overlap_ingest`` (the PP analog of SURVEY.md §2.3: the reference
    ran zoom stages strictly sequentially): a producer thread parses /
    group-routes the NEXT chunk while the device runs the cascade on
    the current one, double-buffered through a depth-1 queue. Chunk
    order — and therefore every vocab id and merge result — is
    identical to the sequential path; peak footprint grows to at most
    3 chunks (building + queued + in-cascade). Set False for the
    strict 1-chunk memory bound.

    ``spill_dir``: write per-chunk level aggregates to disk instead of
    folding them into an in-RAM table, merging one level at a time at
    egress (_SpillMerge) — for near-unique-output shapes where the
    merge table itself outgrows RAM. Byte-identical results.

    Without an explicit ``spill_dir`` the job still AUTO-SPILLS (to
    the system temp dir, or AUTO_SPILL_DIR) once the in-RAM table
    crosses AUTO_SPILL_ROWS: the running table converts to spill run 0
    and later chunks spill directly. Measured strictly better past
    that point (2.8x faster, -3.4 GB at 131M output rows — PERF_NOTES
    round 3); small-output jobs never touch disk, and a RAM-backed
    temp dir (tmpfs /tmp) disables auto-spill rather than fake the
    memory win (_auto_spill_target).
    """
    from heatmap_tpu.utils.trace import get_tracer

    if max_points < 1:
        raise ValueError(f"max_points_in_flight must be >= 1, got {max_points}")
    tracer = get_tracer()
    vocab = UserVocab()
    ts_vocab = TimespanVocab()
    ccfg = config.cascade_config()
    n_levels = ccfg.n_levels + 1
    empty = {
        "ts": np.empty(0, np.int64), "g": np.empty(0, np.int64),
        "code": np.empty(0, np.int64), "value": np.empty(0, np.float64),
    }
    merged = [dict(empty) for _ in range(n_levels)]
    spill = _SpillMerge(spill_dir, n_levels) if spill_dir is not None else None
    spill_runs = 0
    spill_is_auto = False
    # Candidate dir for automatic spill; None = RAM-backed temp (or
    # redirected off) -> keep the in-RAM fold, the pre-round-3 behavior.
    auto_spill_dir = _auto_spill_target() if spill is None else None
    # Spill-volume projection inputs (the free-space check at auto
    # conversion): total chunk count when the source size is estimable.
    est_points = _estimate_source_points(source)
    total_chunks_est = (
        None if est_points is None else -(-est_points // max_points)
    )
    chunks_done = 0
    max_chunk_rows = 0

    def chunks():
        """Sequential chunk builder: ingest batches, cut at max_points.

        ``fast`` consumes the integer fast-batch layout (native CSV
        decoder / HMPB mmap) routed through the shared _FastRouter;
        the string path goes through load_columns + vocab routing.
        Either way a chunk is (lat, lon, gids, stamps, weights) with
        stamps an i64 array (fast) or a Python list (string) —
        build_emissions' timespan labeler accepts both — and weights
        an f64 array for weighted jobs, None otherwise.
        """
        lats, lons, gids, stamps, vals = [], [], [], [], []
        pending = 0

        def cut():
            nonlocal pending
            chunk = (
                np.concatenate(lats),
                np.concatenate(lons),
                np.concatenate(gids).astype(np.int32),
                np.concatenate(stamps) if fast
                else [s for b in stamps for s in b],
                np.concatenate(vals) if config.weighted else None,
            )
            lats.clear(); lons.clear(); gids.clear(); stamps.clear()
            vals.clear()
            pending = 0
            return chunk

        if fast:
            router = _FastRouter(vocab)
            batches = _fast_batches_for(source, min(batch_size, max_points))
        else:
            batches = source.batches(min(batch_size, max_points))
        for batch in batches:
            with tracer.span("ingest.batch"):
                if fast:
                    router.observe(batch)
                    lat, lon, g, ts, v = router.route(batch)
                    if config.weighted:
                        _require_fast_weights(v)
                else:
                    cols = load_columns(batch)
                    lat = cols["latitude"]
                    lon = cols["longitude"]
                    g = vocab.group_ids(cols["user_id"])
                    ts = cols["timestamp"]
                    v = cols.get("value")
                    if config.weighted and v is None:
                        _require_value_column(cols)
                m = len(lat)
                # Cut BEFORE appending when the batch would overshoot,
                # so a chunk never exceeds max_points (batches are read
                # at most max_points long).
                if pending and pending + m > max_points:
                    yield cut()
                lats.append(lat)
                lons.append(lon)
                gids.append(g)
                stamps.append(ts)
                if config.weighted:
                    vals.append(v)
                pending += m
            tracer.add_items("ingest.batch", m)
            if pending >= max_points:
                yield cut()
        if pending:
            yield cut()

    dp_mesh = _dp_mesh(config)
    # Resolved ONCE for the whole job (the property probes jax.devices()
    # on every read) and audited via backend_resolved; per-chunk
    # dispatch details land in cascade_dispatch events.
    resolved_backend = _resolve_backend(
        config, data_parallel=dp_mesh is not None)

    def process(chunk):
        lat, lon, group_ids, flat_stamps, weights = chunk
        with tracer.span("cascade.chunk", items=len(lat),
                         backend=resolved_backend):
            import jax.numpy as jnp

            codes, valid = _cascade_codes(lat, lon, config.detail_zoom)
            e_codes, e_slots, e_valid, _, n_groups, e_weights = (
                build_emissions(
                    codes, valid, group_ids, flat_stamps, config,
                    ts_vocab=ts_vocab, weights=weights,
                )
            )
            if obs.metrics_enabled():
                obs.POINTS_BINNED.inc(int(len(e_codes)),
                                      backend=resolved_backend)
            # jit=False: chunk emission shapes (and sometimes
            # n_slots) vary call to call on the bounded path, so the
            # jitted entry would recompile the whole cascade per chunk.
            level_data = cascade_mod.run_cascade(
                e_codes, e_slots, ccfg,
                n_slots=len(ts_vocab) * n_groups,
                valid=e_valid,
                capacity=min(config.capacity or len(e_codes), len(e_codes)),
                weights=e_weights,
                acc_dtype=jnp.float64 if e_weights is not None else None,
                adaptive=config.adaptive_capacity,
                jit=False,
                backend=resolved_backend,
                mesh=_dp_mesh_for(dp_mesh, config, len(e_codes)),
                merge=config.dp_merge,
                weight_bound=config.weight_bound,
            )
            levels = cascade_mod.decode_levels(level_data, ccfg)
        with tracer.span("merge.chunk"):
            nonlocal spill, spill_runs, spill_is_auto, auto_spill_dir
            nonlocal chunks_done, max_chunk_rows
            chunks_done += 1
            max_chunk_rows = max(
                max_chunk_rows, sum(len(lvl["code"]) for lvl in levels)
            )
            if spill is not None:
                failed_level = None
                try:
                    for i, lvl in enumerate(levels):
                        failed_level = i
                        spill.add_level(
                            spill_runs, i, lvl["slot"] // n_groups,
                            lvl["slot"] % n_groups, lvl["code"],
                            lvl["value"],
                        )
                except OSError as e:
                    if not spill_is_auto:
                        raise  # explicit merge_spill_dir: operator's call
                    # AUTO spill hit a disk error (ENOSPC and kin) on a
                    # job the in-RAM fold might still finish: fold every
                    # spilled run — plus this chunk's unwritten levels —
                    # back into RAM and carry on diskless. Run order is
                    # preserved, so results stay byte-identical to the
                    # never-spilled fold.
                    import warnings

                    # The level that raised may have all four files
                    # PRESENT but the last one truncated (ENOSPC mid
                    # np.save) — existence is not completeness there,
                    # so drop its files outright and re-merge it from
                    # the in-memory chunk data.
                    spill.discard_level(spill_runs, failed_level)
                    written = spill.complete_levels(spill_runs)
                    written.discard(failed_level)
                    for i in range(n_levels):
                        base = spill.merge_level(i, spill_runs + 1)
                        if i not in written:
                            lvl = levels[i]
                            base = _merge_sorted_level(
                                base, lvl["slot"] // n_groups,
                                lvl["slot"] % n_groups, lvl["code"],
                                lvl["value"],
                            )
                        merged[i] = base
                    spill.cleanup()
                    spill = None
                    spill_is_auto = False
                    auto_spill_dir = None
                    warnings.warn(
                        f"auto-spill write failed ({e}); folded spilled "
                        "runs back into RAM and continuing without disk "
                        "(set TMPDIR/AUTO_SPILL_DIR to a larger "
                        "filesystem to re-enable)",
                        RuntimeWarning, stacklevel=2,
                    )
                else:
                    spill_runs += 1
                return
            for i, lvl in enumerate(levels):
                merged[i] = _merge_sorted_level(
                    merged[i], lvl["slot"] // n_groups,
                    lvl["slot"] % n_groups, lvl["code"], lvl["value"],
                )
            table_rows = sum(len(m["code"]) for m in merged)
            if auto_spill_dir is not None and table_rows > AUTO_SPILL_ROWS:
                # The in-RAM fold re-scans this whole table every chunk
                # — past this size the disk-spill merge is strictly
                # better (measured 2.8x faster and -3.4 GB, PERF_NOTES
                # round 3). Convert the accumulated table to spill run
                # 0; later chunks spill directly. Run order preserves
                # chunk-order summation, so results stay byte-identical.
                # But only onto a filesystem the projected volume fits
                # (ADVICE r3: a small disk-backed /tmp must not ENOSPC
                # a job that completed fully in RAM before auto-spill
                # existed); refusal and write failure both fall back to
                # the in-RAM fold with a warning.
                import warnings

                if not _auto_spill_projection_fits(
                        auto_spill_dir, table_rows, chunks_done,
                        total_chunks_est, max_chunk_rows):
                    warnings.warn(
                        f"auto-spill skipped: projected spill volume "
                        f"does not fit {auto_spill_dir!r}; keeping the "
                        "in-RAM merge (set TMPDIR/AUTO_SPILL_DIR to a "
                        "larger filesystem, or pass merge_spill_dir)",
                        RuntimeWarning, stacklevel=2,
                    )
                    auto_spill_dir = None
                    return
                # Construction (makedirs + mkdtemp) can itself raise on
                # a full or unwritable filesystem — that too must fall
                # back to the in-RAM fold, not fail the job.
                converting = None
                try:
                    converting = _SpillMerge(auto_spill_dir, n_levels)
                    for i, m in enumerate(merged):
                        converting.add_level(
                            0, i, m["ts"], m["g"], m["code"], m["value"]
                        )
                except OSError as e:
                    if converting is not None:
                        converting.cleanup()
                    auto_spill_dir = None
                    warnings.warn(
                        f"auto-spill conversion failed ({e}); keeping "
                        "the in-RAM merge",
                        RuntimeWarning, stacklevel=2,
                    )
                else:
                    spill = converting
                    spill_is_auto = True
                    for i in range(n_levels):
                        merged[i] = dict(empty)
                    spill_runs = 1

    # Any failure between the first spilled run and egress must still
    # remove the spill tempdir (tens of GB at the shapes spill
    # targets), so ingest runs under the same cleanup as egress.
    try:
        if not overlap_ingest:
            for chunk in chunks():
                process(chunk)
        else:
            # Double-buffer through the shared host->device feeder
            # (pipeline/feeder.py): the worker thread builds chunk N+1
            # (source IO, parsing, group routing) AND device-feeds its
            # numeric columns while this thread runs chunk N's cascade
            # + merge. Depth-1 queue keeps the same peak-footprint
            # bound as the old host-only prefetch (at most 3 chunks:
            # building + queued + in-cascade); chunk ORDER — and
            # therefore every vocab id and merge result — is identical
            # to the sequential path.
            from heatmap_tpu.pipeline import feeder as feeder_mod

            def feed_chunk(chunk):
                if not jax.config.jax_enable_x64:
                    return chunk  # device_put would downcast (feeder.py)
                lat, lon, g, ts, v = chunk
                return (jax.device_put(lat), jax.device_put(lon), g, ts,
                        None if v is None else jax.device_put(v))

            for item in feeder_mod.feed(chunks(), feed_chunk, depth=1,
                                        thread_name="ingest-prefetch"):
                process(item)
    except BaseException:
        if spill is not None:
            spill.cleanup()
        raise

    # Egress: re-pack slots with the complete vocabs, then the shared
    # finalize + blob path.
    n_groups = len(vocab)
    slot_names = _slot_names(vocab, ts_vocab, n_groups)

    def assemble(m, i):
        rows, cols_ = morton.morton_decode_np(m["code"])
        return {
            "zoom": ccfg.detail_zoom - i,
            "slot": m["ts"] * n_groups + m["g"],
            "code": m["code"],
            "row": rows,
            "col": cols_,
            "value": m["value"],
        }

    try:
        if spill is not None:
            if spill.rows_spilled == 0:
                return {}
            if not config.amplify_all:
                # True one-level-at-a-time egress: merge, finalize and
                # write each level before touching the next — peak is
                # O(chunk + largest single level). Blob ids never
                # collide across levels (the coarse zoom is part of
                # the id) and sinks upsert per blob / per level, so
                # per-level _finish_blobs calls compose exactly.
                out = None
                for i in range(n_levels):
                    part = _finish_blobs(
                        [assemble(spill.merge_level(i, spill_runs), i)],
                        ccfg, slot_names, as_json=True, sink=sink,
                    )
                    if (isinstance(part, dict)
                            and part.get("egress") == "levels"):
                        if out is None:
                            out = {"egress": "levels", "levels": 0,
                                   "rows": 0}
                        out["levels"] += part["levels"]
                        out["rows"] += part["rows"]
                    else:
                        if out is None:
                            out = {}
                        out.update(part)
                return {} if out is None else out
            # amplify_all's cross-level recurrence needs every level in
            # hand (cascade._patch_amplified); materialize the merged
            # levels once, like the unbounded path.
            merged = [spill.merge_level(i, spill_runs)
                      for i in range(n_levels)]
        elif all(len(m["code"]) == 0 for m in merged):
            return {}

        return _finish_blobs(
            [assemble(m, i) for i, m in enumerate(merged)],
            ccfg, slot_names, as_json=True, sink=sink,
        )
    finally:
        if spill is not None:
            spill.cleanup()


class _SpillMerge:
    """Disk-backed cross-chunk merge for the bounded path.

    The in-RAM merge table is O(unique output keys) — the one bound
    ``max_points_in_flight`` cannot give (PERF_NOTES memory model);
    near-unique-output shapes (output ~= input) made it 12 GB RSS at
    20M adversarial points. Spilling instead writes each chunk's
    decoded level aggregates as flat column files (24 B/row:
    int32 ts/g + int64 code + f64 value) and aggregates ONE LEVEL AT A
    TIME at egress via mmap-concat + one stable sort + reduceat, so
    peak host memory is O(chunk + largest single level) instead of
    O(all levels' uniques + merge temporaries) — except under
    ``amplify_all``, whose cross-level recurrence forces all merged
    levels resident at egress (ingest-time memory is still O(chunk)).
    Values sum in chunk order per key — byte-identical to the
    iterative two-run merge.
    The reference analog is Spark's shuffle spill to local disk
    (reference submit-heatmap:14, spark.local.dir).
    """

    def __init__(self, root: str, n_levels: int):
        import tempfile

        os.makedirs(root, exist_ok=True)
        self.dir = tempfile.mkdtemp(prefix="merge-spill-", dir=root)
        self.n_levels = n_levels
        self.rows_spilled = 0

    def _base(self, run: int, level: int) -> str:
        return os.path.join(self.dir, f"run{run:05d}_l{level:02d}")

    def add_level(self, run: int, level: int, ts, g, code, value) -> None:
        if len(code) == 0:
            return  # empty runs simply have no files
        base = self._base(run, level)
        np.save(base + "_ts.npy", np.asarray(ts, np.int32))
        np.save(base + "_g.npy", np.asarray(g, np.int32))
        np.save(base + "_code.npy", np.asarray(code, np.int64))
        np.save(base + "_value.npy", np.asarray(value, np.float64))
        self.rows_spilled += len(code)

    def discard_level(self, run: int, level: int | None) -> None:
        """Remove whatever ``(run, level)`` files exist — a save that
        raised may have left the LAST file truncated-but-present, so
        the failing level must be dropped by name, not by existence."""
        if level is None:
            return
        base = self._base(run, level)
        for name in ("ts", "g", "code", "value"):
            try:
                os.remove(f"{base}_{name}.npy")
            except OSError:
                pass

    def complete_levels(self, run: int) -> set:
        """Levels of ``run`` whose four column files all exist.

        A save that died mid-write (ENOSPC) leaves a partial file set;
        partial levels are DELETED here so a later merge_level never
        reads a half-written run (it keys existence off _code.npy,
        which may exist while _value.npy does not). Used by the
        auto-spill write-failure recovery in _run_job_bounded.
        """
        done = set()
        for level in range(self.n_levels):
            base = self._base(run, level)
            paths = [f"{base}_{name}.npy"
                     for name in ("ts", "g", "code", "value")]
            present = [p for p in paths if os.path.exists(p)]
            if len(present) == len(paths):
                done.add(level)
            else:
                for p in present:
                    try:
                        os.remove(p)
                    except OSError:
                        pass
        return done

    def merge_level(self, level: int, n_runs: int) -> dict:
        cols = {"ts": [], "g": [], "code": [], "value": []}
        for run in range(n_runs):
            base = self._base(run, level)
            if not os.path.exists(base + "_code.npy"):
                continue
            for name in cols:
                cols[name].append(
                    np.load(f"{base}_{name}.npy", mmap_mode="r")
                )
        if not cols["code"]:
            return {
                "ts": np.empty(0, np.int64), "g": np.empty(0, np.int64),
                "code": np.empty(0, np.int64),
                "value": np.empty(0, np.float64),
            }
        ts = np.concatenate(cols["ts"]).astype(np.int64)
        g = np.concatenate(cols["g"]).astype(np.int64)
        code = np.concatenate(cols["code"])
        value = np.concatenate(cols["value"])
        return _aggregate_runs(ts, g, code, value)

    def cleanup(self) -> None:
        import shutil

        shutil.rmtree(self.dir, ignore_errors=True)


def _aggregate_runs(ts, g, code, value) -> dict:
    """Sum values over equal (ts, g, code) keys across concatenated
    runs; output sorted by (ts, g, code). Stable sort keeps run order
    within a key, so f64 sums accumulate in chunk order — the same
    order as the iterative _merge_sorted_level fold."""
    pack = _level_key_packer(ts, g, code)
    if pack is not None:
        order = np.argsort(pack(ts, g, code), kind="stable")
    else:  # pathological widths: correct but slower full sort
        order = np.lexsort((code, g, ts))
    ts, g, code, value = ts[order], g[order], code[order], value[order]
    first = np.empty(len(code), bool)
    first[:1] = True
    first[1:] = (ts[1:] != ts[:-1]) | (g[1:] != g[:-1]) \
        | (code[1:] != code[:-1])
    starts = np.flatnonzero(first)
    return {
        "ts": ts[starts],
        "g": g[starts],
        "code": code[starts],
        "value": np.add.reduceat(value, starts) if len(starts)
        else value[:0],
    }


def _level_key_packer(ts, g, code):
    """Closure packing (ts, g, code) rows into ONE comparable int64 —
    field widths taken from THESE arrays (pass the union of everything
    you will pack) — or None when the widths don't fit 62 bits (the
    cascade's own composite keys already prove slot<<code_bits fits;
    the global G here can only be larger by the vocab tail, so guard).
    Single source of truth for the merge paths: the spill merge's
    byte-identical-to-in-RAM guarantee rests on both using THIS key
    order."""
    code_bits = int(code.max(initial=0)).bit_length()
    gmax = int(g.max(initial=0)) + 1
    tmax = int(ts.max(initial=0)) + 1
    if code_bits + (gmax * tmax).bit_length() >= 62:
        return None

    def pack(t_, g_, c_):
        # int64 up front: ts/g arrive int32 off the native key
        # decoder, and << code_bits (up to 42 at z21) would silently
        # wrap in int32 — unsorted pack keys then corrupt the merges.
        return ((t_.astype(np.int64) * gmax + g_) << code_bits) | c_

    return pack


def _merge_sorted_level(m, ts2, g2, code2, value2):
    """Fold one chunk's level aggregates into the running table.

    Both sides arrive sorted by (ts, g, code): the running table is the
    previous merge's output, and decode_levels emits ascending
    composite-key order, which for slot = ts*G + g (g < G) IS the
    (ts, g, code) lexicographic order. That makes this a two-sorted-run
    merge — O(K log K) binary searches, not a full re-sort of the
    accumulated table per chunk. Equal keys dedupe by summing.
    """
    ts = np.concatenate([m["ts"], ts2])
    g = np.concatenate([m["g"], g2])
    code = np.concatenate([m["code"], code2])
    value = np.concatenate([m["value"], value2])
    if len(code) == 0:
        return m
    pack = _level_key_packer(ts, g, code)
    if pack is not None:
        pa = pack(m["ts"], m["g"], m["code"])
        pb = pack(ts2, g2, code2)
        if len(pa) and len(pb):
            pos_a = np.arange(len(pa)) + np.searchsorted(pb, pa, side="left")
            pos_b = np.arange(len(pb)) + np.searchsorted(pa, pb, side="right")
            order = np.empty(len(pa) + len(pb), np.int64)
            order[pos_a] = np.arange(len(pa))
            order[pos_b] = len(pa) + np.arange(len(pb))
        else:
            order = np.arange(len(code))
    else:  # pathological widths: correct but slower full sort
        order = np.lexsort((code, g, ts))
    ts, g, code, value = ts[order], g[order], code[order], value[order]
    new = np.concatenate([[True],
                          (ts[1:] != ts[:-1]) | (g[1:] != g[:-1])
                          | (code[1:] != code[:-1])])
    seg = np.cumsum(new) - 1
    keep = np.flatnonzero(new)
    return {
        "ts": ts[keep], "g": g[keep], "code": code[keep],
        "value": np.bincount(seg, weights=value),
    }


def _slot_names(vocab, ts_vocab, n_groups):
    """slot id -> (user name, timespan label) table shared by every
    egress path (slot = timespan*G + group)."""
    return {
        t * n_groups + g: (vocab.name_for(g), ts_vocab.label_for(t))
        for t in range(len(ts_vocab))
        for g in range(n_groups)
    }


def _finish_blobs(decoded_levels, ccfg, slot_names, as_json, sink=None):
    """Shared egress tail: finalize decoded levels, then either stream
    columns into a columnar sink (anything with ``write_levels``, e.g.
    io.sinks.LevelArraysSink — no per-blob Python objects at all) or
    build reference-format blobs and upsert them into ``sink``.

    Returns the blob dict on the blob path; on the columnar path a
    small stats dict ``{"egress": "levels", "levels": n, "rows": n}``
    (materializing 100M blob dicts just to return them would defeat
    the columnar sink's point).
    """
    from heatmap_tpu.utils.trace import get_tracer

    tracer = get_tracer()
    with tracer.span("egress.finalize"):
        finalized = cascade_mod.finalize_level_arrays(
            decoded_levels, ccfg, slot_names
        )
    if sink is not None and hasattr(sink, "write_levels"):
        with tracer.span("egress"):
            rows = sink.write_levels(finalized)
        return {"egress": "levels", "levels": len(finalized), "rows": rows}
    with tracer.span("egress.blobs"):
        if as_json:
            # Vectorized direct-to-JSON egress: no per-aggregate dicts
            # and no per-blob json.dumps (the dict assembly dominated
            # large jobs ~10:1 over the device cascade).
            blobs = cascade_mod.json_blobs_from_level_arrays(finalized)
        else:
            blobs = cascade_mod.blobs_from_level_arrays(finalized)
    if sink is not None:
        with tracer.span("egress"):
            sink.write(blobs.items())
    return blobs


def run_job_fast(source, sink=None, config: BatchJobConfig | None = None,
                 batch_size: int = 1 << 20,
                 checkpoint_dir: str | None = None,
                 checkpoint_every: int = 8,
                 fault_injector=None,
                 max_points_in_flight: int | None = None,
                 overlap_ingest: bool = True,
                 merge_spill_dir: str | None = None):
    """Integer-fast-path job: no per-row Python objects anywhere.

    ``source`` is a CSV path (the native C++ decoder parses, routes
    user ids per reference heatmap.py:64-70 and flags background rows
    per heatmap.py:28-29 in its reader threads) or any object with a
    ``fast_batches(batch_size)`` method (io.hmpb.HMPBSource memory-maps
    pre-routed columns). This side only maps the small routed-name
    table into the UserVocab (O(unique users), not O(rows)) and
    filters with numpy masks. Same blobs as the string path.

    Dated timespans work here: fast batches carry an i64 epoch-ms
    ``timestamp`` column (TS_MISSING sentinel), which the factorized
    unique-day labeler consumes without per-row Python; a sentinel row
    under a dated timespan raises exactly like timestamp=None does on
    the string path.

    ``checkpoint_dir`` enables checkpoint/resume with
    run_job_resumable's semantics: ingest progress is checkpointed
    every ``checkpoint_every`` batches, a rerun skips the row-work of
    already-checkpointed batches (the reader still streams them for its
    intern table). Resume-by-batch-index requires a deterministic batch
    order, so checkpointing forces the native CSV reader to a single
    worker (parallel byte-range parsing reorders batches run to run);
    HMPB batches are always in file order.

    ``max_points_in_flight`` bounds peak memory exactly like run_job's
    knob — the cascade runs per chunk of at most that many points with
    fast-path ingest, per-level aggregates merged host-side (the
    BASELINE config-5 shape with mmap/native ingest). Mutually
    exclusive with ``checkpoint_dir`` (chunk boundaries are not batch
    boundaries, so batch-index resume would not line up).

    ``max_points_in_flight=None`` auto-routes oversized sources to the
    bounded path exactly like run_job (same heuristic; ``0`` forces
    single-shot) — unless checkpointing or fault injection is
    configured, which are bounded-path-incompatible and keep the
    operator's explicit choice.
    """
    config = config or BatchJobConfig()
    if checkpoint_every < 1:
        raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
    if (max_points_in_flight is None and checkpoint_dir is None
            and fault_injector is None):
        max_points_in_flight = _auto_points_in_flight(
            source, fast=True,
            n_timespans=(1 if config.first_timespan_only
                         else len(config.timespans)),
            weighted=config.weighted,
        )
    if merge_spill_dir is not None and not max_points_in_flight:
        raise ValueError(
            "merge_spill_dir lives on the bounded path, but this job "
            "routed single-shot; pass max_points_in_flight > 0 to "
            "chunk (see run_job)"
        )
    if max_points_in_flight:  # 0/None -> single-shot
        if checkpoint_dir is not None:
            raise ValueError(
                "max_points_in_flight and checkpoint_dir are mutually "
                "exclusive on the fast path"
            )
        if fault_injector is not None:
            # Silently accepting-and-ignoring the injector would make a
            # recovery test pass without exercising anything.
            raise ValueError(
                "fault_injector is not supported with "
                "max_points_in_flight (no batch-index resume on the "
                "chunked path)"
            )
        return _run_job_bounded(
            source, sink, config, batch_size, max_points_in_flight,
            overlap_ingest=overlap_ingest, fast=True,
            spill_dir=merge_spill_dir,
        )
    from heatmap_tpu.utils.trace import get_tracer

    def make_batches():
        return _fast_batches_for(
            source, batch_size, checkpointing=checkpoint_dir is not None
        )

    vocab = UserVocab()
    router = _FastRouter(vocab)
    tracer = get_tracer()
    lats, lons, gids, tss, vals = [], [], [], [], []
    mgr = None
    done = 0
    if checkpoint_dir is not None:
        from heatmap_tpu.utils import CheckpointManager

        mgr = CheckpointManager(checkpoint_dir)
        if mgr.latest_step() is not None:
            arrays, meta = mgr.load()
            # Batch indices only mean the same rows under the reader
            # that wrote them — refuse checkpoints from the string path
            # (run_job_resumable) instead of resuming into corruption.
            kind = meta.get("job_path", "string")
            if kind != "fast":
                raise RuntimeError(
                    f"checkpoint at {checkpoint_dir!r} was written by the "
                    f"{kind!r} job path; resume it with the same path "
                    "(run_job_resumable / drop --fast) or point --fast at "
                    "a fresh checkpoint dir"
                )
            _check_checkpoint_weighted(meta, config, checkpoint_dir)
            lats = [arrays["latitude"]]
            lons = [arrays["longitude"]]
            gids = [arrays["group_ids"]]
            tss = [arrays["timestamps_ms"]]
            if config.weighted:
                vals = [arrays["values"]]
            for name in meta["group_names"][1:]:  # [0] is always 'all'
                vocab.id_for(name)
            done = meta["batches_done"]

    def checkpoint(step):
        arrays = {
            "latitude": np.concatenate(lats) if lats else np.empty(0),
            "longitude": np.concatenate(lons) if lons else np.empty(0),
            "group_ids": (
                np.concatenate(gids) if gids else np.empty(0, np.int32)
            ),
            "timestamps_ms": (
                np.concatenate(tss) if tss else np.empty(0, np.int64)
            ),
        }
        if config.weighted:
            arrays["values"] = np.concatenate(vals) if vals else np.empty(0)
        mgr.save(step, arrays, {
            "group_names": list(vocab.names),
            "batches_done": step,
            "job_path": "fast",
            "weighted": config.weighted,
        })
        # Collapse accumulated chunks so later checkpoints don't recopy
        # a growing list-of-arrays.
        lats[:] = [arrays["latitude"]]
        lons[:] = [arrays["longitude"]]
        gids[:] = [arrays["group_ids"]]
        tss[:] = [arrays["timestamps_ms"]]
        if config.weighted:
            vals[:] = [arrays["values"]]

    with tracer.span("ingest.fast"):
        for i, b in enumerate(make_batches()):
            # The intern table must grow even for skipped batches: a
            # post-resume batch may reference reader ids first named
            # before the checkpoint. (id_for inside route() is
            # get-or-create, so names restored from a checkpoint keep
            # their original ids on resume.)
            router.observe(b)
            if i < done:
                continue  # rows already checkpointed on a previous run
            if fault_injector is not None:
                fault_injector.check(i)
            tracer.add_items("ingest.fast", len(b["latitude"]))
            lat, lon, g, ts64, v = router.route(b)
            if config.weighted:
                _require_fast_weights(v)
                vals.append(v)
            lats.append(lat)
            lons.append(lon)
            gids.append(g)
            tss.append(ts64)
            done = i + 1
            if mgr is not None and done % checkpoint_every == 0:
                with tracer.span("checkpoint"):
                    checkpoint(done)
    if not lats or sum(len(a) for a in lats) == 0:
        return {}
    lat = np.concatenate(lats)
    with tracer.span("cascade", items=len(lat)):
        blobs = _run_grouped(
            lat,
            np.concatenate(lons),
            np.concatenate(gids),
            np.concatenate(tss),
            vocab,
            config,
            as_json=True,
            sink=sink,
            weights=np.concatenate(vals) if config.weighted else None,
        )
    return blobs


def run_job_resumable(source, checkpoint_dir: str, sink=None,
                      config: BatchJobConfig | None = None,
                      batch_size: int = 1 << 20,
                      checkpoint_every: int = 8,
                      fault_injector=None):
    """``run_job`` with checkpoint/resume over source batches.

    The reference recomputes everything from Cassandra on any failure
    (no checkpointing anywhere, SURVEY.md §5). Here ingest progress is
    checkpointed every ``checkpoint_every`` batches (atomic npz via
    utils.checkpoint); a rerun with the same source/batch_size resumes
    after the last checkpointed batch. The source is still *streamed*
    from the start on resume — pre-checkpoint batches are read and
    discarded; what's skipped is the load_columns/vocab/accumulation
    work and, on the earlier run, everything after the checkpoint.
    Sources must iterate deterministically for resume to be exact — every
    built-in source does (files byte-ordered, synthetic seeded).

    ``fault_injector`` (utils.recovery.FaultInjector) fails chosen
    batch indices for recovery testing.
    """
    from heatmap_tpu.utils import CheckpointManager
    from heatmap_tpu.utils.trace import get_tracer

    config = config or BatchJobConfig()
    if checkpoint_every < 1:
        raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
    tracer = get_tracer()
    mgr = CheckpointManager(checkpoint_dir)
    vocab = UserVocab()
    lats, lons, gids, stamps, vals = [], [], [], [], []
    done = 0
    if mgr.latest_step() is not None:
        arrays, meta = mgr.load()
        kind = meta.get("job_path", "string")
        if kind != "string":
            raise RuntimeError(
                f"checkpoint at {checkpoint_dir!r} was written by the "
                f"{kind!r} job path; resume it with run_job_fast "
                "(--fast) or point this run at a fresh checkpoint dir"
            )
        _check_checkpoint_weighted(meta, config, checkpoint_dir)
        lats, lons = [arrays["latitude"]], [arrays["longitude"]]
        gids = [arrays["group_ids"]]
        if config.weighted:
            vals = [arrays["values"]]
        if "timestamps_ms" in arrays:
            from heatmap_tpu.pipeline.timespan import TS_MISSING

            stamps = [[None if t == TS_MISSING else int(t)
                       for t in arrays["timestamps_ms"]]]
        elif "timestamps_str" in arrays:
            if "timestamps_valid" in arrays:
                stamps = [[s if v else None
                           for s, v in zip(arrays["timestamps_str"],
                                           arrays["timestamps_valid"])]]
            else:
                stamps = [list(arrays["timestamps_str"])]
        else:
            stamps = [[None] * len(arrays["latitude"])]
        for name in meta["group_names"][1:]:  # [0] is always 'all'
            vocab.id_for(name)
        done = meta["batches_done"]

    def checkpoint(step):
        lat = np.concatenate(lats) if lats else np.empty(0)
        arrays = {
            "latitude": lat,
            "longitude": np.concatenate(lons) if lons else np.empty(0),
            "group_ids": np.concatenate(gids) if gids else np.empty(0, np.int32),
        }
        if config.weighted:
            arrays["values"] = (
                np.concatenate(vals) if vals else np.empty(0)
            )
        flat_stamps = [s for chunk in stamps for s in chunk]
        if flat_stamps and any(s is not None for s in flat_stamps):
            # Mixed None/real streams must round-trip: None persists as
            # the TS_MISSING int64 sentinel (or a validity mask on the
            # string path), never by dropping the whole column — a
            # resumed run has to bucket dated timespans exactly like an
            # uninterrupted one.
            from heatmap_tpu.pipeline.timespan import TS_MISSING

            valid = np.asarray([s is not None for s in flat_stamps], bool)
            present = [s for s in flat_stamps if s is not None]
            try:
                ms_present = np.asarray(present, np.int64)
            except (ValueError, TypeError):
                # datetime/date objects: epoch-ms round-trips through
                # timespan._to_date (UTC). Anything else keeps its
                # string form — resumes behave exactly like the
                # original run would have (float()-able strings work,
                # junk raises in _to_date either way).
                import datetime as _dt

                def to_ms(s):
                    if isinstance(s, _dt.datetime):
                        if s.tzinfo is None:
                            s = s.replace(tzinfo=_dt.timezone.utc)
                        return int(s.timestamp() * 1000)
                    if isinstance(s, _dt.date):
                        return int(_dt.datetime(
                            s.year, s.month, s.day,
                            tzinfo=_dt.timezone.utc,
                        ).timestamp() * 1000)
                    return None

                ms = [to_ms(s) for s in present]
                ms_present = (
                    np.asarray(ms, np.int64)
                    if all(m is not None for m in ms) else None
                )
            if ms_present is not None:
                full = np.full(len(flat_stamps), TS_MISSING, np.int64)
                full[valid] = ms_present
                arrays["timestamps_ms"] = full
            else:
                arrays["timestamps_str"] = np.asarray(
                    ["" if s is None else str(s) for s in flat_stamps]
                )
                arrays["timestamps_valid"] = valid
        mgr.save(step, arrays, {
            "group_names": list(vocab.names),
            "batches_done": step,
            "job_path": "string",
            "weighted": config.weighted,
        })
        # Collapse accumulated chunks so later checkpoints don't recopy
        # a growing list-of-arrays.
        lats[:] = [arrays["latitude"]]
        lons[:] = [arrays["longitude"]]
        gids[:] = [arrays["group_ids"]]
        stamps[:] = [flat_stamps]
        if config.weighted:
            vals[:] = [arrays["values"]]

    for i, batch in enumerate(source.batches(batch_size)):
        if i < done:
            continue  # already checkpointed on a previous run
        if fault_injector is not None:
            fault_injector.check(i)
        with tracer.span("ingest.batch"):
            cols = load_columns(batch)
            lats.append(cols["latitude"])
            lons.append(cols["longitude"])
            gids.append(vocab.group_ids(cols["user_id"]))
            stamps.append(cols["timestamp"])
            if config.weighted:
                _require_value_column(cols)
                vals.append(cols["value"])
        tracer.add_items("ingest.batch", len(cols["latitude"]))
        done = i + 1
        if done % checkpoint_every == 0:
            with tracer.span("checkpoint"):
                checkpoint(done)
    if not lats or sum(len(a) for a in lats) == 0:
        return {}
    flat_stamps = [s for chunk in stamps for s in chunk]
    with tracer.span("cascade"):
        blobs = _run_grouped(
            np.concatenate(lats),
            np.concatenate(lons),
            np.concatenate(gids).astype(np.int32),
            flat_stamps,
            vocab,
            config,
            as_json=True,
            sink=sink,
            weights=np.concatenate(vals) if config.weighted else None,
        )
    return blobs


def run_batch(rows, config: BatchJobConfig | None = None, as_json: bool = False):
    """The full job: rows in, heatmap blobs out (reference batchMain).

    Returns {"user|timespan|coarseTileId": {detailTileId: count}} — or
    with ``as_json=True`` the inner dicts as JSON strings, matching the
    reference's (id, heatmap-json) output records
    (reference heatmap.py:156-157).
    """
    config = config or BatchJobConfig()
    data = load_rows(rows)
    if len(data["latitude"]) == 0:
        return {}
    return _run_loaded(data, config, as_json=as_json)


def _run_loaded(data, config: BatchJobConfig, as_json: bool, sink=None):
    vocab = UserVocab()
    group_ids = vocab.group_ids(data["user_id"])
    return _run_grouped(
        data["latitude"], data["longitude"], group_ids,
        data["timestamp"], vocab, config, as_json, sink=sink,
        weights=data.get("value") if config.weighted else None,
    )


def _run_grouped(lat, lon, group_ids, timestamps, vocab,
                 config: BatchJobConfig, as_json: bool, sink=None,
                 weights=None):
    from heatmap_tpu.utils.trace import get_tracer

    if config.weighted and weights is None:
        raise ValueError("config.weighted needs per-point weights "
                         "(a 'value' column in the source)")
    tracer = get_tracer()
    with tracer.span("cascade.project", items=len(lat)):
        codes, valid = _cascade_codes(lat, lon, config.detail_zoom)
    with tracer.span("cascade.emissions"):
        e_codes, e_slots, e_valid, ts_vocab, n_groups, e_weights = (
            build_emissions(
                codes, valid, group_ids, timestamps, config,
                weights=weights if config.weighted else None,
            )
        )
    n_slots = len(ts_vocab) * n_groups

    ccfg = config.cascade_config()
    if config.pad_bucketing != "exact":
        # Pad BEFORE backend/mesh routing so the auto-DP threshold and
        # shard math see the bucket length: routing then is a pure
        # function of the bucket, keeping the compile count bounded by
        # the bucket count rather than by routing crossovers.
        with tracer.span("cascade.bucket", items=len(e_codes)):
            target = bucketing_mod.bucket_size(
                len(e_codes), config.pad_bucketing, config.pad_bucket_min)
            e_codes, e_slots, e_valid, e_weights = (
                bucketing_mod.pad_emissions(
                    e_codes, e_slots, e_valid, e_weights, target))
            n_slots = bucketing_mod.bucket_slots(n_slots)
    mesh0 = _dp_mesh(config)
    plan = None
    if mesh0 is not None and config.spatial_partition != "off":
        from heatmap_tpu.parallel import partition as partition_mod
        from heatmap_tpu.parallel.sharded import _shard_axes

        _, ndev = _shard_axes(mesh0)
        threshold = (AUTO_DP_MIN_EMISSIONS
                     if config.dp_min_emissions is None
                     else config.dp_min_emissions)
        # "auto" plans only at real scale: below the DP threshold the
        # host-side routing pass would cost more than the boundary
        # merge saves (the same never-slow-down rule as auto-DP).
        # "morton" forces the plan whenever a mesh engages.
        if ndev >= 2 and (config.spatial_partition == "morton"
                          or len(e_codes) >= threshold):
            with tracer.span("cascade.partition_plan",
                             items=len(e_codes)):
                plan = partition_mod.plan_partition(
                    np.asarray(e_codes), ndev,
                    detail_zoom=config.detail_zoom,
                    valid=None if e_valid is None
                    else np.asarray(e_valid),
                    n_levels=config.cascade_config().n_levels)
    dp_mesh = _dp_mesh_for(mesh0, config, len(e_codes), plan=plan)
    if plan is not None and (dp_mesh is None or plan.degenerate):
        plan = None  # fallback recorded by _dp_mesh_for
    dispatch = config.resolved_dispatch if dp_mesh is not None else None
    timer = obs.DispatchTimer(dispatch or "single")
    if plan is not None and dispatch != "gspmd":
        # Host-side range routing (shard_map dispatch only — the gspmd
        # program routes ON-DEVICE against the traced splits, so its
        # emissions stay unrouted and this whole host scatter
        # disappears): scatter each emission into its owning shard's
        # contiguous segment (pad lanes valid=False), bucketing the
        # segment length so routed shapes reuse the bucketed compile
        # cache.
        with tracer.span("cascade.partition_route", items=len(e_codes)):
            bucket = None
            if config.pad_bucketing != "exact":
                def bucket(L):
                    return bucketing_mod.bucket_size(
                        L, config.pad_bucketing, config.pad_bucket_min)
            e_codes, e_slots, e_valid, e_weights, _seg = (
                partition_mod.route_emissions(
                    plan, e_codes, e_slots, e_valid, e_weights,
                    bucket=bucket))
    backend = _resolve_backend(config, n_emissions=len(e_codes),
                               data_parallel=dp_mesh is not None)
    with tracer.span("cascade.device", backend=backend):
        import jax.numpy as jnp

        from heatmap_tpu.utils.trace import stage_tracing_enabled

        acc_dtype = jnp.float64 if e_weights is not None else None
        capacity = config.capacity or len(e_codes)
        jit = not stage_tracing_enabled()
        if jit and not config.adaptive_capacity:
            # Mirror the jit cache key (shapes + every static arg of
            # _build_cascade_jit) so bucket hit/miss counters track
            # actual compiles without poking jax internals.
            bucketing_mod.note_dispatch(
                (
                    int(e_codes.shape[0]),
                    str(e_codes.dtype),
                    str(e_slots.dtype),
                    e_valid is not None,
                    None if e_weights is None else str(e_weights.dtype),
                    ccfg,
                    n_slots,
                    capacity,
                    None if acc_dtype is None else str(acc_dtype),
                    backend,
                    None if dp_mesh is None
                    else tuple(sorted(dp_mesh.shape.items())),
                    config.dp_merge,
                    config.weight_bound,
                    # Partition term: the range-sharded kernel is a
                    # distinct trace, but splits are TRACED, so every
                    # plan of the same shard count shares one compile.
                    None if plan is None else ("morton", len(plan.splits)),
                    # Dispatch term: the gspmd and shard_map programs
                    # are distinct traces of the same math.
                    dispatch,
                ),
                config.pad_bucketing,
            )
        partition_splits = (None if plan is None
                            else jnp.asarray(plan.splits, jnp.int64))
        levels = cascade_mod.run_cascade(
            e_codes,
            e_slots,
            ccfg,
            n_slots=n_slots,
            valid=e_valid,
            capacity=capacity,
            weights=e_weights,
            # Weighted sums accumulate in f64 (f32 would both round and
            # stop moving near 2^24-scale cell sums; counts use the
            # int32 path, SURVEY.md §8.8).
            acc_dtype=acc_dtype,
            adaptive=config.adaptive_capacity,
            backend=backend,
            mesh=dp_mesh,
            merge=config.dp_merge,
            weight_bound=config.weight_bound,
            partition_splits=partition_splits,
            dispatch=dispatch or "shard_map",
            # Stage tracing needs the cascade EAGER: under the fused jit
            # the sort/segment-reduce spans would time tracing, not
            # execution (utils/trace.py stage_span).
            jit=jit,
        )
        timer.dispatched()
        if timer.enabled:
            # Force execution so the host/device split measures the
            # program, not async dispatch latency.
            levels = jax.block_until_ready(levels)
        timer.finished(items=len(e_codes))
    with tracer.span("cascade.decode"):
        decoded = cascade_mod.decode_levels(levels, ccfg)
    return _finish_blobs(
        decoded,
        ccfg,
        _slot_names(vocab, ts_vocab, n_groups),
        as_json,
        sink=sink,
    )
