"""End-to-end heatmap pipeline: ingest -> group -> bin -> pyramid -> blobs.

Reproduces the full semantic surface of the reference job
(reference heatmap.py:batchMain, 152-158) on the TPU-native engine:

- ``groups``   — user-id routing rules (reference heatmap.py:64-70).
- ``timespan`` — timespan labels (reference heatmap.py:38-52), fully
  implemented (the reference's is dead code beyond "alltime").
- ``cascade``  — the 16-level zoom cascade and blob regrouping
  (reference heatmap.py:107-118), in correct-rollup mode and in a
  compat mode reproducing the reference's 'all'-amplification quirk.
- ``batch``    — orchestration equivalent to batchMain.
"""

from heatmap_tpu.pipeline.groups import (  # noqa: F401
    ALL_GROUP,
    UserVocab,
    route_user,
)
from heatmap_tpu.pipeline.timespan import timespan_label  # noqa: F401
from heatmap_tpu.pipeline.cascade import (  # noqa: F401
    CascadeConfig,
    build_cascade,
    run_cascade,
)
from heatmap_tpu.pipeline.batch import (  # noqa: F401
    BatchJobConfig,
    load_columns,
    run_batch,
    run_job,
    run_job_fast,
    run_job_resumable,
)
