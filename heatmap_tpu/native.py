"""ctypes bindings to the native runtime library (native/*.cpp).

The reference's ingest decoding and host memory management lived in
native/JVM code outside its repo (the spark-cassandra-connector JAR and
Spark's executor memory manager, reference Dockerfile:5,
submit-heatmap:14-15). Here they are in-repo C++:

- ``parse_csv_batches`` — threaded CSV point decoder with batch
  prefetch (native/pointcodec.cpp). Parsing of batch N+1 overlaps the
  caller's device work on batch N.
- ``StagingPool`` — bounded pool of page-aligned host buffers for
  host->device staging (native/staging.cpp).

The library auto-builds on first import (``make`` in native/) when a
toolchain is present; set ``HEATMAP_TPU_NO_NATIVE_BUILD=1`` to disable.
When the library is unavailable this module still imports, but the
accelerated names are absent — ``from heatmap_tpu.native import
parse_csv_batches`` raises ImportError, which callers (io.sources)
treat as "use the pure-Python path".
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_LIB_NAME = "libheatmap_native.so"


def _lib_candidates():
    env = os.environ.get("HEATMAP_TPU_NATIVE_LIB")
    if env:
        yield env
    yield os.path.join(_NATIVE_DIR, "build", _LIB_NAME)
    yield os.path.join(os.path.dirname(__file__), _LIB_NAME)


def build(quiet: bool = True) -> str | None:
    """Build the native library via make; returns its path or None."""
    if not os.path.isdir(_NATIVE_DIR):
        return None
    out = subprocess.DEVNULL if quiet else None
    try:
        rc = subprocess.call(["make", "-C", _NATIVE_DIR], stdout=out, stderr=out)
    except OSError:
        return None
    path = os.path.join(_NATIVE_DIR, "build", _LIB_NAME)
    return path if rc == 0 and os.path.exists(path) else None


def _load() -> ctypes.CDLL | None:
    for path in _lib_candidates():
        if os.path.exists(path):
            try:
                return ctypes.CDLL(path)
            except OSError:
                continue
    if os.environ.get("HEATMAP_TPU_NO_NATIVE_BUILD"):
        return None
    path = build()
    if path:
        try:
            return ctypes.CDLL(path)
        except OSError:
            return None
    return None


_lib = _load()

if _lib is not None:
    _lib.hm_csv_open.restype = ctypes.c_void_p
    _lib.hm_csv_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int,
    ]
    _lib.hm_csv_peek.restype = ctypes.c_int64
    _lib.hm_csv_peek.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    _lib.hm_csv_take.restype = ctypes.c_int
    _lib.hm_csv_take.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_char_p,
    ]
    _lib.hm_csv_error.restype = ctypes.c_char_p
    _lib.hm_csv_error.argtypes = [ctypes.c_void_p]
    _lib.hm_csv_close.restype = None
    _lib.hm_csv_close.argtypes = [ctypes.c_void_p]
    _lib.hm_ts_missing.restype = ctypes.c_int64

    _lib.hm_pool_create.restype = ctypes.c_void_p
    _lib.hm_pool_create.argtypes = [ctypes.c_int64, ctypes.c_int]
    _lib.hm_pool_acquire.restype = ctypes.c_int
    _lib.hm_pool_acquire.argtypes = [ctypes.c_void_p]
    _lib.hm_pool_try_acquire.restype = ctypes.c_int
    _lib.hm_pool_try_acquire.argtypes = [ctypes.c_void_p]
    _lib.hm_pool_release.restype = None
    _lib.hm_pool_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
    _lib.hm_pool_buffer.restype = ctypes.c_void_p
    _lib.hm_pool_buffer.argtypes = [ctypes.c_void_p, ctypes.c_int]
    _lib.hm_pool_buf_bytes.restype = ctypes.c_int64
    _lib.hm_pool_buf_bytes.argtypes = [ctypes.c_void_p]
    _lib.hm_pool_size.restype = ctypes.c_int
    _lib.hm_pool_size.argtypes = [ctypes.c_void_p]
    _lib.hm_pool_destroy.restype = None
    _lib.hm_pool_destroy.argtypes = [ctypes.c_void_p]

    TS_MISSING = int(_lib.hm_ts_missing())
    # The C sentinel must agree with the canonical Python-side one
    # (pipeline.timespan.TS_MISSING, INT64_MIN) or fast-path missing
    # timestamps would silently stop being detected.
    from heatmap_tpu.pipeline.timespan import TS_MISSING as _PY_TS_MISSING

    assert TS_MISSING == int(_PY_TS_MISSING), (
        f"native TS_MISSING {TS_MISSING} != canonical {_PY_TS_MISSING}"
    )

    def _arena_to_list(buf: bytes, rows: int) -> list:
        # NUL-separated fields, one per row, each NUL-terminated.
        if rows == 0:
            return []
        return buf[:-1].decode("utf-8", "replace").split("\x00")

    def parse_csv_batches(path: str, batch_size: int,
                          queue_depth: int = 3,
                          fast: bool = False,
                          n_workers: int | None = None) -> Iterator[dict]:
        """Columnar batches from a CSV file via the native decoder.

        Default (compat) mode yields the heatmap_tpu.io.sources batch
        layout, with timestamps as Python ints (or None where
        missing/blank) — the pure csv path keeps raw strings;
        downstream never reads them (reference carries but ignores
        timestamp, heatmap.py:33 and SURVEY.md §8 quirk 7).

        ``fast=True`` keeps everything integer — no per-row Python
        objects at all. Batches carry ``latitude``/``longitude`` (f64),
        ``timestamp`` (i64, TS_MISSING sentinel), ``background`` (bool;
        reference heatmap.py:28-29), ``routed`` (i32 ids into the
        reader's routed-group name table, -1 = excluded x-user;
        reference heatmap.py:64-70) and ``new_group_names`` — names the
        consumer hasn't seen yet, in id order, so consumers extend
        their table with ``names += new_group_names``.

        ``n_workers`` defaults to 1 in compat mode (batch order then
        matches the pure-Python reader byte-for-byte) and to the CPU
        count (capped at 8) in fast mode, where the file is parsed in
        parallel byte-range shards and batch order is nondeterministic
        (the aggregation is order-invariant).
        """
        import csv as _csv

        with open(path, newline="") as f:
            header = next(_csv.reader(f), None)
        if header is None:  # zero-byte file: nothing to yield
            return

        def col(name):
            try:
                return header.index(name)
            except ValueError:
                return -1

        lat_c, lon_c = col("latitude"), col("longitude")
        if lat_c < 0 or lon_c < 0:
            raise ValueError(f"{path}: missing latitude/longitude columns")
        if n_workers is None:
            n_workers = min(8, os.cpu_count() or 1) if fast else 1
        handle = _lib.hm_csv_open(
            path.encode(), batch_size, lat_c, lon_c,
            col("user_id"), col("source"), col("timestamp"), queue_depth,
            0 if fast else 1, n_workers,
        )
        if not handle:
            raise OSError(f"native csv open failed for {path}")
        c_dbl = ctypes.POINTER(ctypes.c_double)
        c_i64 = ctypes.POINTER(ctypes.c_int64)
        c_i32 = ctypes.POINTER(ctypes.c_int32)
        c_u8 = ctypes.POINTER(ctypes.c_uint8)
        try:
            while True:
                uid_b = ctypes.c_int64()
                src_b = ctypes.c_int64()
                names_b = ctypes.c_int64()
                rows = _lib.hm_csv_peek(
                    handle, ctypes.byref(uid_b), ctypes.byref(src_b),
                    ctypes.byref(names_b),
                )
                if rows == 0:
                    return
                if rows < 0:
                    err = _lib.hm_csv_error(handle)
                    raise OSError(
                        f"native csv parse failed for {path}: "
                        f"{(err or b'').decode()}"
                    )
                lat = np.empty(rows, np.float64)
                lon = np.empty(rows, np.float64)
                ts = np.empty(rows, np.int64)
                if fast:
                    routed = np.empty(rows, np.int32)
                    bg = np.empty(rows, np.uint8)
                    names_arena = ctypes.create_string_buffer(
                        max(1, names_b.value)
                    )
                    rc = _lib.hm_csv_take(
                        handle,
                        lat.ctypes.data_as(c_dbl),
                        lon.ctypes.data_as(c_dbl),
                        ts.ctypes.data_as(c_i64),
                        None, None,
                        routed.ctypes.data_as(c_i32),
                        bg.ctypes.data_as(c_u8),
                        names_arena,
                    )
                    if rc != 0:
                        raise OSError(
                            "native csv take failed (no pending batch)"
                        )
                    n_new = names_arena.raw[: names_b.value]
                    yield {
                        "latitude": lat,
                        "longitude": lon,
                        "timestamp": ts,
                        "background": bg.astype(bool),
                        "routed": routed,
                        "new_group_names": _arena_to_list(
                            n_new, 1 if names_b.value else 0
                        ),
                    }
                    continue
                uid_arena = ctypes.create_string_buffer(max(1, uid_b.value))
                src_arena = ctypes.create_string_buffer(max(1, src_b.value))
                rc = _lib.hm_csv_take(
                    handle,
                    lat.ctypes.data_as(c_dbl),
                    lon.ctypes.data_as(c_dbl),
                    ts.ctypes.data_as(c_i64),
                    uid_arena,
                    src_arena,
                    None, None, None,
                )
                if rc != 0:
                    raise OSError("native csv take failed (no pending batch)")
                if (ts == TS_MISSING).any():
                    stamps = [None if t == TS_MISSING else int(t)
                              for t in ts.tolist()]
                else:
                    stamps = ts.tolist()
                yield {
                    "latitude": lat,
                    "longitude": lon,
                    "user_id": _arena_to_list(uid_arena.raw[: uid_b.value], rows),
                    "source": _arena_to_list(src_arena.raw[: src_b.value], rows),
                    "timestamp": stamps,
                }
        finally:
            _lib.hm_csv_close(handle)

    class StagingPool:
        """Bounded pool of page-aligned host staging buffers.

        ``acquire(shape, dtype)`` returns ``(id, array)`` where the
        array is a zero-copy numpy view of a pooled buffer; release the
        id once the data has been handed to the device. Blocks when all
        buffers are in flight (back-pressure against compute).

        Views alias pool memory: ``close()`` refuses (raises) while ids
        are outstanding, since freeing under a live view would be a
        use-after-free. Release everything before closing.
        """

        def __init__(self, buf_bytes: int, n_bufs: int = 2):
            self._h = _lib.hm_pool_create(buf_bytes, n_bufs)
            if not self._h:
                raise MemoryError("staging pool allocation failed")
            self.buf_bytes = int(_lib.hm_pool_buf_bytes(self._h))
            self.n_bufs = int(_lib.hm_pool_size(self._h))
            self._outstanding = set()

        def acquire(self, shape, dtype, block: bool = True):
            dtype = np.dtype(dtype)
            need = int(np.prod(shape)) * dtype.itemsize
            if need > self.buf_bytes:
                raise ValueError(
                    f"requested {need} bytes > pool buffer {self.buf_bytes}"
                )
            if block:
                bid = _lib.hm_pool_acquire(self._h)
            else:
                bid = _lib.hm_pool_try_acquire(self._h)
                if bid < 0:
                    return None
            base = _lib.hm_pool_buffer(self._h, bid)
            raw = (ctypes.c_char * self.buf_bytes).from_address(base)
            arr = np.frombuffer(raw, dtype=dtype, count=int(np.prod(shape)))
            self._outstanding.add(bid)
            return bid, arr.reshape(shape)

        def release(self, bid: int):
            self._outstanding.discard(bid)
            _lib.hm_pool_release(self._h, bid)

        def close(self, force: bool = False):
            if getattr(self, "_h", None):
                if self._outstanding and not force:
                    raise RuntimeError(
                        f"staging pool closed with buffers "
                        f"{sorted(self._outstanding)} still acquired — "
                        f"their numpy views would dangle; release them "
                        f"first (or close(force=True) if they are dead)"
                    )
                _lib.hm_pool_destroy(self._h)
                self._h = None

        def __del__(self):
            try:
                self.close(force=True)
            except Exception:
                pass

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            self.close()


if _lib is not None:
    _lib.hm_format_blob_bodies.restype = ctypes.c_int64
    _lib.hm_format_blob_bodies.argtypes = [
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_char_p),
    ]
    _lib.hm_blobfmt_free.restype = None
    _lib.hm_blobfmt_free.argtypes = [ctypes.c_char_p]

    def format_blob_bodies(rows, cols, values, is_start, zoom: int,
                           n_threads: int | None = None) -> list:
        """NUL-separated '{...}' JSON documents for one sorted level.

        Contract of the numpy join/split path in
        pipeline.cascade.json_blobs_from_level_arrays: one document per
        blob start, aggregate order preserved. ``values`` MUST be
        integral doubles with |v| < 1e15 (the caller checks; cascade
        counts always satisfy it — "%lld.0" is then exactly
        repr(float)).
        """
        import numpy as np

        n = len(rows)
        if n == 0:
            return []
        rows = np.ascontiguousarray(rows, np.int64)
        cols = np.ascontiguousarray(cols, np.int64)
        values = np.ascontiguousarray(values, np.float64)
        starts = np.ascontiguousarray(is_start, np.uint8)
        if n_threads is None:
            n_threads = min(8, os.cpu_count() or 1)
        out = ctypes.c_char_p()
        length = _lib.hm_format_blob_bodies(
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            n, zoom, n_threads, ctypes.byref(out),
        )
        if length < 0:
            raise MemoryError("native blob formatter allocation failed")
        try:
            buf = ctypes.string_at(out, length)
        finally:
            _lib.hm_blobfmt_free(out)
        return buf.decode("ascii").split("\x00")
else:
    format_blob_bodies = None


if _lib is not None:
    _lib.hm_format_blob_ids.restype = ctypes.c_int64
    _lib.hm_format_blob_ids.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_char_p),
    ]

    def _name_table(names):
        """UTF-8 concat buffer + int64 offsets for a small name array."""
        import numpy as np

        encoded = [str(s).encode("utf-8") for s in names]
        offs = np.zeros(len(encoded) + 1, np.int64)
        np.cumsum([len(b) for b in encoded], out=offs[1:])
        return b"".join(encoded), offs

    def format_blob_ids(user_idx, ts_idx, coarse_row, coarse_col,
                        coarse_zoom: int, user_names, ts_names,
                        n_threads: int | None = None) -> list:
        """'user|timespan|z_r_c' blob id strings, dictionary-decoded
        and formatted in one threaded C pass (the numpy np.char chain
        this replaces was the dominant cost of reference-format JSON
        egress; reference key codec heatmap.py:54-55)."""
        import numpy as np

        n = len(user_idx)
        if not (len(ts_idx) == len(coarse_row) == len(coarse_col) == n):
            raise ValueError(
                f"column length mismatch: user_idx={n} ts_idx={len(ts_idx)} "
                f"coarse_row={len(coarse_row)} coarse_col={len(coarse_col)}"
            )
        if n == 0:
            return []
        user_idx = np.ascontiguousarray(user_idx, np.int32)
        ts_idx = np.ascontiguousarray(ts_idx, np.int32)
        coarse_row = np.ascontiguousarray(coarse_row, np.int32)
        coarse_col = np.ascontiguousarray(coarse_col, np.int32)
        ubuf, uoffs = _name_table(user_names)
        tbuf, toffs = _name_table(ts_names)
        if n_threads is None:
            n_threads = min(8, os.cpu_count() or 1)
        out = ctypes.c_char_p()
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        length = _lib.hm_format_blob_ids(
            user_idx.ctypes.data_as(i32p),
            ts_idx.ctypes.data_as(i32p),
            coarse_row.ctypes.data_as(i32p),
            coarse_col.ctypes.data_as(i32p),
            n, coarse_zoom,
            ubuf, uoffs.ctypes.data_as(i64p), len(user_names),
            tbuf, toffs.ctypes.data_as(i64p), len(ts_names),
            n_threads, ctypes.byref(out),
        )
        if length == -1:
            raise MemoryError("native blob-id formatter allocation failed")
        if length == -2:
            raise ValueError(
                "blob-id dictionary index out of range for its name table"
            )
        if length < 0:
            raise ValueError(f"coarse_zoom out of range: {coarse_zoom}")
        try:
            buf = ctypes.string_at(out, length)
        finally:
            _lib.hm_blobfmt_free(out)
        return buf.decode("utf-8").split("\x00")[:-1]
else:
    format_blob_ids = None


if _lib is not None:
    _lib.hm_decode_keys.restype = ctypes.c_int32
    _lib.hm_decode_keys.argtypes = [
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
    ]

    def decode_keys(keys, code_bits: int, n_threads: int | None = None,
                    morton_only: bool = False):
        """Split composite cascade keys -> (slot, code, row, col).

        One fused multithreaded pass replacing the numpy
        shift/mask/Morton-compact chain in pipeline.cascade
        (decode_level_keys + tilemath.morton.morton_decode_np). With
        ``morton_only=True`` the slot/code columns are neither
        allocated nor written (returned as None) — the Morton-decode
        fast path for tilemath.morton.morton_decode_np.
        """
        import numpy as np

        keys = np.ascontiguousarray(keys, np.int64)
        if keys.ndim != 1:
            raise ValueError(f"keys must be 1-D, got shape {keys.shape}")
        n = len(keys)
        slot = None if morton_only else np.empty(n, np.int32)
        code = None if morton_only else np.empty(n, np.int64)
        row = np.empty(n, np.int32)
        col = np.empty(n, np.int32)
        if n:
            if n_threads is None:
                n_threads = min(8, os.cpu_count() or 1)
            i32p = ctypes.POINTER(ctypes.c_int32)
            rc = _lib.hm_decode_keys(
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                n, code_bits,
                None if slot is None else slot.ctypes.data_as(i32p),
                None if code is None else code.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int64)),
                row.ctypes.data_as(i32p),
                col.ctypes.data_as(i32p),
                n_threads,
            )
            if rc != 0:
                raise ValueError(
                    f"hm_decode_keys rejected code_bits={code_bits}"
                )
        return slot, code, row, col
else:
    decode_keys = None


def available() -> bool:
    """True when the native library loaded (accelerated paths active)."""
    return _lib is not None
