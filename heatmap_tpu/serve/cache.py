"""TileCache: thread-safe LRU (byte cap) + TTL + single-flight renders.

Serving semantics drive the three mechanisms:

- **LRU by bytes, not entries** — tile payloads span two orders of
  magnitude (a 4-cell JSON doc vs a dense 256px PNG), so an entry-count
  cap would let a few hot dense tiles evict thousands of cheap ones.
- **TTL** — a decayed live layer (serve/live.py) and operators pointing
  the store at a directory another job is rewriting both need staleness
  bounded by wall-clock, not only by explicit invalidation.
- **Single-flight** — N concurrent misses on one cold tile must render
  ONCE: the first requester becomes the flight leader, the rest block
  on its event and share the result (or its exception). Without this, a
  popular tile going cold stampedes the renderer with N identical
  renders — the classic cache-stampede failure under map-client load.

Invalidation is generation-based: every entry is stamped with the
store generation it was rendered from; ``store.reload()`` bumps the
generation and stale entries die lazily on next touch (no O(cache)
sweep on the serving path). Live-stream ticks instead call
``invalidate_keys`` with just the affected tile keys.

Instrumented on the existing obs registry:
``tile_cache_{hits,misses,evictions}_total``,
``tile_cache_stale_serves_total`` and the ``tile_render_seconds``
histogram (observed around the leader's render only — follower waits
are not renders).

**Stale-if-error** (``get_or_render(..., stale_if_error=True)``): a
generation- or TTL-stale entry is kept as a fallback instead of being
dropped before the re-render. If the render fails, the caller gets the
last-good bytes back with ``hit == TileCache.STALE`` (a truthy string
sentinel, so ``hit is True / hit is False`` checks on the normal paths
are unaffected) and the entry stays cached for the next request; a
successful render replaces it as usual. This is what lets the serve
tier degrade to stale-200 instead of 500 when the store or renderer is
having a bad day (docs/robustness.md).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from heatmap_tpu import obs
from heatmap_tpu.obs import tracing

_registry = obs.get_registry()
CACHE_HITS = _registry.counter(
    "tile_cache_hits_total", "Tile requests served from the cache")
CACHE_MISSES = _registry.counter(
    "tile_cache_misses_total", "Tile requests that required a render")
CACHE_EVICTIONS = _registry.counter(
    "tile_cache_evictions_total", "Cache entries dropped",
    labelnames=("reason",))
CACHE_STALE_SERVES = _registry.counter(
    "tile_cache_stale_serves_total",
    "Stale entries served because the replacing render failed")
RENDER_SECONDS = _registry.histogram(
    "tile_render_seconds", "Wall-clock of on-demand tile renders",
    labelnames=("format",),
    buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0))


class _Entry:
    __slots__ = ("value", "nbytes", "generation", "expires")

    def __init__(self, value, nbytes, generation, expires):
        self.value = value
        self.nbytes = nbytes
        self.generation = generation
        self.expires = expires


class _Flight:
    """One in-progress render; followers wait on ``done``."""

    __slots__ = ("done", "value", "error")

    def __init__(self):
        self.done = threading.Event()
        self.value = None
        self.error = None


#: "No stale fallback available" marker (distinct from a cached None).
_NO_FALLBACK = object()


class TileCache:
    """Keys are opaque hashables (the server uses
    ``(layer, z, x, y, fmt)``); values are bytes-like (sized via
    ``len``). ``max_bytes <= 0`` disables caching but keeps
    single-flight dedup — concurrent identical renders still coalesce.
    """

    #: ``hit`` value for a stale entry served under ``stale_if_error``
    #: after the replacing render failed. Truthy, but never ``is True``.
    STALE = "stale"

    def __init__(self, max_bytes: int = 256 << 20,
                 ttl_s: float | None = None, clock=time.monotonic):
        self.max_bytes = int(max_bytes)
        self.ttl_s = ttl_s if (ttl_s is None or ttl_s > 0) else None
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: "OrderedDict" = OrderedDict()
        self._flights: dict = {}
        self._bytes = 0
        self._ttl_scale = 1.0
        # Sliding-window params this cache has served (heatmap_tpu.
        # temporal): targeted invalidation needs to enumerate the
        # window-variant keys of an affected tile, and only the cache
        # knows which ``?window=`` values are actually in play.
        self._window_params: set = set()

    # -- temporal window registry ------------------------------------------

    def note_window_param(self, param: str):
        """Record a served ``?window=`` param so delta refreshes and
        bucket rolls can invalidate its key variants."""
        with self._lock:
            self._window_params.add(str(param))

    def window_params(self) -> tuple:
        with self._lock:
            return tuple(sorted(self._window_params))

    # -- introspection -----------------------------------------------------

    @property
    def nbytes(self) -> int:
        return self._bytes

    def __len__(self):
        return len(self._entries)

    @property
    def ttl_scale(self) -> float:
        return self._ttl_scale

    def set_ttl_scale(self, scale: float) -> None:
        """Stretch (or restore) the effective TTL without touching the
        stamped ``expires`` of existing entries: the brownout ladder's
        serve-stale widening. Scale 1.0 is byte-for-byte the original
        behavior; >1.0 lets entries live ``scale * ttl_s`` from insert.
        Generation-based invalidation is unaffected — a reload still
        retires every entry."""
        if scale < 1.0:
            raise ValueError("ttl scale must be >= 1.0")
        with self._lock:
            self._ttl_scale = float(scale)

    def _effective_expiry(self, entry):
        # Caller holds the lock. entry.expires is insert + ttl_s; the
        # scale widens it by (scale - 1) * ttl_s more.
        expires = entry.expires
        if (expires is not None and self._ttl_scale != 1.0
                and self.ttl_s is not None):
            expires += (self._ttl_scale - 1.0) * self.ttl_s
        return expires

    # -- core --------------------------------------------------------------

    def get_or_render(self, key, generation: int, render_fn, *,
                      fmt: str = "tile", stale_if_error: bool = False):
        """Cached value for ``key`` at ``generation``, rendering at most
        once across concurrent callers. ``render_fn()`` runs OUTSIDE the
        cache lock. Returns ``(value, hit)``; render errors propagate to
        every waiter of that flight (and are not cached).

        With ``stale_if_error=True`` a generation/TTL-stale entry is
        retained as a fallback: if the replacing render raises, the
        stale bytes are returned with ``hit == TileCache.STALE`` (and
        published to the flight's followers) instead of the error."""
        while True:
            fallback = _NO_FALLBACK
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    expires = self._effective_expiry(entry)
                    if entry.generation != generation or (
                            expires is not None
                            and self._clock() >= expires):
                        if stale_if_error:
                            # Keep the entry: a successful render
                            # replaces it via _insert; a failed one
                            # serves it as the last-good fallback.
                            fallback = entry.value
                        else:
                            reason = ("stale"
                                      if entry.generation != generation
                                      else "ttl")
                            self._drop(key, entry, reason)
                    else:
                        self._entries.move_to_end(key)
                        if obs.metrics_enabled():
                            CACHE_HITS.inc()
                        return entry.value, True
                flight = self._flights.get(key)
                if flight is None:
                    flight = self._flights[key] = _Flight()
                    leader = True
                else:
                    leader = False
            if not leader:
                flight.done.wait()
                if flight.error is not None:
                    raise flight.error
                if obs.metrics_enabled():
                    CACHE_HITS.inc()
                return flight.value, True
            # Flight leader: render outside the lock, publish, insert.
            if obs.metrics_enabled():
                CACHE_MISSES.inc()
            t0 = self._clock()
            # Only the leader's render is a span (followers wait, they
            # don't render) — it parents under the request span of the
            # thread that won the flight.
            tsp = tracing.begin_span("tile.render", {"format": fmt})
            try:
                value = render_fn()
            except BaseException as e:
                tracing.end_span(tsp)
                tsp = None
                if stale_if_error and fallback is not _NO_FALLBACK:
                    if obs.metrics_enabled():
                        CACHE_STALE_SERVES.inc()
                    flight.value = fallback
                    with self._lock:
                        self._flights.pop(key, None)
                    flight.done.set()
                    return fallback, self.STALE
                flight.error = e
                with self._lock:
                    self._flights.pop(key, None)
                flight.done.set()
                raise
            tracing.end_span(tsp)
            if obs.metrics_enabled():
                RENDER_SECONDS.observe(self._clock() - t0, format=fmt)
            flight.value = value
            with self._lock:
                self._flights.pop(key, None)
                if value is not None and self.max_bytes > 0:
                    self._insert(key, value, generation)
            flight.done.set()
            return value, False

    def _insert(self, key, value, generation):
        nbytes = len(value)
        old = self._entries.pop(key, None)
        if old is not None:
            self._bytes -= old.nbytes
        if nbytes > self.max_bytes:
            return  # a single over-cap tile must not flush everything
        expires = (self._clock() + self.ttl_s
                   if self.ttl_s is not None else None)
        self._entries[key] = _Entry(value, nbytes, generation, expires)
        self._bytes += nbytes
        while self._bytes > self.max_bytes and self._entries:
            k, e = next(iter(self._entries.items()))
            self._drop(k, e, "lru")

    def _drop(self, key, entry, reason: str):
        # Caller holds the lock.
        self._entries.pop(key, None)
        self._bytes -= entry.nbytes
        if obs.metrics_enabled():
            CACHE_EVICTIONS.inc(reason=reason)

    # -- invalidation ------------------------------------------------------

    def invalidate_keys(self, keys) -> int:
        """Drop specific entries (live-stream ticks: only the tiles a
        batch touched). Returns how many were present."""
        n = 0
        with self._lock:
            for key in keys:
                entry = self._entries.get(key)
                if entry is not None:
                    self._drop(key, entry, "invalidated")
                    n += 1
        return n

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
