"""heatmap_tpu.serve — the read side of the system: tile serving.

The reference job existed to FEED a serving path: blobs went into
Cassandra so a map frontend could fetch heatmap tiles (reference
heatmap.py:149-150); the query path itself lived in some other service.
This package is that service, TPU-framework-native:

- ``store``  — TileStore: batch egress (``arrays:DIR`` per-level npz,
  including multihost ``host*/`` shards, or ``jsonl:``/``dir:`` blob
  records) loaded into a read-optimized Morton-keyed per-zoom index
  with named layers and hot ``reload()``;
- ``cache``  — TileCache: thread-safe byte-capped LRU with TTL,
  single-flight render dedup and generation invalidation;
- ``render`` — on-demand tile materialization: exact tiles at stored
  zooms, 2x2 rollup / quadrant upsample at zooms the pyramid lacks,
  PNG (io/png colormap) or reference-compatible JSON counts;
- ``live``   — a HeatmapStream-backed layer whose update ticks
  invalidate only the affected tile keys;
- ``http``   — stdlib ThreadingHTTPServer frontend with ETag/304,
  ``/healthz`` and a Prometheus ``/metrics`` endpoint (obs registry);
- ``router`` — stateless fleet frontend: rendezvous hashing with
  bounded-load spill, circuit breakers, hedged reads, admission
  control (typed 503 + Retry-After, never a 500);
- ``fleet``  — supervisor spawning N shared-nothing backend processes
  behind one router, restarting crashers with backoff and re-admitting
  them via half-open health probes.

Everything except ``live`` is numpy-only — serving a finished job
never initializes a jax backend (the io/merge.py offline property), so
a tile server runs fine next to a dead accelerator relay.
"""

from heatmap_tpu.serve.cache import TileCache  # noqa: F401
from heatmap_tpu.serve.store import TileStore  # noqa: F401
from heatmap_tpu.serve.render import (  # noqa: F401
    tile_array,
    tile_json_bytes,
    tile_png_bytes,
)
from heatmap_tpu.serve.http import (  # noqa: F401
    ServeApp,
    make_server,
    serve_in_thread,
)
from heatmap_tpu.serve.live import LiveLayer  # noqa: F401
from heatmap_tpu.serve.router import (  # noqa: F401
    BackendClient,
    CircuitBreaker,
    RouterApp,
    rendezvous_order,
    route_key,
)
from heatmap_tpu.serve.fleet import FleetSupervisor  # noqa: F401
