"""Stdlib HTTP frontend: ThreadingHTTPServer over store + cache.

Routes:

- ``GET /tiles/{layer}/{z}/{x}/{y}.png``  — colormapped tile image
- ``GET /tiles/{layer}/{z}/{x}/{y}.json`` — reference-compatible counts
- ``GET /healthz``                        — store/cache stats (JSON)
- ``GET /metrics``                        — Prometheus 0.0.4 text from
  the process-wide obs registry (so serving metrics sit next to any
  pipeline metrics the same process produced)
- ``POST /reload``                        — re-read the store artifact;
  the bumped generation lazily invalidates every cached tile

Tiles carry **strong ETags** (crc32 of the payload — cheap, and tile
payloads are small enough that collision risk is irrelevant for cache
revalidation); a matching ``If-None-Match`` short-circuits to 304 with
no body. The ETag comes from the cached bytes, so revalidation is a
cache hit, not a re-render.

One ServeApp is shared by every handler thread: TileStore swaps are
atomic, TileCache is internally locked, and the obs registry is
thread-safe — the handler itself holds no mutable state. Request
logging goes to the obs event log (``http_request`` events), never
stdout: ``log_message`` is overridden because the serve tree is under
the raw-print grep guard (tests/test_obs.py).
"""

from __future__ import annotations

import json
import re
import threading
import time
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from heatmap_tpu import obs
from heatmap_tpu.serve.cache import TileCache
from heatmap_tpu.serve.render import tile_json_bytes, tile_png_bytes
from heatmap_tpu.serve.store import TileStore

_registry = obs.get_registry()
HTTP_REQUESTS = _registry.counter(
    "http_requests_total", "HTTP requests served",
    labelnames=("route", "status"))

_TILE_RE = re.compile(
    r"^/tiles/(?P<layer>[^/]+)/(?P<z>\d{1,2})/(?P<x>\d+)/(?P<y>\d+)"
    r"\.(?P<fmt>png|json)$")

_CONTENT_TYPES = {"png": "image/png", "json": "application/json"}


def _etag(body: bytes) -> str:
    return f'"{zlib.crc32(body):08x}"'


class ServeApp:
    """Transport-free request core: ``handle()`` maps (method, path,
    if_none_match) -> (status, content_type, body, etag). The HTTP
    handler below is a thin shell around it, which is what makes the
    serving logic testable without sockets."""

    def __init__(self, store: TileStore, cache: TileCache | None = None):
        self.store = store
        self.cache = cache if cache is not None else TileCache()
        self._extra_layers: dict = {}

    # -- layers ------------------------------------------------------------

    def attach_layer(self, name: str, layer) -> None:
        """Mount a non-store layer (live mode). Attached layers survive
        ``/reload`` — that re-reads the artifact only."""
        self._extra_layers[name] = layer

    def layer(self, name: str):
        found = self._extra_layers.get(name)
        return found if found is not None else self.store.layer(name)

    def layer_names(self) -> list:
        return sorted(set(self.store.layer_names()) | set(self._extra_layers))

    # -- request core ------------------------------------------------------

    def handle(self, method: str, path: str,
               if_none_match: str | None = None):
        """Returns ``(status, content_type, body, etag, route, cache)``;
        ``body`` is b"" for 304s, ``cache`` is "hit"/"miss"/None."""
        m = _TILE_RE.match(path)
        if method == "GET" and m is not None:
            return self._handle_tile(m, if_none_match)
        if method == "GET" and path == "/healthz":
            body = json.dumps(self._health(), indent=2).encode()
            return 200, "application/json", body, None, "healthz", None
        if method == "GET" and path == "/metrics":
            body = _registry.render_prometheus().encode()
            return (200, "text/plain; version=0.0.4", body, None,
                    "metrics", None)
        if method == "POST" and path == "/reload":
            generation = self.store.reload()
            body = json.dumps({"generation": generation}).encode()
            return 200, "application/json", body, None, "reload", None
        body = json.dumps({"error": "not found", "path": path}).encode()
        return 404, "application/json", body, None, "other", None

    def _handle_tile(self, m, if_none_match):
        layer_name = m["layer"]
        z, x, y = int(m["z"]), int(m["x"]), int(m["y"])
        fmt = m["fmt"]
        layer = self.layer(layer_name)
        if layer is None or not (0 <= x < (1 << z) and 0 <= y < (1 << z)):
            body = json.dumps({
                "error": "unknown layer" if layer is None else "off-grid tile",
                "layers": self.layer_names(),
            }).encode()
            return 404, "application/json", body, None, "tiles", None
        render = tile_png_bytes if fmt == "png" else tile_json_bytes
        body, hit = self.cache.get_or_render(
            (layer_name, z, x, y, fmt), self.store.generation,
            lambda: render(layer, z, x, y), fmt=fmt)
        cache = "hit" if hit else "miss"
        if body is None:
            payload = json.dumps({"error": "empty tile"}).encode()
            return 404, "application/json", payload, None, "tiles", cache
        etag = _etag(body)
        if if_none_match is not None and etag in if_none_match:
            return 304, _CONTENT_TYPES[fmt], b"", etag, "tiles", cache
        return 200, _CONTENT_TYPES[fmt], body, etag, "tiles", cache

    def _health(self) -> dict:
        stats = self.store.stats()
        for name, layer in sorted(self._extra_layers.items()):
            stats["layers"][name] = {
                "user": layer.user,
                "timespan": layer.timespan,
                "detail_zooms": layer.detail_zooms,
                "result_delta": layer.result_delta,
                "rows": int(sum(len(l) for l in layer.levels.values())),
                "live": True,
            }
        stats["cache"] = {"entries": len(self.cache),
                          "bytes": self.cache.nbytes}
        stats["status"] = "ok"
        return stats


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # Keep-alive + small responses otherwise hit the Nagle/delayed-ACK
    # interaction: every cached tile pays a ~40ms ACK stall.
    disable_nagle_algorithm = True
    app: ServeApp  # bound by make_server

    def _dispatch(self, method: str):
        t0 = time.monotonic()
        try:
            status, ctype, body, etag, route, cache = self.app.handle(
                method, self.path, self.headers.get("If-None-Match"))
        except Exception as e:  # defensive: a render bug must not kill serving
            status, ctype, route, cache = 500, "application/json", "error", None
            body = json.dumps({"error": repr(e)}).encode()
            etag = None
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if etag is not None:
            self.send_header("ETag", etag)
        self.end_headers()
        if body:
            self.wfile.write(body)
        if obs.metrics_enabled():
            HTTP_REQUESTS.inc(route=route, status=str(status))
        obs.emit("http_request", route=route, status=int(status),
                 path=self.path, ms=round((time.monotonic() - t0) * 1e3, 3),
                 bytes=len(body), **({"cache": cache} if cache else {}))

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass  # request logging goes through obs events, never stdout


def make_server(app: ServeApp, host: str = "127.0.0.1",
                port: int = 0) -> ThreadingHTTPServer:
    """Bound-but-not-serving ThreadingHTTPServer (port 0 = ephemeral;
    read the real one from ``server.server_address[1]``). Caller runs
    ``serve_forever()`` — inline (CLI) or in a thread (tests/bench)."""
    handler = type("Handler", (_Handler,), {"app": app})
    server = ThreadingHTTPServer((host, port), handler)
    server.daemon_threads = True
    return server


def serve_in_thread(app: ServeApp, host: str = "127.0.0.1", port: int = 0):
    """Test/bench helper: returns ``(server, base_url)`` with
    serve_forever running on a daemon thread; ``server.shutdown()``
    stops it."""
    server = make_server(app, host, port)
    thread = threading.Thread(target=server.serve_forever,
                              name="serve-http", daemon=True)
    thread.start()
    h, p = server.server_address[:2]
    return server, f"http://{h}:{p}"
