"""Stdlib HTTP frontend: ThreadingHTTPServer over store + cache.

Routes:

- ``GET /tiles/{layer}/{z}/{x}/{y}.png``  — colormapped tile image
- ``GET /tiles/{layer}/{z}/{x}/{y}.json`` — reference-compatible counts
- ``?synopsis=1`` on a tile URL opts into the wavelet-synopsis path
  (docs/synopsis.md): when the source zoom the exact path would use
  carries a decoded synopsis, the tile is synthesized from it and the
  response carries ``X-Heatmap-Synopsis: max_err=<n>`` plus a
  ``"syn-``-prefixed ETag (approximate and exact bytes must never
  revalidate against each other). Without a synopsis at that zoom —
  including every ``z >= synopsis_max_z`` request — the exact path
  answers byte-identically to an un-annotated request.
- ``GET /query?layer=&bbox=&z=&op=sum|topk|quantile&k=&q=`` — O(1)
  range analytics over the integral pyramids (docs/analytics.md):
  ``bbox`` is an inclusive cell rect ``x0,y0,x1,y1`` at source grid
  zoom ``z``. Served from the level's summed-area table when the store
  carries one, falling through to an exact row scan (slower, same
  answer) when it predates integral artifacts; brownout rung >= 1
  answers ``op=sum`` from the synopsis-reconstructed grid with the
  achieved L-inf error bound in ``X-Heatmap-Query-Error``. Malformed
  parameters get typed 400s; ETags live in a ``"q-``-prefixed
  namespace and results ride the same byte-capped LRU with
  stale-if-error semantics as tiles.
- ``GET /series?name=&label=&from=&to=&step=`` — aligned history
  frames from the embedded telemetry tiers (obs/timeseries.py) with
  the achieved resolution stamped per frame; a well-formed
  ``enabled: false`` answer when the sampler is off
- ``GET /dashboard``                      — self-contained operational
  page (serve/dashboard.py): inline HTML/SVG sparklines over
  ``/series`` + ``/healthz``, zero external assets
- ``GET /healthz``                        — store/cache stats (JSON)
- ``GET /metrics``                        — Prometheus 0.0.4 text from
  the process-wide obs registry (so serving metrics sit next to any
  pipeline metrics the same process produced)
- ``POST /reload``                        — re-read the store artifact;
  the bumped generation lazily invalidates every cached tile

**Graceful degradation** (docs/robustness.md): tile renders run under
the ``tile.render`` fault site and an optional per-render timeout; a
failed render serves the last-good cached bytes (stale-200, cache
``"stale"`` in the ``http_request`` event) when the TileCache has them
and a typed 503 JSON body otherwise — never a 500. A failed
``/reload`` keeps the last-good index (TileStore builds the new index
before swapping) and returns 503. Both paths flip the app into a
degraded state with a named cause, edge-triggered as
``degraded_enter``/``degraded_exit`` obs events, and ``/healthz``
reports ``"status": "degraded"`` with the live causes until the next
successful render/reload clears them.

Tiles carry **strong ETags** (crc32 of the payload — cheap, and tile
payloads are small enough that collision risk is irrelevant for cache
revalidation); a matching ``If-None-Match`` short-circuits to 304 with
no body. The ETag comes from the cached bytes, so revalidation is a
cache hit, not a re-render.

One ServeApp is shared by every handler thread: TileStore swaps are
atomic, TileCache is internally locked, and the obs registry is
thread-safe — the handler itself holds no mutable state. Request
logging goes to the obs event log (``http_request`` events), never
stdout: ``log_message`` is overridden because the serve tree is under
the raw-print grep guard (tests/test_obs.py).
"""

from __future__ import annotations

import concurrent.futures
import json
import re
import threading
import time
import urllib.parse
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from heatmap_tpu import faults, obs
from heatmap_tpu.analytics import metrics as analytics_metrics
from heatmap_tpu.analytics import query as analytics_query
from heatmap_tpu.obs import (anomaly, incident, recorder, slo, timeseries,
                             tracing)
from heatmap_tpu.serve import dashboard as dashboard_mod
from heatmap_tpu.serve import degrade as degrade_mod
from heatmap_tpu.serve.cache import TileCache
from heatmap_tpu.serve.render import (SynopsisLayer, synopsis_source,
                                      tile_json_bytes, tile_png_bytes)
from heatmap_tpu.serve.store import TileStore

_registry = obs.get_registry()
HTTP_REQUESTS = _registry.counter(
    "http_requests_total", "HTTP requests served",
    labelnames=("route", "status"))

_TILE_RE = re.compile(
    r"^/tiles/(?P<layer>[^/]+)/(?P<z>\d{1,2})/(?P<x>\d+)/(?P<y>\d+)"
    r"\.(?P<fmt>png|json)$")

_CONTENT_TYPES = {"png": "image/png", "json": "application/json"}


def _etag(body: bytes) -> str:
    return f'"{zlib.crc32(body):08x}"'


def _syn_etag(body: bytes) -> str:
    # Distinct namespace from exact ETags: a client holding exact bytes
    # must re-fetch when it asks for a synopsis (and vice versa), even
    # on the astronomically-unlikely crc collision.
    return f'"syn-{zlib.crc32(body):08x}"'


def _query_etag(body: bytes) -> str:
    # Query results get their own namespace too: a /query body must
    # never revalidate against a tile's (or a synopsis tile's) ETag.
    return f'"q-{zlib.crc32(body):08x}"'


def _temporal_etag(body: bytes) -> str:
    # Temporal folds are a fourth namespace: an as_of/window tile must
    # never revalidate against the all-time tile's ETag (same bytes at
    # one instant is a coincidence, not an identity).
    return f'"t-{zlib.crc32(body):08x}"'


def _temporal_opt(query: str) -> dict | None:
    """Raw ``?as_of=/window=/decay=`` values (last-wins), or None when
    the request has no temporal params. The query string still never
    participates in routing, so the fleet router colocates every
    temporal variant of a tile with its all-time twin for free."""
    if not query:
        return None
    params = urllib.parse.parse_qs(query)
    out = {}
    for name in ("as_of", "window", "decay"):
        vals = params.get(name)
        if vals:
            out[name] = vals[-1]
    return out or None


def local_series_response(query: str):
    """Answer ``GET /series`` from this process's telemetry store —
    the same 6-tuple contract as ``handle()``. Module-level (not a
    ServeApp method) so the fleet router serves its own history
    through the identical parser before merging backend frames."""
    params = urllib.parse.parse_qs(query) if query else {}

    def _param(key, default=None):
        vals = params.get(key)
        return vals[-1] if vals else default

    try:
        name = _param("name")
        if not name:
            raise ValueError("missing required parameter name")
        labels = {}
        for raw in params.get("label", []):
            key, eq, value = raw.partition("=")
            if not eq or not key:
                raise ValueError(
                    f"label must be key=value, got {raw!r}")
            labels[key] = value
        bounds = {}
        for key, attr in (("from", "start"), ("to", "end"),
                          ("step", "step")):
            raw = _param(key)
            if raw is None:
                continue
            try:
                bounds[attr] = float(raw)
            except ValueError:
                raise ValueError(f"{key} must be a number, got {raw!r}")
        if bounds.get("step") is not None and bounds["step"] <= 0:
            raise ValueError(f"step must be > 0, got {bounds['step']}")
    except ValueError as e:
        body = json.dumps({"error": "bad query",
                           "detail": str(e)}).encode()
        return 400, "application/json", body, None, "series", None
    store = timeseries.get_store()
    if store is None:
        body = json.dumps({
            "enabled": False, "name": name, "frames": [],
            "detail": "telemetry sampler off "
                      "(--telemetry-sample-interval 0)",
        }, sort_keys=True).encode()
        return 200, "application/json", body, None, "series", None
    doc = store.query(name, labels=labels or None, **bounds)
    doc["enabled"] = True
    body = json.dumps(doc, sort_keys=True).encode()
    return 200, "application/json", body, None, "series", None


class Response(tuple):
    """``handle()`` result. Unpacks as the historical 6-tuple
    ``(status, content_type, body, etag, route, cache)`` — every
    existing consumer keeps working — while optionally carrying extra
    transport headers (``X-Heatmap-Synopsis``) in ``.headers`` for the
    HTTP shell and the fleet router's relay to forward."""

    headers: dict | None = None

    def __new__(cls, status, ctype, body, etag, route, cache,
                headers=None):
        self = super().__new__(
            cls, (status, ctype, body, etag, route, cache))
        if headers:
            self.headers = headers
        return self


class ServeApp:
    """Transport-free request core: ``handle()`` maps (method, path,
    if_none_match) -> (status, content_type, body, etag). The HTTP
    handler below is a thin shell around it, which is what makes the
    serving logic testable without sockets."""

    def __init__(self, store: TileStore, cache: TileCache | None = None,
                 *, render_timeout_s: float | None = None,
                 max_inflight: int | None = None,
                 retry_after_s: float = 1.0,
                 synopsis_default: bool = False,
                 degrade: "degrade_mod.BrownoutController | None" = None,
                 disk_cache=None, prewarm=None):
        self.store = store
        self.cache = cache if cache is not None else TileCache()
        # Disk tier (tilefs.DiskTileCache | None): consulted by the
        # heap cache's flight leader before rendering, write-through
        # after — single-flight for free. Keys carry (generation,
        # delta_epoch), so epochs invalidate structurally.
        self.disk_cache = disk_cache
        # Pre-warm config (tilefs.PrewarmConfig | None): replayed by
        # prewarm_now() at startup (cli/fleet call it once bound) and
        # after every successful /reload.
        self.prewarm = prewarm
        self._prewarm_last: dict | None = None
        self.render_timeout_s = render_timeout_s
        self.max_inflight = max_inflight
        self.retry_after_s = retry_after_s  # advertised on every 503
        # Layer policy for tile requests with no ?synopsis= parameter;
        # an explicit synopsis=0/1 on the URL always wins.
        self.synopsis_default = synopsis_default
        # Brownout ladder (serve/degrade.py); None = compiled out. At
        # rung 0 every request is byte-identical to degrade=None
        # (pinned in tests/test_degrade.py).
        self.degrade = degrade
        self._extra_layers: dict = {}
        self._degraded_lock = threading.Lock()
        self._degraded: dict[str, str] = {}  # cause -> detail
        self._render_pool = None  # lazy; only built when timeouts are on
        self._inflight_lock = threading.Lock()
        self._inflight = 0
        self._draining = False

    # -- degraded state ----------------------------------------------------

    def degraded_causes(self) -> dict:
        """Live degradation causes (empty == healthy)."""
        with self._degraded_lock:
            return dict(self._degraded)

    def _degrade(self, cause: str, detail: str = ""):
        with self._degraded_lock:
            entering = cause not in self._degraded
            self._degraded[cause] = detail
        if entering:  # edge-triggered: one event per episode, not per request
            obs.emit("degraded_enter", cause=cause,
                     **({"detail": detail} if detail else {}))

    def _recover(self, cause: str):
        with self._degraded_lock:
            was_degraded = self._degraded.pop(cause, None) is not None
        if was_degraded:
            obs.emit("degraded_exit", cause=cause)

    # -- layers ------------------------------------------------------------

    def attach_layer(self, name: str, layer) -> None:
        """Mount a non-store layer (live mode). Attached layers survive
        ``/reload`` — that re-reads the artifact only."""
        self._extra_layers[name] = layer

    def layer(self, name: str):
        found = self._extra_layers.get(name)
        return found if found is not None else self.store.layer(name)

    def layer_names(self) -> list:
        return sorted(set(self.store.layer_names()) | set(self._extra_layers))

    # -- request core ------------------------------------------------------

    def handle(self, method: str, path: str,
               if_none_match: str | None = None):
        """Returns ``(status, content_type, body, etag, route, cache)``;
        ``body`` is b"" for 304s, ``cache`` is "hit"/"miss"/"stale"/None.
        Synopsis tile answers are a :class:`Response` whose ``.headers``
        carries ``X-Heatmap-Synopsis`` (it still unpacks as the 6-tuple).
        Injected ``http.request`` faults surface as typed 503s — the
        chaos soak pins that no injected fault ever becomes a 500."""
        try:
            faults.check("http.request", key=method)
        except faults.InjectedFault as e:
            body = json.dumps({"error": "service unavailable",
                               "detail": str(e)}).encode()
            return 503, "application/json", body, None, "error", None
        ctl = self.degrade
        if ctl is not None:
            # Rate-limited burn re-evaluation; between polls this is one
            # clock read. Rung side effects (cache TTL stretch) apply on
            # the edge so the rung-0 path never touches the cache.
            ctl.poll()
            scale = ctl.ttl_scale()
            if scale != self.cache.ttl_scale:
                self.cache.set_ttl_scale(scale)
        # The query string never participates in routing (so the fleet
        # router's rendezvous key colocates ?synopsis=1 with the exact
        # tile); it only carries per-request options.
        path, _, query = path.partition("?")
        m = _TILE_RE.match(path)
        if method == "GET" and m is not None:
            return self._admitted_tile(m, if_none_match,
                                       self._synopsis_opt(query),
                                       _temporal_opt(query))
        if method == "GET" and path == "/query":
            return self._handle_query(query, if_none_match)
        if method == "GET" and path == "/series":
            return self._handle_series(query)
        if method == "GET" and path == "/dashboard":
            body = dashboard_mod.render_page()
            return (200, "text/html; charset=utf-8", body, None,
                    "dashboard", None)
        if method == "GET" and path == "/healthz":
            body = json.dumps(self._health(), indent=2).encode()
            return 200, "application/json", body, None, "healthz", None
        if method == "GET" and path == "/metrics":
            obs.refresh_process_gauges()
            body = _registry.render_prometheus().encode()
            return (200, "text/plain; version=0.0.4", body, None,
                    "metrics", None)
        if method == "POST" and path == "/reload":
            return self._handle_reload()
        if method == "POST" and path in ("/drain", "/undrain"):
            return self._handle_drain(path == "/drain")
        body = json.dumps({"error": "not found", "path": path}).encode()
        return 404, "application/json", body, None, "other", None

    # -- admission + drain -------------------------------------------------

    def _handle_drain(self, draining: bool):
        """Graceful drain: in-flight requests finish, new tile traffic
        sheds with a typed 503 until ``/undrain``. The fleet router
        drains a backend router-side first (pulls it from the ring),
        then forwards here so directly-addressed clients shed too."""
        self._draining = draining
        if draining:
            self._degrade("drain", "draining: shedding tile traffic")
        else:
            self._recover("drain")
        with self._inflight_lock:
            inflight = self._inflight
        body = json.dumps({"draining": draining,
                           "inflight": inflight}).encode()
        return 200, "application/json", body, None, "drain", None

    def _synopsis_opt(self, query: str) -> bool:
        """Resolve the ``synopsis`` query parameter (last value wins,
        per urllib convention) against the app default."""
        if not query:
            return self.synopsis_default
        vals = urllib.parse.parse_qs(query).get("synopsis")
        if not vals:
            return self.synopsis_default
        return vals[-1] not in ("0", "false", "no")

    def _admitted_tile(self, m, if_none_match, synopsis=False,
                       temporal=None):
        """Tile dispatch behind the drain gate and the in-flight bound.
        Shed responses are typed 503s (never 500) and edge-trigger the
        ``shed`` degradation cause so /healthz names why."""
        if self._draining:
            body = json.dumps({"error": "service unavailable",
                               "cause": "drain"}).encode()
            return 503, "application/json", body, None, "tiles", None
        ctl = self.degrade
        if ctl is not None:
            if ctl.shed((m["layer"], m["z"], m["x"], m["y"], m["fmt"])):
                # Top rung: deterministic fractional shed by tile key
                # (same seeded hash router-side, so the fleet agrees).
                if obs.metrics_enabled():
                    degrade_mod.DEGRADE_SHED.inc()
                self._degrade("brownout",
                              f"rung {ctl.rung}: shedding "
                              f"{ctl.shed_fraction:.0%} of tile keys")
                incident.trigger("shed",
                                 detail=f"brownout rung {ctl.rung}")
                body = json.dumps({"error": "service unavailable",
                                   "cause": "brownout"}).encode()
                return 503, "application/json", body, None, "tiles", None
            if ctl.rung < ctl.max_rung:
                self._recover("brownout")
        limit = (self.max_inflight if ctl is None
                 else ctl.inflight_limit(self.max_inflight))
        if limit is None:
            return self._handle_tile(m, if_none_match, synopsis, temporal)
        with self._inflight_lock:
            if self._inflight >= limit:
                admitted = False
            else:
                admitted = True
                self._inflight += 1
        if not admitted:
            self._degrade("shed",
                          f"in-flight bound {limit} reached")
            # Every typed-503 shed is an incident trigger edge (the
            # manager rate-limits per kind, so a shed burst flushes
            # one bundle, not one per rejected request).
            incident.trigger(
                "shed", detail=f"in-flight bound {limit}")
            body = json.dumps({"error": "service unavailable",
                               "cause": "shed"}).encode()
            return 503, "application/json", body, None, "tiles", None
        try:
            self._recover("shed")
            return self._handle_tile(m, if_none_match, synopsis, temporal)
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    def _handle_reload(self):
        try:
            generation = self.store.reload()
        except Exception as e:
            # TileStore builds the new index before swapping, so the
            # last-good one is still serving; report that honestly.
            self._degrade("reload", repr(e))
            body = json.dumps({
                "error": "reload failed", "detail": repr(e),
                "generation": self.store.generation,
            }).encode()
            return 503, "application/json", body, None, "reload", None
        self._recover("reload")
        # Re-warm after the swap: the new generation/delta_epoch keys
        # are all cold, and the reload already paid the expensive part
        # (index rebuild), so replaying the popular head now converts
        # the first post-reload requests from misses into hits.
        self.prewarm_now(source="reload")
        body = json.dumps({"generation": generation}).encode()
        return 200, "application/json", body, None, "reload", None

    def prewarm_now(self, source: str = "startup"):
        """Replay the configured popularity plan (tilefs.PrewarmConfig)
        through :meth:`handle`, filling the heap + disk caches. No-op
        without a config or recorded traffic; returns the warm summary
        (also kept for ``/healthz``). Callers decide *when*: the cli and
        fleet backends warm once bound, ``_handle_reload`` re-warms, and
        a bare ServeApp never warms implicitly."""
        cfg = self.prewarm
        if cfg is None:
            return None
        from heatmap_tpu.tilefs import prewarm as prewarm_mod

        plan = prewarm_mod.build_plan(cfg.events, top_k=cfg.top_k,
                                      half_life=cfg.half_life)
        if not plan:
            return None
        summary = prewarm_mod.warm(self, plan, budget_s=cfg.budget_s,
                                   budget_bytes=cfg.budget_bytes,
                                   source=source)
        self._prewarm_last = summary
        return summary

    # -- telemetry ---------------------------------------------------------

    def _handle_series(self, query: str):
        """``GET /series?name=&label=k=v&from=&to=&step=``: aligned
        frames from the telemetry tiers (obs/timeseries.py), achieved
        resolution stamped per frame. Sampler off is a well-formed
        answer (``enabled: false``, no frames), not an error — the
        dashboard polls this unconditionally. Deterministic: the same
        explicit ``from``/``to`` window over a quiescent store answers
        byte-identically on every query (pinned in
        tests/test_timeseries.py)."""
        return local_series_response(query)

    # -- range queries -----------------------------------------------------

    def _handle_query(self, query: str, if_none_match):
        """``GET /query``: O(1) range analytics (docs/analytics.md).

        Path selection, most to least exact-and-fast: the level's
        integral pyramid (four SAT corner lookups / pruned descent);
        the exact level rows when the store predates integral
        artifacts (slower, identical answer); the synopsis grid for
        ``op=sum`` under brownout rung >= 1, with the achieved error
        bound (stamped cell bound x rect area) in
        ``X-Heatmap-Query-Error``. Results are cached in the shared
        byte-capped LRU under the store generation (plus the synopsis
        epoch on the brownout path) with tile-style stale-if-error."""
        t0 = time.monotonic()
        params = urllib.parse.parse_qs(query) if query else {}

        def _param(name, default=None):
            vals = params.get(name)
            return vals[-1] if vals else default

        try:
            op = analytics_query.validate_op(_param("op", "sum"))
            if op in analytics_query.TEMPORAL_OPS:
                # Time-axis ops have their own parameter surface
                # (window instead of bbox) and their own evaluator.
                return self._handle_growth_query(params, if_none_match)
            layer_name = urllib.parse.unquote(_param("layer", "default"))
            z_raw = _param("z")
            if z_raw is None:
                raise ValueError(
                    "missing required parameter z (source grid zoom)")
            try:
                z = int(z_raw)
            except ValueError:
                raise ValueError(f"z must be an integer zoom, got {z_raw!r}")
            if not 0 <= z <= 30:
                raise ValueError(f"z must be in [0, 30], got {z}")
            bbox_raw = _param("bbox")
            if bbox_raw is None:
                raise ValueError("missing required parameter bbox "
                                 "('x0,y0,x1,y1' inclusive cells)")
            rect = analytics_query.parse_bbox(bbox_raw, z)
            try:
                k = int(_param("k", "10"))
            except ValueError:
                raise ValueError(f"k must be an integer, got {_param('k')!r}")
            if op == "topk" and k < 1:
                raise ValueError(f"k must be >= 1, got {k}")
            try:
                q = float(_param("q", "0.5"))
            except ValueError:
                raise ValueError(f"q must be a float, got {_param('q')!r}")
            if op == "quantile" and not 0.0 <= q <= 1.0:
                raise ValueError(f"q must be in [0, 1], got {q}")
        except ValueError as e:
            body = json.dumps({"error": "bad query",
                               "detail": str(e)}).encode()
            return 400, "application/json", body, None, "query", None
        layer = self.layer(layer_name)
        if layer is None:
            body = json.dumps({"error": "unknown layer",
                               "layers": self.layer_names()}).encode()
            return 404, "application/json", body, None, "query", None
        integrals = getattr(layer, "integrals", None) or {}
        synopses = getattr(layer, "synopses", None) or {}
        ctl = self.degrade
        syn_view = None
        if (ctl is not None and ctl.force_synopsis() and op == "sum"
                and z in synopses):
            # Brownout: answer from the synopsis-reconstructed grid
            # when one exists at this zoom; otherwise stay exact (an
            # exact answer under load beats a missing one).
            syn_view = synopses[z]
        if syn_view is not None:
            mode = "synopsis"
        elif z in integrals:
            mode = "integral"
        elif z in getattr(layer, "levels", {}):
            mode = "fallback"
        else:
            body = json.dumps({
                "error": f"no stored level at zoom {z}",
                "detail_zooms": sorted(getattr(layer, "levels", {})),
            }).encode()
            return 404, "application/json", body, None, "query", None
        r0, c0, r1, c1 = rect
        area = (r1 - r0 + 1) * (c1 - c0 + 1)
        doc = {"op": op, "layer": layer_name, "z": z,
               "bbox": [c0, r0, c1, r1], "path": mode}
        if op == "topk":
            doc["k"] = k
        elif op == "quantile":
            doc["q"] = q
        extra = None
        if mode == "synopsis":
            # Per-cell bound from the artifact stamp; a rect sum over
            # ``area`` cells can be off by at most ``max_err * area``.
            bound = float(syn_view.max_err) * area
            extra = {"X-Heatmap-Query-Error": f"max_err={bound:.6g}"}
            doc["max_err"] = bound
            key = ("query", layer_name, z, rect, op, "syn",
                   self.store.synopsis_epoch)
        else:
            key = ("query", layer_name, z, rect, op,
                   k if op == "topk" else None,
                   q if op == "quantile" else None)

        def _evaluate() -> bytes:
            out = dict(doc)
            if mode == "integral":
                pair = integrals[z]
                out["cells"] = pair.cell_count(*rect)
                if op == "sum":
                    out["sum"] = analytics_query.range_sum(pair, rect)
                elif op == "topk":
                    out["hotspots"] = [
                        [int(c), int(r), v] for r, c, v in
                        analytics_query.top_k_hotspots(pair, rect, k)]
                else:
                    out["value"] = analytics_query.quantile(pair, rect, q)
            else:
                level = (syn_view.level if mode == "synopsis"
                         else layer.levels[z])
                rows, cols, vals = analytics_query.level_cells(level, rect)
                out["cells"] = int(len(vals))
                if op == "sum":
                    out["sum"] = float(vals.sum()) if len(vals) else 0.0
                elif op == "topk":
                    out["hotspots"] = [
                        [int(c), int(r), v] for r, c, v in
                        analytics_query.top_k_rows(level, rect, k)]
                else:
                    out["value"] = analytics_query.quantile_rows(
                        level, rect, q)
            return json.dumps(out).encode()

        try:
            body, hit = self.cache.get_or_render(
                key, self.store.generation, _evaluate, fmt="query",
                stale_if_error=True)
        except Exception as e:
            self._degrade("render", repr(e))
            payload = json.dumps({"error": "query failed",
                                  "detail": repr(e)}).encode()
            return 503, "application/json", payload, None, "query", None
        if hit == TileCache.STALE:
            self._degrade("render", "serving stale query results")
            cache = "stale"
        else:
            if hit is False:
                self._recover("render")
            cache = "hit" if hit else "miss"
        ms = round((time.monotonic() - t0) * 1e3, 3)
        if obs.metrics_enabled():
            analytics_metrics.QUERY_SECONDS.observe(
                time.monotonic() - t0, op=op)
        cells = json.loads(body).get("cells")
        obs.emit("query_served", op=op, zoom=int(z), path=mode,
                 layer=layer_name, bbox_area=int(area), ms=ms,
                 **({"cells": int(cells)} if cells is not None else {}),
                 **({"k": k} if op == "topk" else {}),
                 **({"q": q} if op == "quantile" else {}),
                 **({"max_err": doc["max_err"]}
                    if mode == "synopsis" else {}))
        etag = _query_etag(body)
        if if_none_match is not None and etag in if_none_match:
            return Response(304, "application/json", b"", etag, "query",
                            cache, headers=extra)
        return Response(200, "application/json", body, etag, "query",
                        cache, headers=extra)

    def _handle_growth_query(self, params, if_none_match):
        """``GET /query?op=topk_growth&window=1w``: top-k cells by
        growth over the trailing window, from Haar wavelet histograms
        over the per-bucket cell series (temporal/timequery.py). The
        answer is approximate with a SOUND stamped bound: the achieved
        error rides ``X-Heatmap-Query-Error`` exactly like the synopsis
        /query path, and the oracle test pins ``|approx - exact| <=
        bound`` cell by cell. Cached under the fold selection token, so
        results survive until the underlying buckets actually change."""
        from heatmap_tpu.temporal import buckets as tb
        from heatmap_tpu.temporal import fold as tfold
        from heatmap_tpu.temporal import timequery
        from heatmap_tpu.temporal.metrics import TEMPORAL_REQUESTS

        t0 = time.monotonic()

        def _param(name, default=None):
            vals = params.get(name)
            return vals[-1] if vals else default

        try:
            layer_name = urllib.parse.unquote(_param("layer", "default"))
            z_raw = _param("z")
            if z_raw is None:
                raise ValueError(
                    "missing required parameter z (source grid zoom)")
            try:
                z = int(z_raw)
            except ValueError:
                raise ValueError(f"z must be an integer zoom, got {z_raw!r}")
            window_raw = _param("window")
            if window_raw is None:
                raise ValueError("op=topk_growth requires window= "
                                 "(1h|1d|1w or seconds)")
            try:
                k = int(_param("k", "10"))
            except ValueError:
                raise ValueError(f"k must be an integer, got {_param('k')!r}")
            if k < 1:
                raise ValueError(f"k must be >= 1, got {k}")
            try:
                coeffs = int(_param("m", str(timequery.DEFAULT_COEFFS)))
            except ValueError:
                raise ValueError(
                    f"m must be an integer coefficient budget, "
                    f"got {_param('m')!r}")
            if coeffs < 1:
                raise ValueError(f"m must be >= 1, got {coeffs}")
            root = self.store.temporal_root()
            if root is None:
                raise ValueError(
                    "op=topk_growth needs a delta-shaped store "
                    f"(store spec is {self.store.spec!r})")
            cfg = tfold.temporal_config(root)
            if cfg is None:
                raise ValueError(
                    "store has no temporal config — run a bucketed "
                    "compaction (docs/temporal.md) first")
            window = tb.parse_window(window_raw, cfg)
        except ValueError as e:
            body = json.dumps({"error": "bad query",
                               "detail": str(e)}).encode()
            return 400, "application/json", body, None, "query", None
        layer = self.layer(layer_name)
        if layer is None:
            body = json.dumps({"error": "unknown layer",
                               "layers": self.layer_names()}).encode()
            return 404, "application/json", body, None, "query", None
        # select_fold is metadata-only and deterministic, so this token
        # is the same one the evaluator will compute — a valid pre-
        # render cache key that retires exactly when buckets change.
        sel = tfold.select_fold(root, window=window)
        key = ("query", layer_name, z, "growth", window_raw, k, coeffs,
               sel.token)

        def _evaluate() -> bytes:
            doc = timequery.topk_growth(
                root, user=layer.user, timespan=layer.timespan,
                zoom=z, window=window, k=k, coeffs=coeffs)
            doc["layer"] = layer_name
            return json.dumps(doc).encode()

        try:
            body, hit = self.cache.get_or_render(
                key, self.store.generation, _evaluate, fmt="query",
                stale_if_error=True)
        except Exception as e:
            self._degrade("render", repr(e))
            payload = json.dumps({"error": "query failed",
                                  "detail": repr(e)}).encode()
            return 503, "application/json", payload, None, "query", None
        if hit == TileCache.STALE:
            self._degrade("render", "serving stale query results")
            cache = "stale"
        else:
            if hit is False:
                self._recover("render")
            cache = "hit" if hit else "miss"
        doc = json.loads(body)
        ms = round((time.monotonic() - t0) * 1e3, 3)
        if obs.metrics_enabled():
            analytics_metrics.QUERY_SECONDS.observe(
                time.monotonic() - t0, op="topk_growth")
            TEMPORAL_REQUESTS.inc(mode="growth")
        obs.emit("query_served", op="topk_growth", zoom=int(z),
                 path="temporal", layer=layer_name, k=k, ms=ms,
                 window=window_raw, slots=int(doc.get("slots", 0)),
                 max_err=float(doc.get("max_err", 0.0)),
                 cells=len(doc.get("cells", [])))
        extra = {"X-Heatmap-Query-Error":
                 f"max_err={doc.get('max_err', 0.0):.6g}"}
        etag = _query_etag(body)
        if if_none_match is not None and etag in if_none_match:
            return Response(304, "application/json", b"", etag, "query",
                            cache, headers=extra)
        return Response(200, "application/json", body, etag, "query",
                        cache, headers=extra)

    def _handle_temporal_tile(self, m, if_none_match, temporal):
        """``?as_of=/window=/decay=`` tiles: render from a partial-
        pyramid fold (heatmap_tpu.temporal) instead of the all-time
        index. Cache keys carry the bucket cut: undecayed window tiles
        use the STABLE key ``(..., "w", param)`` so delta refreshes and
        bucket rolls can invalidate exactly the dirtied entries, while
        as_of/decay tiles fold the selection token into the key —
        history below a cut is immutable, so those entries survive
        unrelated ingest structurally. A torn bucket surfaces inside
        the render and the stale-if-error cache serves last-good bytes;
        the all-time path never reads buckets and is unaffected."""
        from heatmap_tpu.temporal import buckets as tb
        from heatmap_tpu.temporal import fold as tfold
        from heatmap_tpu.temporal.metrics import TEMPORAL_REQUESTS

        t0 = time.monotonic()
        layer_name = urllib.parse.unquote(m["layer"])
        z, x, y = int(m["z"]), int(m["x"]), int(m["y"])
        fmt = m["fmt"]
        if not (0 <= x < (1 << z) and 0 <= y < (1 << z)):
            body = json.dumps({"error": "off-grid tile",
                               "layers": self.layer_names()}).encode()
            return 404, "application/json", body, None, "tiles", None
        root = self.store.temporal_root()
        try:
            if root is None:
                raise ValueError(
                    "temporal params need a delta-shaped store "
                    f"(store spec is {self.store.spec!r})")
            cfg = tfold.temporal_config(root)
            if cfg is None:
                raise ValueError(
                    "store has no temporal config — run a bucketed "
                    "compaction (docs/temporal.md) before temporal "
                    "queries")
            as_of = (float(temporal["as_of"])
                     if "as_of" in temporal else None)
            window = (tb.parse_window(temporal["window"], cfg)
                      if "window" in temporal else None)
            decay = (tb.parse_window(temporal["decay"], cfg)
                     if "decay" in temporal else None)
        except (ValueError, TypeError) as e:
            body = json.dumps({"error": "bad temporal query",
                               "detail": str(e)}).encode()
            return 400, "application/json", body, None, "tiles", None
        mode = ("as_of" if as_of is not None
                else "decay" if decay is not None else "window")
        if mode == "window" and decay is None and as_of is None:
            key = (layer_name, z, x, y, fmt, "w", temporal["window"])
            self.cache.note_window_param(temporal["window"])
        else:
            # select_fold reads only CURRENT + manifest + journal meta
            # (never bucket bytes), so keying cannot trip on a torn
            # bucket — that surfaces inside the render below, where
            # stale-if-error can absorb it.
            sel = tfold.select_fold(root, as_of=as_of, window=window,
                                    decay=decay)
            key = (layer_name, z, x, y, fmt, "t", sel.token)
        render = tile_png_bytes if fmt == "png" else tile_json_bytes

        def render_fn():
            layers, _token = self.store.temporal_view(
                as_of=as_of, window=window, decay=decay)
            layer = layers.get(layer_name)
            if layer is None:
                return None  # no data for this layer inside the cut
            return self._render(render, layer, z, x, y, fmt)

        try:
            body, hit = self.cache.get_or_render(
                key, self.store.generation, render_fn,
                fmt=fmt, stale_if_error=True)
        except Exception as e:
            self._degrade("render", repr(e))
            payload = json.dumps({"error": "render failed",
                                  "detail": repr(e)}).encode()
            return 503, "application/json", payload, None, "tiles", None
        if hit == TileCache.STALE:
            self._degrade("render", "serving stale tiles")
            cache = "stale"
        else:
            if hit is False:
                self._recover("render")
            cache = "hit" if hit else "miss"
        if body is None:
            payload = json.dumps({"error": "empty tile"}).encode()
            return 404, "application/json", payload, None, "tiles", cache
        if obs.metrics_enabled():
            TEMPORAL_REQUESTS.inc(mode=mode)
        obs.emit("temporal_served", layer=layer_name, zoom=int(z),
                 mode=mode, cache=cache,
                 ms=round((time.monotonic() - t0) * 1e3, 3),
                 **{k: temporal[k] for k in ("as_of", "window", "decay")
                    if k in temporal})
        extra = {"X-Heatmap-Temporal": mode}
        etag = _temporal_etag(body)
        if if_none_match is not None and etag in if_none_match:
            return Response(304, _CONTENT_TYPES[fmt], b"", etag, "tiles",
                            cache, headers=extra)
        return Response(200, _CONTENT_TYPES[fmt], body, etag, "tiles",
                        cache, headers=extra)

    def _handle_tile(self, m, if_none_match, synopsis=False,
                     temporal=None):
        if temporal is not None:
            return self._handle_temporal_tile(m, if_none_match, temporal)
        # Layer names may carry characters clients percent-encode in a
        # path segment (the delta stores' "user|timespan" keys).
        layer_name = urllib.parse.unquote(m["layer"])
        z, x, y = int(m["z"]), int(m["x"]), int(m["y"])
        fmt = m["fmt"]
        layer = self.layer(layer_name)
        if layer is None or not (0 <= x < (1 << z) and 0 <= y < (1 << z)):
            body = json.dumps({
                "error": "unknown layer" if layer is None else "off-grid tile",
                "layers": self.layer_names(),
            }).encode()
            return 404, "application/json", body, None, "tiles", None
        # ?synopsis=1 only takes effect when the SAME source zoom the
        # exact path would use carries a decoded synopsis; otherwise
        # fall through to the exact path under the exact cache key and
        # ETag — byte-identical to an un-annotated request. The brownout
        # ladder overrides the opt-in: rung >= 1 forces the synopsis
        # path, rung >= 2 additionally stretches it (a coarser
        # synopsis-carrying source upsamples into zooms that have no
        # natural synopsis — the raised zoom ceiling).
        ctl = self.degrade
        stretch = False
        if ctl is not None:
            synopsis = synopsis or ctl.force_synopsis()
            stretch = ctl.stretch_synopsis()
        syn_view = syn_src = None
        stretched = False
        if synopsis:
            src, view = synopsis_source(layer, z)
            if view is None and stretch:
                src, view = synopsis_source(layer, z, stretch=True)
                stretched = view is not None
            if view is not None:
                syn_view, syn_src = view, src
                layer = SynopsisLayer(
                    layer, max_level=src if stretched else None)
        if syn_view is None:
            key = (layer_name, z, x, y, fmt)
        else:
            # The synopsis_epoch in the key retires approximate bytes
            # whenever the decoded views change (reload, refresh, a
            # provisional early-serve publish) — the generation alone
            # does not move on a provisional overlay.
            key = (layer_name, z, x, y, fmt, "syn",
                   self.store.synopsis_epoch)
        render = tile_png_bytes if fmt == "png" else tile_json_bytes
        render_fn = lambda: self._render(render, layer, z, x, y, fmt)  # noqa: E731
        if self.disk_cache is not None:
            # Disk tier between the heap LRU and the renderer. The heap
            # cache's single-flight leader runs this fill, so at most
            # one thread touches disk per key. The key folds in the
            # store's invalidation epochs: generation retires bytes on
            # reload/compaction, delta_epoch on every journal apply
            # (synopsis keys already carry synopsis_epoch in `key`).
            # A torn or missing entry reads as a miss; a failed
            # write-through is a skipped optimization, never an error.
            dkey = (key, self.store.generation, self.store.delta_epoch)
            inner = render_fn

            def render_fn():
                cached = self.disk_cache.get(dkey)
                if cached is not None:
                    return cached
                body = inner()
                if body is not None:
                    self.disk_cache.put(dkey, body)
                return body
        try:
            body, hit = self.cache.get_or_render(
                key, self.store.generation, render_fn,
                fmt=fmt, stale_if_error=True)
        except Exception as e:
            # No last-good bytes to fall back on: typed 503, never 500.
            self._degrade("render", repr(e))
            payload = json.dumps({"error": "render failed",
                                  "detail": repr(e)}).encode()
            return 503, "application/json", payload, None, "tiles", None
        if hit == TileCache.STALE:
            self._degrade("render", "serving stale tiles")
            cache = "stale"
        else:
            if hit is False:  # a fresh render succeeded end-to-end
                self._recover("render")
            cache = "hit" if hit else "miss"
        if body is None:
            payload = json.dumps({"error": "empty tile"}).encode()
            return 404, "application/json", payload, None, "tiles", cache
        extra = None
        if syn_view is not None:
            marker = f"max_err={syn_view.max_err:.6g}"
            if syn_view.stale:
                marker += "; stale=1"
            if stretched:
                # Raised-ceiling answers add quadrant-upsample error on
                # top of the stamped coefficient error; say so.
                marker += "; stretch=1"
            extra = {"X-Heatmap-Synopsis": marker}
            obs.emit("synopsis_served", layer=layer_name, zoom=int(z),
                     max_err=float(syn_view.max_err),
                     source_zoom=int(syn_src),
                     **({"stale": True} if syn_view.stale else {}),
                     **({"stretched": True} if stretched else {}))
            etag = _syn_etag(body)
        else:
            etag = _etag(body)
        if if_none_match is not None and etag in if_none_match:
            return Response(304, _CONTENT_TYPES[fmt], b"", etag, "tiles",
                            cache, headers=extra)
        return Response(200, _CONTENT_TYPES[fmt], body, etag, "tiles",
                        cache, headers=extra)

    def _render(self, render, layer, z, x, y, fmt: str):
        """One tile render under the ``tile.render`` fault site and the
        optional per-render deadline. The deadline runs the render on a
        worker thread so a wedged renderer costs the request a bounded
        wait, not the whole server a thread forever; the abandoned
        render finishes (or dies) in the pool without a waiter."""
        faults.check("tile.render", key=fmt)
        if self.render_timeout_s is None:
            return render(layer, z, x, y)
        if self._render_pool is None:
            with self._degraded_lock:
                if self._render_pool is None:
                    self._render_pool = (
                        concurrent.futures.ThreadPoolExecutor(
                            max_workers=4,
                            thread_name_prefix="tile-render"))
        # context_bound carries the ambient request span into the pool
        # worker (a plain submit would start from an empty context and
        # the worker-side span would orphan into its own trace).
        def pooled(layer, z, x, y):
            span = tracing.begin_span("tile.render.worker", {"format": fmt})
            try:
                return render(layer, z, x, y)
            finally:
                tracing.end_span(span)

        future = self._render_pool.submit(
            tracing.context_bound(pooled), layer, z, x, y)
        try:
            return future.result(timeout=self.render_timeout_s)
        except concurrent.futures.TimeoutError:
            future.cancel()
            raise TimeoutError(
                f"tile render exceeded {self.render_timeout_s}s deadline")

    def _health(self) -> dict:
        stats = self.store.stats()
        for name, layer in sorted(self._extra_layers.items()):
            stats["layers"][name] = {
                "user": layer.user,
                "timespan": layer.timespan,
                "detail_zooms": layer.detail_zooms,
                "result_delta": layer.result_delta,
                "rows": int(sum(len(l) for l in layer.levels.values())),
                "live": True,
            }
        stats["cache"] = {"entries": len(self.cache),
                          "bytes": self.cache.nbytes}
        if self.disk_cache is not None:
            stats["disk_cache"] = self.disk_cache.stats()
        if self._prewarm_last is not None:
            stats["prewarm"] = self._prewarm_last
        with self._inflight_lock:
            stats["inflight"] = self._inflight
        stats["draining"] = self._draining
        causes = self.degraded_causes()
        stats["status"] = "degraded" if causes else "ok"
        if causes:
            stats["degraded"] = causes
        slo_state = slo.slo_status()
        if slo_state is not None:
            stats["slo"] = slo_state
        # Numeric distance-to-breach, not just breach: per-objective
        # burn fractions ({} folded away when no engine is installed)
        # plus the brownout ladder state the router probes read.
        burns = slo.burn_values()
        if burns:
            stats["slo_burn"] = {k: round(float(v), 4)
                                 for k, v in sorted(burns.items())}
        if self.degrade is not None:
            stats["degrade"] = self.degrade.snapshot()
        # Telemetry store + anomaly engine state (when armed): the
        # dashboard's status chips and anomaly panel read these.
        ts_store = timeseries.get_store()
        if ts_store is not None:
            stats["telemetry"] = ts_store.stats()
        engine = anomaly.get_engine()
        if engine is not None:
            stats["anomalies"] = engine.recent(16)
            stats["anomaly_watches"] = engine.status()["watches"]
        return stats


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # Keep-alive + small responses otherwise hit the Nagle/delayed-ACK
    # interaction: every cached tile pays a ~40ms ACK stall.
    disable_nagle_algorithm = True
    app: ServeApp  # bound by make_server

    def _dispatch(self, method: str):
        t0 = time.monotonic()
        # Each request is a trace root (sampled per --trace-sample); an
        # incoming traceparent header instead continues the client's
        # trace, inheriting its sampled flag. Handler threads start
        # with a fresh context, so every request tree is independent.
        req_span = tracing.begin_span(
            "serve.request", {"method": method, "path": self.path},
            traceparent=self.headers.get("traceparent"))
        try:
            try:
                result = self.app.handle(
                    method, self.path, self.headers.get("If-None-Match"))
                status, ctype, body, etag, route, cache = result
                extra_headers = getattr(result, "headers", None)
            except Exception as e:  # defensive: a render bug must not kill serving
                status, ctype, route, cache = (500, "application/json",
                                               "error", None)
                body = json.dumps({"error": repr(e)}).encode()
                etag = None
                extra_headers = None
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            if extra_headers:
                for name, value in extra_headers.items():
                    self.send_header(name, value)
            if status == 503:
                # Shed/drain/degraded answers are retryable by
                # construction; tell well-behaved clients when. The
                # advertised delay carries seeded jitter (the
                # faults/retry.py shape) so a burst of shed clients
                # does not come back as a synchronized thundering herd.
                retry_after = getattr(self.app, "retry_after_s", 1.0)
                self.send_header(
                    "Retry-After",
                    str(degrade_mod.retry_after_jitter(
                        retry_after, self.path, int(t0))))
            if etag is not None:
                self.send_header("ETag", etag)
            tp = tracing.current_traceparent()
            if tp is not None:
                self.send_header("traceparent", tp)
            self.end_headers()
            if body:
                self.wfile.write(body)
            if obs.metrics_enabled():
                HTTP_REQUESTS.inc(route=route, status=str(status))
            ms = round((time.monotonic() - t0) * 1e3, 3)
            # Emitted while the request span is still ambient, so the
            # event is stamped with this tree's trace_id/span_id.
            obs.emit("http_request", route=route, status=int(status),
                     path=self.path, ms=ms, bytes=len(body),
                     **({"cache": cache} if cache else {}))
            # Tail-based retention: a 5xx or a tail-latency outlier
            # promotes this request's tree out of the flight-recorder
            # ring even when head sampling dropped it. Must run before
            # end_span so the root itself rides the live-forward path.
            recorder.maybe_promote(req_span, status=status, ms=ms)
        finally:
            tracing.end_span(req_span)

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass  # request logging goes through obs events, never stdout


def make_server(app: ServeApp, host: str = "127.0.0.1",
                port: int = 0) -> ThreadingHTTPServer:
    """Bound-but-not-serving ThreadingHTTPServer (port 0 = ephemeral;
    read the real one from ``server.server_address[1]``). Caller runs
    ``serve_forever()`` — inline (CLI) or in a thread (tests/bench)."""
    handler = type("Handler", (_Handler,), {"app": app})
    server = ThreadingHTTPServer((host, port), handler)
    server.daemon_threads = True
    return server


def serve_in_thread(app: ServeApp, host: str = "127.0.0.1", port: int = 0):
    """Test/bench helper: returns ``(server, base_url)`` with
    serve_forever running on a daemon thread; ``server.shutdown()``
    stops it."""
    server = make_server(app, host, port)
    thread = threading.Thread(target=server.serve_forever,
                              name="serve-http", daemon=True)
    thread.start()
    h, p = server.server_address[:2]
    return server, f"http://{h}:{p}"
