"""Serve-fleet supervisor: N shared-nothing backends + one router.

Each backend is an ordinary :class:`ServeApp` over its **own**
``TileStore`` instance reading the same artifact — shared-nothing, so
a backend crash loses only its LRU, and rendezvous routing means each
backend's cache specializes to the key range the ring hands it.

Two backend modes behind one handle interface:

- ``process`` (production, ``serve --fleet N``): each backend is a
  child ``python -m heatmap_tpu.serve.fleet --backend`` with its own
  interpreter (no shared GIL). The child binds an ephemeral port and
  reports it through a **port file** (atomic tmp+rename) — the
  supervisor never parses child output, and a child that dies before
  writing the file just times out the spawn.
- ``thread`` (tests, soak harnesses): the backend is an in-process
  ``ServeApp`` on a daemon HTTP thread. Same router, same wire
  protocol, no fork cost.

Crash handling: the monitor thread notices a dead backend, force-opens
its breaker (``fleet_backend_down``), and restarts it with exponential
backoff and seeded jitter (the ``faults/retry.py`` shape). The restart
does **not** re-admit the backend — the router's half-open health
probe does, once the replacement actually answers ``/healthz``
(``fleet_backend_up``). All waiting uses ``Event.wait``; nothing in
serve/ sleeps raw (grep guard, tests/test_obs.py).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

from heatmap_tpu import faults, obs
from heatmap_tpu.obs import anomaly, slo, timeseries
from heatmap_tpu.serve import degrade as degrade_mod
from heatmap_tpu.serve.cache import TileCache
from heatmap_tpu.serve.http import ServeApp, make_server, serve_in_thread
from heatmap_tpu.serve.router import (FLEET_RESTARTS, BackendClient,
                                      RouterApp)
from heatmap_tpu.serve.store import TileStore
from heatmap_tpu.tilefs import DiskTileCache, PrewarmConfig


def _backend_serving_extras(backend_id: str, disk_cache_opts,
                            prewarm_opts):
    """Materialize the per-backend disk cache + prewarm config from the
    supervisor's option dicts. Each backend caches under its own subdir
    — entries are cheap to refill and a shared directory would race the
    deterministic tmp names across processes."""
    disk_cache = None
    if disk_cache_opts and disk_cache_opts.get("root"):
        disk_cache = DiskTileCache(
            os.path.join(disk_cache_opts["root"], backend_id),
            max_bytes=int(disk_cache_opts.get("max_bytes", 1 << 30)))
    prewarm = None
    if prewarm_opts and prewarm_opts.get("events"):
        prewarm = PrewarmConfig(
            events=tuple(prewarm_opts["events"]),
            top_k=int(prewarm_opts.get("top_k", 64)),
            half_life=float(prewarm_opts.get("half_life", 512.0)),
            budget_s=float(prewarm_opts.get("budget_s", 10.0)),
            budget_bytes=int(prewarm_opts.get("budget_bytes", 64 << 20)))
    return disk_cache, prewarm


def _warm_in_background(app: ServeApp):
    """Replay the popularity plan without delaying readiness: the
    backend reports its port first, then fills caches while early
    requests are already being answered (worst case: they miss)."""
    if app.prewarm is None:
        return
    threading.Thread(target=app.prewarm_now,
                     kwargs={"source": "startup"},
                     name="prewarm", daemon=True).start()


class _ThreadBackend:
    """In-process backend: ServeApp + daemon HTTP thread."""

    def __init__(self, backend_id: str, store_factory, *,
                 host: str = "127.0.0.1", cache_bytes: int = 64 << 20,
                 max_inflight: int | None = None,
                 render_timeout_s: float | None = None,
                 degrade_opts: dict | None = None,
                 disk_cache_opts: dict | None = None,
                 prewarm_opts: dict | None = None):
        self.id = backend_id
        self._store_factory = store_factory
        self._host = host
        self._cache_bytes = cache_bytes
        self._max_inflight = max_inflight
        self._render_timeout_s = render_timeout_s
        self._degrade_opts = degrade_opts
        self._disk_cache_opts = disk_cache_opts
        self._prewarm_opts = prewarm_opts
        self.app: ServeApp | None = None
        self._server = None
        self._alive = False
        self.started_at = 0.0

    def start(self, stop_event: threading.Event | None = None):
        store = self._store_factory()
        # Each backend gets its own ladder; in thread mode they share
        # the process-global SLO engine, so they step together.
        controller = (degrade_mod.controller_from_flags(
            True, **self._degrade_opts) if self._degrade_opts else None)
        disk_cache, prewarm = _backend_serving_extras(
            self.id, self._disk_cache_opts, self._prewarm_opts)
        self.app = ServeApp(store, TileCache(max_bytes=self._cache_bytes),
                            max_inflight=self._max_inflight,
                            render_timeout_s=self._render_timeout_s,
                            degrade=controller, disk_cache=disk_cache,
                            prewarm=prewarm)
        self._server, _ = serve_in_thread(self.app, host=self._host)
        self._alive = True
        self.started_at = time.monotonic()
        host, port = self._server.server_address[:2]
        _warm_in_background(self.app)
        return host, port

    def alive(self) -> bool:
        return self._alive

    def kill(self):
        """Hard stop — the thread-mode stand-in for SIGKILL."""
        self._alive = False
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    stop = kill


class _ProcessBackend:
    """Child-process backend driven through ``--backend`` below."""

    def __init__(self, backend_id: str, store_spec: str, *,
                 host: str = "127.0.0.1", cache_bytes: int = 64 << 20,
                 max_inflight: int | None = None,
                 render_timeout_s: float | None = None,
                 chaos: str | None = None, workdir: str = ".",
                 spawn_timeout_s: float = 30.0,
                 degrade_opts: dict | None = None,
                 slo_specs: list | None = None,
                 disk_cache_opts: dict | None = None,
                 prewarm_opts: dict | None = None,
                 telemetry_opts: dict | None = None):
        self.id = backend_id
        self._store_spec = store_spec
        self._host = host
        self._cache_bytes = cache_bytes
        self._max_inflight = max_inflight
        self._render_timeout_s = render_timeout_s
        self._chaos = chaos
        self._workdir = workdir
        self._spawn_timeout_s = spawn_timeout_s
        self._degrade_opts = degrade_opts
        self._slo_specs = list(slo_specs or [])
        self._disk_cache_opts = disk_cache_opts
        self._prewarm_opts = prewarm_opts
        self._telemetry_opts = telemetry_opts
        self.proc: subprocess.Popen | None = None
        self.started_at = 0.0
        self._seq = 0

    def start(self, stop_event: threading.Event | None = None):
        self._seq += 1
        port_file = os.path.join(self._workdir,
                                 f"{self.id}.{self._seq}.port")
        argv = [sys.executable, "-m", "heatmap_tpu.serve.fleet",
                "--backend", "--store", self._store_spec,
                "--port-file", port_file, "--host", self._host,
                "--cache-bytes", str(self._cache_bytes)]
        if self._max_inflight is not None:
            argv += ["--max-inflight", str(self._max_inflight)]
        if self._render_timeout_s is not None:
            argv += ["--render-timeout", str(self._render_timeout_s)]
        if self._chaos:
            argv += ["--chaos", self._chaos]
        for spec in self._slo_specs:
            argv += ["--slo", spec]
        if self._telemetry_opts and self._telemetry_opts.get("interval"):
            # Forwarded like --slo: each child samples its own registry
            # so the router's fleet-merged /series carries per-backend
            # history, and child-side watches score child-side traffic.
            argv += ["--telemetry-sample-interval",
                     str(self._telemetry_opts["interval"])]
            for spec in self._telemetry_opts.get("watches") or []:
                argv += ["--watch", spec]
        if self._degrade_opts:
            argv += ["--degrade",
                     "--degrade-dwell",
                     str(self._degrade_opts.get("dwell_s", 10.0)),
                     "--degrade-hold",
                     str(self._degrade_opts.get("hold_s", 30.0))]
            ladder = self._degrade_opts.get("ladder_spec", "")
            if ladder:
                argv += ["--degrade-ladder", ladder]
        if self._disk_cache_opts and self._disk_cache_opts.get("root"):
            # Per-backend subdir (same reasoning as
            # _backend_serving_extras): a shared directory would race
            # the deterministic tmp names across processes.
            argv += ["--disk-cache",
                     os.path.join(self._disk_cache_opts["root"], self.id),
                     "--disk-cache-bytes",
                     str(self._disk_cache_opts.get("max_bytes", 1 << 30))]
        if self._prewarm_opts and self._prewarm_opts.get("events"):
            for path in self._prewarm_opts["events"]:
                argv += ["--prewarm-events", path]
            argv += ["--prewarm-top-k",
                     str(self._prewarm_opts.get("top_k", 64)),
                     "--prewarm-budget-s",
                     str(self._prewarm_opts.get("budget_s", 10.0)),
                     "--prewarm-bytes",
                     str(self._prewarm_opts.get("budget_bytes", 64 << 20))]
        env = os.environ.copy()
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get(
            "PYTHONPATH", "")
        self.proc = subprocess.Popen(argv, env=env,
                                     stdout=subprocess.DEVNULL,
                                     stderr=subprocess.DEVNULL)
        self.started_at = time.monotonic()
        return self._wait_port(port_file, stop_event)

    def _wait_port(self, port_file: str, stop_event):
        waiter = stop_event or threading.Event()
        deadline = time.monotonic() + self._spawn_timeout_s
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"backend {self.id} exited with "
                    f"{self.proc.returncode} before binding a port")
            try:
                with open(port_file) as fh:
                    info = json.load(fh)
                os.unlink(port_file)
                return info["host"], int(info["port"])
            except (OSError, ValueError, KeyError):
                if waiter.wait(0.02):
                    raise RuntimeError("supervisor stopping") from None
        raise RuntimeError(
            f"backend {self.id} did not report a port within "
            f"{self._spawn_timeout_s}s")

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def kill(self):
        """SIGKILL — the chaos path (``backend_loss``)."""
        if self.proc is not None:
            self.proc.kill()

    def stop(self):
        if self.proc is None:
            return
        self.proc.terminate()
        try:
            self.proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=5.0)


class FleetSupervisor:
    """Spawn N backends, front them with a :class:`RouterApp`, restart
    crashers with exponential backoff, let half-open probes re-admit.

    ``mode="process"`` needs ``store_spec`` (a ``TileStore`` spec
    string); ``mode="thread"`` accepts ``store_factory`` instead for
    stores that are not spec-addressable (tests over tmp dirs are).
    """

    def __init__(self, store_spec: str | None, n_backends: int, *,
                 mode: str = "process", store_factory=None,
                 host: str = "127.0.0.1", cache_bytes: int = 64 << 20,
                 backend_max_inflight: int | None = None,
                 render_timeout_s: float | None = None,
                 chaos: str | None = None,
                 max_inflight: int = 32, queue_deadline_s: float = 0.25,
                 hedge_quantile: float = 0.95,
                 probe_interval_s: float = 0.25,
                 restart_base_s: float = 0.2, restart_cap_s: float = 5.0,
                 monitor_interval_s: float = 0.1,
                 spawn_timeout_s: float = 30.0,
                 degrade_opts: dict | None = None,
                 slo_specs: list | None = None,
                 disk_cache_opts: dict | None = None,
                 prewarm_opts: dict | None = None,
                 telemetry_opts: dict | None = None):
        if mode not in ("process", "thread"):
            raise ValueError(f"unknown fleet mode {mode!r}")
        if mode == "process" and not store_spec:
            raise ValueError("process mode needs a store spec")
        self.mode = mode
        self.n_backends = int(n_backends)
        if self.n_backends < 1:
            raise ValueError("a fleet needs at least one backend")
        self._store_spec = store_spec
        self._store_factory = store_factory or (
            lambda: TileStore(store_spec))
        self._host = host
        self._cache_bytes = cache_bytes
        self._backend_max_inflight = backend_max_inflight
        self._render_timeout_s = render_timeout_s
        self._chaos = chaos
        self._spawn_timeout_s = spawn_timeout_s
        self._degrade_opts = degrade_opts
        self._slo_specs = list(slo_specs or [])
        self._disk_cache_opts = disk_cache_opts
        self._prewarm_opts = prewarm_opts
        # process mode only: thread-mode backends share the supervisor
        # process's global sampler/engine (same sharing as the SLO
        # engine above), so there is nothing per-backend to arm.
        self._telemetry_opts = telemetry_opts
        self.restart_base_s = restart_base_s
        self.restart_cap_s = restart_cap_s
        self.monitor_interval_s = monitor_interval_s
        self._router_opts = dict(max_inflight=max_inflight,
                                 queue_deadline_s=queue_deadline_s,
                                 hedge_quantile=hedge_quantile,
                                 probe_interval_s=probe_interval_s)
        self.router: RouterApp | None = None
        self._handles: dict = {}
        self._restart_counts: dict[str, int] = {}
        self._stop = threading.Event()
        self._monitor: threading.Thread | None = None
        self._workdir: str | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self.mode == "process":
            self._workdir = tempfile.mkdtemp(prefix="heatmap-fleet-")
        clients = []
        try:
            for i in range(self.n_backends):
                backend_id = f"b{i}"
                handle = self._make_handle(backend_id)
                host, port = handle.start(self._stop)
                self._handles[backend_id] = handle
                clients.append(BackendClient(backend_id, host, port))
        except Exception:
            self.stop()
            raise
        self.router = RouterApp(clients, **self._router_opts).start()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="fleet-monitor", daemon=True)
        self._monitor.start()
        return self

    def _make_handle(self, backend_id: str):
        if self.mode == "thread":
            return _ThreadBackend(
                backend_id, self._store_factory, host=self._host,
                cache_bytes=self._cache_bytes,
                max_inflight=self._backend_max_inflight,
                render_timeout_s=self._render_timeout_s,
                degrade_opts=self._degrade_opts,
                disk_cache_opts=self._disk_cache_opts,
                prewarm_opts=self._prewarm_opts)
        return _ProcessBackend(
            backend_id, self._store_spec, host=self._host,
            cache_bytes=self._cache_bytes,
            max_inflight=self._backend_max_inflight,
            render_timeout_s=self._render_timeout_s, chaos=self._chaos,
            workdir=self._workdir, spawn_timeout_s=self._spawn_timeout_s,
            degrade_opts=self._degrade_opts, slo_specs=self._slo_specs,
            disk_cache_opts=self._disk_cache_opts,
            prewarm_opts=self._prewarm_opts,
            telemetry_opts=self._telemetry_opts)

    def stop(self):
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        if self.router is not None:
            self.router.close()
        for handle in self._handles.values():
            try:
                handle.stop()
            except Exception:
                pass
        self._handles.clear()
        if self._workdir is not None:
            shutil.rmtree(self._workdir, ignore_errors=True)
            self._workdir = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- chaos / test hooks ------------------------------------------------

    def kill_backend(self, backend_id: str):
        """SIGKILL (or thread-mode equivalent) — the monitor restarts
        it; the router's probes re-admit it."""
        self._handles[backend_id].kill()

    def backend(self, backend_id: str):
        return self._handles[backend_id]

    # -- monitor -----------------------------------------------------------

    def _restart_delay_s(self, backend_id: str, count: int) -> float:
        plane = faults.get_plane()
        seed = plane.seed if plane is not None else 0
        scale = plane.backoff_scale if plane is not None else 1.0
        nominal = min(self.restart_cap_s,
                      self.restart_base_s * 2.0 ** count)
        jitter = 0.5 + 0.5 * faults.hash01(
            seed, "restart", backend_id, count)
        return nominal * jitter * scale

    def _monitor_loop(self):
        pending: dict[str, float] = {}  # backend_id -> restart deadline
        while not self._stop.wait(self.monitor_interval_s):
            now = time.monotonic()
            for backend_id, handle in list(self._handles.items()):
                client = self.router.backends[backend_id]
                if handle.alive():
                    # Stable for a while: forget the crash history so
                    # the next incident starts from the base delay.
                    if (backend_id in self._restart_counts
                            and now - handle.started_at
                            > 4 * self.restart_cap_s):
                        self._restart_counts.pop(backend_id, None)
                    continue
                if backend_id not in pending:
                    self.router.note_failure(client, "crashed", force=True)
                    count = self._restart_counts.get(backend_id, 0)
                    pending[backend_id] = (
                        now + self._restart_delay_s(backend_id, count))
                    continue
                if now < pending[backend_id]:
                    continue
                del pending[backend_id]
                self._restart_counts[backend_id] = (
                    self._restart_counts.get(backend_id, 0) + 1)
                try:
                    replacement = self._make_handle(backend_id)
                    host, port = replacement.start(self._stop)
                except Exception:
                    # Spawn failed (port timeout, bad artifact): leave
                    # the breaker open and try again after a full cap.
                    pending[backend_id] = (time.monotonic()
                                           + self.restart_cap_s)
                    continue
                self._handles[backend_id] = replacement
                client.set_address(host, port)
                if obs.metrics_enabled():
                    FLEET_RESTARTS.inc(backend=backend_id)


# -- backend child process entrypoint --------------------------------------


def backend_main(argv=None) -> int:
    """``python -m heatmap_tpu.serve.fleet --backend``: one ServeApp on
    an ephemeral port, reported through ``--port-file`` (atomic write).
    No output on stdout/stderr — the port file is the only protocol."""
    parser = argparse.ArgumentParser(prog="heatmap_tpu.serve.fleet")
    parser.add_argument("--backend", action="store_true", required=True)
    parser.add_argument("--store", required=True)
    parser.add_argument("--port-file", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--cache-bytes", type=int, default=64 << 20)
    parser.add_argument("--max-inflight", type=int, default=None)
    parser.add_argument("--render-timeout", type=float, default=None)
    parser.add_argument("--chaos", default=None)
    parser.add_argument("--slo", action="append", default=[])
    parser.add_argument("--telemetry-sample-interval", type=float,
                        default=0.0)
    parser.add_argument("--watch", action="append", default=[])
    parser.add_argument("--degrade", action="store_true")
    parser.add_argument("--degrade-dwell", type=float, default=10.0)
    parser.add_argument("--degrade-hold", type=float, default=30.0)
    parser.add_argument("--degrade-ladder", default="")
    parser.add_argument("--disk-cache", default=None)
    parser.add_argument("--disk-cache-bytes", type=int, default=1 << 30)
    parser.add_argument("--prewarm-events", action="append", default=[])
    parser.add_argument("--prewarm-top-k", type=int, default=64)
    parser.add_argument("--prewarm-budget-s", type=float, default=10.0)
    parser.add_argument("--prewarm-bytes", type=int, default=64 << 20)
    args = parser.parse_args(argv)

    faults.install_from_env(args.chaos)
    obs.enable_metrics(True)
    # Per-child SLO engine: the brownout ladder's burn source. The
    # supervisor forwards the serve process's --slo specs so every
    # backend evaluates the same objectives over its own traffic.
    if args.slo:
        slo.install_specs(args.slo)
    # Per-child telemetry sampler + watches (forwarded like --slo):
    # each backend samples its own registry so the router's
    # fleet-merged /series carries per-backend history. 0 = the
    # pinned zero-cost off path — nothing armed.
    if args.telemetry_sample_interval:
        engine = None
        if args.watch:
            engine = anomaly.AnomalyEngine(
                [anomaly.parse_watch_spec(s) for s in args.watch])
            anomaly.set_engine(engine)
        timeseries.arm(args.telemetry_sample_interval, engine=engine)
    controller = degrade_mod.controller_from_flags(
        args.degrade, args.degrade_dwell, args.degrade_hold,
        args.degrade_ladder)
    store = TileStore(args.store)
    disk_cache = (DiskTileCache(args.disk_cache,
                                max_bytes=args.disk_cache_bytes)
                  if args.disk_cache else None)
    prewarm = (PrewarmConfig(events=tuple(args.prewarm_events),
                             top_k=args.prewarm_top_k,
                             budget_s=args.prewarm_budget_s,
                             budget_bytes=args.prewarm_bytes)
               if args.prewarm_events else None)
    app = ServeApp(store, TileCache(max_bytes=args.cache_bytes),
                   max_inflight=args.max_inflight,
                   render_timeout_s=args.render_timeout,
                   degrade=controller, disk_cache=disk_cache,
                   prewarm=prewarm)
    server = make_server(app, host=args.host, port=args.port)
    host, port = server.server_address[:2]
    tmp = args.port_file + ".tmp"
    with open(tmp, "w") as fh:
        json.dump({"host": host, "port": port, "pid": os.getpid()}, fh)
    os.replace(tmp, args.port_file)
    _warm_in_background(app)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        timeseries.shutdown()
        server.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(backend_main())
