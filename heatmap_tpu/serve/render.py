"""On-demand tile materialization from a TileStore.

A request names a coarse tile ``(z, x, y)`` (slippy-map: x = column,
y = row). The payload is the block of detail counts ``result_delta``
zooms finer — the same fan-in as the reference blob format (32x32 at
DETAIL_ZOOM_DELTA=5, reference heatmap.py:16,89).

Stored zooms are exact: the detail tiles under a coarse tile occupy one
contiguous Morton range, so the query is a searchsorted pair in the
layer's sorted code array. Zooms the pyramid lacks are synthesized from
the nearest stored level:

- **rollup** (stored level finer than wanted): shift the stored codes
  right ``2*(d_src - d)`` — Morton parenthood is a right shift and
  preserves sort order — and segment-sum into the wanted cells; exact,
  identical to what the cascade itself would have produced.
- **quadrant upsample** (stored level coarser): each stored cell's
  value paints its whole quadrant block (np.kron with a ones block) —
  a constant-interpolation preview, clearly marked approximate.

JSON bodies at stored zooms byte-match the batch blob egress: blob
stores serve the verbatim on-disk document; columnar stores rebuild
``{detail_id: value}`` in stored Morton order, which is exactly the
within-blob entry order ``json_blobs_from_level_arrays`` emits (level
rows arrive composite-key-sorted), and ``json.dumps`` of round-trip
doubles matches numpy's shortest-roundtrip formatting byte-for-byte.
"""

from __future__ import annotations

import json

import numpy as np

from heatmap_tpu.io.png import raster_to_png
from heatmap_tpu.serve.store import Layer, TileStore
from heatmap_tpu.tilemath.morton import morton_decode_np, morton_encode_np


def _tile_base_code(z: int, x: int, y: int) -> int:
    if not (0 <= x < (1 << z) and 0 <= y < (1 << z)):
        raise ValueError(f"tile ({z}/{x}/{y}) outside the zoom-{z} grid")
    return int(morton_encode_np(np.int64(y), np.int64(x)))


def tile_array(layer: Layer, z: int, x: int, y: int,
               pixel_delta: int | None = None):
    """(px, px) float64 counts raster for coarse tile (z, x, y) at
    detail zoom ``z + pixel_delta``, or None when no stored data
    intersects the tile. ``pixel_delta`` defaults to the layer's
    result_delta. Second return: the stored detail zoom used (for vmax
    consistency), or None."""
    delta = layer.result_delta if pixel_delta is None else pixel_delta
    if delta is None or not layer.levels:
        return None, None
    px = 1 << delta
    want = z + delta
    src = layer.source_zoom(want)
    if src is None:
        return None, None
    level = layer.levels[src]
    base = _tile_base_code(z, x, y)
    raster = np.zeros((px, px), np.float64)
    if src >= z:
        # The stored cells under this tile are one Morton range.
        shift = 2 * (src - z)
        codes, values = level.range(base << shift, (base + 1) << shift)
        if len(codes) == 0:
            return None, src
        rel = codes - (base << shift)
        if src >= want:
            # Exact or rollup: parent shift then bin (order-preserving,
            # so np.add.at degenerates to a segment sum).
            cell = rel >> np.int64(2 * (src - want))
            rr, cc = morton_decode_np(cell)
            np.add.at(raster, (rr.astype(np.int64), cc.astype(np.int64)),
                      values)
        else:
            # Stored coarser than wanted but finer than the tile zoom:
            # paint each stored cell's quadrant block.
            side = 1 << (src - z)
            small = np.zeros((side, side), np.float64)
            rr, cc = morton_decode_np(rel)
            np.add.at(small, (rr.astype(np.int64), cc.astype(np.int64)),
                      values)
            k = px // side
            raster = np.kron(small, np.ones((k, k)))
    else:
        # Whole requested tile lies inside ONE stored ancestor cell.
        value = level.lookup(base >> (2 * (z - src)))
        if value == 0.0:
            return None, src
        raster[:] = value
    if not raster.any():
        return None, src
    return raster, src


def _json_doc_from_level(layer: Layer, z: int, x: int, y: int):
    """Stored-zoom JSON document for a columnar store: detail ids ->
    values in stored Morton order (the blob egress entry order)."""
    delta = layer.result_delta
    want = z + delta
    level = layer.levels.get(want)
    if level is None:
        return None
    base = _tile_base_code(z, x, y)
    shift = 2 * delta
    codes, values = level.range(base << shift, (base + 1) << shift)
    if len(codes) == 0:
        return None
    rows, cols = morton_decode_np(codes)
    doc = {
        f"{want}_{int(r)}_{int(c)}": float(v)
        for r, c, v in zip(rows, cols, values)
    }
    return json.dumps(doc)


def tile_json_bytes(layer: Layer, z: int, x: int, y: int):
    """Reference-compatible JSON counts for (z, x, y), or None (-> 404).

    Byte-identical to the batch artifact at stored zooms (see module
    docstring); synthesized zooms serve the rollup/upsample raster's
    non-zero cells (row-major) at ``z + result_delta``.
    """
    raw = layer.blob_json.get((z, int(y), int(x)))
    if raw is not None:
        return raw.encode()
    doc = _json_doc_from_level(layer, z, x, y)
    if doc is not None:
        return doc.encode()
    raster, _ = tile_array(layer, z, x, y)
    if raster is None:
        return None
    delta = layer.result_delta
    want = z + delta
    rr, cc = np.nonzero(raster)
    doc = {
        f"{want}_{int(y) * (1 << delta) + int(r)}_"
        f"{int(x) * (1 << delta) + int(c)}": float(raster[r, c])
        for r, c in zip(rr, cc)
    }
    return json.dumps(doc).encode()


def tile_png_bytes(layer: Layer, z: int, x: int, y: int):
    """Heat-colormapped PNG tile (io/png.py), or None (-> 404). vmax is
    the source level's max so the colormap is consistent across tiles
    of one layer/zoom (the cmd_render shared-vmax convention)."""
    raster, src = tile_array(layer, z, x, y)
    if raster is None:
        return None
    vmax = layer.levels[src].vmax if src in layer.levels else None
    return raster_to_png(raster, vmax=vmax)


class SynopsisLayer:
    """Layer facade for synopsis rendering: the decoded synopsis level
    replaces the exact level at every zoom that carries one, so the
    rollup/upsample machinery above serves approximate tiles
    unchanged. ``blob_json`` is empty on purpose — verbatim on-disk
    documents are an exact-path contract."""

    __slots__ = ("user", "timespan", "result_delta", "levels", "blob_json")

    source_zoom = Layer.source_zoom

    def __init__(self, layer: Layer, *, max_level: int | None = None):
        self.user = layer.user
        self.timespan = layer.timespan
        self.result_delta = layer.result_delta
        self.levels = {
            z: (layer.synopses[z].level if z in layer.synopses else lvl)
            for z, lvl in layer.levels.items()
            # max_level caps the source ladder: the brownout stretch
            # path (synopsis_source(..., stretch=True)) pins rendering
            # to a synopsis-carrying zoom even when a finer exact level
            # exists — the upsample machinery paints the rest.
            if max_level is None or z <= max_level
        }
        self.blob_json = {}


def synopsis_source(layer: Layer, z: int, *, stretch: bool = False):
    """Decide whether tile zoom ``z`` can be served from a synopsis:
    returns ``(source_zoom, SynopsisView)`` when the SAME source level
    the exact path would pick carries a decoded synopsis, else
    ``(None, None)`` — the caller falls back to the exact path (and
    byte-identical output), which is what happens for every
    ``z + result_delta >= synopsis_max_z`` tile.

    ``stretch=True`` raises the synopsis zoom ceiling (the brownout
    ladder's rung 2): when the natural source carries no synopsis, the
    finest *coarser* synopsis-carrying level answers instead — the
    caller must then cap the layer at that zoom
    (``SynopsisLayer(layer, max_level=src)``) so the quadrant-upsample
    path paints the missing detail rather than the exact level
    reclaiming the render."""
    delta = layer.result_delta
    # Attached live layers (serve/live.py) have no synopses attribute;
    # they always take the exact path.
    if delta is None or not getattr(layer, "synopses", None):
        return None, None
    src = layer.source_zoom(z + delta)
    view = layer.synopses.get(src) if src is not None else None
    if view is None and stretch and src is not None:
        coarser = [s for s in layer.synopses if s < src]
        if coarser:
            src = max(coarser)
            view = layer.synopses[src]
    if view is None:
        return None, None
    return src, view


def render_tile(store: TileStore, layer_name: str, z: int, x: int, y: int,
                fmt: str, *, synopsis: bool = False):
    """Dispatch for the HTTP layer: bytes or None (missing layer or
    empty tile -> 404). ``synopsis=True`` renders from the layer's
    decoded synopsis views where available (callers gate on
    :func:`synopsis_source` first; with no synopsis at the source zoom
    this falls back to exact bytes)."""
    layer = store.layer(layer_name)
    if layer is None:
        return None
    if synopsis:
        src, view = synopsis_source(layer, z)
        if view is not None:
            layer = SynopsisLayer(layer)
    if fmt == "json":
        return tile_json_bytes(layer, z, x, y)
    if fmt == "png":
        return tile_png_bytes(layer, z, x, y)
    raise ValueError(f"unknown tile format {fmt!r}")
