"""Stateless fleet router: rendezvous hashing + the robustness stack.

The router is the thin frontend of the serve fleet (``serve/fleet.py``
spawns the backends). It owns no tile state — every decision is a pure
function of the request key and the live ring — so any number of
router processes could front the same fleet. Placement uses
**rendezvous (HRW) hashing** on the tile key ``layer/z/x/y`` (format
excluded, so a tile's .png and .json land on the same backend and
share its LRU locality) with **bounded-load spill**: when the
top-ranked backend is at its in-flight cap the request spills to the
next-ranked one instead of queueing behind a hot key (the
consistent-hashing-with-bounded-load construction, arXiv:1608.01350).

Robustness machinery, in the order a request meets it:

- **Admission control**: per-backend in-flight bound; a request that
  cannot find a slot within ``queue_deadline_s`` is shed with a typed
  503 + ``Retry-After`` — never a 500, and never an unbounded queue.
- **Circuit breakers** (closed → open → half-open): passive signals
  (connection failures, HTTP 5xx) open a backend's breaker after
  ``fail_threshold`` consecutive failures; cooldowns escalate per
  episode with seeded jitter (same ``hash01`` shape as
  ``faults/retry.py`` backoff, scaled by the installed plane's
  ``backoff_scale``). Open backends leave the ring; the prober's
  half-open trial probe re-admits them. Ring edges are emitted as
  ``fleet_backend_down`` / ``fleet_backend_up`` events — one pair per
  outage, not one per failed request.
- **Hedged reads** ("The Tail at Scale"): once the latency window has
  enough samples, a request still unanswered past the
  ``hedge_quantile`` latency fires a duplicate on the next replica in
  rendezvous order; first response wins and the loser's connection is
  closed (cancelled losers never feed the breaker).
- **One-retry-on-next-replica**: a connection failure (including an
  injected ``router.forward`` fault) burns the single retry from the
  ``POLICIES`` table and lands on the next eligible replica — the
  failover is the backoff, a request handler never sleeps.

Byte-equality contract: everything that is not a router-owned
endpoint (``/healthz``, ``/metrics``, ``/series``, ``/dashboard``,
``/reload``, ``/fleet/*``) is
forwarded verbatim — status, body, ETag, and ``If-None-Match``
revalidation all come from an ordinary ``ServeApp`` backend, so a
fleet response is byte-identical to a single process no matter which
path (direct, spilled, hedged, retried, mid-drain) produced it.
``RouterApp.handle`` returns the same 6-tuple as ``ServeApp.handle``
and is served by the same ``_Handler``/``make_server`` shell.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import queue
import re
import threading
import time
import urllib.parse
from collections import deque

from heatmap_tpu import faults, obs
from heatmap_tpu.obs import anomaly, incident, timeseries, tracing
from heatmap_tpu.serve import dashboard as dashboard_mod
from heatmap_tpu.serve import degrade as degrade_mod
from heatmap_tpu.serve.http import _TILE_RE, Response, local_series_response

_registry = obs.get_registry()
FLEET_REQUESTS = _registry.counter(
    "fleet_requests_total", "Forward attempts by the fleet router",
    labelnames=("backend", "outcome"))
FLEET_ROUTED = _registry.counter(
    "fleet_routed_total", "Requests routed, by placement path",
    labelnames=("path",))
FLEET_HEDGES = _registry.counter(
    "fleet_hedges_total", "Hedged duplicate requests launched",
    labelnames=("outcome",))
FLEET_SHED = _registry.counter(
    "fleet_shed_total", "Requests shed by router admission control",
    labelnames=("cause",))
FLEET_BACKEND_STATE = _registry.gauge(
    "fleet_backend_state",
    "Breaker state per backend (0 closed, 1 half-open, 2 open)",
    labelnames=("backend",))
FLEET_INFLIGHT = _registry.gauge(
    "fleet_inflight_requests", "In-flight forwards per backend",
    labelnames=("backend",))
FLEET_RESTARTS = _registry.counter(
    "fleet_backend_restarts_total", "Backend restarts by the supervisor",
    labelnames=("backend",))

# Connection-level failures that trigger failover to the next replica.
# HTTP status codes are NOT in this set: a backend's typed 503 passes
# through to the client untouched (it is an answer, not an absence).
_CONN_ERRORS = (OSError, http.client.HTTPException, faults.InjectedFault)

_STATE_VALUE = {"closed": 0, "half_open": 1, "open": 2}


def rendezvous_order(key: str, backend_ids) -> list:
    """Backends ranked by highest-random-weight for ``key``.

    A pure function of ``(key, set(backend_ids))``: removing one
    backend only moves the keys it owned (everyone else's ranking is
    untouched), and two routers with the same ring place identically —
    which is what makes replays and the byte-equality pin exact.
    """
    def score(bid):
        digest = hashlib.blake2b(f"{bid}|{key}".encode(),
                                 digest_size=8).digest()
        return int.from_bytes(digest, "big")

    return sorted(backend_ids, key=lambda bid: (-score(bid), bid))


def route_key(path: str) -> str:
    """The placement key for a request path: ``layer/z/x/y`` for tiles
    (format stripped, so .png and .json colocate), ``query:layer/z/bbox``
    for /query (op/k/q excluded, so repeated analytics of the same
    region — sum, then top-k, then a quantile — land on one backend and
    share its LRU locality), the raw path otherwise. For tiles the
    query string is excluded, so ``?synopsis=1`` and the exact tile
    colocate too."""
    bare, _, query = path.partition("?")
    m = _TILE_RE.match(bare)
    if m is not None:
        return f"{m['layer']}/{m['z']}/{m['x']}/{m['y']}"
    if bare == "/query":
        params = urllib.parse.parse_qs(query) if query else {}

        def last(name, default=""):
            vals = params.get(name)
            return vals[-1] if vals else default

        return (f"query:{last('layer', 'default')}/{last('z')}/"
                f"{last('bbox')}")
    return path


def _flag_opt(query: str, name: str) -> bool:
    """Boolean query option (last value wins, urllib convention)."""
    if not query:
        return False
    vals = urllib.parse.parse_qs(query).get(name)
    if not vals:
        return False
    return vals[-1] not in ("0", "false", "no")


# One exposition sample line: name, optional {labels}, rest (value and
# any OpenMetrics exemplar suffix — which carries its own {...} and
# must not be touched by the relabel).
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?(?P<rest> .*)$")


def relabel_metrics(text: str, **extra_labels) -> str:
    """Inject labels (e.g. ``backend="b0"``) into every sample line of
    a Prometheus text exposition. HELP/TYPE comment lines pass through
    unchanged — :func:`merge_expositions` dedupes them so the merged
    fleet page keeps one header block per metric family (the scraping
    router's own when it shares the family, else one adopted from the
    first backend that exposes it)."""
    injected = ",".join(f'{k}="{v}"' for k, v in sorted(
        extra_labels.items()))
    out = []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            out.append(line)
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        labels = m["labels"]
        merged = f"{injected},{labels}" if labels else injected
        out.append(f"{m['name']}{{{merged}}}{m['rest']}")
    return "\n".join(out) + ("\n" if out else "")


def merge_expositions(own: str, extra: str) -> str:
    """Fold relabeled backend sample lines into the router's own
    exposition, grouped by metric family. Naively concatenating the
    per-backend chunks after the router's page puts the same family
    (``http_requests_total`` on the router shell AND on every backend)
    in non-contiguous runs — which strict Prometheus text parsers
    reject, silently costing the scrape the router's own registry
    (``fleet_*``, its shell's ``http_requests_total``). Here every
    family appears exactly once: the router's HELP/TYPE block and own
    samples first, backend-labeled samples appended inside the same
    block, backend-only families as new blocks at the end (pinned by
    the scrape-parse test in tests/test_fleet.py)."""
    families: list = []     # (family, header_lines, sample_lines)
    by_family: dict = {}

    def _group(name):
        entry = by_family.get(name)
        if entry is None:
            entry = (name, [], [])
            families.append(entry)
            by_family[name] = entry
        return entry

    current = None
    for line in own.splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            name = line.split(" ", 3)[2]
            current = _group(name)
            current[1].append(line)
        elif line:
            m = _SAMPLE_RE.match(line)
            if current is None or (m is not None
                                   and not m["name"].startswith(
                                       current[0])):
                current = _group(m["name"] if m is not None else line)
            current[2].append(line)
    # Histogram families expose suffixed sample names; map them back so
    # a backend's _bucket lines land inside the family's block.
    sample_to_family = {}
    for name, _header, _samples in families:
        sample_to_family[name] = name
        for suffix in ("_bucket", "_sum", "_count"):
            sample_to_family[name + suffix] = name
    for line in extra.splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            # Backend-only family: adopt its header block (one copy —
            # every backend chunk repeats it) so suffixed histogram
            # samples still parse as ONE typed family downstream.
            name = line.split(" ", 3)[2]
            family = sample_to_family.get(name)
            if family is None:
                family = name
                for sname in (name, name + "_bucket", name + "_sum",
                              name + "_count"):
                    sample_to_family.setdefault(sname, family)
            entry = _group(family)
            kind = line.split(" ", 2)[1]
            if not any(h.split(" ", 2)[1] == kind for h in entry[1]):
                entry[1].append(line)
            continue
        m = _SAMPLE_RE.match(line) if line else None
        if m is None:
            continue
        family = sample_to_family.get(m["name"])
        if family is None:
            family = m["name"]
            for suffix in ("_bucket", "_sum", "_count"):
                if family.endswith(suffix):
                    family = family[:-len(suffix)]
                    break
            for sname in (family, family + "_bucket", family + "_sum",
                          family + "_count"):
                sample_to_family.setdefault(sname, family)
        _group(family)[2].append(line)
    out = []
    for _name, header, samples in families:
        out.extend(header)
        out.extend(samples)
    return "\n".join(out) + ("\n" if out else "")


class CircuitBreaker:
    """Per-backend breaker: closed → open → half-open.

    ``fail_threshold`` consecutive failures open it; the open cooldown
    escalates per episode (``open_base_s * 2**(episode-1)``, capped)
    with seeded jitter in [0.5, 1.0) of the nominal — the
    ``faults/retry.py`` backoff shape, deterministic under the
    installed plane's seed and scaled by its ``backoff_scale``. After
    the cooldown a single half-open trial is handed out
    (``admits_trial``); success closes the breaker and resets the
    escalation, failure re-opens with a longer cooldown.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, backend_id: str, *, fail_threshold: int = 3,
                 open_base_s: float = 0.25, open_cap_s: float = 15.0,
                 clock=time.monotonic):
        self.backend_id = backend_id
        self.fail_threshold = fail_threshold
        self.open_base_s = open_base_s
        self.open_cap_s = open_cap_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive = 0
        self._episode = 0  # open episodes since the last close
        self._open_until = 0.0
        self._trial_out = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._peek_state()

    def _peek_state(self) -> str:
        if self._state == self.OPEN and self._clock() >= self._open_until:
            return self.HALF_OPEN
        return self._state

    def admits(self) -> bool:
        """True only when closed — the ring membership test. Half-open
        trials go through ``admits_trial`` (the prober), so regular
        traffic never lands on a suspect backend."""
        with self._lock:
            return self._state == self.CLOSED

    def admits_trial(self) -> bool:
        """Hand out the single half-open trial once the cooldown has
        expired (or pass the regular health check while closed)."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._clock() < self._open_until:
                return False
            if self._state == self.OPEN:
                self._state = self.HALF_OPEN
                self._trial_out = False
            if not self._trial_out:
                self._trial_out = True
                return True
            return False

    def record_success(self) -> bool:
        """Returns True on the re-close edge (open/half-open → closed)."""
        with self._lock:
            reclosed = self._state != self.CLOSED
            self._state = self.CLOSED
            self._consecutive = 0
            self._episode = 0
            self._trial_out = False
            return reclosed

    def record_failure(self, *, force: bool = False) -> bool:
        """Returns True on the closed → open edge (the start of an
        outage episode; half-open → open re-opens silently). ``force``
        opens immediately regardless of the threshold (supervisor saw
        the process die)."""
        with self._lock:
            was_closed = self._state == self.CLOSED
            self._consecutive += 1
            if (was_closed and not force
                    and self._consecutive < self.fail_threshold):
                return False
            self._episode += 1
            self._state = self.OPEN
            self._trial_out = False
            self._open_until = self._clock() + self._cooldown_s()
            self._consecutive = 0
            return was_closed

    def _cooldown_s(self) -> float:
        plane = faults.get_plane()
        seed = plane.seed if plane is not None else 0
        scale = plane.backoff_scale if plane is not None else 1.0
        nominal = min(self.open_cap_s,
                      self.open_base_s * 2.0 ** (self._episode - 1))
        jitter = 0.5 + 0.5 * faults.hash01(
            seed, "breaker", self.backend_id, self._episode)
        return nominal * jitter * scale


class BackendClient:
    """One backend's address, connection pool, breaker, and ring flags.

    Pooled keep-alive connections are invalidated wholesale when the
    supervisor restarts the backend on a new port (``set_address``
    bumps the epoch). A request on a stale pooled connection gets one
    silent same-backend retry on a fresh connection before the failure
    counts — a keep-alive the server closed between requests is not a
    backend fault.
    """

    def __init__(self, backend_id: str, host: str, port: int, *,
                 timeout_s: float = 10.0, breaker: CircuitBreaker | None = None):
        self.id = backend_id
        self.timeout_s = timeout_s
        self.breaker = breaker or CircuitBreaker(backend_id)
        self.draining = False
        self.ejected: str | None = None  # cause; non-None = out of the ring
        self.inflight = 0  # guarded by the router's slot condition
        self.down_announced = False  # guards the down/up event pair
        # Last brownout snapshot the prober read from this backend's
        # /healthz (serve/degrade.py); None until one is seen.
        self.degrade: dict | None = None
        # Last prewarm summary from the same probe (tilefs/prewarm.py);
        # lets operators check cache warm-up fleet-wide from the router.
        self.prewarm: dict | None = None
        self._lock = threading.Lock()
        self._host, self._port = host, int(port)
        self._epoch = 0
        self._pool: list = []

    @property
    def address(self) -> str:
        with self._lock:
            return f"{self._host}:{self._port}"

    def set_address(self, host: str, port: int):
        with self._lock:
            self._host, self._port = host, int(port)
            self._epoch += 1
            stale, self._pool = self._pool, []
        for conn in stale:
            conn.close()

    def eligible(self) -> bool:
        return (not self.draining and self.ejected is None
                and self.breaker.admits())

    def _acquire(self, fresh: bool = False):
        with self._lock:
            if not fresh and self._pool:
                return self._pool.pop(), False, self._epoch
            conn = http.client.HTTPConnection(
                self._host, self._port, timeout=self.timeout_s)
            return conn, True, self._epoch

    def _release(self, conn, epoch: int, reusable: bool):
        if reusable:
            with self._lock:
                if epoch == self._epoch and len(self._pool) < 8:
                    self._pool.append(conn)
                    return
        conn.close()

    def fetch(self, method: str, path: str, headers: dict | None = None,
              *, conn_box: dict | None = None):
        """One HTTP round-trip: ``(status, headers, body)``. Raises
        ``_CONN_ERRORS`` members on connection-level failure. When
        ``conn_box`` is given, the live connection is published there
        so a hedging winner can cancel this attempt by closing it."""
        conn, fresh, epoch = self._acquire()
        try:
            return self._roundtrip(conn, epoch, method, path, headers,
                                   conn_box)
        except (OSError, http.client.HTTPException):
            conn.close()
            if fresh or (conn_box is not None and conn_box.get("cancelled")):
                raise
            # Stale pooled keep-alive: one silent fresh-conn retry.
            conn, _, epoch = self._acquire(fresh=True)
            try:
                return self._roundtrip(conn, epoch, method, path, headers,
                                       conn_box)
            except (OSError, http.client.HTTPException):
                conn.close()
                raise

    def _roundtrip(self, conn, epoch, method, path, headers, conn_box):
        if conn_box is not None:
            conn_box["conn"] = conn
        conn.request(method, path, headers=headers or {})
        resp = conn.getresponse()
        body = resp.read()
        reusable = not resp.will_close and (conn_box is None
                                            or not conn_box.get("cancelled"))
        self._release(conn, epoch, reusable)
        return resp.status, dict(resp.getheaders()), body


class _LatencyWindow:
    """Ring buffer of recent forward latencies; the hedge trigger."""

    def __init__(self, maxlen: int = 512, min_samples: int = 32):
        self._lock = threading.Lock()
        self._window = deque(maxlen=maxlen)
        self.min_samples = min_samples

    def record(self, seconds: float):
        with self._lock:
            self._window.append(seconds)

    def quantile(self, q: float) -> float | None:
        with self._lock:
            if len(self._window) < self.min_samples:
                return None
            ordered = sorted(self._window)
        idx = min(len(ordered) - 1, int(q * len(ordered)))
        return ordered[idx]


class RouterApp:
    """Transport-free router core: same ``handle()`` contract as
    ``ServeApp``, served by the same HTTP shell (``make_server``)."""

    def __init__(self, backends, *, max_inflight: int = 32,
                 queue_deadline_s: float = 0.25,
                 hedge_quantile: float = 0.95,
                 hedge_min_wait_s: float = 0.005,
                 probe_interval_s: float = 1.0,
                 retry_after_s: float = 1.0,
                 clock=time.monotonic):
        self.backends: dict[str, BackendClient] = {b.id: b for b in backends}
        self.max_inflight = max_inflight
        self.queue_deadline_s = queue_deadline_s
        self.hedge_quantile = hedge_quantile
        self.hedge_min_wait_s = hedge_min_wait_s
        self.probe_interval_s = probe_interval_s
        self.retry_after_s = retry_after_s
        self._clock = clock
        self._slot_cond = threading.Condition()
        self._latency = _LatencyWindow()
        self._retry_budget = faults.policy_for("router.forward").retries
        self._stop = threading.Event()
        self._prober: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Start the active health prober (half-open re-admission)."""
        if self._prober is None:
            self._stop.clear()
            self._prober = threading.Thread(
                target=self._probe_loop, name="fleet-prober", daemon=True)
            self._prober.start()
        return self

    def close(self):
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=5.0)
            self._prober = None

    # -- ring membership events --------------------------------------------

    def _announce_down(self, backend: BackendClient, reason: str,
                       detail: str = ""):
        if obs.metrics_enabled():
            FLEET_BACKEND_STATE.set(
                _STATE_VALUE.get(backend.breaker.state, 2),
                backend=backend.id)
        if not backend.down_announced:
            backend.down_announced = True
            obs.emit("fleet_backend_down", backend=backend.id, reason=reason,
                     **({"detail": detail} if detail else {}))

    def _announce_up(self, backend: BackendClient):
        if obs.metrics_enabled():
            FLEET_BACKEND_STATE.set(0, backend=backend.id)
        if (backend.down_announced and backend.ejected is None
                and backend.breaker.state == CircuitBreaker.CLOSED):
            backend.down_announced = False
            obs.emit("fleet_backend_up", backend=backend.id)

    def note_failure(self, backend: BackendClient, reason: str,
                     detail: str = "", *, force: bool = False):
        if backend.breaker.record_failure(force=force):
            self._announce_down(backend, reason, detail)

    def note_success(self, backend: BackendClient):
        if backend.breaker.record_success():
            self._announce_up(backend)

    # -- prober ------------------------------------------------------------

    def _probe_loop(self):
        while not self._stop.wait(self.probe_interval_s):
            for backend in list(self.backends.values()):
                if self._stop.is_set():
                    return
                if backend.draining or not backend.breaker.admits_trial():
                    continue
                self._probe_once(backend)

    def _probe_once(self, backend: BackendClient) -> bool:
        try:
            faults.check("backend.probe", key=backend.id)
            status, _, body = backend.fetch("GET", "/healthz")
            ok = status == 200
        except Exception:
            ok = False
            body = b""
        if ok:
            self.note_success(backend)
            # Probe piggyback: read the backend's brownout ladder state
            # so the router agrees fleet-wide on the active rung
            # without a second endpoint or any push machinery.
            try:
                health = json.loads(body)
            except (ValueError, AttributeError):
                health = {}
            if not isinstance(health, dict):
                health = {}
            snap = health.get("degrade")
            backend.degrade = snap if isinstance(snap, dict) else None
            warm = health.get("prewarm")
            backend.prewarm = warm if isinstance(warm, dict) else None
        else:
            self.note_failure(backend, "probe")
        return ok

    # -- request core ------------------------------------------------------

    def handle(self, method: str, path: str,
               if_none_match: str | None = None):
        """Same 6-tuple contract as ``ServeApp.handle``."""
        # Router-owned endpoints match on the bare path so a query
        # string (``/metrics?fleet=1``) selects options instead of
        # falling through to the placement ring.
        bare, _, query = path.partition("?")
        if method == "GET" and bare == "/healthz":
            body = json.dumps(self._health(), indent=2).encode()
            return 200, "application/json", body, None, "healthz", None
        if method == "GET" and bare == "/metrics":
            obs.refresh_process_gauges()
            text = _registry.render_prometheus()
            if _flag_opt(query, "fleet"):
                # Family-grouped merge: the router's own registry and
                # every backend's samples in one parse-valid exposition
                # (a plain concat puts shared families in
                # non-contiguous runs, which strict scrapers reject).
                text = merge_expositions(text, self._fleet_metrics())
            body = text.encode()
            return (200, "text/plain; version=0.0.4", body, None,
                    "metrics", None)
        if method == "GET" and bare == "/series":
            return self._handle_series(query)
        if method == "GET" and bare == "/dashboard":
            body = dashboard_mod.render_page(title="heatmap-tpu fleet ops")
            return (200, "text/html; charset=utf-8", body, None,
                    "dashboard", None)
        if method == "POST" and bare == "/reload":
            return self._rolling_reload()
        if method == "POST" and bare.startswith("/fleet/"):
            return self._fleet_op(bare)
        return self._route(method, path, if_none_match)

    def _fleet_metrics(self) -> str:
        """Scrape each live backend's ``/metrics`` and merge the series
        under a ``backend`` label next to the router's own registry
        (``GET /metrics?fleet=1``). Unreachable backends are skipped —
        a scrape must never trip breakers or block on a dead ring
        member beyond the client timeout."""
        chunks = []
        for bid in sorted(self.backends):
            backend = self.backends[bid]
            if not backend.eligible():
                continue
            try:
                status, _, body = backend.fetch("GET", "/metrics")
            except Exception:
                continue
            if status != 200:
                continue
            chunks.append(relabel_metrics(
                body.decode("utf-8", "replace"), backend=bid))
        return "".join(chunks)

    def _handle_series(self, query: str):
        """``GET /series`` router-side: the router's own telemetry
        store through the same parser as ServeApp, and — under
        ``?fleet=1``, the ``/metrics?fleet=1`` fan-out shape — each
        live backend's frames merged in, stamped with a ``backend``
        label (router-own frames stamped ``"router"``). Unreachable
        backends are skipped, never a 5xx: a dashboard poll must not
        trip breakers or fail on a dead ring member."""
        result = local_series_response(query)
        status, ctype, body, etag, route, cache = result
        if status != 200 or not _flag_opt(query, "fleet"):
            return result
        doc = json.loads(body)
        frames = doc.get("frames") or []
        for frame in frames:
            frame["backend"] = "router"
        enabled = bool(doc.get("enabled"))
        for bid in sorted(self.backends):
            backend = self.backends[bid]
            if not backend.eligible():
                continue
            try:
                b_status, _, b_body = backend.fetch(
                    "GET", f"/series?{query}")
            except Exception:
                continue
            if b_status != 200:
                continue
            try:
                b_doc = json.loads(b_body)
            except ValueError:
                continue
            for frame in b_doc.get("frames") or []:
                frame["backend"] = bid
                frames.append(frame)
            enabled = enabled or bool(b_doc.get("enabled"))
        doc["frames"] = frames
        doc["enabled"] = enabled
        if enabled:
            doc.pop("detail", None)  # at least one sampler is on
        body = json.dumps(doc, sort_keys=True).encode()
        return 200, "application/json", body, None, "series", None

    # -- routing -----------------------------------------------------------

    def _shed(self, cause: str, detail: str = "", status: int = 503):
        if obs.metrics_enabled():
            FLEET_SHED.inc(cause=cause)
        if status == 503:
            # Router-side typed 503s are incident trigger edges too
            # (rate-limited per kind by the manager).
            incident.trigger("shed", detail=cause)
        body = json.dumps({"error": "service unavailable", "cause": cause,
                           **({"detail": detail} if detail else {})}).encode()
        return status, "application/json", body, None, "shed", None

    def fleet_degrade(self) -> dict | None:
        """Fleet-wide brownout agreement: the hottest backend's ladder
        snapshot (max rung wins — one overloaded ring member is enough
        to start protecting it). None until a probe has seen one."""
        hottest = None
        for backend in self.backends.values():
            snap = backend.degrade
            if snap is None:
                continue
            if hottest is None or snap.get("rung", 0) > hottest.get(
                    "rung", 0):
                hottest = snap
        return hottest

    def _route(self, method, path, if_none_match):
        key = route_key(path)
        snap = self.fleet_degrade()
        if snap is not None and snap.get("rung", 0) >= snap.get(
                "max_rung", degrade_mod.MAX_RUNG):
            # Top rung somewhere in the ring: apply the backends' own
            # deterministic key shed router-side, before spending a
            # forward slot — the seeded hash agrees with every backend,
            # so the router sheds exactly the keys they would.
            m = _TILE_RE.match(path.partition("?")[0])
            if m is not None and degrade_mod.shed_tile(
                    float(snap.get("shed_fraction", 0.0)),
                    (m["layer"], m["z"], m["x"], m["y"], m["fmt"])):
                return self._shed(
                    "brownout", f"fleet rung {snap.get('rung')}")
        order = [self.backends[bid] for bid in
                 rendezvous_order(key, list(self.backends))]
        primary, rank = self._admit(order)
        if primary is None:
            if rank < 0:
                return self._shed("no_backends",
                                  "no eligible backend in the ring")
            return self._shed("overload",
                              f"no slot within {self.queue_deadline_s}s")
        placement = "direct" if rank == 0 else "spill"
        if obs.metrics_enabled():
            FLEET_ROUTED.inc(path=placement)
        return self._forward(method, path, if_none_match, order, primary)

    def _admit(self, order):
        """Claim an in-flight slot on the best-ranked eligible backend,
        spilling down the rendezvous order past saturated ones; block
        up to the queue deadline for a slot. Returns ``(backend, rank)``
        or ``(None, -1)`` when the ring is empty / ``(None, 0)`` on
        queue-deadline overload."""
        deadline = self._clock() + self.queue_deadline_s
        with self._slot_cond:
            while True:
                any_eligible = False
                for rank, backend in enumerate(order):
                    if not backend.eligible():
                        continue
                    any_eligible = True
                    if backend.inflight < self.max_inflight:
                        self._claim_locked(backend)
                        return backend, rank
                if not any_eligible:
                    return None, -1
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return None, 0
                self._slot_cond.wait(remaining)

    def _claim_locked(self, backend):
        backend.inflight += 1
        if obs.metrics_enabled():
            FLEET_INFLIGHT.set(backend.inflight, backend=backend.id)

    def _claim_extra(self, order, used):
        """Claim the next-ranked eligible, under-cap backend not in
        ``used`` (hedge / retry target); None when the ring is spent."""
        with self._slot_cond:
            for backend in order:
                if (backend.id not in used and backend.eligible()
                        and backend.inflight < self.max_inflight):
                    self._claim_locked(backend)
                    return backend
        return None

    def _release_slot(self, backend):
        with self._slot_cond:
            backend.inflight -= 1
            if obs.metrics_enabled():
                FLEET_INFLIGHT.set(backend.inflight, backend=backend.id)
            self._slot_cond.notify_all()

    def _forward(self, method, path, if_none_match, order, primary):
        headers = {}
        if if_none_match is not None:
            headers["If-None-Match"] = if_none_match
        traceparent = tracing.current_traceparent()
        if traceparent is not None:
            headers["traceparent"] = traceparent

        outcomes: queue.SimpleQueue = queue.SimpleQueue()
        boxes: dict[str, dict] = {}
        used = {primary.id}
        live = [0]

        def attempt_run(backend, kind):
            box = {"conn": None, "cancelled": False}
            boxes[backend.id] = box
            live[0] += 1

            def run():
                t0 = self._clock()
                try:
                    faults.check("router.forward", key=backend.id)
                    result = backend.fetch(method, path, headers,
                                           conn_box=box)
                    outcomes.put((kind, backend, result, None,
                                  self._clock() - t0))
                except Exception as exc:
                    outcomes.put((kind, backend, None, exc,
                                  self._clock() - t0))
                finally:
                    self._release_slot(backend)

            threading.Thread(target=tracing.context_bound(run),
                             name=f"fleet-fwd-{backend.id}",
                             daemon=True).start()

        attempt_run(primary, "primary")
        hedge_at = None
        hedge_q = self._latency.quantile(self.hedge_quantile)
        if hedge_q is not None:
            hedge_at = self._clock() + max(self.hedge_min_wait_s, hedge_q)
        retries_used = 0
        last_exc: Exception | None = None

        while live[0] > 0:
            timeout = None
            if hedge_at is not None:
                timeout = max(0.0, hedge_at - self._clock())
            try:
                kind, backend, result, exc, dt = outcomes.get(
                    timeout=timeout)
            except queue.Empty:
                # Hedge timer fired with no answer yet: duplicate the
                # request on the next replica in rendezvous order.
                hedge_at = None
                extra = self._claim_extra(order, used)
                if extra is not None:
                    used.add(extra.id)
                    if obs.metrics_enabled():
                        FLEET_ROUTED.inc(path="hedge")
                    attempt_run(extra, "hedge")
                continue
            live[0] -= 1
            box = boxes.get(backend.id, {})
            if box.get("cancelled"):
                continue  # loser of a hedge race; already answered
            if exc is None:
                status = result[0]
                if status >= 500:
                    # An answer, but also a passive breaker signal; a
                    # typed 503 passes through rather than failing over
                    # (it is load shedding, not absence).
                    self.note_failure(backend, f"http_{status}")
                else:
                    self.note_success(backend)
                    self._latency.record(dt)
                if obs.metrics_enabled():
                    FLEET_REQUESTS.inc(backend=backend.id, outcome="ok")
                    if kind == "hedge":
                        FLEET_HEDGES.inc(outcome="win")
                self._cancel_others(boxes, backend.id)
                return self._relay(path, result)
            # Connection-level failure: feed the breaker, fail over.
            last_exc = exc
            self.note_failure(backend, "connect", repr(exc))
            if obs.metrics_enabled():
                FLEET_REQUESTS.inc(backend=backend.id, outcome="error")
                if kind == "hedge":
                    FLEET_HEDGES.inc(outcome="lose")
            if live[0] == 0 and retries_used < self._retry_budget:
                extra = self._claim_extra(order, used)
                if extra is not None:
                    retries_used += 1
                    used.add(extra.id)
                    if obs.metrics_enabled():
                        FLEET_ROUTED.inc(path="retry")
                    attempt_run(extra, "retry")
        return self._shed("upstream_unreachable",
                          repr(last_exc) if last_exc else "")

    def _cancel_others(self, boxes, winner_id):
        for backend_id, box in boxes.items():
            if backend_id == winner_id:
                continue
            box["cancelled"] = True
            conn = box.get("conn")
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass

    def _relay(self, path, result):
        status, resp_headers, body = result
        etag = resp_headers.get("ETag")
        ctype = resp_headers.get("Content-Type", "application/octet-stream")
        route = ("tiles" if _TILE_RE.match(path.partition("?")[0])
                 else "proxy")
        forwarded = {
            name: resp_headers[name]
            for name in ("X-Heatmap-Synopsis", "X-Heatmap-Query-Error")
            if resp_headers.get(name) is not None}
        if forwarded:
            # Part of the byte-equality contract: the error annotations
            # a backend stamped must survive the fleet hop.
            return Response(status, ctype, body, etag, route, None,
                            headers=forwarded)
        return status, ctype, body, etag, route, None

    # -- fleet operations --------------------------------------------------

    def _fleet_op(self, path):
        parts = path.strip("/").split("/")
        # /fleet/{backend_id}/drain | undrain
        if len(parts) != 3 or parts[2] not in ("drain", "undrain"):
            body = json.dumps({"error": "not found", "path": path}).encode()
            return 404, "application/json", body, None, "fleet", None
        backend = self.backends.get(parts[1])
        if backend is None:
            body = json.dumps({"error": "unknown backend", "backend": parts[1],
                               "backends": sorted(self.backends)}).encode()
            return 404, "application/json", body, None, "fleet", None
        if parts[2] == "drain":
            backend.draining = True
            # Forward so the backend itself sheds direct traffic too;
            # best-effort (the router-side flag already pulls it from
            # the ring even if the backend is unreachable).
            detail = self._forward_op(backend, "POST", "/drain")
        else:
            backend.draining = False
            detail = self._forward_op(backend, "POST", "/undrain")
        body = json.dumps({"backend": backend.id,
                           "draining": backend.draining,
                           "inflight": backend.inflight,
                           "backend_response": detail}).encode()
        return 200, "application/json", body, None, "fleet", None

    def _forward_op(self, backend, method, path):
        try:
            status, _, body = backend.fetch(method, path)
            try:
                payload = json.loads(body)
            except ValueError:
                payload = body.decode("utf-8", "replace")
            return {"status": status, "body": payload}
        except Exception as exc:
            return {"error": repr(exc)}

    def _rolling_reload(self):
        """Rolling ``/reload`` across the fleet, atomic per backend: a
        backend that fails reload keeps its last-good index (single
        process semantics) and stays **ejected** from the ring rather
        than serving a mixed generation; the next successful rolling
        reload re-admits it."""
        results = {}
        all_ok = True
        for backend in list(self.backends.values()):
            backend.ejected = "reloading"
            outcome = self._forward_op(backend, "POST", "/reload")
            if outcome.get("status") == 200:
                backend.ejected = None
                results[backend.id] = {"ok": True, **outcome}
                self._announce_up(backend)
            else:
                backend.ejected = "reload_failed"
                results[backend.id] = {"ok": False, **outcome}
                all_ok = False
                self._announce_down(
                    backend, "reload_failed",
                    json.dumps(outcome.get("body", outcome.get("error", ""))))
        status = 200 if all_ok else 503
        body = json.dumps({"ok": all_ok, "backends": results}).encode()
        return status, "application/json", body, None, "reload", None

    # -- health ------------------------------------------------------------

    def _health(self) -> dict:
        states = {}
        for backend in self.backends.values():
            states[backend.id] = {
                "address": backend.address,
                "breaker": backend.breaker.state,
                "inflight": backend.inflight,
                "draining": backend.draining,
                "ejected": backend.ejected,
                "eligible": backend.eligible(),
            }
            if backend.degrade is not None:
                states[backend.id]["degrade_rung"] = backend.degrade.get(
                    "rung", 0)
            if backend.prewarm is not None:
                states[backend.id]["prewarm"] = backend.prewarm
        eligible = [bid for bid, st in states.items() if st["eligible"]]
        doc = {
            "role": "router",
            "status": "ok" if eligible else "degraded",
            "fleet": {
                "size": len(self.backends),
                "eligible": eligible,
                "backends": states,
            },
            "admission": {
                "max_inflight": self.max_inflight,
                "queue_deadline_s": self.queue_deadline_s,
            },
        }
        snap = self.fleet_degrade()
        if snap is not None:
            # The agreed fleet-wide ladder state (max rung across the
            # ring) — what operators and upstream layers should read.
            doc["degrade"] = snap
        # Router-process telemetry + anomaly state, when armed — the
        # dashboard served off the router reads these chips.
        ts_store = timeseries.get_store()
        if ts_store is not None:
            doc["telemetry"] = ts_store.stats()
        engine = anomaly.get_engine()
        if engine is not None:
            doc["anomalies"] = engine.recent(16)
        return doc
