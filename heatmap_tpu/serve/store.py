"""TileStore: batch egress -> read-optimized per-zoom tile index.

Loads any batch egress artifact the job side writes —

- ``arrays:DIR``   columnar per-level npz (LevelArraysSink), including
                   a directory of multihost ``host*/`` shards, merged
                   through the existing io/merge.py level mergers;
- ``jsonl:PATH``   blob records (JSONLBlobSink lines);
- ``dir:PATH``     one blob JSON file per id (DirectoryBlobSink);
- ``delta:ROOT``   an incremental delta store (heatmap_tpu.delta):
                   the current base pyramid overlaid with the live
                   delta stack, additively merged on read;
- ``tilefs:ROOT``  a zero-copy mmap'd tilefs store (heatmap_tpu.tilefs):
                   ``tilefs-z*.bin`` column segments served straight
                   from the kernel page cache (N backends on one host
                   share the pyramid's pages instead of N heap copies);
                   handles both plain converted dirs and delta-shaped
                   roots (mmap'd base ⊕ in-heap live deltas), falling
                   back to the sibling npz level per zoom when a tilefs
                   file is torn — served bytes are identical either way;

— into per-layer, per-detail-zoom **Morton-keyed sorted arrays**
(tilemath/morton.py): a tile request at coarse tile (z, row, col) is a
single ``searchsorted`` range probe, because every detail tile under a
coarse tile is a contiguous Morton range ``[code << 2d, (code+1) << 2d)``.

Layers map the reference's blob-id prefix (``user|timespan``) to URL
path segments. By default every (user, timespan) pair present in the
artifact becomes a layer named ``user|timespan``, and ``default``
aliases ``all|alltime`` when present — so a fresh count job serves at
``/tiles/default/...`` with zero configuration.

``reload()`` re-reads the artifact and atomically swaps the index,
bumping ``generation`` — the cache invalidation token — so a newer job
run is picked up without restarting the server. ``refresh_layers()``
is the targeted sibling for delta stores: it swaps the index WITHOUT
the bump, so only the tile keys a delta actually touched need explicit
invalidation (heatmap_tpu.delta.refresh_serving) and the rest of the
cache survives.

Numpy-only on purpose: no jax import, no backend init (the io/merge.py
offline discipline) — a tile server must keep serving when the
accelerator relay is down.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

from heatmap_tpu import obs
from heatmap_tpu.analytics import integral as integral_build
from heatmap_tpu.io.sinks import LevelArraysSink
from heatmap_tpu.synopsis import build as synopsis_build
from heatmap_tpu.synopsis import metrics as synopsis_metrics
from heatmap_tpu.tilemath.keys import parse_tile_id
from heatmap_tpu.tilemath.morton import morton_encode_np

#: Store spec kinds ``TileStore`` accepts (subset of the sink kinds —
#: the batch egress surfaces that persist to disk — plus the delta
#: store overlay).
STORE_KINDS = ("arrays", "jsonl", "dir", "delta", "tilefs", "writeplane")


class Level:
    """One detail-zoom slice of a layer: sorted Morton codes + values."""

    __slots__ = ("zoom", "codes", "values", "vmax")

    def __init__(self, zoom: int, codes: np.ndarray, values: np.ndarray):
        order = np.argsort(codes, kind="stable")
        self.zoom = int(zoom)
        self.codes = np.asarray(codes, np.int64)[order]
        self.values = np.asarray(values, np.float64)[order]
        self.vmax = float(self.values.max()) if len(self.values) else 0.0

    def range(self, lo: int, hi: int):
        """(codes, values) with codes in ``[lo, hi)`` — one searchsorted
        pair; Morton contiguity makes this the whole spatial query."""
        i = np.searchsorted(self.codes, lo, side="left")
        j = np.searchsorted(self.codes, hi, side="left")
        return self.codes[i:j], self.values[i:j]

    def lookup(self, code: int) -> float:
        """Single-cell probe (ancestor fills); 0.0 on miss."""
        i = int(np.searchsorted(self.codes, code, side="left"))
        if i < len(self.codes) and int(self.codes[i]) == code:
            return float(self.values[i])
        return 0.0

    def __len__(self):
        return len(self.codes)


class MappedLevel(Level):
    """Zero-copy Level over tilefs mmap column views.

    The writer already applied Level's stable argsort-by-code, so the
    views are used verbatim, and vmax comes from the footer index —
    construction touches no data pages; the kernel faults them in only
    when a tile's Morton range is actually probed."""

    __slots__ = ()

    def __init__(self, zoom: int, codes, values, vmax: float):
        self.zoom = int(zoom)
        self.codes = codes
        self.values = values
        self.vmax = float(vmax)


class SynopsisView:
    """One decoded wavelet synopsis level, ready to serve.

    ``level`` is the decoded count grid as an ordinary :class:`Level`
    (render.py treats it like any stored level); ``max_err`` the
    stamped L-inf bound from the artifact header; ``stale`` marks a
    provisional early-serve overlay (ingest published the micro-batch
    counts before the exact apply landed).
    """

    __slots__ = ("level", "max_err", "stale")

    def __init__(self, level: Level, max_err: float, stale: bool = False):
        self.level = level
        self.max_err = float(max_err)
        self.stale = bool(stale)


class Layer:
    """One (user, timespan) slice: detail levels + raw blob documents.

    ``blob_json`` holds the verbatim on-disk JSON document per coarse
    tile for blob-record stores (jsonl:/dir:), so the JSON endpoint
    serves byte-identical bytes to the artifact. Columnar stores carry
    no document form; render.py rebuilds it in stored-row order.

    ``synopses`` maps detail zooms to decoded :class:`SynopsisView`\\ s
    when the artifact carries ``synopsis-z*.npz`` files; empty
    otherwise. Exact serving never reads it.

    ``integrals`` maps detail zooms to
    :class:`heatmap_tpu.analytics.IntegralPair` summed-area tables when
    the artifact carries ``integral-z*.npz`` files (with live delta
    rows already folded in — exact); empty otherwise, in which case
    /query falls through to the exact level rows.
    """

    __slots__ = ("user", "timespan", "levels", "result_delta", "blob_json",
                 "synopses", "integrals")

    def __init__(self, user: str, timespan: str, result_delta: int | None):
        self.user = user
        self.timespan = timespan
        self.levels: dict[int, Level] = {}
        self.result_delta = result_delta
        self.blob_json: dict[tuple, str] = {}
        self.synopses: dict[int, SynopsisView] = {}
        self.integrals: dict[int, "integral_build.IntegralPair"] = {}

    @property
    def detail_zooms(self) -> list[int]:
        return sorted(self.levels)

    def source_zoom(self, detail_zoom: int) -> int | None:
        """Nearest stored detail zoom for a wanted one: exact when
        stored; else the closest FINER level (rollup is exact), else
        the closest coarser (quadrant upsample)."""
        if detail_zoom in self.levels:
            return detail_zoom
        finer = [z for z in self.levels if z > detail_zoom]
        if finer:
            return min(finer)
        coarser = [z for z in self.levels if z < detail_zoom]
        return max(coarser) if coarser else None


def _parse_store_spec(spec: str) -> tuple[str, str]:
    kind, sep, rest = spec.partition(":")
    if sep and kind in STORE_KINDS:
        return kind, rest
    # Bare paths: sniff like open_source/open_sink do.
    if spec.endswith((".jsonl", ".ndjson")):
        return "jsonl", spec
    if os.path.isdir(spec):
        from heatmap_tpu.tilefs.format import sniff_tilefs

        names = os.listdir(spec)
        if "MANIFEST" in names or (
                "ranges" in names and any(
                    n.startswith("manifest-") for n in names)):
            # A write-plane root (epoch-unified manifest over per-range
            # delta stores — heatmap_tpu/writeplane/).
            return "writeplane", spec
        if "CURRENT" in names or "journal" in names:
            # A converted delta store (tilefs files in the CURRENT
            # base) serves zero-copy by default — byte-identity makes
            # the mmap path a pure speedup, never a behavior change.
            from heatmap_tpu.delta.compact import read_current

            cur = read_current(spec)
            if cur.get("base") and sniff_tilefs(
                    os.path.join(spec, cur["base"])):
                return "tilefs", spec
            return "delta", spec
        if sniff_tilefs(spec):
            return "tilefs", spec
        if any(n.startswith("level_z") for n in names) or any(
                n.startswith("host") and
                os.path.isdir(os.path.join(spec, n)) for n in names):
            return "arrays", spec
        return "dir", spec
    raise ValueError(
        f"unrecognized store spec {spec!r}: kind must be one of "
        f"{', '.join(STORE_KINDS)} (e.g. arrays:levels/)"
    )


def _live_delta_epoch(root: str, cur: dict) -> int:
    """Newest epoch visible in a delta-shaped store: max of CURRENT's
    ``applied_through`` and the live journal head. The disk cache tier
    keys rendered bytes on this, so every apply invalidates exactly the
    epoch's worth of entries while compaction (which folds the head
    into ``applied_through`` without changing it) invalidates none."""
    from heatmap_tpu.delta.compact import live_entries

    epochs = [int(e["epoch"]) for e in live_entries(root)]
    return max([int(cur.get("applied_through", 0) or 0)] + epochs)


def _combine_cells(codes: np.ndarray, values: np.ndarray):
    """Sum duplicate Morton cells and drop non-positive results —
    Level wants unique sorted codes (``lookup`` probes a single row)."""
    order = np.argsort(codes, kind="stable")
    codes, values = codes[order], values[order]
    uniq, starts = np.unique(codes, return_index=True)
    sums = np.add.reduceat(values, starts) if len(values) else values
    keep = sums > 0.0
    return uniq[keep], sums[keep]


def _finalized_to_loaded(merged) -> dict[int, dict]:
    """Finalized (dictionary-encoded) -> loaded (string columns), the
    shape LevelArraysSink.load returns."""
    out = {}
    for lvl in merged:
        cols = dict(lvl)
        cols["user"] = np.asarray(lvl["user_names"])[lvl["user_idx"]]
        cols["timespan"] = np.asarray(
            lvl["timespan_names"])[lvl["timespan_idx"]]
        out[int(lvl["zoom"])] = cols
    return out


def _load_levels(path: str) -> dict[int, dict]:
    """``arrays:`` loader: plain LevelArraysSink dir, or a directory of
    multihost ``host*/`` shards merged through io/merge.py."""
    names = sorted(os.listdir(path))
    shard_dirs = [os.path.join(path, n) for n in names
                  if n.startswith("host")
                  and os.path.isdir(os.path.join(path, n))]
    if shard_dirs and not any(n.startswith("level_z") for n in names):
        from heatmap_tpu.io.merge import merge_level_dirs

        return _finalized_to_loaded(merge_level_dirs(shard_dirs))
    return LevelArraysSink.load(path)


def _iter_blob_records(kind: str, path: str):
    """Yield (blob_id, raw_json_str) with last-write-wins per id —
    JSONLBlobSink.load upsert semantics, raw strings preserved."""
    if kind == "jsonl":
        out: dict[str, str] = {}
        with open(path) as f:
            for line in f:
                if line.strip():
                    rec = json.loads(line)
                    out[rec["id"]] = rec["heatmap"]
        yield from out.items()
        return
    for name in sorted(os.listdir(path)):
        if not name.endswith(".json"):
            continue
        with open(os.path.join(path, name)) as f:
            yield name[: -len(".json")], f.read()


class TileStore:
    """The serving index over one batch-egress artifact.

    ``layers`` (optional) maps exposed layer names to ``"user|timespan"``
    selectors; by default every pair found in the artifact is exposed
    under its own ``user|timespan`` name plus the ``default`` alias for
    ``all|alltime``. Unknown selectors raise at load time — a typo'd
    ``--layers`` must not 404 forever at runtime.
    """

    def __init__(self, spec: str, layers: dict[str, str] | None = None):
        self.spec = spec
        self.kind, self.path = _parse_store_spec(spec)
        self._layer_spec = dict(layers) if layers else None
        self._lock = threading.Lock()
        self.generation = 0
        # Synopsis cache token: bumped by every index swap AND every
        # provisional publish, and folded into synopsis cache keys —
        # approximate bytes must never outlive the view they were
        # decoded from (exact tiles keep the cheaper generation +
        # targeted-invalidation scheme).
        self.synopsis_epoch = 0
        # Delta-apply token for the disk cache tier: the newest epoch
        # visible in the store (max of CURRENT's applied_through and
        # the live journal head) for delta-shaped roots, 0 otherwise.
        # Invariant across compaction — the fold sets applied_through
        # to the epoch of the newest delta it consumed — so disk-cached
        # renders survive compaction but can never outlive an apply.
        self.delta_epoch = 0
        self._layers: dict[str, Layer] = {}
        # Temporal fold views (heatmap_tpu.temporal), keyed by fold
        # token: tiny LRU — each view is a full layer index over the
        # cut, and distinct live cuts are few (the active windows plus
        # whatever as_of epochs clients are replaying).
        self._temporal_views: dict = {}
        self.reload(_initial=True)

    # -- queries -----------------------------------------------------------

    @property
    def layers(self) -> dict[str, Layer]:
        return self._layers

    def layer(self, name: str) -> Layer | None:
        return self._layers.get(name)

    def layer_names(self) -> list[str]:
        return sorted(self._layers)

    # -- (re)loading -------------------------------------------------------

    def reload(self, _initial: bool = False) -> int:
        """Re-read the artifact and atomically swap the index; returns
        the new generation (the cache-invalidation token).

        Build-before-swap is a contract the serve tier's degraded mode
        relies on (serve/http.py, tests/test_chaos.py): ``_build()``
        runs to completion BEFORE ``self._layers`` is touched, so a
        reload that raises — unreadable artifact, store mid-rewrite —
        leaves the last-good index serving and the generation
        unchanged."""
        t0 = time.monotonic()
        built = self._build()
        with self._lock:
            old = self.generation
            self._layers = built
            if not _initial:
                self.generation += 1
            self.synopsis_epoch += 1
            generation = self.generation
        # Full reloads invalidate every cached tile via the generation
        # bump; the event makes them distinguishable from targeted
        # delta refreshes in the log.
        obs.emit("store_reload", old_generation=old, generation=generation,
                 levels=sum(len(layer.levels) for layer in built.values()),
                 seconds=round(time.monotonic() - t0, 6), spec=self.spec,
                 layers=len(built), initial=bool(_initial))
        return generation

    def refresh_layers(self) -> int:
        """Re-read the artifact and swap the index WITHOUT bumping the
        generation — the delta-apply path: an additive delta cannot
        change untouched tiles' bytes, so their cache entries stay
        valid and the caller invalidates only the affected keys
        (heatmap_tpu.delta.refresh_serving). Returns the (unchanged)
        generation."""
        built = self._build()
        with self._lock:
            self._layers = built
            # Fresh synopsis views supersede any provisional overlay
            # published since the last swap (the early-serve contract).
            self.synopsis_epoch += 1
            return self.generation

    #: Max distinct fold views kept per store (LRU).
    TEMPORAL_VIEW_CAP = 8

    def temporal_root(self) -> str | None:
        """The delta-store root behind this store, if its spec has one
        (delta: always; tilefs: when the path is a delta-shaped root).
        Temporal folds need CURRENT + journal + buckets — a plain
        artifact has no history to cut."""
        if self.kind == "delta":
            return self.path
        if self.kind == "tilefs" and os.path.exists(
                os.path.join(self.path, "CURRENT")):
            return self.path
        return None

    def temporal_view(self, *, as_of: float | None = None,
                      window: float | None = None,
                      decay: float | None = None):
        """Layers for a temporal cut: fold the selected buckets + live
        deltas (heatmap_tpu.temporal.fold) and index them exactly like
        the all-time build — same Morton levels, same naming — so the
        render path is unchanged downstream of layer lookup.

        Returns ``(layers, token)``; the token names the fold inputs
        and is the cache-key component for as_of/decay tiles. Views are
        memoised per (token, generation): history below a cut is
        immutable under ingest, so a view keeps serving until the cut
        itself changes (retraction/compaction below it, or a reload).
        Raises ``ValueError`` for a store with no temporal config and
        ``TornBucketError`` when a selected bucket is quarantined —
        the serve tier's stale-if-error path takes it from there."""
        root = self.temporal_root()
        if root is None:
            raise ValueError(
                f"store {self.spec} has no delta root — temporal "
                "queries need a delta-shaped store")
        from heatmap_tpu.temporal import fold as tfold
        from heatmap_tpu.temporal.metrics import TEMPORAL_FOLD_SECONDS

        sel = tfold.select_fold(root, as_of=as_of, window=window,
                                decay=decay)
        key = (sel.token, self.generation)
        with self._lock:
            view = self._temporal_views.get(key)
            if view is not None:
                return view
        t0 = time.monotonic()
        levels = tfold.fold_levels(root, sel, decay_half_life=decay)
        by_pair = self._build_from_levels(_finalized_to_loaded(levels))
        named = self._name_layers(by_pair, strict=False)
        TEMPORAL_FOLD_SECONDS.observe(time.monotonic() - t0)
        view = (named, sel.token)
        with self._lock:
            self._temporal_views[key] = view
            while len(self._temporal_views) > self.TEMPORAL_VIEW_CAP:
                self._temporal_views.pop(
                    next(iter(self._temporal_views)))
        return view

    def _build(self) -> dict[str, Layer]:
        syn_dir: str | None = None
        delta_dirs: list[str] = []
        delta_epoch = 0
        if self.kind == "arrays":
            by_pair = self._build_from_levels(_load_levels(self.path))
            syn_dir = self.path
        elif self.kind == "delta":
            from heatmap_tpu.delta.compact import (load_overlay_levels,
                                                   overlay_dirs,
                                                   read_current)
            from heatmap_tpu.tilefs import sniff_tilefs

            cur = read_current(self.path)
            delta_epoch = _live_delta_epoch(self.path, cur)
            if cur.get("base"):
                syn_dir = os.path.join(self.path, cur["base"])
                delta_dirs = [
                    d for d in overlay_dirs(self.path)
                    if os.path.normpath(d) != os.path.normpath(syn_dir)]
            if syn_dir is not None and sniff_tilefs(syn_dir):
                # A converted base serves zero-copy even under the
                # explicit delta: spec — same bytes, mmap'd pages.
                by_pair = self._build_from_tilefs(syn_dir, delta_dirs)
            else:
                by_pair = self._build_from_levels(
                    _finalized_to_loaded(load_overlay_levels(self.path)))
        elif self.kind == "writeplane":
            from heatmap_tpu.delta.compact import drop_zero_rows
            from heatmap_tpu.io.merge import merge_level_dirs
            from heatmap_tpu.writeplane import manifest as wp_manifest

            # One manifest read pins the whole cross-range overlay:
            # the snapshot names immutable artifact dirs, so the merge
            # below can never mix two epochs' views even while writers
            # advance. The manifest epoch is the disk-cache token (the
            # writeplane analog of _live_delta_epoch — it bumps on
            # every publish, i.e. exactly when visible bytes can
            # change). A torn newest manifest falls back to the last
            # good epoch inside read_manifest.
            snap = wp_manifest.read_manifest(self.path)
            dirs = ([] if snap is None
                    else wp_manifest.overlay_dirs(self.path, snap))
            delta_epoch = 0 if snap is None else int(snap["epoch"])
            merged = (drop_zero_rows(merge_level_dirs(dirs))
                      if dirs else [])
            by_pair = self._build_from_levels(_finalized_to_loaded(merged))
        elif self.kind == "tilefs":
            names = (os.listdir(self.path)
                     if os.path.isdir(self.path) else [])
            if "CURRENT" in names or "journal" in names:
                from heatmap_tpu.delta.compact import (overlay_dirs,
                                                       read_current)

                cur = read_current(self.path)
                delta_epoch = _live_delta_epoch(self.path, cur)
                base = (os.path.join(self.path, cur["base"])
                        if cur.get("base") else None)
                delta_dirs = [
                    d for d in overlay_dirs(self.path)
                    if base is None
                    or os.path.normpath(d) != os.path.normpath(base)]
                by_pair = self._build_from_tilefs(base, delta_dirs)
                syn_dir = base
            else:
                by_pair = self._build_from_tilefs(self.path, [])
                syn_dir = self.path
        else:
            by_pair = self._build_from_blobs(
                _iter_blob_records(self.kind, self.path))
        if syn_dir is not None:
            self._attach_synopses(by_pair, syn_dir, delta_dirs)
            self._attach_integrals(by_pair, syn_dir, delta_dirs)
        named = self._name_layers(by_pair, strict=True)
        self.delta_epoch = delta_epoch
        return named

    def _name_layers(self, by_pair: dict, *, strict: bool) -> dict:
        """Apply the exposed-layer naming to a (user, timespan) -> Layer
        map: the ``--layers`` spec when given, else every pair under its
        own name plus the ``default`` alias. ``strict`` raises on a
        spec'd pair the artifact lacks (a typo'd --layers must not 404
        forever); temporal folds pass strict=False — a window with no
        data for some pair is an honest 404, not a config error."""
        named: dict[str, Layer] = {}
        if self._layer_spec is None:
            for (user, ts), layer in by_pair.items():
                named[f"{user}|{ts}"] = layer
            if ("all", "alltime") in by_pair:
                named.setdefault("default", by_pair[("all", "alltime")])
        else:
            for name, sel in self._layer_spec.items():
                user, _, ts = sel.partition("|")
                layer = by_pair.get((user, ts or "alltime"))
                if layer is None:
                    if strict:
                        raise ValueError(
                            f"layer {name!r}: no ({user!r}, "
                            f"{ts or 'alltime'!r}) slice in {self.spec}; "
                            "available: "
                            f"{sorted('|'.join(p) for p in by_pair)}"
                        )
                    continue
                named[name] = layer
        return named

    def _build_from_tilefs(self, base_dir: str | None,
                           delta_dirs: list[str]) -> dict:
        """mmap'd base ⊕ in-heap live deltas, byte-identical to the
        heap merge.

        Pairs untouched by any delta serve :class:`MappedLevel` views
        straight off the page cache (zero copies, zero data pages
        faulted at build time). Pairs a delta touched are composed in
        the exact order the heap path sums them — base rows first, then
        deltas oldest-first, stable-sorted by code, ``np.add.reduceat``
        per cell, exact zeros dropped — so float summation order (and
        therefore every served byte) matches ``load_overlay_levels``.
        A torn/unreadable tilefs file falls back to the sibling npz
        levels for that zoom; the recovery sweep owns quarantining it.
        """
        from heatmap_tpu.tilefs import format as tilefs_format

        # Live delta rows per (zoom, pair), in overlay (oldest-first)
        # order — the summation order the heap merge uses.
        delta_rows: dict[int, dict[tuple, list]] = {}
        delta_rd: dict[int, int] = {}
        for d in delta_dirs:
            try:
                loaded = LevelArraysSink.load(d)
            except OSError:
                continue
            for zoom, cols in loaded.items():
                zoom = int(zoom)
                users = np.asarray(cols["user"], str)
                tss = np.asarray(cols["timespan"], str)
                codes = morton_encode_np(
                    np.asarray(cols["row"], np.int64),
                    np.asarray(cols["col"], np.int64))
                values = np.asarray(cols["value"], np.float64)
                delta_rd[zoom] = int(cols["zoom"]) - int(
                    cols["coarse_zoom"])
                pair_key = np.char.add(np.char.add(users, "|"), tss)
                for pk in np.unique(pair_key):
                    sel = pair_key == pk
                    user, _, ts = str(pk).partition("|")
                    delta_rows.setdefault(zoom, {}).setdefault(
                        (user, ts), []).append((codes[sel], values[sel]))

        tilefs_files = (tilefs_format.list_tilefs(base_dir)
                        if base_dir else {})
        npz_zooms = set()
        if base_dir and os.path.isdir(base_dir):
            for name in os.listdir(base_dir):
                if name.startswith("level_z") or (
                        name.startswith("host")
                        and os.path.isdir(os.path.join(base_dir, name))):
                    npz_zooms.add(name)
        heap_cols: dict[int, dict] | None = None

        def heap_zoom(zoom: int):
            # Lazy: the npz dir is only loaded when a zoom has no
            # servable tilefs file (partial conversion or a torn one).
            nonlocal heap_cols
            if heap_cols is None:
                heap_cols = (_load_levels(base_dir)
                             if base_dir and npz_zooms else {})
            return heap_cols.get(zoom)

        by_pair: dict[tuple, Layer] = {}

        def compose(zoom: int, parts: list) -> Level:
            codes = np.concatenate([p[0] for p in parts])
            values = np.concatenate([p[1] for p in parts])
            order = np.argsort(codes, kind="stable")
            codes, values = codes[order], values[order]
            uniq, starts = np.unique(codes, return_index=True)
            sums = (np.add.reduceat(values, starts)
                    if len(values) else values)
            keep = sums != 0.0  # retraction zeros, like drop_zero_rows
            return Level(zoom, uniq[keep], sums[keep])

        all_zooms = sorted(set(tilefs_files) | set(delta_rows))
        if npz_zooms:
            # Partially converted dirs: heap levels may carry zooms the
            # tilefs mirrors don't (and vice versa).
            if heap_cols is None:
                heap_cols = _load_levels(base_dir)
            all_zooms = sorted(set(all_zooms) | set(heap_cols))
        for zoom in all_zooms:
            reader = None
            if zoom in tilefs_files:
                from heatmap_tpu import faults

                try:
                    reader = tilefs_format.open_tilefs(tilefs_files[zoom])
                except (tilefs_format.TilefsError, faults.InjectedFault):
                    # Torn file, or an injected tilefs.read fault
                    # (retries=0 by policy): either way the sibling
                    # npz level serves this zoom, bytes unchanged.
                    reader = None
            zoom_deltas = dict(delta_rows.get(zoom, {}))
            if reader is not None:
                rd = reader.zoom - reader.coarse_zoom
                for seg in reader.pairs:
                    pair = (seg["user"], seg["timespan"])
                    codes, values = reader.arrays(seg)
                    layer = by_pair.setdefault(
                        pair, Layer(pair[0], pair[1], rd))
                    extra = zoom_deltas.pop(pair, None)
                    if extra:
                        layer.levels[zoom] = compose(
                            zoom, [(codes, values)] + extra)
                    else:
                        layer.levels[zoom] = MappedLevel(
                            zoom, codes, values, float(seg["vmax"]))
            else:
                cols = heap_zoom(zoom)
                rd = (int(cols["zoom"]) - int(cols["coarse_zoom"])
                      if cols is not None else delta_rd.get(zoom))
                if cols is not None:
                    users = np.asarray(cols["user"], str)
                    tss = np.asarray(cols["timespan"], str)
                    codes = morton_encode_np(
                        np.asarray(cols["row"], np.int64),
                        np.asarray(cols["col"], np.int64))
                    values = np.asarray(cols["value"], np.float64)
                    pair_key = np.char.add(np.char.add(users, "|"), tss)
                    for pk in np.unique(pair_key):
                        sel = pair_key == pk
                        user, _, ts = str(pk).partition("|")
                        pair = (user, ts)
                        layer = by_pair.setdefault(
                            pair, Layer(user, ts, rd))
                        extra = zoom_deltas.pop(pair, None)
                        if extra:
                            layer.levels[zoom] = compose(
                                zoom, [(codes[sel], values[sel])] + extra)
                        else:
                            layer.levels[zoom] = Level(
                                zoom, codes[sel], values[sel])
            # Pairs present only in live deltas at this zoom.
            for pair, parts in zoom_deltas.items():
                rd_pair = (reader.zoom - reader.coarse_zoom
                           if reader is not None else delta_rd.get(zoom))
                layer = by_pair.setdefault(
                    pair, Layer(pair[0], pair[1], rd_pair))
                layer.levels[zoom] = compose(zoom, parts)
        return by_pair

    def _build_from_levels(self, levels: dict[int, dict]) -> dict:
        by_pair: dict[tuple, Layer] = {}
        for zoom in sorted(levels):
            cols = levels[zoom]
            users = np.asarray(cols["user"], str)
            tss = np.asarray(cols["timespan"], str)
            delta = int(cols["zoom"]) - int(cols["coarse_zoom"])
            codes = morton_encode_np(
                np.asarray(cols["row"], np.int64),
                np.asarray(cols["col"], np.int64),
            )
            values = np.asarray(cols["value"], np.float64)
            # One pass per (user, timespan) pair present at this level.
            pair_key = np.char.add(np.char.add(users, "|"), tss)
            for pk in np.unique(pair_key):
                sel = pair_key == pk
                user, _, ts = str(pk).partition("|")
                layer = by_pair.setdefault((user, ts),
                                           Layer(user, ts, delta))
                layer.levels[int(zoom)] = Level(zoom, codes[sel],
                                                values[sel])
        return by_pair

    def _build_from_blobs(self, records) -> dict:
        staged: dict[tuple, dict[int, list]] = {}
        by_pair: dict[tuple, Layer] = {}
        for blob_id, raw in records:
            try:
                user, ts, coarse_id = blob_id.split("|", 2)
            except ValueError:
                continue  # not a heatmap blob id; skip like parse_tile_id
            coarse = parse_tile_id(coarse_id)
            if coarse is None:
                continue
            heat = json.loads(raw)
            layer = by_pair.get((user, ts))
            if layer is None:
                layer = by_pair[(user, ts)] = Layer(user, ts, None)
            layer.blob_json[coarse] = raw
            buckets = staged.setdefault((user, ts), {})
            for tid, value in heat.items():
                parsed = parse_tile_id(tid)
                if parsed is None:
                    continue
                z, r, c = parsed
                buckets.setdefault(z, []).append((r, c, float(value)))
                if layer.result_delta is None:
                    layer.result_delta = z - coarse[0]
        for pair, buckets in staged.items():
            layer = by_pair[pair]
            for zoom, rows in buckets.items():
                arr = np.asarray(rows, np.float64)
                layer.levels[zoom] = Level(
                    zoom,
                    morton_encode_np(arr[:, 0].astype(np.int64),
                                     arr[:, 1].astype(np.int64)),
                    arr[:, 2],
                )
        return by_pair

    # -- wavelet synopses --------------------------------------------------

    def _attach_synopses(self, by_pair: dict, syn_dir: str,
                         delta_dirs: list[str]):
        """Decode every readable ``synopsis-z*.npz`` in ``syn_dir``
        into servable :class:`SynopsisView`\\ s on the matching layers.

        For delta stores the synopses describe the BASE pyramid, so
        the live delta dirs' rows are scatter-added on top of the
        decoded grid — an exact addition, keeping every cell within
        the stamped bound of the base ⊕ deltas overlay the exact path
        serves. Unreadable artifacts are skipped (serving falls back
        to exact; the recovery sweep owns quarantining them)."""
        syn = synopsis_build.load_synopses(syn_dir)
        if not syn:
            return
        extras: dict[int, list] = {}
        for d in delta_dirs:
            try:
                loaded = LevelArraysSink.load(d)
            except OSError:
                continue
            for zoom, cols in loaded.items():
                if int(zoom) in syn:
                    extras.setdefault(int(zoom), []).append(cols)
        for zoom, pairs in syn.items():
            for sp in pairs:
                layer = by_pair.get((sp.user, sp.timespan))
                if layer is None:
                    continue
                parts = [[], [], []]
                for cols in extras.get(zoom, ()):
                    users = np.asarray(cols["user"], str)
                    tss = np.asarray(cols["timespan"], str)
                    sel = (users == sp.user) & (tss == sp.timespan)
                    if sel.any():
                        parts[0].append(np.asarray(cols["row"],
                                                   np.int64)[sel])
                        parts[1].append(np.asarray(cols["col"],
                                                   np.int64)[sel])
                        parts[2].append(np.asarray(cols["value"],
                                                   np.float64)[sel])
                extra = (tuple(np.concatenate(p) for p in parts)
                         if parts[0] else None)
                t0 = time.monotonic()
                # Clamp decoded noise below zero: counts are
                # non-negative, so clamping only moves cells TOWARD
                # the exact value — the stamped bound still holds.
                grid = np.maximum(sp.decode(extra), 0.0)
                r, c = np.nonzero(grid)
                level = Level(zoom,
                              morton_encode_np(r.astype(np.int64),
                                               c.astype(np.int64)),
                              grid[r, c])
                if obs.metrics_enabled():
                    synopsis_metrics.SYNOPSIS_DECODE_SECONDS.observe(
                        time.monotonic() - t0)
                layer.synopses[zoom] = SynopsisView(level, sp.max_err)

    # -- integral pyramids -------------------------------------------------

    def _attach_integrals(self, by_pair: dict, syn_dir: str,
                          delta_dirs: list[str]):
        """Load every readable ``integral-z*.npz`` in ``syn_dir`` onto
        the matching layers (heatmap_tpu.analytics).

        For delta stores the integrals describe the BASE pyramid, so
        the live delta dirs' rows are folded in by recovering the grid
        from the SAT, scatter-adding, and rescanning — an exact
        operation for integer grids, keeping /query answers equal to a
        full recompute over base ⊕ deltas. Unreadable artifacts are
        skipped (/query falls through to exact rows; the recovery
        sweep owns quarantining them)."""
        ints = integral_build.load_integrals(syn_dir)
        if not ints:
            return
        extras: dict[int, list] = {}
        for d in delta_dirs:
            try:
                loaded = LevelArraysSink.load(d)
            except OSError:
                continue
            for zoom, cols in loaded.items():
                if int(zoom) in ints:
                    extras.setdefault(int(zoom), []).append(cols)
        for zoom, pairs in ints.items():
            for ip in pairs:
                layer = by_pair.get((ip.user, ip.timespan))
                if layer is None:
                    continue
                parts = [[], [], []]
                for cols in extras.get(zoom, ()):
                    users = np.asarray(cols["user"], str)
                    tss = np.asarray(cols["timespan"], str)
                    sel = (users == ip.user) & (tss == ip.timespan)
                    if sel.any():
                        parts[0].append(np.asarray(cols["row"],
                                                   np.int64)[sel])
                        parts[1].append(np.asarray(cols["col"],
                                                   np.int64)[sel])
                        parts[2].append(np.asarray(cols["value"],
                                                   np.float64)[sel])
                if parts[0]:
                    ip = ip.with_extras(np.concatenate(parts[0]),
                                        np.concatenate(parts[1]),
                                        np.concatenate(parts[2]))
                layer.integrals[zoom] = ip

    def publish_provisional(self, rows_by: dict) -> int:
        """Early-serving hook (ingest/loop.py): overlay a just-journaled
        micro-batch's coarse cell counts onto the current synopsis
        views, ahead of the exact delta apply.

        ``rows_by`` is ``{(user, timespan): {zoom: (rows, cols,
        values)}}``. Only (pair, zoom) slots that already carry a
        synopsis are touched — the overlay is an exact addition on the
        decoded grid, so the stamped bound is unchanged; the view is
        marked ``stale`` until the exact apply's ``refresh_layers``
        rebuilds the index (which supersedes every provisional view).
        Returns the number of views updated; bumps ``synopsis_epoch``
        so cached synopsis tiles cannot alias the provisional bytes.
        """
        by_pair: dict[tuple, Layer] = {}
        for layer in self._layers.values():
            by_pair.setdefault((layer.user, layer.timespan), layer)
        updated = 0
        per_zoom: dict[int, list] = {}
        for pair, zooms in rows_by.items():
            layer = by_pair.get(tuple(pair))
            if layer is None:
                continue
            for zoom, (r, c, v) in zooms.items():
                view = layer.synopses.get(int(zoom))
                if view is None or not len(np.asarray(r)):
                    continue
                lvl = view.level
                codes = np.concatenate([
                    lvl.codes,
                    morton_encode_np(np.asarray(r, np.int64),
                                     np.asarray(c, np.int64))])
                values = np.concatenate([lvl.values,
                                         np.asarray(v, np.float64)])
                codes, values = _combine_cells(codes, values)
                layer.synopses[int(zoom)] = SynopsisView(
                    Level(zoom, codes, values), view.max_err, stale=True)
                per_zoom.setdefault(int(zoom), []).append(view.max_err)
                updated += 1
        if updated:
            with self._lock:
                self.synopsis_epoch += 1
            for zoom, errs in sorted(per_zoom.items()):
                # bytes=0: an in-memory overlay, no artifact written.
                obs.emit("synopsis_built", zoom=zoom, pairs=len(errs),
                         bytes=0, max_err=float(max(errs)),
                         provisional=True)
        return updated

    def stats(self) -> dict:
        """Small JSON-ready summary for /healthz."""
        return {
            "spec": self.spec,
            "kind": self.kind,
            "generation": self.generation,
            "synopsis_epoch": self.synopsis_epoch,
            "delta_epoch": self.delta_epoch,
            "layers": {
                name: {
                    "user": layer.user,
                    "timespan": layer.timespan,
                    "detail_zooms": layer.detail_zooms,
                    "result_delta": layer.result_delta,
                    "rows": int(sum(len(l) for l in layer.levels.values())),
                    "synopsis_zooms": sorted(layer.synopses),
                    "synopsis_stale": any(v.stale for v in
                                          layer.synopses.values()),
                    "integral_zooms": sorted(layer.integrals),
                }
                for name, layer in sorted(self._layers.items())
            },
        }
