"""``GET /dashboard`` — a single self-contained operational page.

One HTML document, served by the stdlib HTTP shell with **no external
assets**: styles, scripts, and SVG are all inline, so the page works
from an air-gapped TPU host, over an SSH tunnel, or saved to disk next
to an incident bundle. The page polls the endpoints the server already
exposes — ``/healthz`` for status (SLO burn, brownout rung, fleet
ring, recent anomalies) and ``/series`` (obs/timeseries.py) for
history — and renders live sparklines for the headline series. On a
fleet router the same page fans out automatically: its ``/series``
requests carry ``fleet=1``, so each card folds every backend's
history.

Charting follows the repo's data-viz conventions: single-series
sparklines (the card title names the series — no legend), a
min/max band under a 2 px ``last``-value line, categorical slot-1
blue for series ink, reserved status colors (always icon + label,
never color alone) for health chips, recessive hairline grid, text in
ink tokens, dark mode as selected steps of the same palette (not an
automatic flip), and a per-card data table as the non-visual
fallback. Sampler off (``--telemetry-sample-interval 0``) degrades
gracefully: cards say so instead of erroring, and the status row
still works from ``/healthz`` alone.

No jax anywhere in this module — it is served from the same
process-light shell as serve/http.py (tests/test_obs.py pins the
import graph).
"""

from __future__ import annotations

import json

#: Headline cards: ``name`` is the flattened telemetry series
#: (histograms read via their ``_sum``/``_count`` pair), ``mode`` how
#: the sampled buckets become a plotted value — ``rate`` (per-second
#: delta of a counter), ``mean`` (delta-sum over delta-count of a
#: histogram pair), ``level`` (the sampled gauge value), — and
#: ``agg`` how frames (label sets, fleet backends) fold into one line.
DEFAULT_HEADLINES = (
    {"title": "Requests / s", "name": "http_requests_total",
     "mode": "rate", "agg": "sum", "unit": "req/s"},
    {"title": "Request latency (mean)", "name": "serve_request_seconds",
     "mode": "mean", "agg": "mean", "unit": "s"},
    {"title": "Ingest lag (mean)", "name": "ingest_lag_seconds",
     "mode": "mean", "agg": "mean", "unit": "s"},
    {"title": "Tile cache bytes", "name": "tile_cache_bytes",
     "mode": "level", "agg": "sum", "unit": "B"},
    {"title": "Brownout rung", "name": "degrade_rung",
     "mode": "level", "agg": "max", "unit": ""},
    {"title": "Incident bundles", "name": "incidents_total",
     "mode": "rate", "agg": "sum", "unit": "/s"},
)


def render_page(headlines=DEFAULT_HEADLINES, refresh_s: float = 3.0,
                title: str = "heatmap-tpu ops") -> bytes:
    """Build the dashboard document (bytes, utf-8 HTML)."""
    config = {"headlines": list(headlines), "refresh_s": float(refresh_s),
              "title": title}
    doc = _PAGE.replace("__CONFIG_JSON__", json.dumps(config))
    doc = doc.replace("__TITLE__", title)
    return doc.encode("utf-8")


_PAGE = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>__TITLE__</title>
<style>
  .viz-root {
    color-scheme: light;
    --surface-1: #fcfcfb; --page: #f9f9f7;
    --ink-1: #0b0b0b; --ink-2: #52514e; --muted: #898781;
    --grid: #e1e0d9; --baseline: #c3c2b7;
    --border: rgba(11,11,11,0.10);
    --series-1: #2a78d6; --series-band: rgba(42,120,214,0.16);
    --status-good: #0ca30c; --status-warning: #fab219;
    --status-serious: #ec835a; --status-critical: #d03b3b;
  }
  @media (prefers-color-scheme: dark) {
    :root:where(:not([data-theme="light"])) .viz-root {
      color-scheme: dark;
      --surface-1: #1a1a19; --page: #0d0d0d;
      --ink-1: #ffffff; --ink-2: #c3c2b7; --muted: #898781;
      --grid: #2c2c2a; --baseline: #383835;
      --border: rgba(255,255,255,0.10);
      --series-1: #3987e5; --series-band: rgba(57,135,229,0.22);
    }
  }
  :root[data-theme="dark"] .viz-root {
    color-scheme: dark;
    --surface-1: #1a1a19; --page: #0d0d0d;
    --ink-1: #ffffff; --ink-2: #c3c2b7; --muted: #898781;
    --grid: #2c2c2a; --baseline: #383835;
    --border: rgba(255,255,255,0.10);
    --series-1: #3987e5; --series-band: rgba(57,135,229,0.22);
  }
  body.viz-root {
    margin: 0; background: var(--page); color: var(--ink-1);
    font: 14px/1.45 system-ui, -apple-system, "Segoe UI", sans-serif;
  }
  header { padding: 14px 20px 6px; }
  header h1 { font-size: 17px; margin: 0 0 8px; font-weight: 650; }
  #chips { display: flex; flex-wrap: wrap; gap: 8px; }
  .chip {
    display: inline-flex; align-items: center; gap: 6px;
    background: var(--surface-1); border: 1px solid var(--border);
    border-radius: 999px; padding: 3px 11px; color: var(--ink-2);
    font-size: 12.5px;
  }
  .chip .dot { font-weight: 700; }
  .chip.good .dot { color: var(--status-good); }
  .chip.warning .dot { color: var(--status-warning); }
  .chip.serious .dot { color: var(--status-serious); }
  .chip.critical .dot { color: var(--status-critical); }
  main {
    display: grid; gap: 14px; padding: 12px 20px 24px;
    grid-template-columns: repeat(auto-fill, minmax(280px, 1fr));
  }
  .card {
    background: var(--surface-1); border: 1px solid var(--border);
    border-radius: 8px; padding: 12px 14px 8px; position: relative;
  }
  .card h2 { font-size: 12.5px; font-weight: 600; color: var(--ink-2);
             margin: 0; }
  .card .value { font-size: 22px; font-weight: 650; margin: 2px 0 4px; }
  .card .value .unit { font-size: 12px; color: var(--muted);
                       font-weight: 500; margin-left: 4px; }
  .card svg { display: block; width: 100%; height: 64px; }
  .card .meta { color: var(--muted); font-size: 11.5px; margin: 4px 0; }
  .card details { margin: 2px 0 4px; }
  .card summary { color: var(--muted); font-size: 11.5px;
                  cursor: pointer; }
  .card table { width: 100%; border-collapse: collapse; font-size: 11.5px;
                color: var(--ink-2);
                font-variant-numeric: tabular-nums; }
  .card td, .card th { text-align: right; padding: 1px 4px;
                       border-top: 1px solid var(--grid); }
  .card th { color: var(--muted); font-weight: 500; }
  #lists { display: grid; gap: 14px; padding: 0 20px 28px;
           grid-template-columns: repeat(auto-fill, minmax(340px, 1fr)); }
  .panel { background: var(--surface-1); border: 1px solid var(--border);
           border-radius: 8px; padding: 12px 14px; }
  .panel h2 { font-size: 12.5px; font-weight: 600; color: var(--ink-2);
              margin: 0 0 6px; }
  .panel ul { margin: 0; padding: 0; list-style: none; font-size: 12.5px; }
  .panel li { padding: 3px 0; border-top: 1px solid var(--grid);
              color: var(--ink-2); }
  .panel li:first-child { border-top: 0; }
  .panel .empty { color: var(--muted); }
  #tooltip {
    position: fixed; pointer-events: none; display: none; z-index: 10;
    background: var(--surface-1); border: 1px solid var(--border);
    border-radius: 6px; padding: 4px 8px; font-size: 11.5px;
    color: var(--ink-1); box-shadow: 0 2px 8px rgba(0,0,0,0.18);
    font-variant-numeric: tabular-nums;
  }
  #foot { color: var(--muted); font-size: 11.5px; padding: 0 20px 18px; }
</style>
</head>
<body class="viz-root">
<header>
  <h1>__TITLE__</h1>
  <div id="chips"><span class="chip"><span class="dot">·</span>
    loading…</span></div>
</header>
<main id="cards"></main>
<div id="lists">
  <div class="panel"><h2>SLO burn</h2><ul id="slo-list">
    <li class="empty">no SLO engine installed</li></ul></div>
  <div class="panel"><h2>Recent anomalies</h2><ul id="anomaly-list">
    <li class="empty">none</li></ul></div>
  <div class="panel"><h2>Fleet</h2><ul id="fleet-list">
    <li class="empty">single process</li></ul></div>
</div>
<div id="foot"></div>
<div id="tooltip"></div>
<script>
"use strict";
const CONFIG = __CONFIG_JSON__;
const tooltip = document.getElementById("tooltip");

function fmt(v, unit) {
  if (v === null || v === undefined || !isFinite(v)) return "–";
  const a = Math.abs(v);
  let s;
  if (a >= 1e9) s = (v / 1e9).toFixed(2) + "G";
  else if (a >= 1e6) s = (v / 1e6).toFixed(2) + "M";
  else if (a >= 1e4) s = (v / 1e3).toFixed(1) + "k";
  else if (a >= 100) s = v.toFixed(0);
  else if (a >= 1) s = v.toFixed(2);
  else if (a === 0) s = "0";
  else s = v.toPrecision(2);
  return unit ? s + " " + unit : s;
}
function clock(ts) {
  return new Date(ts * 1000).toTimeString().slice(0, 8);
}

// points: [ts, min, max, sum, count, last] per bucket (obs/timeseries).
function toValues(points, step, mode) {
  const out = [];
  if (mode === "rate") {
    for (let i = 1; i < points.length; i++) {
      const dt = points[i][0] - points[i - 1][0];
      if (dt <= 0) continue;
      const dv = points[i][5] - points[i - 1][5];
      out.push({ts: points[i][0], v: Math.max(0, dv / dt),
                lo: null, hi: null});
    }
  } else {
    for (const p of points)
      out.push({ts: p[0], v: p[5], lo: p[1], hi: p[2]});
  }
  return out;
}
// Histogram mean: pair the _sum/_count series bucket-by-bucket.
function meanValues(sumPts, countPts) {
  const counts = new Map(countPts.map(p => [p[0], p[5]]));
  const raw = [];
  for (const p of sumPts) {
    const c = counts.get(p[0]);
    if (c !== undefined) raw.push([p[0], p[5], c]);
  }
  const out = [];
  for (let i = 1; i < raw.length; i++) {
    const dc = raw[i][2] - raw[i - 1][2];
    if (dc <= 0) continue;
    out.push({ts: raw[i][0], v: (raw[i][1] - raw[i - 1][1]) / dc,
              lo: null, hi: null});
  }
  return out;
}
function foldFrames(perFrame, agg) {
  const byTs = new Map();
  for (const vals of perFrame)
    for (const p of vals) {
      const cur = byTs.get(p.ts);
      if (!cur) byTs.set(p.ts, {ts: p.ts, v: p.v, lo: p.lo, hi: p.hi, n: 1});
      else {
        cur.n += 1;
        if (agg === "max") cur.v = Math.max(cur.v, p.v);
        else cur.v += p.v;
        if (p.lo !== null) cur.lo = cur.lo === null ? p.lo
            : Math.min(cur.lo, p.lo);
        if (p.hi !== null) cur.hi = cur.hi === null ? p.hi
            : Math.max(cur.hi, p.hi);
      }
    }
  const out = [...byTs.values()].sort((a, b) => a.ts - b.ts);
  if (agg === "mean") for (const p of out) p.v /= p.n;
  return out;
}

function sparkline(el, vals, unit, step) {
  const W = 300, H = 64, PAD = 4;
  if (!vals.length) {
    el.innerHTML = '<text x="8" y="36" fill="var(--muted)" ' +
      'font-size="12">no data (sampler off?)</text>';
    return;
  }
  let lo = Infinity, hi = -Infinity;
  for (const p of vals) {
    lo = Math.min(lo, p.lo !== null && p.lo !== undefined ? p.lo : p.v);
    hi = Math.max(hi, p.hi !== null && p.hi !== undefined ? p.hi : p.v);
  }
  if (hi === lo) { hi += 1; lo -= lo === 0 ? 0 : 1; }
  const t0 = vals[0].ts, t1 = vals[vals.length - 1].ts || t0 + 1;
  const x = ts => t1 === t0 ? PAD
      : PAD + (W - 2 * PAD) * (ts - t0) / (t1 - t0);
  const y = v => H - PAD - (H - 2 * PAD) * (v - lo) / (hi - lo);
  let band = "";
  if (vals.some(p => p.lo !== null && p.lo !== undefined)) {
    const top = vals.map(p => x(p.ts).toFixed(1) + "," +
        y(p.hi === null ? p.v : p.hi).toFixed(1));
    const bot = [...vals].reverse().map(p => x(p.ts).toFixed(1) + "," +
        y(p.lo === null ? p.v : p.lo).toFixed(1));
    band = '<polygon points="' + top.concat(bot).join(" ") +
        '" fill="var(--series-band)" stroke="none"/>';
  }
  const line = vals.map(p => x(p.ts).toFixed(1) + "," +
      y(p.v).toFixed(1)).join(" ");
  const last = vals[vals.length - 1];
  el.setAttribute("viewBox", "0 0 " + W + " " + H);
  el.innerHTML =
    '<line x1="0" y1="' + (H - PAD) + '" x2="' + W + '" y2="' +
    (H - PAD) + '" stroke="var(--baseline)" stroke-width="1"/>' + band +
    '<polyline points="' + line + '" fill="none" ' +
    'stroke="var(--series-1)" stroke-width="2" stroke-linejoin="round" ' +
    'stroke-linecap="round"/>' +
    '<circle cx="' + x(last.ts).toFixed(1) + '" cy="' +
    y(last.v).toFixed(1) + '" r="3" fill="var(--series-1)"/>';
  el.onmousemove = ev => {
    const rect = el.getBoundingClientRect();
    const fx = (ev.clientX - rect.left) / rect.width * W;
    let best = vals[0], d = Infinity;
    for (const p of vals) {
      const dd = Math.abs(x(p.ts) - fx);
      if (dd < d) { d = dd; best = p; }
    }
    tooltip.style.display = "block";
    tooltip.style.left = (ev.clientX + 12) + "px";
    tooltip.style.top = (ev.clientY + 12) + "px";
    tooltip.textContent = clock(best.ts) + "  " + fmt(best.v, unit) +
        (best.lo !== null && best.lo !== undefined
         ? "  (min " + fmt(best.lo, "") + " / max " + fmt(best.hi, "") + ")"
         : "");
  };
  el.onmouseleave = () => { tooltip.style.display = "none"; };
}

async function getJSON(url) {
  const resp = await fetch(url, {cache: "no-store"});
  if (!resp.ok) throw new Error(url + " -> " + resp.status);
  return resp.json();
}
async function series(name) {
  const doc = await getJSON("/series?fleet=1&name=" +
      encodeURIComponent(name));
  return doc.frames || [];
}

function card(h) {
  const div = document.createElement("div");
  div.className = "card";
  div.innerHTML = '<h2></h2><div class="value">–</div>' +
    '<svg role="img"></svg><div class="meta">–</div>' +
    '<details><summary>data</summary><table></table></details>';
  div.querySelector("h2").textContent = h.title;
  div.querySelector("svg").setAttribute("aria-label", h.title);
  document.getElementById("cards").appendChild(div);
  return div;
}

async function refreshCard(h, el) {
  let vals = [], step = null, tier = null;
  try {
    if (h.mode === "mean") {
      const sums = await series(h.name + "_sum");
      const counts = await series(h.name + "_count");
      const byKey = new Map(counts.map(f => [
        (f.backend || "") + "|" + f.key, f]));
      const perFrame = [];
      for (const f of sums) {
        const cf = byKey.get((f.backend || "") + "|" +
            f.key.replace("_sum", "_count"));
        if (cf) perFrame.push(meanValues(f.points, cf.points));
        if (step === null) { step = f.step; tier = f.tier; }
      }
      vals = foldFrames(perFrame, h.agg === "max" ? "max" : "mean");
    } else {
      const frames = await series(h.name);
      const perFrame = [];
      for (const f of frames) {
        perFrame.push(toValues(f.points, f.step, h.mode));
        if (step === null) { step = f.step; tier = f.tier; }
      }
      vals = foldFrames(perFrame, h.agg);
    }
  } catch (e) { vals = []; }
  const last = vals.length ? vals[vals.length - 1].v : null;
  el.querySelector(".value").innerHTML = "";
  el.querySelector(".value").append(fmt(last, ""));
  if (h.unit) {
    const u = document.createElement("span");
    u.className = "unit"; u.textContent = h.unit;
    el.querySelector(".value").appendChild(u);
  }
  sparkline(el.querySelector("svg"), vals, h.unit, step);
  el.querySelector(".meta").textContent = step === null
      ? "awaiting samples"
      : "resolution " + step + " s (tier " + tier + ") · " +
        vals.length + " buckets";
  const rows = vals.slice(-10).map(p => "<tr><td>" + clock(p.ts) +
      "</td><td>" + fmt(p.v, h.unit) + "</td></tr>").join("");
  el.querySelector("table").innerHTML =
    "<tr><th>time</th><th>value</th></tr>" + rows;
}

function chip(cls, icon, label) {
  return '<span class="chip ' + cls + '"><span class="dot">' + icon +
      '</span>' + label + '</span>';
}

function renderHealth(h) {
  const chips = [];
  const status = h.status || "unknown";
  chips.push(status === "ok"
      ? chip("good", "\\u2713", "serving ok")
      : chip("serious", "\\u26a0", "status: " + status));
  const slo = h.slo;
  if (slo) {
    const breaching = slo.breaching || [];
    chips.push(breaching.length
        ? chip("critical", "\\u2715", "SLO breach: " + breaching.join(", "))
        : chip("good", "\\u2713", "SLO ok"));
  }
  const degrade = h.degrade;
  if (degrade && degrade.rung !== undefined) {
    const r = degrade.rung;
    chips.push(chip(r === 0 ? "good" : (r >= 3 ? "critical" : "warning"),
        r === 0 ? "\\u2713" : "\\u26a0", "brownout rung " + r));
  }
  const anomalies = h.anomalies || [];
  chips.push(anomalies.length
      ? chip("warning", "\\u26a0", anomalies.length + " recent anomalies")
      : chip("good", "\\u2713", "no anomalies"));
  const fleet = h.fleet;
  if (fleet && fleet.backends) {
    const n = Object.keys(fleet.backends).length;
    const up = (fleet.eligible || []).length;
    chips.push(chip(up === n ? "good" : (up ? "warning" : "critical"),
        up === n ? "\\u2713" : "\\u26a0",
        "fleet " + up + "/" + n + " eligible"));
  }
  const tstats = h.telemetry;
  if (tstats) chips.push(chip("good", "\\u00b7", tstats.series +
      " series · " + tstats.points + " pts"));
  document.getElementById("chips").innerHTML = chips.join("");

  const sloList = document.getElementById("slo-list");
  if (slo && slo.objectives && Object.keys(slo.objectives).length) {
    sloList.innerHTML = Object.entries(slo.objectives).map(([name, o]) => {
      const burn = (h.slo_burn || {})[name];
      return "<li>" + name + " — burn " +
          (burn === undefined ? "–" : fmt(burn, "")) +
          (o.breaching ? " \\u2715 breaching" : "") + "</li>";
    }).join("");
  }
  const aList = document.getElementById("anomaly-list");
  if (anomalies.length) {
    aList.innerHTML = anomalies.slice().reverse().map(a =>
      "<li>" + clock(a.ts) + " " + a.series + " z=" + a.z +
      " (threshold " + a.threshold + ")</li>").join("");
  } else {
    aList.innerHTML = '<li class="empty">none</li>';
  }
  const fList = document.getElementById("fleet-list");
  if (fleet && fleet.backends) {
    fList.innerHTML = Object.entries(fleet.backends).map(([bid, b]) =>
      "<li>" + bid + " — " + (b.breaker || b.state || "?") +
      ((fleet.eligible || []).includes(bid) ? "" : " (out of ring)") +
      "</li>").join("");
  }
}

const cards = CONFIG.headlines.map(h => [h, card(h)]);
let ticking = false;
async function tick() {
  if (ticking) return;
  ticking = true;
  try {
    try { renderHealth(await getJSON("/healthz")); } catch (e) {}
    await Promise.all(cards.map(([h, el]) => refreshCard(h, el)));
    document.getElementById("foot").textContent =
      "refreshed " + new Date().toTimeString().slice(0, 8) +
      " · every " + CONFIG.refresh_s + " s · /series · /healthz · " +
      "/metrics";
  } finally { ticking = false; }
}
tick();
setInterval(tick, CONFIG.refresh_s * 1000);
</script>
</body>
</html>
"""
