"""Brownout control: an SLO-driven adaptive-fidelity ladder.

The SLO burn-rate engine (obs/slo.py) knows when latency/staleness
budgets are burning, the wavelet-synopsis tier can serve any coarse
tile at a stamped L-inf error for a fraction of the bytes, and the
admission machinery already sheds with typed 503s — this module closes
the loop between them. :class:`BrownoutController` is a small,
deterministic rung-ladder state machine:

====  ============  ======================================================
rung  name          serving policy
====  ============  ======================================================
0     full          exact bytes, byte-identical to a controller-less app
1     synopsis      coarse zooms answered from decoded synopses (achieved
                    error stamped in ``X-Heatmap-Synopsis``)
2     stale_wide    synopsis zoom ceiling raised (coarser sources upsample
                    into zooms with no natural synopsis) and cache TTLs
                    stretched so serve-stale widens
3     shed          admission tightened (in-flight bound halved) and a
                    deterministic fraction of tile keys shed as typed 503s
====  ============  ======================================================

**Hysteresis.** A step *up* requires the burn signal to sit at or above
``up_threshold`` continuously for ``dwell_s``; a step *down* requires it
at or below ``down_threshold`` continuously for ``hold_s``. Between the
thresholds both timers reset (a dead band holds the current rung), and
every transition restarts the clock — so an oscillating burn signal
moves the ladder at most once per dwell/hold window and never flaps.

**Determinism.** The controller owns no thread and reads no ambient
state: the clock (``clock=time.monotonic``) and the burn source (a
callable returning ``{slo_name: burn}``; default: the installed SLO
engine via :func:`heatmap_tpu.obs.slo.burn_values`) are both injectable,
so tests and the chaos soak pin the whole ladder with a fake clock and
a scripted burn schedule. Shedding at the top rung is a seeded hash of
the tile key (the faults-plane ``hash01``, the same determinism idiom as
retry backoff), never an RNG — the router and every backend agree on
which keys shed without coordination.

**Observability.** Every transition is one edge-triggered
``degrade_step`` event (rung, direction, cause, burn) plus the
``degrade_rung`` gauge; reaching the top rung fires a rate-limited
``brownout`` incident trigger so a flight-recorder bundle captures the
episode. ``snapshot()`` folds into ``/healthz`` and is what the fleet
router reads from backend probes for fleet-wide rung agreement.

Zero-cost-when-off: at rung 0 every policy helper returns the
pass-through value and the serve path's bytes, ETags, cache keys and
TTLs are untouched — pinned by the byte-identity legs in
tests/test_degrade.py, the same contract as tracing and the recorder.
"""

from __future__ import annotations

import threading
import time

from heatmap_tpu import faults, obs
from heatmap_tpu.obs import incident, slo

_registry = obs.get_registry()
DEGRADE_RUNG = _registry.gauge(
    "degrade_rung", "Active brownout rung (0 = full fidelity)")
DEGRADE_STEPS = _registry.counter(
    "degrade_steps_total", "Brownout ladder transitions",
    labelnames=("direction",))
DEGRADE_SHED = _registry.counter(
    "degrade_shed_total", "Tile requests shed by the brownout ladder")

#: Rung names, index == rung. The ladder's top rung defaults to the
#: last entry but can be capped lower per controller.
RUNG_NAMES = ("full", "synopsis", "stale_wide", "shed")
MAX_RUNG = len(RUNG_NAMES) - 1

#: ``--degrade-ladder`` spec keys -> (attribute, parser, validator).
_LADDER_KEYS = {
    "up": ("up_threshold", float, lambda v: v > 0),
    "down": ("down_threshold", float, lambda v: v >= 0),
    "ttl": ("ttl_stretch", float, lambda v: v >= 1.0),
    "shed": ("shed_fraction", float, lambda v: 0.0 <= v <= 1.0),
    "max": ("max_rung", int, lambda v: 1 <= v <= MAX_RUNG),
}


def parse_ladder_spec(spec: str) -> dict:
    """Parse a ``--degrade-ladder`` spec (``up=1.0,down=0.5,ttl=4,
    shed=0.5,max=3``) into BrownoutController kwargs. Raises ValueError
    on unknown keys or out-of-range values (the CLI turns that into a
    SystemExit, same convention as --slo/--chaos specs)."""
    out: dict = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        key, sep, raw = part.partition("=")
        if not sep or key not in _LADDER_KEYS:
            raise ValueError(
                f"unknown ladder knob {key!r} "
                f"(expected {','.join(sorted(_LADDER_KEYS))})")
        attr, conv, ok = _LADDER_KEYS[key]
        try:
            value = conv(raw)
        except ValueError:
            raise ValueError(f"ladder knob {key}={raw!r} is not a number")
        if not ok(value):
            raise ValueError(f"ladder knob {key}={raw} out of range")
        out[attr] = value
    return out


def shed_tile(fraction: float, key: tuple) -> bool:
    """Deterministic shed decision for one tile key: a seeded hash of
    the key against ``fraction``, using the installed faults plane's
    seed (0 without one) — so repeat runs shed the same keys and the
    router agrees with every backend without coordination."""
    if fraction <= 0.0:
        return False
    plane = faults.get_plane()
    seed = plane.seed if plane is not None else 0
    return faults.hash01(seed, "brownout", *map(str, key)) < fraction


def retry_after_jitter(nominal_s: float, path: str, bucket: int) -> int:
    """Seeded jitter for the ``Retry-After`` header on typed 503s: the
    faults/retry.py jitter shape (deterministic ``hash01``, never RNG)
    spread over [0.5, 1.5) x nominal so shed clients don't retry in a
    synchronized thundering herd. ``bucket`` is a coarse time bucket
    (whole seconds) so one client's successive retries re-jitter while
    the value stays deterministic under a seeded plane."""
    plane = faults.get_plane()
    seed = plane.seed if plane is not None else 0
    jitter = 0.5 + faults.hash01(seed, "retry.after", path, bucket)
    return max(1, round(nominal_s * jitter))


class BrownoutController:
    """Hysteresis-guarded rung ladder; see the module docstring.

    Thread-safe: ``poll``/``observe`` serialize under a lock; the policy
    helpers (``force_synopsis``/``ttl_scale``/...) read the rung without
    locking — a plain int read, which is what keeps the rung-0 fast path
    free. ``poll()`` is rate-limited to ``poll_interval_s`` so calling
    it per-request costs one clock read between evaluations.
    """

    def __init__(self, *, up_threshold: float = 1.0,
                 down_threshold: float = 0.5,
                 dwell_s: float = 10.0, hold_s: float = 30.0,
                 max_rung: int = MAX_RUNG, ttl_stretch: float = 4.0,
                 shed_fraction: float = 0.5,
                 poll_interval_s: float = 1.0,
                 burn_source=None, clock=time.monotonic):
        if down_threshold >= up_threshold:
            raise ValueError(
                f"down threshold {down_threshold} must sit below the up "
                f"threshold {up_threshold} (the hysteresis dead band)")
        if dwell_s < 0 or hold_s < 0:
            raise ValueError("dwell/hold must be >= 0 seconds")
        if not 1 <= max_rung <= MAX_RUNG:
            raise ValueError(f"max_rung must be in 1..{MAX_RUNG}")
        if ttl_stretch < 1.0:
            raise ValueError("ttl stretch must be >= 1.0")
        if not 0.0 <= shed_fraction <= 1.0:
            raise ValueError("shed fraction must be in [0, 1]")
        self.up_threshold = float(up_threshold)
        self.down_threshold = float(down_threshold)
        self.dwell_s = float(dwell_s)
        self.hold_s = float(hold_s)
        self.max_rung = int(max_rung)
        self.ttl_stretch = float(ttl_stretch)
        self.shed_fraction = float(shed_fraction)
        self.poll_interval_s = float(poll_interval_s)
        self._burn_source = (burn_source if burn_source is not None
                             else slo.burn_values)
        self._clock = clock
        self._lock = threading.Lock()
        self.rung = 0
        self._high_since: float | None = None
        self._low_since: float | None = None
        self._next_poll: float | None = None
        self._last_burns: dict = {}

    # -- control loop ------------------------------------------------------

    def poll(self, now: float | None = None) -> int:
        """Re-evaluate the burn signal and maybe step the ladder.
        Called from the request path; between poll intervals it is one
        clock read and a compare."""
        now = self._clock() if now is None else now
        with self._lock:
            if self._next_poll is not None and now < self._next_poll:
                return self.rung
            self._next_poll = now + self.poll_interval_s
        return self.observe(self._burn_source() or {}, now)

    def observe(self, burns: dict, now: float) -> int:
        """Feed one burn sample (``{slo_name: burn}``) at ``now`` and
        step the ladder if a dwell/hold window has elapsed. Returns the
        (possibly new) rung."""
        burn = max(burns.values(), default=0.0)
        with self._lock:
            self._last_burns = dict(burns)
            direction = None
            if burn >= self.up_threshold:
                self._low_since = None
                if self._high_since is None:
                    self._high_since = now
                if (now - self._high_since >= self.dwell_s
                        and self.rung < self.max_rung):
                    direction = "up"
            elif burn <= self.down_threshold:
                self._high_since = None
                if self._low_since is None:
                    self._low_since = now
                if (now - self._low_since >= self.hold_s
                        and self.rung > 0):
                    direction = "down"
            else:
                # Dead band: hold the rung, restart both windows.
                self._high_since = self._low_since = None
            if direction is None:
                return self.rung
            from_rung = self.rung
            self.rung = from_rung + (1 if direction == "up" else -1)
            # A fresh dwell/hold must elapse before the next step — this
            # reset is the at-most-one-step-per-window guarantee.
            self._high_since = self._low_since = now
            rung = self.rung
        cause = (max(burns, key=burns.get) if burns and direction == "up"
                 else "recovery")
        self._transition(from_rung, rung, direction, cause, burn)
        return rung

    def _transition(self, from_rung: int, rung: int, direction: str,
                    cause: str, burn: float) -> None:
        if obs.metrics_enabled():
            DEGRADE_RUNG.set(float(rung))
            DEGRADE_STEPS.inc(direction=direction)
        obs.emit("degrade_step", rung=int(rung), from_rung=int(from_rung),
                 direction=direction, cause=cause,
                 burn=round(float(burn), 4))
        if direction == "up" and rung == self.max_rung:
            # Top of the ladder: capture the episode. The incident
            # manager rate-limits per kind, so a long brownout flushes
            # one bundle, not one per poll.
            incident.trigger(
                "brownout",
                detail=f"rung {rung} ({RUNG_NAMES[rung]}): "
                       f"burn {burn:.3g} via {cause}")

    # -- serving policy ----------------------------------------------------

    def force_synopsis(self) -> bool:
        """Rung >= 1: coarse zooms answer from synopses."""
        return self.rung >= 1

    def stretch_synopsis(self) -> bool:
        """Rung >= 2: raise the synopsis zoom ceiling (coarser sources
        upsample into zooms with no natural synopsis)."""
        return self.rung >= 2

    def ttl_scale(self) -> float:
        """Rung >= 2: multiply cache TTLs so serve-stale widens."""
        return self.ttl_stretch if self.rung >= 2 else 1.0

    def inflight_limit(self, base: int | None) -> int | None:
        """Rung == max: halve the admission bound (an unbounded app
        stays unbounded — there is nothing to tighten)."""
        if base is None or self.rung < self.max_rung:
            return base
        return max(1, base // 2)

    def shed(self, key: tuple) -> bool:
        """Rung == max: deterministic fractional shed by tile key."""
        return (self.rung >= self.max_rung
                and shed_tile(self.shed_fraction, key))

    def snapshot(self) -> dict:
        """JSON-ready state for /healthz and router probes."""
        with self._lock:
            rung = self.rung
            burns = {k: round(float(v), 4)
                     for k, v in sorted(self._last_burns.items())}
        return {
            "rung": rung,
            "rung_name": RUNG_NAMES[rung],
            "max_rung": self.max_rung,
            "shed_fraction": self.shed_fraction,
            "burns": burns,
            "thresholds": {"up": self.up_threshold,
                           "down": self.down_threshold},
            "dwell_s": self.dwell_s,
            "hold_s": self.hold_s,
        }


def controller_from_flags(enabled: bool, dwell_s: float, hold_s: float,
                          ladder_spec: str = "",
                          **kwargs) -> BrownoutController | None:
    """Build the controller the CLI/fleet way: ``None`` when disabled
    (the default — brownout is opt-in), else a controller from the
    dwell/hold knobs plus a parsed ladder spec. Raises ValueError on a
    bad spec or out-of-range knob."""
    if not enabled:
        return None
    params = parse_ladder_spec(ladder_spec or "")
    params.update(kwargs)
    return BrownoutController(dwell_s=dwell_s, hold_s=hold_s, **params)
