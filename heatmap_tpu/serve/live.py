"""LiveLayer: serve a decaying HeatmapStream window as a tile layer.

Replaces the write-PNGs-to-a-directory stream output (``stream
--output live_tiles``) as the real-time path: the stream's HBM raster
is snapshotted once per micro-batch tick and indexed like any stored
level, so the HTTP frontend serves it through the exact same
store/cache/render machinery as batch layers.

Invalidation is **targeted**: each tick reports only the coarse tile
keys the batch's points actually landed in (per zoom, both formats),
and the server drops just those cache entries. Exponential decay does
drift every *other* cached tile between renders — that staleness is
bounded by the cache TTL, which is why ``cmd_serve`` forces a finite
TTL in live mode instead of flushing the whole cache per tick.

This is the only serve module that touches jax (through HeatmapStream);
importing it is gated behind ``--follow-stream``.
"""

from __future__ import annotations

import threading

import numpy as np

from heatmap_tpu.serve.store import Layer, Level
from heatmap_tpu.tilemath.mercator import project_points_np
from heatmap_tpu.tilemath.morton import morton_encode_np

#: Tile formats the HTTP layer caches under — one invalidation key per
#: (zoom, tile, format).
TILE_FORMATS = ("png", "json")


class LiveLayer(Layer):
    """A Layer whose single level is the stream's current window raster.

    ``tick(lat, lon, t)`` advances the stream one micro-batch, rebuilds
    the level from a fresh snapshot, and returns the cache keys to
    invalidate. Rebuild-on-tick (not on read) keeps the serving path
    lock-free: readers always see a complete, immutable Level; the swap
    is a single attribute store under ``_swap_lock``.
    """

    def __init__(self, stream, name: str = "live",
                 result_delta: int | None = None):
        window = stream.config.window
        delta = (min(5, int(window.zoom)) if result_delta is None
                 else int(result_delta))
        super().__init__(user=name, timespan="live", result_delta=delta)
        self.name = name
        self.stream = stream
        self.window = window
        self._swap_lock = threading.Lock()
        self._refresh()

    def _refresh(self):
        raster = self.stream.snapshot()
        rr, cc = np.nonzero(raster)
        level = Level(
            self.window.zoom,
            morton_encode_np(rr.astype(np.int64) + int(self.window.row0),
                             cc.astype(np.int64) + int(self.window.col0)),
            raster[rr, cc].astype(np.float64),
        )
        with self._swap_lock:
            self.levels = {int(self.window.zoom): level}

    def tick(self, lat, lon, t: float, weights=None) -> set:
        """One micro-batch; returns the affected cache keys:
        ``(layer_name, z, x, y, fmt)`` for every coarse tile (at every
        zoom up to the window zoom) containing a batch point."""
        self.stream.update(lat, lon, t, weights=weights)
        self._refresh()
        return self.affected_keys(lat, lon)

    def affected_keys(self, lat, lon) -> set:
        zoom = int(self.window.zoom)
        row, col, valid = project_points_np(lat, lon, zoom)
        row, col = row[valid], col[valid]
        keys: set = set()
        for z in range(zoom + 1):
            shift = zoom - z
            tiles = np.unique(np.stack([row >> shift, col >> shift], 1),
                              axis=0)
            for r, c in tiles:
                for fmt in TILE_FORMATS:
                    keys.add((self.name, z, int(c), int(r), fmt))
        return keys
