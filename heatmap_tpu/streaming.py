"""Streaming micro-batches: incremental tile rasters with time decay.

BASELINE.md config 4 — the Spark-Streaming-shaped workload the
reference job never grew (its batch path recomputes everything from
Cassandra each run, reference heatmap.py:152-158). TPU-native design:

- The live heatmap is a dense window raster **resident in HBM**; each
  micro-batch is one jitted step ``raster' = decay^dt * raster +
  bin(batch)`` with the raster buffer **donated**, so the update is
  in-place and the only host traffic is the incoming points.
- Decay is exponential with a configurable half-life, applied by
  elapsed stream time between batches (per-batch scalar, so the decay
  multiply fuses into the scatter-add's epilogue under XLA).
- Multi-chip: the same step over a row-sharded raster via the
  ``parallel`` layer — points go data-parallel, partial rasters merge
  with a psum_scatter, and the decay multiply is purely local (the
  spatial/sequence-parallel axis: each chip owns a latitude band).

Float policy: f32 accumulation is exact for counts < 2^24 per cell and
decayed streams are bounded by ``incoming_rate * half_life / ln 2``;
pass ``acc_dtype=jnp.float64`` (with x64) for extreme cell densities.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

from heatmap_tpu import obs
from heatmap_tpu.ops import Window, bin_points_window
from heatmap_tpu.parallel.mesh import DATA_AXIS


@dataclasses.dataclass(frozen=True)
class StreamConfig:
    """Static stream parameters (compiled into the step)."""

    window: Window
    half_life_s: float = 3600.0
    proj_dtype: object = jnp.float32
    acc_dtype: object = jnp.float32
    #: Pad every micro-batch to this many points (one compiled step for
    #: the whole stream; batches longer than this raise). None = compile
    #: per distinct batch length.
    pad_to: int | None = None
    #: Binning backend (ops.histogram): "xla", "pallas", or "auto"
    #: (pallas MXU kernel on TPU for blob-sized windows).
    backend: str = "auto"

    @property
    def decay_rate(self) -> float:
        """Per-second multiplicative decay exponent: 2^(-dt/half_life)."""
        return math.log(2.0) / self.half_life_s


def make_update_step(config: StreamConfig, mesh=None):
    """Build the jitted micro-batch step.

    Returns ``step(raster, lat, lon, dt_s, weights?) -> raster`` with
    the raster argument donated (in-place HBM update). With ``mesh``
    the raster is row-sharded over the mesh's ``data`` axis and points
    are consumed data-parallel (see parallel.bin_points_rowsharded).
    """
    window = config.window
    ln2_over_hl = config.decay_rate

    if mesh is None:

        def step(raster, lat, lon, dt_s, weights, valid):
            decay = jnp.exp(-ln2_over_hl * dt_s.astype(raster.dtype))
            fresh = bin_points_window(
                lat, lon, window,
                weights=weights,
                valid=valid,
                proj_dtype=config.proj_dtype,
                dtype=raster.dtype,
                backend=config.backend,
            )
            return raster * decay + fresh

        return jax.jit(step, donate_argnums=0)

    from heatmap_tpu.parallel import bin_points_rowsharded

    def step_sharded(raster, lat, lon, dt_s, weights, valid):
        decay = jnp.exp(-ln2_over_hl * dt_s.astype(raster.dtype))
        fresh = bin_points_rowsharded(
            lat, lon, window, mesh,
            weights=weights,
            valid=valid,
            proj_dtype=config.proj_dtype,
            dtype=raster.dtype,
            backend=config.backend,
        )
        return raster * decay + fresh

    return jax.jit(step_sharded, donate_argnums=0)


class HeatmapStream:
    """Stateful micro-batch driver around the jitted step.

    Batches carry stream timestamps (seconds, monotone non-decreasing);
    the state decays by the elapsed time since the previous batch, so
    replaying the same timestamped batches reproduces the same raster
    (deterministic resume — checkpoint/restore via ``state_dict`` /
    ``load_state_dict``).
    """

    def __init__(self, config: StreamConfig, mesh=None):
        self.config = config
        self._step = make_update_step(config, mesh=mesh)
        self._mesh = mesh
        h, w = config.window.shape
        raster = jnp.zeros((h, w), config.acc_dtype)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            raster = jax.device_put(
                raster, NamedSharding(mesh, P(DATA_AXIS, None))
            )
        self.raster = raster
        self.t: float | None = None
        self.n_batches = 0
        self._ndev = 1 if mesh is None else int(mesh.shape.get(DATA_AXIS, 1))
        if config.pad_to is not None and config.pad_to % self._ndev:
            raise ValueError(
                f"pad_to={config.pad_to} must divide evenly across the "
                f"mesh's {self._ndev}-way {DATA_AXIS} axis"
            )

    def update(self, lat, lon, t: float, weights=None):
        """Consume one micro-batch stamped at stream time ``t``.

        Batches are padded (masked invalid) to ``config.pad_to`` when
        set, else to a multiple of the mesh's data-axis size — sharded
        inputs must split evenly across devices.
        """
        if self.t is not None and t < self.t:
            raise ValueError(f"stream time went backwards: {t} < {self.t}")
        dt = 0.0 if self.t is None else t - self.t
        lat = np.asarray(lat)
        lon = np.asarray(lon)
        n = lat.shape[0]
        target = self.config.pad_to
        if target is not None and n > target:
            raise ValueError(f"batch of {n} points exceeds pad_to={target}")
        if target is None and self._ndev > 1:
            target = -(-n // self._ndev) * self._ndev
        valid = None
        if target is not None and target != n:
            pad = target - n
            lat = np.concatenate([lat, np.zeros(pad, lat.dtype)])
            lon = np.concatenate([lon, np.zeros(pad, lon.dtype)])
            valid = np.arange(target) < n
            if weights is not None:
                weights = np.concatenate(
                    [np.asarray(weights), np.zeros(pad, np.asarray(weights).dtype)]
                )
        self.raster = self._step(
            self.raster,
            jnp.asarray(lat),
            jnp.asarray(lon),
            jnp.asarray(dt, self.config.acc_dtype),
            None if weights is None else jnp.asarray(weights),
            None if valid is None else jnp.asarray(valid),
        )
        self.t = t
        self.n_batches += 1
        if obs.metrics_enabled():
            obs.STREAM_POINTS.inc(int(n))
            obs.STREAM_BATCHES.inc()
            obs.STREAM_TIME.set(float(t))
        return self

    def snapshot(self) -> np.ndarray:
        """Device -> host copy of the current decayed raster."""
        return np.asarray(self.raster)

    def state_dict(self) -> dict:
        return {
            "raster": self.snapshot(),
            "t": self.t,
            "n_batches": self.n_batches,
        }

    def checkpoint(self, manager, weighted: bool | None = None) -> str:
        """Atomic checkpoint via utils.checkpoint.CheckpointManager,
        numbered by batches consumed.

        ``weighted`` records the ingest semantics (value sums vs
        counts) so a resume under the other mode fails loudly instead
        of blending counted and weighted mass in one raster; None skips
        recording (library callers managing their own semantics)."""
        w = self.config.window
        meta = {"t": self.t, "n_batches": self.n_batches,
                "window": [int(w.zoom), int(w.row0), int(w.col0)]}
        if weighted is not None:
            meta["weighted"] = bool(weighted)
        return manager.save(self.n_batches, {"raster": self.snapshot()}, meta)

    def restore(self, manager, step: int | None = None,
                weighted: bool | None = None):
        """Load the latest (or a given) checkpoint into this stream.

        Validates the checkpoint's window ORIGIN, not just its shape:
        a same-shaped raster restored into a shifted window (e.g.
        --auto-bounds over a file whose extent moved) would silently
        paint the old mass at the wrong place on the map. ``weighted``
        (when given AND recorded in the checkpoint) must match the
        recorded ingest semantics — resuming a weighted stream as a
        counted one would blend value-sums and counts in one raster.
        """
        arrays, meta = manager.load(step)
        w = self.config.window
        ck_win = meta.get("window")  # absent in pre-origin checkpoints
        if ck_win is not None and list(ck_win) != [int(w.zoom),
                                                   int(w.row0),
                                                   int(w.col0)]:
            raise ValueError(
                f"checkpoint window (zoom,row0,col0)={tuple(ck_win)} != "
                f"stream window {(w.zoom, w.row0, w.col0)} — the data's "
                "bounds changed (e.g. --auto-bounds over a grown file); "
                "restart with fixed --lat/--lon flags or a fresh "
                "checkpoint dir"
            )
        ck_weighted = meta.get("weighted")
        if (weighted is not None and ck_weighted is not None
                and bool(weighted) != bool(ck_weighted)):
            raise ValueError(
                f"checkpoint was written by a "
                f"{'weighted' if ck_weighted else 'counted'} stream but "
                f"this resume is {'weighted' if weighted else 'counted'} "
                "— rerun with the matching --weighted setting or a "
                "fresh checkpoint dir"
            )
        return self.load_state_dict({
            "raster": arrays["raster"],
            "t": meta["t"],
            "n_batches": meta["n_batches"],
        })

    def load_state_dict(self, state: dict):
        raster = jnp.asarray(state["raster"], self.config.acc_dtype)
        if raster.shape != tuple(self.config.window.shape):
            raise ValueError(
                f"checkpoint raster {raster.shape} != window {self.config.window.shape}"
            )
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            raster = jax.device_put(
                raster, NamedSharding(self._mesh, P(DATA_AXIS, None))
            )
        self.raster = raster
        self.t = state["t"]
        self.n_batches = state["n_batches"]
        return self


def default_stream_hook(stream: HeatmapStream, t: float):
    """The default ``on_batch``: per-tick telemetry. No-op unless a
    metrics sink is enabled (``HeatmapStream.update`` already keeps the
    ingest counters; this adds the decay-tick view the run_stream loop
    owns). Deliberately does NOT snapshot the raster — that is a
    device->host copy per tick; pass a custom hook for that.

    .. deprecated:: The recorder now lives in
       ``heatmap_tpu.ingest.metrics.record_stream_tick`` (the unified
       continuous-ingest loop); this wrapper keeps the historical
       counter names and hook signature for existing callers.
    """
    from heatmap_tpu.ingest.metrics import record_stream_tick

    record_stream_tick(t)


def run_stream(stream: HeatmapStream, timed_batches, *, on_batch=None):
    """Drive a stream from an iterable of ``(t_seconds, batch)`` pairs,
    where ``batch`` is a columnar point batch (heatmap_tpu.io layout;
    background rows dropped like the batch path, reference
    heatmap.py:28-29). ``on_batch(stream, t)`` fires after each step;
    the default is ``default_stream_hook`` (decay-tick and ingest
    gauges, free when telemetry is off).

    .. deprecated:: This is a compat shim over
       ``heatmap_tpu.ingest.run_ticks`` — streaming ticks and journaled
       delta applies are the same pump at different cadences (ROADMAP
       "unify streaming.py with the delta engine"). New code that wants
       journaled, servable ingest should use
       ``heatmap_tpu.ingest.run_ingest``; this driver keeps the
       raster-decay workload and its synchronous cadence.
    """
    from heatmap_tpu.ingest.loop import run_ticks
    from heatmap_tpu.pipeline import load_columns

    if on_batch is None:
        on_batch = default_stream_hook

    def _tick(item, ctx):
        t, batch = item
        cols = load_columns(batch)
        stream.update(cols["latitude"], cols["longitude"], t)
        on_batch(stream, t)

    run_ticks(timed_batches, _tick)
    return stream


def decayed_oracle(window: Window, timed_points, half_life_s: float):
    """Pure-numpy reference for tests: same decay-then-add semantics.

    ``timed_points``: iterable of (t, lat_array, lon_array).
    """
    raster = np.zeros(window.shape, np.float64)
    last_t = None
    n = 1 << window.zoom
    for t, lat, lon in timed_points:
        dt = 0.0 if last_t is None else t - last_t
        raster *= 2.0 ** (-dt / half_life_s)
        phi = np.asarray(lat, np.float64) * math.pi / 180
        y = (1 - np.log(np.tan(phi) + 1 / np.cos(phi)) / math.pi) / 2
        row = np.floor(y * n) - window.row0
        col = np.floor((np.asarray(lon, np.float64) + 180.0) / 360.0 * n) - window.col0
        ok = (
            np.isfinite(row) & (row >= 0) & (row < window.height)
            & (col >= 0) & (col < window.width)
        )
        np.add.at(raster, (row[ok].astype(np.int64), col[ok].astype(np.int64)), 1.0)
        last_t = t
    return raster
