"""Write-plane metric handles on the shared obs registry.

Module-level, created once at import (the delta/metrics.py pattern):
handles survive ``registry.reset()`` between tests and self-gate on
``registry.enabled``, so call sites pay one boolean when metrics are
off.
"""

from __future__ import annotations

from heatmap_tpu import obs

_registry = obs.get_registry()

WRITEPLANE_POINTS = _registry.counter(
    "writeplane_points_total",
    "Points applied through the partitioned write plane, per range",
    labelnames=("range",))
WRITEPLANE_APPENDS = _registry.counter(
    "writeplane_appends_total",
    "Per-range sub-batch applies (status = applied|duplicate|error)",
    labelnames=("range", "status"))
WRITEPLANE_APPEND_SECONDS = _registry.histogram(
    "writeplane_append_seconds",
    "Wall-clock of one routed full-batch append across its ranges",
    buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0))
WRITEPLANE_PUBLISHES = _registry.counter(
    "writeplane_publishes_total",
    "Manifest epochs published (the cross-range visibility flips)")
WRITEPLANE_MANIFEST_EPOCH = _registry.gauge(
    "writeplane_manifest_epoch",
    "Newest manifest epoch published by this process's write plane")
WRITEPLANE_REBALANCES = _registry.counter(
    "writeplane_rebalances_total",
    "Hot-range re-splits performed (journal handoff + new range)")
