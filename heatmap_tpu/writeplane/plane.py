"""The partitioned multi-writer write plane.

``WritePlane`` shards the delta journal and delta store by the Morton
ranges ``parallel/partition.py`` plans: each range is an ordinary,
fully independent delta store (``ranges/rNNN/`` — its own journal,
apply loop, compaction, and recovery sweep), and incoming batches are
routed host-side by detail-zoom Morton code (``tilemath.mercator.
project_points_np`` + ``morton_encode_np`` — the exact projection the
cascade itself bins with, so routing and binning can never disagree).
Readers see the union through the epoch-unified manifest
(writeplane/manifest.py); cross-writer coordination is that one
pointer flip.

Correctness model (pinned in tests/test_writeplane.py):

- **Byte identity.** Every point belongs to exactly one range
  (``searchsorted`` ownership, the cascade's convention), so a
  boundary-straddling batch splits into per-range sub-batches whose
  union is the batch. Tile counts are pure sums and integer-valued
  counts are exact in f64, so merging all ranges' overlays re-sums the
  same cells a single-writer store holds — served blobs and level
  arrays come out byte-identical, retractions included (linearity).
- **Exactly-once, two layers.** Per range, ``delta.apply_batch``'s
  content-hash journal already dedups sub-batches — routing is
  deterministic for a fixed plan, so a replayed batch re-splits
  identically and each range no-ops its half. Across plan *changes*
  (rebalance moves a split, so a replay re-splits differently), the
  plane keeps a top-level **ledger**: a ``DeltaJournal`` over the
  un-split batch hash, recorded only after every routed sub-apply
  landed. A batch found in the ledger never routes at all, so the
  dedup window survives re-partitioning.
- **Crash anywhere.** Sub-applies and the ledger record are each
  atomic; a crash between them leaves a partially-applied batch whose
  replay is healed by the per-range layer (plan unchanged until the
  ledger record lands — ``rebalance`` is an explicit coordinator
  action, never implicit). Torn manifests quarantine + fall back to
  the last good epoch (writeplane/recover.py).

Rebalance is journal handoff + re-split: the hot range compacts (its
live journal folds into the base — the handoff), the base's detail
rows vote a weighted-median split (``partition.split_range_median``,
the planner's re-split move against materialized mass), and a fresh
empty range takes ownership of the right half. The parent keeps its
historical base — reads merge every range, so ownership handoff needs
no data movement — and the new manifest epoch records the new plan
plus the child's lineage (``parent``).
"""

from __future__ import annotations

import dataclasses
import os
import re
import threading
import time

import numpy as np

from heatmap_tpu import faults, obs
import importlib

from heatmap_tpu.delta import DeltaResult, apply_batch as delta_apply_batch
from heatmap_tpu.delta.compute import ColumnsSource, read_columns
from heatmap_tpu.delta.journal import DeltaJournal, batch_content_hash
from heatmap_tpu.parallel.partition import plan_partition, split_range_median
from heatmap_tpu.tilemath.mercator import project_points_np
from heatmap_tpu.tilemath.morton import morton_encode_np, morton_range_shards_np
from heatmap_tpu.writeplane import manifest as manifest_mod
from heatmap_tpu.writeplane.metrics import (
    WRITEPLANE_APPEND_SECONDS, WRITEPLANE_APPENDS, WRITEPLANE_MANIFEST_EPOCH,
    WRITEPLANE_POINTS, WRITEPLANE_PUBLISHES, WRITEPLANE_REBALANCES)

# The delta package re-exports its ``compact`` *function*, shadowing the
# submodule attribute — import the module itself by dotted name.
compact_mod = importlib.import_module("heatmap_tpu.delta.compact")

#: Ledger entries have no artifact directory — the sentinel keeps
#: ``entry_digest`` a pure identity hash (the path never exists).
LEDGER_ARTIFACT = "-"

_RANGE_RE = re.compile(r"^r(\d{3})$")


@dataclasses.dataclass(frozen=True)
class PlaneConfig:
    """Write-plane parameters (the pyramid config stays a
    BatchJobConfig, shared by every range — delta/compact.py pins it
    per range on first apply)."""

    #: Ingest pumps = initial Morton ranges (rebalance can add more).
    n_writers: int = 2
    #: Per-range journal entries kept after compaction (the per-range
    #: exactly-once window — docs/write-plane.md).
    retention: int = 2
    #: Hard floor under ``retention``: a per-range compact below it is
    #: refused, because partitioning multiplies replay exposure (every
    #: range must cover the full redelivery horizon on its own).
    retention_floor: int = 2
    #: Live deltas per range before the pump compacts it (0 = never).
    compact_every: int = 0
    #: Full-batch ledger entries retained (the cross-rebalance dedup
    #: window; size it like retention — to the redelivery horizon).
    ledger_keep: int = 64
    #: Manifest snapshot files retained after a publish (readers pinned
    #: to an older epoch fall back within this window; snapshots are
    #: tiny JSON, so keep a generous history).
    manifest_keep: int = 8
    #: Skew threshold for rebalance: hottest range mass over mean.
    balance_factor: float = 1.25
    #: Partition-plan sample seed (determinism knob).
    seed: int = 0

    def __post_init__(self):
        if self.n_writers < 1:
            raise ValueError(f"n_writers must be >= 1, got {self.n_writers}")
        if self.retention_floor < 1:
            raise ValueError("retention_floor must be >= 1, got "
                             f"{self.retention_floor}")
        if self.retention < self.retention_floor:
            raise ValueError(
                f"retention {self.retention} is below retention_floor "
                f"{self.retention_floor}: the per-range dedup window must "
                "cover the redelivery horizon (docs/write-plane.md)")
        if self.ledger_keep < 1:
            raise ValueError(f"ledger_keep must be >= 1, got "
                             f"{self.ledger_keep}")
        if self.manifest_keep < 1:
            raise ValueError(f"manifest_keep must be >= 1, got "
                             f"{self.manifest_keep}")


@dataclasses.dataclass
class PlaneAppend:
    """Outcome of one full-batch append across its routed ranges."""

    content_hash: str
    points: int
    sign: int
    duplicate: bool          #: full-batch ledger hit — nothing routed
    results: dict            #: range name -> DeltaResult (routed ranges)
    seconds: float
    affected_keys: set = dataclasses.field(default_factory=set)


def _watermark(cols) -> float | None:
    stamps = cols.get("timestamp")
    if stamps is None or not len(stamps):
        return None
    try:
        return max(float(t) for t in stamps if t is not None)
    except (TypeError, ValueError):
        return None


def _take_cols(cols: dict, idx: np.ndarray) -> dict:
    """Slice every column by row indices, preserving order and the
    ndarray-vs-list layout ColumnsSource accepts."""
    out = {}
    for k, v in cols.items():
        if isinstance(v, np.ndarray):
            out[k] = v[idx]
        else:
            out[k] = [v[i] for i in idx]
    return out


def _pad_cols(cols: dict, target: int) -> dict:
    """Pad a routed sub-batch to ``target`` rows with masked-invalid
    lanes: NaN lat/lon project invalid (tilemath.mercator), so the
    cascade drops the pad lanes exactly as ``bucketing.pad_emissions``
    drops its own — byte-neutral by the same masking contract.

    Routed sub-batch sizes vary every tick (a range owns whatever
    share of each micro-batch lands in its interval), and the
    pre-bucketing pipeline stages (projection jit, emission assembly)
    compile per distinct *point* count — without this pad an N-writer
    plane pays a fresh XLA compile on nearly every apply. Padding is a
    pure function of the sub-batch length, so a crash replay re-pads
    identically and the range journal's content hash still dedups.
    """
    n = len(cols["latitude"])
    pad = target - n
    if pad <= 0:
        return cols
    out = {}
    for k, v in cols.items():
        if isinstance(v, np.ndarray):
            fill = (np.full(pad, np.nan, np.float64)
                    if k in ("latitude", "longitude")
                    else np.zeros(pad, np.asarray(v).dtype))
            out[k] = np.concatenate([np.asarray(v), fill])
        else:
            filler = {"user_id": "x-pad", "source": "pad"}.get(k, 0)
            out[k] = list(v) + [filler] * pad
    return out


class WritePlane:
    """One write-plane root: N range stores + manifest + ledger.

    Thread-safe: per-range applies may run concurrently (pumps.py);
    plan/manifest/ledger mutations serialize on one re-entrant lock.
    """

    def __init__(self, root: str, config, plane: PlaneConfig | None = None):
        from heatmap_tpu.writeplane import recover as recover_mod

        self.root = root
        self.config = config
        self.plane = plane or PlaneConfig()
        self._lock = threading.RLock()
        os.makedirs(root, exist_ok=True)
        os.makedirs(os.path.join(root, manifest_mod.RANGES_DIRNAME),
                    exist_ok=True)
        os.makedirs(manifest_mod.ledger_dir(root), exist_ok=True)
        recover_mod.sweep_plane(root)
        self._ledger = DeltaJournal(manifest_mod.ledger_dir(root))
        self._splits: list | None = None
        self._order: list = []
        self._points: dict = {}
        self._parents: dict = {}
        self._epoch = 0
        snap = manifest_mod.read_manifest(root)
        if snap is not None:
            plan_dz = int(snap["plan"]["detail_zoom"])
            if config is not None and plan_dz != int(config.detail_zoom):
                raise ValueError(
                    f"write plane {root} was planned at detail_zoom "
                    f"{plan_dz}; refusing a config with detail_zoom "
                    f"{config.detail_zoom}")
            self._epoch = int(snap["epoch"])
            self._splits = [int(s) for s in snap["plan"]["splits"]]
            self._order = list(snap["order"])
            for name, entry in snap.get("ranges", {}).items():
                self._points[name] = int(entry.get("points", 0))
                if entry.get("parent"):
                    self._parents[name] = entry["parent"]
            # Heal a stale manifest: if the pointed epoch references a
            # pruned base/delta dir (a crash landed between a per-range
            # compact and the follow-up publish), republish from each
            # range's CURRENT — the per-range source of truth.
            if self._manifest_stale(snap):
                with self._lock:
                    self._publish_locked()

    def _manifest_stale(self, snap: dict) -> bool:
        """True when the snapshot references an artifact dir that no
        longer exists (compaction pruned it before the next publish)."""
        for name in snap.get("order", ()):
            entry = snap.get("ranges", {}).get(name, {})
            rroot = self.range_root(name)
            dirs = []
            if entry.get("base"):
                dirs.append(entry["base"])
            dirs.extend(entry.get("deltas", ()))
            for d in dirs:
                if not os.path.isdir(os.path.join(rroot, d)):
                    return True
        return False

    # -- plan / routing ----------------------------------------------------

    @property
    def planned(self) -> bool:
        return self._splits is not None

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def order(self) -> list:
        with self._lock:
            return list(self._order)

    @property
    def splits(self) -> list:
        with self._lock:
            return list(self._splits or [])

    def range_root(self, name: str) -> str:
        return manifest_mod.range_root(self.root, name)

    def _codes(self, cols):
        lat = np.asarray(cols["latitude"], np.float64)
        lon = np.asarray(cols["longitude"], np.float64)
        row, col, valid = project_points_np(lat, lon,
                                            int(self.config.detail_zoom))
        return morton_encode_np(row, col), valid

    def ensure_plan(self, cols: dict):
        """Plan the Morton ranges from the first batch's sampled codes
        (skew-resistant quantile split — parallel/partition.py), create
        the range stores, and publish manifest epoch 1. No-op once
        planned; a restart adopts the persisted plan instead."""
        if self._splits is not None:
            return
        codes, valid = self._codes(cols)
        plan = plan_partition(codes, self.plane.n_writers,
                              detail_zoom=int(self.config.detail_zoom),
                              valid=valid, seed=self.plane.seed,
                              balance_factor=self.plane.balance_factor)
        with self._lock:
            if self._splits is not None:
                return
            self._splits = [int(s) for s in plan.splits]
            self._order = [f"r{i:03d}"
                           for i in range(len(self._splits) + 1)]
            for name in self._order:
                compact_mod.init_store(self.range_root(name))
            self._publish_locked()

    def route(self, cols: dict) -> list:
        """Split a normalized column batch into (range_name, sub_cols)
        parts by detail-zoom Morton ownership. Deterministic for a
        fixed plan; row order is preserved within each part, so a
        replayed batch re-splits into byte-identical sub-batches.
        Invalid (out-of-projection) rows ride range 0 — the cascade
        drops them there exactly as a single writer would.

        The (splits, order) pair is snapshotted under the plane lock so
        a concurrent ``rebalance`` (which mutates both) can never be
        observed half-applied — routing sees either the old plan or the
        new one, whole."""
        with self._lock:
            if self._splits is None:
                raise ValueError("write plane has no partition plan yet "
                                 "(ensure_plan runs on the first append)")
            splits = np.asarray(self._splits, np.int64)
            order = tuple(self._order)
        codes, valid = self._codes(cols)
        shards = morton_range_shards_np(splits, codes)
        shards = np.where(np.asarray(valid, bool), shards, 0)
        parts = []
        for k, name in enumerate(order):
            idx = np.flatnonzero(shards == k)
            if len(idx):
                parts.append((name, _take_cols(cols, idx)))
        return parts

    # -- append ------------------------------------------------------------

    def ledger_find(self, content_hash: str):
        with self._lock:
            return self._ledger.find(content_hash)

    def record_batch(self, content_hash: str, *, points: int, sign: int,
                     watermark=None) -> dict:
        """Ledger a fully-applied batch (idempotent). Only call after
        every routed sub-apply landed — the ledger hit short-circuits
        routing, so a premature record would lose the tail ranges.

        Serialized on the plane lock: ``DeltaJournal.append`` is a
        non-atomic find → next_epoch → rename sequence, so two batches
        completing on different pump threads could otherwise claim the
        same epoch and the later rename would silently drop the
        earlier batch's hash from the exactly-once ledger (and the
        prune in ``_publish_locked`` could race an append and shrink
        the keep window by one)."""
        with self._lock:
            return self._ledger.append(content_hash=content_hash,
                                       points=points, sign=sign,
                                       artifact=LEDGER_ARTIFACT,
                                       watermark=watermark)

    def apply_range(self, name: str, cols: dict, *, sign: int = 1,
                    batch_size: int = 1 << 20) -> DeltaResult:
        """One routed sub-batch into one range store, under the
        ``writeplane.append`` fault site. Idempotent end to end (the
        range's own content-hash journal), so the retry policy is safe
        by construction."""
        rroot = self.range_root(name)
        n_real = int(len(cols["latitude"]))
        if getattr(self.config, "pad_bucketing", "exact") != "exact":
            from heatmap_tpu.pipeline import bucketing

            cols = _pad_cols(cols, bucketing.bucket_size(
                n_real, self.config.pad_bucketing,
                self.config.pad_bucket_min))

        def _apply():
            return delta_apply_batch(rroot, ColumnsSource(cols),
                                     self.config, sign=sign,
                                     batch_size=batch_size)

        try:
            res = faults.retry_call(_apply, site="writeplane.append",
                                    key=name)
        except BaseException:
            WRITEPLANE_APPENDS.inc(range=name, status="error")
            raise
        if res.points != n_real:  # report real points, not pad lanes
            res = dataclasses.replace(res, points=n_real)
        if not res.duplicate:
            with self._lock:
                self._points[name] = (self._points.get(name, 0)
                                      + n_real)
            WRITEPLANE_POINTS.inc(n_real, range=name)
        WRITEPLANE_APPENDS.inc(
            range=name, status="duplicate" if res.duplicate else "applied")
        return res

    def append_columns(self, cols: dict, *, sign: int = 1,
                       batch_size: int = 1 << 20) -> PlaneAppend:
        """Route + apply one full batch synchronously (the pump-less
        path; pumps.py parallelizes the per-range applies)."""
        if sign not in (1, -1):
            raise ValueError("sign must be +1 (insert) or -1 (retraction)")
        t0 = time.monotonic()
        self.ensure_plan(cols)
        content_hash = batch_content_hash(cols, sign=sign)
        existing = self.ledger_find(content_hash)
        n_points = int(len(cols["latitude"]))
        if existing is not None:
            seconds = time.monotonic() - t0
            obs.emit("writeplane_append", points=existing["points"],
                     ranges=0, sign=sign, duplicate=True,
                     seconds=round(seconds, 6), content_hash=content_hash)
            return PlaneAppend(content_hash=content_hash,
                               points=existing["points"], sign=sign,
                               duplicate=True, results={}, seconds=seconds)
        results = {}
        keys: set = set()
        for name, sub in self.route(cols):
            res = self.apply_range(name, sub, sign=sign,
                                   batch_size=batch_size)
            results[name] = res
            keys |= res.affected_keys
        self.record_batch(content_hash, points=n_points, sign=sign,
                          watermark=_watermark(cols))
        seconds = time.monotonic() - t0
        WRITEPLANE_APPEND_SECONDS.observe(seconds)
        obs.emit("writeplane_append", points=n_points, ranges=len(results),
                 sign=sign, duplicate=False, seconds=round(seconds, 6),
                 content_hash=content_hash)
        return PlaneAppend(content_hash=content_hash, points=n_points,
                           sign=sign, duplicate=False, results=results,
                           seconds=seconds, affected_keys=keys)

    def append(self, source, *, sign: int = 1,
               batch_size: int = 1 << 20) -> PlaneAppend:
        """Drain a source into one routed batch (read_columns
        normalizes exactly as delta.apply_batch would, so the ledger
        hash matches a single-writer run's journal hash)."""
        cols = read_columns(source, batch_size=batch_size)
        return self.append_columns(cols, sign=sign, batch_size=batch_size)

    # -- publish / compact -------------------------------------------------

    def publish(self) -> int:
        """Flip one manifest epoch: snapshot every range's CURRENT +
        live journal into an immutable manifest file and point MANIFEST
        at it (writeplane.publish fault site). This is the only
        cross-range coordination point — and the only moment new
        applies become reader-visible through a ``writeplane:`` store."""
        with self._lock:
            return self._publish_locked()

    def _publish_locked(self) -> int:
        t0 = time.monotonic()
        epoch = self._epoch + 1
        ranges = {}
        live_total = 0
        for name in self._order:
            rroot = self.range_root(name)
            cur = compact_mod.read_current(rroot)
            live = compact_mod.live_entries(rroot)
            live_total += len(live)
            entry = {"base": cur.get("base"),
                     "deltas": [e["artifact"] for e in live],
                     "applied_through": int(cur.get("applied_through", 0)),
                     "points": int(self._points.get(name, 0))}
            if self._parents.get(name):
                entry["parent"] = self._parents[name]
            ranges[name] = entry
        snap = {"schema": manifest_mod.MANIFEST_SCHEMA, "epoch": epoch,
                "plan": {"detail_zoom": int(self.config.detail_zoom),
                         "splits": [int(s) for s in self._splits or []]},
                "order": list(self._order), "ranges": ranges}
        faults.retry_call(manifest_mod.write_snapshot, self.root, snap,
                          site="writeplane.publish", key="manifest")
        self._epoch = epoch
        self._ledger.prune(applied_through=self._ledger.latest_epoch(),
                           retention=self.plane.ledger_keep)
        for old in manifest_mod.list_epochs(self.root):
            if old <= epoch - self.plane.manifest_keep:
                try:
                    os.unlink(manifest_mod.manifest_path(self.root, old))
                except OSError:
                    pass
        seconds = time.monotonic() - t0
        WRITEPLANE_PUBLISHES.inc()
        WRITEPLANE_MANIFEST_EPOCH.set(epoch)
        obs.emit("writeplane_publish", epoch=epoch,
                 ranges=len(self._order), seconds=round(seconds, 6),
                 live_deltas=live_total)
        return epoch

    def compact_range(self, name: str, *, retention: int | None = None,
                      inflight: int = 0) -> dict:
        """Per-range fold, guarded by the per-range exactly-once
        window: a retention below the plane's floor, or below the
        range's in-flight journal depth, is refused (ValueError) —
        pruning would forget hashes a pump can still replay."""
        retention = (self.plane.retention if retention is None
                     else int(retention))
        if retention < self.plane.retention_floor:
            raise ValueError(
                f"writeplane range {name}: retention {retention} is below "
                f"the per-range floor {self.plane.retention_floor} — the "
                "dedup window must cover every batch a pump can replay "
                "(docs/write-plane.md)")
        summary = compact_mod.compact(self.range_root(name),
                                      retention=retention, inflight=inflight)
        if summary.get("status") == "ok":
            # Compaction pruned dirs the current manifest epoch may
            # still reference; republish immediately so readers never
            # dwell on a snapshot with missing artifacts. (A crash in
            # the gap is healed by the staleness check at init.)
            with self._lock:
                self._publish_locked()
        return summary

    def maybe_compact(self, name: str, *, inflight: int = 0):
        """The pump's compaction policy: fold when ``compact_every``
        live deltas accumulated, unless the in-flight depth exceeds the
        retention window (deferred, never forced — the next quiet tick
        retries)."""
        every = self.plane.compact_every
        if not every:
            return None
        if inflight > self.plane.retention:
            return None  # window would not cover the queue; defer
        if len(compact_mod.live_entries(self.range_root(name))) < every:
            return None
        return self.compact_range(name, inflight=inflight)

    # -- rebalance ---------------------------------------------------------

    def _range_bounds(self, index: int) -> tuple:
        total = 1 << (2 * int(self.config.detail_zoom))
        splits = self._splits or []
        lo = int(splits[index - 1]) if index > 0 else 0
        hi = int(splits[index]) if index < len(splits) else total
        return lo, hi

    def _next_range_name(self) -> str:
        rdir = os.path.join(self.root, manifest_mod.RANGES_DIRNAME)
        nums = [int(n[1:]) for n in self._order]
        try:
            nums += [int(m.group(1)) for m in
                     (_RANGE_RE.match(n) for n in os.listdir(rdir)) if m]
        except OSError:
            pass
        return f"r{(max(nums) + 1 if nums else 0):03d}"

    def rebalance(self, *, force_range: str | None = None,
                  reason: str = "skew", inflight: int = 0) -> dict | None:
        """Hot-range re-split: journal handoff (compact folds the hot
        range's live journal into its base) + a weighted-median split
        of its materialized detail mass + a fresh empty range owning
        the right half, published as a new manifest epoch under the
        ``writeplane.rebalance`` fault site.

        Returns a summary dict, or None when no range exceeds
        ``balance_factor`` times the mean applied mass (or the hot
        range is a single-code irreducible hotspot). ``force_range``
        skips the skew check (the operator runbook's knob).

        ``inflight`` is the hot range's queued-but-unapplied batch
        depth (a pump's queue size; 0 after a drain). The handoff
        compact runs through :meth:`compact_range`, so the per-range
        retention floor and in-flight guard apply to it exactly as to
        a pump-triggered fold; a rebalance whose handoff would shrink
        the dedup window below the queue is deferred (returns None)
        rather than forced."""
        if inflight > self.plane.retention:
            return None  # handoff would prune under queued batches; defer
        with self._lock:
            if self._splits is None:
                return None
            masses = [self._points.get(n, 0) for n in self._order]
            total = sum(masses)
            if force_range is not None:
                if force_range not in self._order:
                    raise ValueError(f"unknown range {force_range!r}; "
                                     f"have {self._order}")
                hot_i = self._order.index(force_range)
            else:
                if total == 0:
                    return None
                mean = total / len(self._order)
                hot_i = int(np.argmax(masses))
                if masses[hot_i] <= self.plane.balance_factor * mean:
                    return None
            hot = self._order[hot_i]
            lo, hi = self._range_bounds(hot_i)
            t0 = time.monotonic()

            def _resplit():
                # Handoff: fold the hot range's live journal into its
                # base so the split votes on everything applied (and
                # the child starts from an empty store — the parent's
                # base keeps serving both halves' history by merge).
                # Through compact_range so the retention-floor and
                # in-flight-depth guards cover the handoff too.
                self.compact_range(hot, inflight=inflight)
                levels = compact_mod.load_overlay_levels(
                    self.range_root(hot))
                dz = int(self.config.detail_zoom)
                codes, weights = [], []
                for lvl in levels:
                    if int(lvl["zoom"]) != dz:
                        continue
                    codes.append(morton_encode_np(
                        np.asarray(lvl["row"], np.int64),
                        np.asarray(lvl["col"], np.int64)))
                    weights.append(np.abs(np.asarray(lvl["value"],
                                                     np.float64)))
                if not codes:
                    return None
                split = split_range_median(np.concatenate(codes),
                                           np.concatenate(weights), lo, hi)
                if split is None:
                    return None
                new_name = self._next_range_name()
                compact_mod.init_store(self.range_root(new_name))
                return split, new_name

            out = faults.retry_call(_resplit, site="writeplane.rebalance",
                                    key=hot)
            if out is None:
                return None
            split, new_name = out
            self._splits.insert(hot_i, int(split))
            self._order.insert(hot_i + 1, new_name)
            self._parents[new_name] = hot
            # Halve the mass estimate so the skew signal re-arms from
            # the post-split shape instead of instantly re-firing.
            half = masses[hot_i] // 2
            self._points[hot] = half
            self._points[new_name] = masses[hot_i] - half
            epoch = self._publish_locked()
            seconds = time.monotonic() - t0
            WRITEPLANE_REBALANCES.inc()
            obs.emit("writeplane_rebalance", range=hot, new_range=new_name,
                     split=int(split), reason=reason,
                     seconds=round(seconds, 6))
            return {"range": hot, "new_range": new_name,
                    "split": int(split), "epoch": epoch,
                    "reason": reason, "seconds": seconds}


def refresh_serving(result: PlaneAppend, store, cache=None) -> int:
    """Bring a live TileStore (mounted on this plane's ``writeplane:``
    spec) up to date after an append **and** publish — the targeted
    alternative to ``store.reload()``, same contract as
    ``delta.refresh_serving``: no generation bump, only the union of
    the routed ranges' affected tile keys invalidated. Returns cache
    entries dropped. (The store re-reads the manifest, so publish
    first — an unpublished apply is invisible by design.)"""
    if result.duplicate or not result.results:
        return 0
    store.refresh_layers()
    if cache is None:
        return 0
    return cache.invalidate_keys(result.affected_keys)
