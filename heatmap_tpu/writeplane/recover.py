"""Crash recovery for a write-plane root.

Extends the delta store's sweep taxonomy (delta/recover.py) one level
up: the plane's own garbage is torn or orphaned *manifests* and torn
*ledger* entries, and every range store underneath gets the ordinary
per-root sweep. Same stance throughout: quarantine (move under
``wroot/quarantine/``), never delete — an operator inspects what a
crash or a chaos storm left behind.

Taxonomy:

- ``orphan_tmp`` — staging files from a crashed snapshot/pointer flip.
- ``torn_manifest`` — a ``manifest-XXXXXX.json`` that fails to load or
  whose digest mismatches its body. Readers already skip these
  (manifest.read_manifest falls back to the last good epoch); the
  sweep moves them out and repairs the MANIFEST pointer to the newest
  valid epoch so the fallback scan never runs twice.
- ``torn_ledger`` — an unreadable/malformed/digest-mismatched
  full-batch ledger entry. Its batch simply re-ledgers on replay (the
  per-range journals still dedup the sub-batches).
- ``orphan_range`` — a ``ranges/rNNN`` store referenced by **no**
  valid manifest epoch: the residue of a crash between range creation
  and the publish that would have made it real (first plan, or a
  rebalance that never flipped). Invisible to readers and writers
  alike, so it quarantines whole.

Every surviving range root then runs ``delta.recover.sweep`` — the
per-range torn-journal/orphan-artifact/torn-synopsis sweep is
unchanged by partitioning.
"""

from __future__ import annotations

import os
import re

from heatmap_tpu.delta import recover as delta_recover
from heatmap_tpu.delta.journal import entry_digest
from heatmap_tpu.utils.checkpoint import load_checkpoint
from heatmap_tpu.writeplane import manifest as manifest_mod

_LEDGER_ENTRY_RE = re.compile(r"^ckpt-(\d+)\.npz$")
_RANGE_RE = re.compile(r"^r\d{3}$")
_REQUIRED_LEDGER_META = ("epoch", "content_hash", "artifact", "sign",
                         "points")


def _ledger_fault(root: str, path: str, name: str):
    """-> (reason, detail); reason None for a valid ledger entry."""
    try:
        _, meta = load_checkpoint(path)
    except Exception as e:  # torn npz, bad zip, bad meta JSON
        return "unreadable", repr(e)
    missing = [k for k in _REQUIRED_LEDGER_META if meta.get(k) is None]
    if missing:
        return "malformed", f"missing fields {missing}"
    m = _LEDGER_ENTRY_RE.match(name)
    if m and int(meta["epoch"]) != int(m.group(1)):
        return "malformed", (f"epoch {meta['epoch']} != filename epoch "
                             f"{m.group(1)}")
    recorded = meta.get("entry_digest")
    if recorded is not None:
        actual = entry_digest(root, content_hash=meta["content_hash"],
                              sign=meta["sign"], points=meta["points"],
                              artifact=meta["artifact"])
        if actual != recorded:
            return "digest_mismatch", (
                f"recorded {recorded[:23]}..., actual {actual[:23]}...")
    return None, None


def sweep_plane(root: str) -> dict:
    """Quarantine crash garbage under a write-plane root; returns
    ``{"quarantined": [...], "ranges": {name: per-range sweep}}``
    (both empty when the plane is clean or ``root`` does not exist)."""
    items: list = []
    out = {"quarantined": items, "ranges": {}}
    if not os.path.isdir(root):
        return out

    # Orphan staging files from a crashed snapshot/pointer flip.
    for name in sorted(os.listdir(root)):
        if name.endswith(".tmp"):
            delta_recover.quarantine_item(
                root, os.path.join(root, name), "orphan_tmp", "tmp", items)

    # Torn manifests: quarantine every epoch file that fails to load
    # clean, remember the valid ones for pointer repair + liveness.
    valid_epochs: list = []
    referenced: set = set()
    for epoch in manifest_mod.list_epochs(root):
        try:
            snap = manifest_mod.load_snapshot(root, epoch)
        except ValueError as e:
            delta_recover.quarantine_item(
                root, manifest_mod.manifest_path(root, epoch),
                "torn_manifest", "manifest", items, detail=str(e))
            continue
        valid_epochs.append(epoch)
        referenced.update(snap.get("order", ()))
        referenced.update(snap.get("ranges", {}).keys())

    # Pointer repair: MANIFEST must name a valid epoch (readers fall
    # back by scanning, but the repaired pointer makes recovery a
    # one-read operation again). No valid epoch -> no pointer.
    ptr = manifest_mod.read_pointer(root)
    if valid_epochs:
        newest = max(valid_epochs)
        if ptr not in valid_epochs:
            manifest_mod._write_json_atomic(
                root, manifest_mod.POINTER_NAME,
                {"schema": manifest_mod.MANIFEST_SCHEMA, "epoch": newest})
    elif ptr is not None or os.path.exists(
            os.path.join(root, manifest_mod.POINTER_NAME)):
        delta_recover.quarantine_item(
            root, os.path.join(root, manifest_mod.POINTER_NAME),
            "torn_manifest", "manifest", items,
            detail="pointer with no valid manifest epoch")

    # Torn ledger entries.
    ldir = manifest_mod.ledger_dir(root)
    if os.path.isdir(ldir):
        for name in sorted(os.listdir(ldir)):
            if not _LEDGER_ENTRY_RE.match(name):
                continue
            path = os.path.join(ldir, name)
            reason, detail = _ledger_fault(root, path, name)
            if reason is not None:
                delta_recover.quarantine_item(
                    root, path, reason, "torn_ledger", items, detail=detail)

    # Orphan ranges (created but never published), then the per-range
    # sweep for every surviving referenced store.
    rdir = os.path.join(root, manifest_mod.RANGES_DIRNAME)
    if os.path.isdir(rdir):
        for name in sorted(os.listdir(rdir)):
            full = os.path.join(rdir, name)
            if not (os.path.isdir(full) and _RANGE_RE.match(name)):
                continue
            if name not in referenced:
                delta_recover.quarantine_item(
                    root, full, "orphan_range", "range", items,
                    detail="referenced by no valid manifest epoch")
    for name in sorted(referenced):
        rroot = manifest_mod.range_root(root, name)
        if os.path.isdir(rroot):
            out["ranges"][name] = delta_recover.sweep(rroot)
    return out
