"""Epoch-unified manifest over per-range delta stores.

A write-plane root is a directory of ordinary delta stores (one per
Morton range, each with its own CURRENT / base-* / delta-* / journal/)
plus a top-level **manifest**: an epoch-numbered snapshot file naming,
for every range, exactly which immutable artifact dirs a reader merges
(``base`` + live ``deltas``) and the partition plan the routers used.

    wroot/
      MANIFEST               atomic JSON pointer {schema, epoch}
      manifest-XXXXXX.json   immutable epoch snapshot (digest-stamped)
      ranges/rNNN/           one delta store root per Morton range
      ledger/                full-batch dedup journal (plane.py)
      quarantine/            torn/orphan manifests (recover.py)

The flip discipline is delta/compact.py's CURRENT contract verbatim:
the snapshot file is staged ``.tmp`` + fsync + ``os.replace`` + parent
fsync, then the MANIFEST pointer flips the same way. Because per-range
artifact dirs are immutable once published (appends create new
``delta-*`` dirs; compaction publishes a new ``base-*`` and only then
prunes), a snapshot stays internally consistent forever: a reader that
loaded epoch E keeps serving one coherent cross-range overlay while
writers advance — it can never observe half of epoch E and half of
E+1. Snapshot integrity is self-checked: ``digest`` is the sha256 of
the canonical JSON minus the digest field, so a torn write is detected
on read (skipped in favor of the last good epoch) and quarantined by
the sweep (writeplane/recover.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import tempfile

from heatmap_tpu.utils.checkpoint import fsync_dir

MANIFEST_SCHEMA = "heatmap-tpu.writeplane.v1"
POINTER_NAME = "MANIFEST"
RANGES_DIRNAME = "ranges"
LEDGER_DIRNAME = "ledger"

_MANIFEST_RE = re.compile(r"^manifest-(\d{6})\.json$")


def manifest_name(epoch: int) -> str:
    return f"manifest-{int(epoch):06d}.json"


def manifest_path(root: str, epoch: int) -> str:
    return os.path.join(root, manifest_name(epoch))


def range_root(root: str, name: str) -> str:
    return os.path.join(root, RANGES_DIRNAME, name)


def ledger_dir(root: str) -> str:
    return os.path.join(root, LEDGER_DIRNAME)


def snapshot_digest(snap: dict) -> str:
    """sha256 over the canonical JSON of everything but ``digest``."""
    body = {k: v for k, v in snap.items() if k != "digest"}
    return "sha256:" + hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()).hexdigest()


def _write_json_atomic(root: str, final: str, payload: dict):
    """tmp + fsync + os.replace + parent fsync (the CURRENT contract)."""
    fd, tmp = tempfile.mkstemp(dir=root, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(root, final))
        fsync_dir(root)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_snapshot(root: str, snap: dict):
    """Publish one manifest epoch: stage + flip the snapshot file, then
    flip the MANIFEST pointer to it. Both steps are individually atomic,
    so a crash leaves either the old pointer (the new snapshot file is
    unreferenced garbage the sweep quarantines) or the new pointer with
    its snapshot complete — never a torn visible epoch. Re-running the
    whole publish is idempotent (same epoch, same bytes)."""
    epoch = int(snap["epoch"])
    snap = dict(snap)
    snap["schema"] = MANIFEST_SCHEMA
    snap["digest"] = snapshot_digest(snap)
    _write_json_atomic(root, manifest_name(epoch), snap)
    _write_json_atomic(root, POINTER_NAME,
                       {"schema": MANIFEST_SCHEMA, "epoch": epoch})


def read_pointer(root: str):
    """MANIFEST's epoch, or None when absent/unreadable."""
    try:
        with open(os.path.join(root, POINTER_NAME)) as f:
            ptr = json.load(f)
        return int(ptr["epoch"])
    except (OSError, ValueError, KeyError, TypeError):
        return None


def list_epochs(root: str) -> list[int]:
    """Epochs with a snapshot file on disk, ascending (no validation)."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for name in names:
        m = _MANIFEST_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def load_snapshot(root: str, epoch: int) -> dict:
    """One epoch's snapshot, digest-verified; raises ValueError on a
    torn/malformed/mismatched file (the sweep quarantines those)."""
    path = manifest_path(root, epoch)
    try:
        with open(path) as f:
            snap = json.load(f)
    except OSError as e:
        raise ValueError(f"manifest epoch {epoch}: unreadable "
                         f"({e!r})") from e
    except json.JSONDecodeError as e:
        raise ValueError(f"manifest epoch {epoch}: torn JSON "
                         f"({e!r})") from e
    if not isinstance(snap, dict):
        raise ValueError(f"manifest epoch {epoch}: not an object")
    if snap.get("schema") != MANIFEST_SCHEMA:
        raise ValueError(f"manifest epoch {epoch}: schema "
                         f"{snap.get('schema')!r} != {MANIFEST_SCHEMA!r}")
    if int(snap.get("epoch", -1)) != int(epoch):
        raise ValueError(f"manifest epoch {epoch}: file claims epoch "
                         f"{snap.get('epoch')!r}")
    recorded = snap.get("digest")
    if recorded != snapshot_digest(snap):
        raise ValueError(f"manifest epoch {epoch}: digest mismatch "
                         f"(recorded {str(recorded)[:23]}...)")
    return snap


def read_manifest(root: str) -> dict | None:
    """The newest *valid* snapshot: the pointer's epoch when it loads
    clean, else the newest earlier epoch that does (torn-manifest
    fallback — readers serve the last good epoch; quarantining the torn
    file is the sweep's job, never the read path's). None on a root
    with no valid snapshot (an empty plane)."""
    tried = set()
    ptr = read_pointer(root)
    if ptr is not None:
        try:
            return load_snapshot(root, ptr)
        except ValueError:
            tried.add(ptr)
    for epoch in reversed(list_epochs(root)):
        if epoch in tried:
            continue
        try:
            return load_snapshot(root, epoch)
        except ValueError:
            continue
    return None


def overlay_dirs(root: str, snap: dict) -> list[str]:
    """Artifact dirs a reader merges for this snapshot, range-ordered
    (base first, then deltas oldest-first per range). Driven entirely
    by the snapshot, never by globbing — an artifact a writer published
    after this epoch is invisible until the next manifest flip."""
    dirs = []
    for name in snap.get("order", ()):
        entry = snap.get("ranges", {}).get(name, {})
        rroot = range_root(root, name)
        if entry.get("base"):
            d = os.path.join(rroot, entry["base"])
            if os.path.isdir(d):
                dirs.append(d)
        for art in entry.get("deltas", ()):
            d = os.path.join(rroot, art)
            if os.path.isdir(d):
                dirs.append(d)
    return dirs
