"""Multi-writer ingest pumps over one write plane.

One **router** + N **pumps**, mirroring the elastic fleet's
thread-per-host drivers (parallel/elastic.py): the router drains a
source through the ingest loop's bounded producer/consumer queue
(``ingest.loop.run_ticks`` — the same back-pressure machinery, reused
verbatim), content-hashes each full micro-batch against the plane's
ledger, routes it by Morton ownership, and enqueues per-range
sub-batches into per-pump bounded queues. Each pump thread drains its
own queue: apply (``WritePlane.apply_range`` — the ``writeplane.append``
fault site + the range's own exactly-once journal), then the
``compact_every`` policy with the in-flight-depth guard.

A **coordinator** tracks per-batch completion: only when every routed
sub-apply landed is the batch recorded in the full-batch ledger, and
every ``publish_every`` finished batches (completed *or* failed) the
plane flips a manifest epoch — so a dead writer never stalls
visibility for the survivors.

Writer loss is survived, not masked: a pump whose apply raises
terminally (a killed writer, chaos ``writeplane.append@rNNN``) marks
itself dead and fast-fails its remaining queue items, so the router
never blocks on a corpse and the other ranges keep applying and
publishing. The dead range's batches are simply never ledgered;
re-running the same source after a restart heals them exactly-once —
survivors' sub-batches dedup in their range journals, the dead range
applies its missing halves, and the ledger records close
(tools/chaos_soak.py ``writer_loss`` phase pins the byte identity).
"""

from __future__ import annotations

import dataclasses
import itertools
import queue as queue_mod
import threading
import time

from heatmap_tpu.delta.compute import ColumnsSource, read_columns
from heatmap_tpu.delta.journal import batch_content_hash
from heatmap_tpu.ingest.loop import run_ticks
from heatmap_tpu.writeplane.plane import WritePlane, _watermark

_STOP = object()


@dataclasses.dataclass
class PumpStats:
    """One pump's (range's) view of the run."""

    applied: int = 0      #: sub-batches applied (new epochs)
    duplicates: int = 0   #: sub-batches the range journal deduped
    points: int = 0
    compactions: int = 0
    errors: int = 0
    dead: bool = False
    error: str | None = None


@dataclasses.dataclass
class PlaneStats:
    """The coordinator's view of one pumped run."""

    batches: int = 0      #: full batches the router saw
    completed: int = 0    #: fully applied + ledger-recorded
    duplicates: int = 0   #: full-batch ledger hits (never routed)
    failed: int = 0       #: >= 1 sub-apply failed (not ledgered)
    points: int = 0       #: points in completed batches
    publishes: int = 0
    publish_errors: int = 0
    epoch: int = 0        #: newest manifest epoch published
    seconds: float = 0.0
    lags_s: list = dataclasses.field(default_factory=list)
    pumps: dict = dataclasses.field(default_factory=dict)


class PlanePumps:
    """Router + per-range pump threads + completion coordinator."""

    def __init__(self, plane: WritePlane, *, queue_depth: int = 4,
                 publish_every: int = 1):
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        if publish_every < 1:
            raise ValueError(
                f"publish_every must be >= 1, got {publish_every}")
        self.plane = plane
        self.queue_depth = queue_depth
        self.publish_every = publish_every
        self.stats = PlaneStats()
        self._queues: dict = {}
        self._threads: dict = {}
        self._mu = threading.Lock()
        self._outstanding: dict = {}
        self._pending_lag: list = []
        self._finished_since_publish = 0
        self._dirty = False  # applies since the last manifest flip

    # -- pumps -------------------------------------------------------------

    def _ensure_pumps(self):
        for name in self.plane.order:
            if name not in self._queues:
                q: queue_mod.Queue = queue_mod.Queue(
                    maxsize=self.queue_depth)
                self._queues[name] = q
                self.stats.pumps[name] = PumpStats()
                t = threading.Thread(target=self._pump, args=(name,),
                                     name=f"writeplane-pump-{name}",
                                     daemon=True)
                self._threads[name] = t
                t.start()

    def _pump(self, name: str):
        q = self._queues[name]
        ps = self.stats.pumps[name]
        while True:
            item = q.get()
            try:
                if item is _STOP:
                    return
                try:
                    self._pump_one(name, q, ps, *item)
                except BaseException as e:  # noqa: BLE001 — keep the loop
                    # _pump_one already routes apply failures through
                    # the writer-loss path; anything escaping it is a
                    # coordinator/bookkeeping failure. If it killed the
                    # thread, the router's bounded queue for this range
                    # would fill and q.put would block forever — so
                    # reuse the writer-loss path: mark the pump dead
                    # (subsequent items fast-fail) and best-effort fail
                    # the part so the batch resolves instead of
                    # dangling in _outstanding.
                    ps.errors += 1
                    ps.dead = True
                    ps.error = repr(e)
                    try:
                        self._part_done(item[0], ok=False)
                    except BaseException:  # noqa: BLE001 — stay alive
                        pass
            finally:
                q.task_done()

    def _pump_one(self, name: str, q, ps: PumpStats, seq, sub, sign):
        if ps.dead:
            # Fast-fail so the router never blocks on a corpse.
            self._part_done(seq, ok=False)
            return
        try:
            res = self.plane.apply_range(name, sub, sign=sign)
        except BaseException as e:  # noqa: BLE001 — writer loss
            ps.errors += 1
            ps.dead = True
            ps.error = repr(e)
            self._part_done(seq, ok=False)
            return
        if res.duplicate:
            ps.duplicates += 1
        else:
            ps.applied += 1
            ps.points += res.points
        self._part_done(seq, ok=True)
        try:
            if self.plane.maybe_compact(
                    name, inflight=q.qsize()) is not None:
                ps.compactions += 1
        except Exception as e:  # noqa: BLE001 — defer, don't die
            ps.errors += 1
            ps.error = repr(e)

    # -- coordinator -------------------------------------------------------

    def _part_done(self, seq: int, *, ok: bool):
        with self._mu:
            # .get, not []: a batch can already be resolved when the
            # pump's failure handler re-fails a part (double-completion
            # must be a no-op, never a KeyError that kills the thread).
            ent = self._outstanding.get(seq)
            if ent is None:
                return
            ent["left"] -= 1
            if not ok:
                ent["failed"] = True
            if ent["left"] > 0:
                return
            del self._outstanding[seq]
        if ent["failed"]:
            with self._mu:
                self.stats.failed += 1
        else:
            # The commit point: every routed sub-apply landed, so the
            # full-batch hash enters the dedup ledger (atomic append).
            try:
                self.plane.record_batch(ent["hash"], points=ent["points"],
                                        sign=ent["sign"],
                                        watermark=ent["watermark"])
                with self._mu:
                    self.stats.completed += 1
                    self.stats.points += ent["points"]
                    self._pending_lag.append(ent["enqueued"])
            except Exception:  # noqa: BLE001 — replay re-ledgers it
                with self._mu:
                    self.stats.failed += 1
        self._finished_one()

    def _finished_one(self):
        with self._mu:
            self._dirty = True
            self._finished_since_publish += 1
            if self._finished_since_publish < self.publish_every:
                return
            self._finished_since_publish = 0
        self._publish()

    def _publish(self):
        with self._mu:
            if not self._dirty:
                return
            self._dirty = False
        try:
            epoch = self.plane.publish()
        except Exception:  # noqa: BLE001 — next cadence supersedes it
            with self._mu:
                self.stats.publish_errors += 1
                self._dirty = True
            return
        now = time.monotonic()
        with self._mu:
            self.stats.publishes += 1
            self.stats.epoch = epoch
            lags, self._pending_lag = self._pending_lag, []
        self.stats.lags_s.extend(now - t for t in lags)

    # -- run ---------------------------------------------------------------

    def run(self, source, *, micro_batch: int = 1 << 14, sign: int = 1,
            max_ticks: int | None = None,
            router_queue_depth: int | None = None) -> PlaneStats:
        """Drain ``source`` through the plane; blocks until every pump
        finished and a final manifest epoch covers everything applied.
        Safe to re-run with the same source after a crash or writer
        loss: the two dedup layers make the replay exactly-once."""
        t0 = time.monotonic()
        seq_counter = itertools.count()

        def _route_tick(batch, ctx):
            cols = read_columns(ColumnsSource(batch))
            self.plane.ensure_plan(cols)
            self._ensure_pumps()
            h = batch_content_hash(cols, sign=sign)
            with self._mu:
                self.stats.batches += 1
            if self.plane.ledger_find(h) is not None:
                with self._mu:
                    self.stats.duplicates += 1
                return
            parts = self.plane.route(cols)
            if not parts:  # empty batch: nothing to route, just ledger
                self.plane.record_batch(h, points=len(cols["latitude"]),
                                        sign=sign,
                                        watermark=_watermark(cols))
                with self._mu:
                    self.stats.completed += 1
                self._finished_one()
                return
            seq = next(seq_counter)
            with self._mu:
                self._outstanding[seq] = {
                    "left": len(parts), "failed": False, "hash": h,
                    "points": int(len(cols["latitude"])), "sign": sign,
                    "watermark": _watermark(cols),
                    "enqueued": ctx.enqueued_at}
            for name, sub in parts:
                self._queues[name].put((seq, sub, sign))

        items = source.batches(micro_batch)
        if max_ticks is not None:
            items = itertools.islice(items, max_ticks)
        try:
            run_ticks(items, _route_tick, queue_depth=router_queue_depth,
                      name="writeplane-router")
        finally:
            for q in self._queues.values():
                q.put(_STOP)
            for t in self._threads.values():
                t.join()
        self._publish()
        self.stats.seconds = time.monotonic() - t0
        return self.stats


def run_plane_ingest(plane: WritePlane, source, *,
                     micro_batch: int = 1 << 14, sign: int = 1,
                     queue_depth: int = 4, publish_every: int = 1,
                     max_ticks: int | None = None,
                     router_queue_depth: int | None = None) -> PlaneStats:
    """One pumped run over a source (the CLI/bench entry)."""
    pumps = PlanePumps(plane, queue_depth=queue_depth,
                       publish_every=publish_every)
    return pumps.run(source, micro_batch=micro_batch, sign=sign,
                     max_ticks=max_ticks,
                     router_queue_depth=router_queue_depth)
