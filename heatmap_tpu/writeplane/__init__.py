"""Partitioned multi-writer write plane.

Shards the delta journal and delta store by planned Morton ranges so N
ingest pumps append, apply, and compact independently, unified for
readers by an epoch-numbered manifest whose flip is the only
cross-writer coordination (ROADMAP "production write scale"). See
plane.py for the correctness model (byte identity to a single writer,
two-layer exactly-once), manifest.py for the epoch/flip discipline,
pumps.py for the thread drivers, recover.py for the plane-level sweep,
and docs/write-plane.md for the operator view.
"""

from heatmap_tpu.writeplane.manifest import (ledger_dir, load_snapshot,
                                             overlay_dirs, read_manifest,
                                             read_pointer, range_root,
                                             write_snapshot)
from heatmap_tpu.writeplane.plane import (PlaneAppend, PlaneConfig,
                                          WritePlane, refresh_serving)
from heatmap_tpu.writeplane.pumps import (PlanePumps, PlaneStats, PumpStats,
                                          run_plane_ingest)
from heatmap_tpu.writeplane.recover import sweep_plane

__all__ = [
    "PlaneAppend", "PlaneConfig", "PlanePumps", "PlaneStats", "PumpStats",
    "WritePlane", "ledger_dir", "load_snapshot", "overlay_dirs",
    "read_manifest", "read_pointer", "range_root", "refresh_serving",
    "run_plane_ingest", "sweep_plane", "write_snapshot",
]
