"""Crash-recovery sweep for the delta store.

The store's write paths are atomic (save_checkpoint entries, tmp+rename
artifact publishes, the CURRENT pointer flip), so a crash can only
leave *garbage*, never a half-applied state the read path would serve:
orphan ``*.tmp`` staging files/dirs, a journal entry torn mid-write by
a power cut that beat the fsync, or an artifact dir whose journal
append never landed. This sweep finds all of it and moves it into
``root/quarantine/`` — quarantine, not delete, so an operator can
inspect what a chaotic run left behind — emitting one ``quarantine``
obs event per item.

What gets quarantined:

- any ``*.tmp`` entry in the root or the journal dir (crashed staging);
- journal entries that fail to load (torn npz), are missing required
  meta fields, disagree with their filename epoch, or whose
  ``entry_digest`` no longer matches the digest recomputed over the
  meta identity + artifact bytes (tampered content hash, torn or
  swapped artifact). Entries predating the digest field are legacy and
  skip digest verification;
- ``delta-XXXXXX`` dirs no surviving journal entry references (a
  crashed apply; also freed when their entry was quarantined — the
  next submit of that batch re-journals under a fresh epoch and
  re-applies cleanly, exactly once);
- ``base-XXXXXX`` dirs other than CURRENT's base (a compaction that
  crashed between publishing the new base and flipping the pointer, or
  between flipping and pruning);
- torn or schema-invalid ``synopsis-z*.npz`` artifacts inside CURRENT's
  base (and their orphan ``.tmp`` staging files). Serving already skips
  unreadable synopses — exact levels answer instead — so this step only
  makes the corruption visible and stops every reload from re-reading a
  bad file;
- torn or schema-invalid ``integral-z*.npz`` artifacts inside CURRENT's
  base, same contract (reason ``torn_integral``): /query falls through
  to the exact rows, so quarantining only surfaces the corruption;
- torn ``tilefs-z*.bin`` zero-copy mirrors inside CURRENT's base, same
  contract (reason ``torn_tilefs``, heatmap_tpu.tilefs): the store
  falls back to the exact npz level for that zoom, so quarantining
  costs mmap page sharing, never bytes.

Digest verification re-hashes artifact bytes, so results are memoised
per entry file identity (path, size, mtime_ns) — journaled entries and
their artifacts are immutable by contract, making entry-file identity a
sound cache key. ``clear_verified_cache`` resets it (tests).

Runs at ``init_store`` (the head of every apply) and at the top of
``compact``; the serve tier never sweeps — it is read-only and handles
store corruption by degrading instead (docs/robustness.md).

Quarantine growth is bounded, not infinite: every sweep refreshes the
``quarantine_bytes`` gauge, and ``prune_quarantine`` (called after each
successful compaction under the store's ``--retention`` knob) deletes
the oldest entries beyond the retention count — never an entry younger
than the minimum age, so an operator always gets a full
investigation window for recent incidents.
"""

from __future__ import annotations

import os
import re
import shutil

from heatmap_tpu.delta.journal import entry_digest
from heatmap_tpu.utils.checkpoint import load_checkpoint

QUARANTINE_DIRNAME = "quarantine"

_ENTRY_RE = re.compile(r"^ckpt-(\d+)\.npz$")
_DELTA_RE = re.compile(r"^delta-\d{6}$")
_BASE_RE = re.compile(r"^base-\d{6}$")

_REQUIRED_META = ("epoch", "content_hash", "artifact", "sign", "points")

# (entry abspath, size, mtime_ns) -> True for digest-verified entries.
_VERIFIED: dict = {}


def clear_verified_cache():
    _VERIFIED.clear()


def _quarantine(root: str, path: str, reason: str, kind: str,
                items: list, detail: str | None = None):
    from heatmap_tpu import obs

    qdir = os.path.join(root, QUARANTINE_DIRNAME)
    os.makedirs(qdir, exist_ok=True)
    base = os.path.basename(path.rstrip(os.sep))
    dest = os.path.join(qdir, base)
    n = 0
    while os.path.exists(dest):
        n += 1
        dest = os.path.join(qdir, f"{base}.{n}")
    try:
        shutil.move(path, dest)
    except FileNotFoundError:
        return  # concurrently removed — nothing left to quarantine
    rel = os.path.relpath(path, root)
    items.append({"path": rel, "reason": reason, "kind": kind})
    fields = {"detail": detail} if detail else {}
    obs.emit("quarantine", root=root, path=rel, reason=reason, kind=kind,
             **fields)


def quarantine_item(root: str, path: str, reason: str, kind: str,
                    items: list, detail: str | None = None):
    """Public quarantine move: relocate ``path`` under
    ``root/quarantine/`` (never delete), record it in ``items`` and as
    a ``quarantine`` event. The write plane's sweep
    (writeplane/recover.py) reuses this for torn/orphan manifests and
    ledger entries so every quarantine in the system shares one
    discipline and one event shape."""
    _quarantine(root, path, reason, kind, items, detail)


def _entry_fault(root: str, name: str, verify: bool):
    """-> (meta, reason, detail): reason is None for a valid entry."""
    path = os.path.join(root, "journal", name)
    try:
        st = os.stat(path)
        cache_key = (os.path.abspath(path), st.st_size, st.st_mtime_ns)
    except OSError:
        return None, None, None  # vanished concurrently
    if cache_key in _VERIFIED:
        # Cached metas are not kept; reload (cheap — digest is the
        # expensive part and that is what the cache skips).
        verify = False
    try:
        _, meta = load_checkpoint(path)
    except Exception as e:  # torn npz, bad zip, bad meta JSON
        return None, "unreadable", repr(e)
    missing = [k for k in _REQUIRED_META if meta.get(k) is None]
    if missing:
        return meta, "malformed", f"missing fields {missing}"
    m = _ENTRY_RE.match(name)
    if m and int(meta["epoch"]) != int(m.group(1)):
        return meta, "malformed", (
            f"epoch {meta['epoch']} != filename epoch {m.group(1)}")
    recorded = meta.get("entry_digest")
    if verify and recorded is not None:
        actual = entry_digest(root, content_hash=meta["content_hash"],
                              sign=meta["sign"], points=meta["points"],
                              artifact=meta["artifact"])
        if actual != recorded:
            return meta, "digest_mismatch", (
                f"recorded {recorded[:23]}..., actual {actual[:23]}...")
        _VERIFIED[cache_key] = True
    return meta, None, None


def quarantine_bytes(root: str) -> int:
    """Total bytes under ``root/quarantine/`` (0 when absent); also
    refreshes the ``quarantine_bytes`` gauge."""
    from heatmap_tpu.delta.metrics import QUARANTINE_BYTES

    qdir = os.path.join(root, QUARANTINE_DIRNAME)
    total = 0
    for dirpath, _dirs, files in os.walk(qdir):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(dirpath, name))
            except OSError:
                continue  # pruned/moved concurrently
    QUARANTINE_BYTES.set(total)
    return total


def prune_quarantine(root: str, *, keep: int, min_age_s: float = 0.0,
                     now: float | None = None) -> dict:
    """Bound ``root/quarantine/`` growth: delete the oldest entries
    beyond the newest ``keep``, but NEVER an entry younger than
    ``min_age_s`` — recent quarantines are exactly the ones an operator
    investigating a live incident still needs, so age wins over count.

    The count cap rides the delta store's existing ``--retention``
    knob (delta/compact.py calls this after every successful
    compaction). Returns ``{"pruned": [names], "kept": n, "bytes":
    remaining}`` and refreshes the ``quarantine_bytes`` gauge.
    """
    import time as _time

    from heatmap_tpu import obs

    if keep < 0:
        raise ValueError("keep must be >= 0")
    if now is None:
        now = _time.time()
    qdir = os.path.join(root, QUARANTINE_DIRNAME)
    pruned: list = []
    if os.path.isdir(qdir):
        entries = []
        for name in os.listdir(qdir):
            full = os.path.join(qdir, name)
            try:
                entries.append((os.path.getmtime(full), name, full))
            except OSError:
                continue
        entries.sort(reverse=True)  # newest first
        for mtime, name, full in entries[keep:]:
            if now - mtime < min_age_s:
                continue
            if os.path.isdir(full):
                shutil.rmtree(full, ignore_errors=True)
            else:
                try:
                    os.remove(full)
                except OSError:
                    continue
            pruned.append(name)
            obs.emit("quarantine", root=root,
                     path=os.path.join(QUARANTINE_DIRNAME, name),
                     reason="pruned", kind="prune",
                     detail=f"beyond retention keep={keep}")
    remaining = quarantine_bytes(root)
    kept = (len([n for n in os.listdir(qdir)])
            if os.path.isdir(qdir) else 0)
    return {"pruned": pruned, "kept": kept, "bytes": remaining}


def sweep(root: str, *, verify: bool = True) -> dict:
    """Quarantine crash garbage under ``root``; see module docstring.

    Returns ``{"quarantined": [{"path", "reason", "kind"}, ...]}``
    (empty list when the store is clean or ``root`` does not exist).
    """
    from heatmap_tpu.delta.compact import journal_dir, read_current

    items: list = []
    if not os.path.isdir(root):
        return {"quarantined": items}

    # 1. Orphan *.tmp staging entries (root + journal dir).
    for d in (root, journal_dir(root)):
        if not os.path.isdir(d):
            continue
        for name in sorted(os.listdir(d)):
            if name.endswith(".tmp"):
                _quarantine(root, os.path.join(d, name), "orphan_tmp",
                            "tmp", items)

    # 2. Torn / malformed / digest-mismatched journal entries.
    jdir = journal_dir(root)
    survivors: list = []
    if os.path.isdir(jdir):
        for name in sorted(os.listdir(jdir)):
            if not _ENTRY_RE.match(name):
                continue
            meta, reason, detail = _entry_fault(root, name, verify)
            if reason is not None:
                _quarantine(root, os.path.join(jdir, name), reason,
                            "journal_entry", items, detail)
            elif meta is not None:
                survivors.append(meta)

    # 3. Delta artifacts no surviving entry references (crashed applies
    #    and the artifacts of entries quarantined above).
    referenced = {e["artifact"] for e in survivors}
    cur = read_current(root)
    for name in sorted(os.listdir(root)):
        full = os.path.join(root, name)
        if _DELTA_RE.match(name) and os.path.isdir(full):
            if name not in referenced:
                _quarantine(root, full, "orphan_artifact",
                            "delta_artifact", items)
        elif _BASE_RE.match(name) and os.path.isdir(full):
            # 4. Bases CURRENT does not point at (crashed compaction).
            if name != cur.get("base"):
                _quarantine(root, full, "orphan_base", "base", items)

    # 5. Torn synopsis / integral artifacts inside CURRENT's base.
    base = cur.get("base")
    bdir = os.path.join(root, base) if base else None
    if bdir and os.path.isdir(bdir):
        from heatmap_tpu.analytics.integral import verify_integral
        from heatmap_tpu.synopsis.build import verify_synopsis

        for name in sorted(os.listdir(bdir)):
            full = os.path.join(bdir, name)
            if name.startswith("synopsis-") and name.endswith(".tmp"):
                _quarantine(root, full, "orphan_tmp", "synopsis", items)
            elif name.startswith("synopsis-z") and name.endswith(".npz"):
                detail = verify_synopsis(full)
                if detail is not None:
                    _quarantine(root, full, "torn_synopsis", "synopsis",
                                items, detail)
            elif name.startswith("integral-") and name.endswith(".tmp"):
                _quarantine(root, full, "orphan_tmp", "integral", items)
            elif name.startswith("integral-z") and name.endswith(".npz"):
                detail = verify_integral(full)
                if detail is not None:
                    _quarantine(root, full, "torn_integral", "integral",
                                items, detail)
            elif name.startswith("tilefs-") and name.endswith(".tmp"):
                _quarantine(root, full, "orphan_tmp", "tilefs", items)
            elif name.startswith("tilefs-z") and name.endswith(".bin"):
                from heatmap_tpu.tilefs import verify_tilefs

                detail = verify_tilefs(full)
                if detail is not None:
                    # Same contract as synopsis/integral: serving falls
                    # back to the exact npz level for that zoom, so
                    # quarantining a torn mirror costs mmap sharing,
                    # never correctness.
                    _quarantine(root, full, "torn_tilefs", "tilefs",
                                items, detail)

    # 6. Temporal buckets inside CURRENT's base (heatmap_tpu.temporal):
    #    torn buckets quarantine; folds over a quarantined bucket raise
    #    TornBucketError and the serve tier answers stale-if-error,
    #    while the all-time path — which never reads buckets — is
    #    untouched.
    if bdir and os.path.isdir(bdir):
        _sweep_buckets(root, bdir, items)

    quarantine_bytes(root)  # refresh the growth gauge every sweep
    return {"quarantined": items}


def _sweep_buckets(root: str, bdir: str, items: list):
    """Verify the base's TEMPORAL.json manifest against its bucket
    dirs: a bucket whose recomputed digest mismatches the manifest
    (torn write, tampered levels) is quarantined, as is any bucket dir
    the manifest does not list (a crashed pass's stray). Digest
    results are memoised per (dir, recorded digest) — published
    buckets are immutable by contract, same stance as journal entry
    verification."""
    from heatmap_tpu.temporal import buckets as tb

    subdir = os.path.join(bdir, tb.BUCKETS_DIRNAME)
    manifest = tb.read_manifest(bdir)
    if manifest is None:
        mpath = os.path.join(bdir, tb.MANIFEST_NAME)
        if os.path.isdir(subdir):
            if os.path.exists(mpath):
                # Unreadable manifest over existing buckets: temporal
                # serving for this base is gone either way; make the
                # corruption visible instead of re-parsing every read.
                _quarantine(root, mpath, "torn_manifest",
                            "temporal_manifest", items)
            for name in sorted(os.listdir(subdir)):
                _quarantine(root, os.path.join(subdir, name),
                            "orphan_bucket", "temporal_bucket", items)
        return
    listed = {}
    for b in manifest.get("buckets") or []:
        listed[b["name"]] = b.get("digest")
    if manifest.get("none"):
        listed[tb.NONE_NAME] = manifest["none"].get("digest")
    present = sorted(os.listdir(subdir)) if os.path.isdir(subdir) else []
    for name in present:
        full = os.path.join(subdir, name)
        recorded = listed.get(name)
        if recorded is None:
            _quarantine(root, full, "orphan_bucket", "temporal_bucket",
                        items)
            continue
        cache_key = (os.path.abspath(full), recorded)
        if cache_key in _VERIFIED:
            continue
        actual = tb.bucket_digest(full)
        if actual != recorded:
            _quarantine(root, full, "torn_bucket", "temporal_bucket",
                        items,
                        f"recorded {recorded[:23]}..., "
                        f"actual {actual[:23]}...")
        else:
            _VERIFIED[cache_key] = True
