"""Delta-engine metric handles on the shared obs registry.

Module-level, created once at import (the serve/cache.py pattern):
handles survive ``registry.reset()`` between tests and self-gate on
``registry.enabled``, so call sites pay one boolean when metrics are
off.
"""

from __future__ import annotations

from heatmap_tpu import obs

_registry = obs.get_registry()

DELTA_POINTS = _registry.counter(
    "delta_points_total", "Points ingested by incremental delta applies",
    labelnames=("kind",))  # kind = insert | retract
DELTA_APPLY_SECONDS = _registry.histogram(
    "delta_apply_seconds",
    "Wall-clock of one journaled delta apply (hash + cascade + journal)",
    buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0))
COMPACTION_SECONDS = _registry.histogram(
    "compaction_seconds",
    "Wall-clock of folding the live delta stack into a new base",
    buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0))
QUARANTINE_BYTES = _registry.gauge(
    "quarantine_bytes",
    "Bytes held in the most recently swept store's quarantine/ dir")
