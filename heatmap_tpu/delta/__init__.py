"""Incremental update engine: journaled deltas over an additive pyramid.

The reference job recomputes all 16 levels from source on every run
(reference heatmap.py:152-158); because tile counts are pure sums, the
pyramid is an additively mergeable sketch, so new points only need to
touch the tiles they land in. This package turns the one-shot batch
job into a journaled, compacting pipeline:

- ``journal.py``  — content-hashed, epoch-numbered ingest journal
  (idempotent re-submits, signed entries for retractions).
- ``compute.py``  — a delta artifact is the ordinary cascade run over
  just the new points, in the columnar level format io/merge.py
  already merges.
- ``compact.py``  — base + delta stack overlaid on read; compaction
  folds deltas into a new base behind an atomic pointer flip and
  prunes behind a retention window.

``apply_batch`` is the ingest entry; ``refresh_serving`` brings a live
``serve.TileStore``/``TileCache`` up to date by rebuilding the overlay
index without a generation bump and invalidating only the affected
tile keys (the serve/live.py mechanism) — untouched tiles keep their
cache entries because an additive delta cannot change their bytes.

Correctness anchor (pinned in tests/test_delta.py): base ⊕ deltas is
byte-identical — at the served-blob level — to a full recompute over
the union of surviving points, before and after compaction.
"""

from __future__ import annotations

import dataclasses
import os
import time

from heatmap_tpu import obs
from heatmap_tpu.obs import tracing
from heatmap_tpu.delta import compact as compact_mod
from heatmap_tpu.delta.compact import (check_config, compact, init_store,
                                       live_entries, load_overlay_levels,
                                       overlay_dirs, read_current)
from heatmap_tpu.delta.compute import (ColumnsSource, affected_tile_keys,
                                       compute_delta, read_columns)
from heatmap_tpu.delta.journal import (DeltaJournal, batch_content_hash,
                                       entry_digest)
from heatmap_tpu.delta.metrics import (COMPACTION_SECONDS,
                                       DELTA_APPLY_SECONDS, DELTA_POINTS)
from heatmap_tpu.delta.recover import sweep
from heatmap_tpu.io.sinks import LevelArraysSink

# retract imports back into this package lazily, so this import must
# stay below the names it uses (apply_batch is defined further down —
# the lazy function-body import in retract.py resolves it at call
# time, not here).
from heatmap_tpu.delta.retract import parse_where, retract_predicate


@dataclasses.dataclass
class DeltaResult:
    """Outcome of one apply_batch call."""

    epoch: int
    points: int
    sign: int
    duplicate: bool
    artifact: str | None
    rows: int
    seconds: float
    affected_keys: set = dataclasses.field(default_factory=set)


def _watermark(cols) -> float | None:
    stamps = cols.get("timestamp")
    if stamps is None or not len(stamps):
        return None
    try:
        return max(float(t) for t in stamps if t is not None)
    except (TypeError, ValueError):
        return None


#: apply_batch sentinel: derive the watermark from the batch's own
#: timestamps (the default). Retraction passes an explicit override so
#: a counter-batch lands in the SAME temporal bucket as the entry it
#: cancels (heatmap_tpu.temporal) instead of at its submission time.
_AUTO_WATERMARK = object()


def apply_batch(root: str, source, config, *, sign: int = 1,
                batch_size: int = 1 << 20,
                watermark=_AUTO_WATERMARK) -> DeltaResult:
    """Journal + compute one incremental batch against a delta store.

    Idempotent: a batch whose content hash is already journaled is a
    no-op (no new epoch, no artifact written, no bytes changed).
    ``sign=-1`` retracts the batch's points — an exact correction by
    linearity (the artifact carries negated counts).
    """
    if sign not in (1, -1):
        raise ValueError("sign must be +1 (insert) or -1 (retraction)")
    # Root-on-demand: under a CLI `update` root this nests; a direct
    # apply_batch call with tracing on becomes its own connected tree.
    tsp = tracing.begin_span("delta.apply", {"sign": sign})
    try:
        t0 = time.monotonic()
        init_store(root)
        cols = read_columns(source, batch_size=batch_size)
        salt = (None if watermark is _AUTO_WATERMARK
                else f"watermark={watermark}")
        content_hash = batch_content_hash(cols, sign=sign, salt=salt)
        journal = DeltaJournal(compact_mod.journal_dir(root))
        existing = journal.find(content_hash)
        if existing is not None:
            seconds = time.monotonic() - t0
            obs.emit("delta_applied", epoch=existing["epoch"],
                     points=existing["points"], sign=existing["sign"],
                     seconds=round(seconds, 6), duplicate=True,
                     content_hash=content_hash)
            return DeltaResult(epoch=existing["epoch"],
                               points=existing["points"],
                               sign=existing["sign"], duplicate=True,
                               artifact=existing.get("artifact"), rows=0,
                               seconds=seconds)
        check_config(root, config)
        n_points = int(len(cols["latitude"]))
        epoch = journal.next_epoch()
        artifact = f"delta-{epoch:06d}"
        out_dir = os.path.join(root, artifact)
        stats = compute_delta(ColumnsSource(cols), out_dir, config,
                              sign=sign, batch_size=batch_size)
        rows = int(stats.get("rows", 0)) if isinstance(stats, dict) else 0
        if watermark is _AUTO_WATERMARK:
            watermark = _watermark(cols)
        journal.append(content_hash=content_hash, points=n_points,
                       sign=sign, artifact=artifact, watermark=watermark,
                       cols=cols)
        keys = affected_tile_keys(LevelArraysSink.load(out_dir))
        seconds = time.monotonic() - t0
        DELTA_POINTS.inc(n_points, kind="insert" if sign > 0 else "retract")
        DELTA_APPLY_SECONDS.observe(seconds)
        obs.emit("delta_applied", epoch=epoch, points=n_points, sign=sign,
                 seconds=round(seconds, 6), content_hash=content_hash,
                 artifact=artifact, rows=rows, watermark=watermark,
                 keys_invalidated=len(keys))
        return DeltaResult(epoch=epoch, points=n_points, sign=sign,
                           duplicate=False, artifact=artifact, rows=rows,
                           seconds=seconds, affected_keys=keys)
    finally:
        tracing.end_span(tsp)


def refresh_serving(result: DeltaResult, store, cache=None) -> int:
    """Bring a live TileStore (mounted on this store's ``delta:`` spec)
    up to date after ``apply_batch`` — the targeted alternative to
    ``store.reload()``: the overlay index is rebuilt WITHOUT a
    generation bump (an additive delta cannot change untouched tiles'
    bytes, so their cache entries stay valid) and only the affected
    tile keys are invalidated. Sliding-window fold variants of the
    same keys (heatmap_tpu.temporal; the cache tracks which window
    params it has served) ride the same targeted pass — a new batch
    changes a window tile exactly where it changes the all-time tile.
    Returns the number of cache entries dropped."""
    if result.duplicate:
        return 0
    store.refresh_layers()
    if cache is None:
        return 0
    keys = set(result.affected_keys)
    params = getattr(cache, "window_params", lambda: ())()
    if params:
        from heatmap_tpu.temporal.fold import window_variants

        keys.update(window_variants(result.affected_keys, params))
    return cache.invalidate_keys(keys)


__all__ = [
    "COMPACTION_SECONDS", "ColumnsSource", "DELTA_APPLY_SECONDS",
    "DELTA_POINTS", "DeltaJournal", "DeltaResult", "affected_tile_keys",
    "apply_batch", "batch_content_hash", "check_config", "compact",
    "compute_delta", "entry_digest", "init_store", "live_entries",
    "load_overlay_levels", "overlay_dirs", "parse_where", "read_columns",
    "read_current", "refresh_serving", "retract_predicate", "sweep",
]
