"""Predicate retraction: journal scan -> exact signed counter-batches.

The per-batch mechanism has existed since the journal landed: submit
the same points with ``sign=-1`` and linearity cancels them exactly.
This module closes the GDPR-shaped other half — "delete everything
matching ``user=U``" when the caller no longer HAS the original
batches. The journal does: every entry stores its point columns
(journal.py encode_points), so a retraction is

1. scan retained entries, match rows against the predicate;
2. net the matches as a signed multiset (insert entries add, earlier
   counter entries subtract — re-running a retraction, or retracting
   after a partial one, never double-cancels);
3. group surviving rows by the temporal bucket of their entry's
   watermark (heatmap_tpu.temporal) and by column signature;
4. apply one ``sign=-1`` counter-batch per group with the group's
   watermark as an explicit override, so each cancellation lands in
   the SAME bucket as the rows it removes — all-time AND every
   temporal fold converge to a clean recompute over survivors.

The scan horizon is the journal retention window: entries pruned after
compaction have no payload left, and entries from stores predating
point payloads never had one — both raise instead of silently
retracting less than the predicate asked for (docs/temporal.md).

Idempotent end to end: counter-batches are content-hashed (salted with
the watermark override), so re-running the same retraction re-nets to
zero surviving matches and applies nothing.
"""

from __future__ import annotations

import time

import numpy as np

from heatmap_tpu import obs
from heatmap_tpu.delta.compact import journal_dir, read_current
from heatmap_tpu.delta.journal import DeltaJournal

#: Predicate aliases accepted by ``--where`` (CLI friendliness: the
#: serve tier calls user layers "layers").
_ALIASES = {"user": "user_id", "layer": "user_id"}
_FLOAT_COLS = ("latitude", "longitude", "value")
_OBJECT_COLS = ("user_id", "source", "timestamp")
_ROW_COLS = _FLOAT_COLS + _OBJECT_COLS


def parse_where(pairs) -> dict:
    """["user=alice", "source=gps"] -> canonical predicate dict."""
    where = {}
    for p in pairs:
        if "=" not in p:
            raise ValueError(f"--where wants column=value, got {p!r}")
        k, v = p.split("=", 1)
        k = _ALIASES.get(k, k)
        if k not in _ROW_COLS:
            raise ValueError(
                f"--where column {k!r} is not a point column "
                f"({', '.join(_ROW_COLS)})")
        where[k] = v
    if not where:
        raise ValueError("retraction needs at least one --where clause")
    return where


def _match_mask(cols: dict, where: dict, n: int) -> np.ndarray:
    mask = np.ones(n, bool)
    for k, v in where.items():
        col = cols.get(k)
        if col is None:
            return np.zeros(n, bool)  # column absent: nothing matches
        if k in _FLOAT_COLS:
            mask &= np.asarray(col, np.float64) == float(v)
        else:
            mask &= np.asarray(
                [str(c) for c in col], str) == str(v)
    return mask


def _row_key(cols: dict, i: int) -> tuple:
    out = []
    for k in _ROW_COLS:
        col = cols.get(k)
        if col is None:
            out.append(None)
        elif k in _FLOAT_COLS:
            out.append(float(np.asarray(col)[i]))
        else:
            out.append(col[i])
    return tuple(out)


def _config_from_current(root: str):
    """Rehydrate the byte-affecting cascade config from the CURRENT
    fingerprint — a retraction must aggregate its counter-batch with
    exactly the pinned pyramid shape, and the store already knows it."""
    from heatmap_tpu.pipeline.batch import BatchJobConfig

    fp = read_current(root).get("config")
    if fp is None:
        raise ValueError(
            f"store {root} has no pinned config (no batch ever "
            "applied) — nothing to retract")
    kw = {k: tuple(v) if isinstance(v, list) else v
          for k, v in fp.items()}
    return BatchJobConfig(**kw)


def retract_predicate(root: str, where: dict, *, config=None,
                      batch_size: int = 1 << 20) -> dict:
    """Retract every journaled row matching ``where``; see module
    docstring. Returns a summary dict (rows retracted, counter-batch
    epochs, scan horizon)."""
    from heatmap_tpu.delta import (ColumnsSource, apply_batch,
                                   init_store)
    from heatmap_tpu.temporal import buckets as tb

    t0 = time.monotonic()
    init_store(root)
    if config is None:
        config = _config_from_current(root)
    tcfg = read_current(root).get("temporal")
    if tcfg is not None:
        tcfg = tb.normalize_config(tcfg)
    journal = DeltaJournal(journal_dir(root))
    entries = journal.entries()
    # Net signed multiset per (bucket, column-signature) group.
    groups: dict = {}
    scanned = 0
    for e in entries:
        cols = journal.load_points(int(e["epoch"]))
        if cols is None:
            raise ValueError(
                f"journal entry epoch {e['epoch']} has no point "
                "payload (pre-payload store or pruned horizon) — "
                "cannot guarantee an exact predicate retraction; see "
                "docs/temporal.md")
        n = len(cols["latitude"])
        scanned += n
        mask = _match_mask(cols, where, n)
        if not mask.any():
            continue
        wm = e.get("watermark")
        if tcfg is not None and wm is not None:
            bucket = tb.bucket_of(float(wm), tcfg)[0]
        else:
            bucket = None
        sig = tuple(k for k in _ROW_COLS if cols.get(k) is not None)
        key = (bucket, sig)
        g = groups.setdefault(key, {"counts": {}, "watermark": None})
        if wm is not None:
            g["watermark"] = (wm if g["watermark"] is None
                              else max(g["watermark"], float(wm)))
        sgn = int(e.get("sign", 1))
        for i in np.flatnonzero(mask):
            rk = _row_key(cols, int(i))
            g["counts"][rk] = g["counts"].get(rk, 0) + sgn
    results = []
    rows_retracted = 0
    for (bucket, sig), g in sorted(
            groups.items(),
            key=lambda kv: (str(kv[0][0]), kv[0][1])):
        survivors = [(rk, c) for rk, c in sorted(g["counts"].items(),
                                                 key=lambda kv: str(kv[0]))
                     if c > 0]
        if not survivors:
            continue
        cols: dict = {k: [] for k in sig}
        for rk, count in survivors:
            for _ in range(count):
                for k, v in zip(_ROW_COLS, rk):
                    if k in cols:
                        cols[k].append(v)
        n = len(cols["latitude"])
        res = apply_batch(root, ColumnsSource(cols), config, sign=-1,
                          batch_size=batch_size,
                          watermark=g["watermark"])
        rows_retracted += 0 if res.duplicate else n
        results.append(res)
    seconds = time.monotonic() - t0
    epochs = [r.epoch for r in results if not r.duplicate]
    obs.emit("retraction_applied", root=root, rows=rows_retracted,
             batches=len(epochs), scanned=scanned,
             where={k: str(v) for k, v in sorted(where.items())},
             epochs=epochs, seconds=round(seconds, 6))
    return {"rows": rows_retracted, "batches": len(epochs),
            "epochs": epochs, "scanned": scanned,
            "entries": len(entries), "seconds": seconds,
            "results": results}
