"""LSM-style maintenance for the delta store.

Store layout (one directory, self-describing):

    root/
      CURRENT            atomic JSON pointer {base, applied_through,
                         config} — the only mutable cell
      base-XXXXXX/       compacted base pyramid (LevelArraysSink dir),
                         named by the last epoch folded into it
      delta-XXXXXX/      one delta artifact per journaled epoch
      journal/           ckpt-<epoch>.npz entries (delta/journal.py)

Reads overlay base + live deltas (journal entries newer than
``applied_through``) through ``io.merge.merge_level_parts`` — the same
re-aggregation the multihost shard merge uses — then prune exact-zero
cells left by retractions, so the overlay is indistinguishable from a
full recompute over the surviving points.

Compaction writes the merged pyramid to a ``.tmp`` dir, publishes it to
its final ``base-XXXXXX`` name through ``utils.checkpoint.publish_dir``
(per-file fsync + rename + parent-dir fsync — the directory-shaped
``save_checkpoint`` contract), then atomically rewrites CURRENT (tmp +
fsync + os.replace + parent fsync). A crash at any point leaves either
the old pointer with the old base intact, or the new pointer with the
new base complete — never a half-merged store. Superseded bases and
journal entries older than the retention window are pruned afterwards;
garbage from a crashed pass (orphan ``*.tmp`` staging dirs, an
unflipped base) is quarantined by the recovery sweep
(delta/recover.py) that runs at the head of ``init_store`` and
``compact``.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time

import numpy as np

from heatmap_tpu import faults
from heatmap_tpu.delta.journal import DeltaJournal
from heatmap_tpu.io.merge import merge_level_dirs
from heatmap_tpu.io.sinks import LevelArraysSink
from heatmap_tpu.utils.checkpoint import fsync_dir, publish_dir

CURRENT_SCHEMA = "heatmap-tpu.delta_store.v1"
JOURNAL_DIRNAME = "journal"

#: Quarantined garbage younger than this is never pruned regardless of
#: the retention count — a day is the operator's minimum window to
#: inspect what a chaotic run left behind (delta/recover.py).
QUARANTINE_MIN_AGE_S = 24 * 3600.0

#: Config fields that change pyramid bytes: every batch applied to a
#: store must agree on them or base ⊕ delta is meaningless. Runtime
#: knobs (cascade_backend, data_parallel, chunking) are byte-neutral
#: and deliberately excluded.
CONFIG_FIELDS = ("detail_zoom", "min_detail_zoom", "result_delta",
                 "timespans", "weighted", "amplify_all",
                 "first_timespan_only")


def journal_dir(root: str) -> str:
    return os.path.join(root, JOURNAL_DIRNAME)


def read_current(root: str) -> dict:
    """The store pointer; a missing CURRENT is an empty store."""
    try:
        with open(os.path.join(root, "CURRENT")) as f:
            return json.load(f)
    except FileNotFoundError:
        return {"schema": CURRENT_SCHEMA, "base": None,
                "applied_through": 0, "config": None}


def write_current(root: str, cur: dict):
    """Atomic pointer flip: tmp + fsync + os.replace + parent-dir
    fsync, the save_checkpoint contract. Runs under the
    ``compact.publish`` fault site + retry policy — the flip is atomic,
    so a retried attempt lands the pointer exactly once."""

    def _flip():
        fd, tmp = tempfile.mkstemp(dir=root, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(cur, f, indent=2, sort_keys=True)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(root, "CURRENT"))
            fsync_dir(root)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    faults.retry_call(_flip, site="compact.publish", key="current")


def init_store(root: str, base_dir: str | None = None) -> dict:
    """Create (or no-op on) a delta store root; optionally adopt an
    existing arrays artifact as the initial base (copied in, so the
    store owns its files and compaction can prune them).

    Runs the crash-recovery sweep first (delta/recover.py), so every
    apply starts from a store with no torn journal entries or orphan
    staging dirs — a batch whose entry was quarantined re-journals
    under a fresh epoch and applies cleanly."""
    from heatmap_tpu.delta import recover

    os.makedirs(root, exist_ok=True)
    os.makedirs(journal_dir(root), exist_ok=True)
    recover.sweep(root)
    cur = read_current(root)
    if base_dir is not None:
        if cur.get("base"):
            raise ValueError(
                f"delta store {root} already has base {cur['base']!r}; "
                "refusing to overwrite it with --base")
        name = "base-000000"
        shutil.copytree(base_dir, os.path.join(root, name),
                        dirs_exist_ok=True)
        cur["base"] = name
    write_current(root, cur)
    return cur


def config_fingerprint(config) -> dict:
    out = {}
    for field in CONFIG_FIELDS:
        v = getattr(config, field, None)
        out[field] = list(v) if isinstance(v, tuple) else v
    return out


def check_config(root: str, config) -> dict:
    """Pin the byte-affecting config on first apply; later applies must
    match it exactly (mixing zooms/timespans would corrupt the sums)."""
    cur = read_current(root)
    fp = config_fingerprint(config)
    if cur.get("config") is None:
        cur["config"] = fp
        write_current(root, cur)
    elif cur["config"] != fp:
        raise ValueError(
            f"delta store {root} was built with config {cur['config']}; "
            f"refusing to apply a batch with {fp}")
    return cur


def live_entries(root: str) -> list[dict]:
    """Journal entries not yet folded into the base, oldest first."""
    cur = read_current(root)
    journal = DeltaJournal(journal_dir(root))
    applied_through = int(cur.get("applied_through", 0))
    return [e for e in journal.entries() if e["epoch"] > applied_through]


def overlay_dirs(root: str) -> list[str]:
    """Level dirs the read path merges: current base + live deltas.
    Driven by CURRENT + the journal, never by globbing — an orphan
    artifact from a crashed apply (dir written, journal append lost)
    is invisible until its batch is retried."""
    cur = read_current(root)
    dirs = []
    if cur.get("base"):
        base = os.path.join(root, cur["base"])
        if os.path.isdir(base):
            dirs.append(base)
    for entry in live_entries(root):
        d = os.path.join(root, entry["artifact"])
        if os.path.isdir(d):
            dirs.append(d)
    return dirs


def drop_zero_rows(levels: list) -> list:
    """Remove exact-zero cells left by retractions.

    A full recompute over the surviving points never emits these rows,
    and the serve tier's JSON docs would otherwise carry spurious 0.0
    entries — breaking the byte-identity anchor. Counts cancel exactly
    in f64 (small integers), so ``== 0.0`` is precise, and it also
    catches -0.0.
    """
    out = []
    for lvl in levels:
        value = np.asarray(lvl["value"])
        keep = value != 0.0
        if keep.all():
            out.append(lvl)
            continue
        pruned = dict(lvl)
        for k in LevelArraysSink.COLUMNS:
            if k in pruned:
                pruned[k] = np.asarray(pruned[k])[keep]
        # Re-compact the name vocabularies: a fully-retracted user (or
        # timespan) must vanish from the name table too, or the bytes
        # diverge from the clean recompute (which derives names from
        # the rows it actually has). Dropping entries from a sorted
        # vocab keeps it sorted, so only the indices need remapping.
        for prefix in ("user", "timespan"):
            names = pruned.get(f"{prefix}_names")
            idx = pruned.get(f"{prefix}_idx")
            if names is None or idx is None:
                continue
            names = np.asarray(names)
            idx = np.asarray(idx)
            used = np.unique(idx)
            if len(used) == len(names):
                continue
            remap = np.full(len(names), -1, np.int32)
            remap[used] = np.arange(len(used), dtype=np.int32)
            # Rebuild through a list so the dtype re-tightens to the
            # widest SURVIVING name — a <U5 array keeping only "bob"
            # would otherwise differ on disk from the recompute's <U3.
            pruned[f"{prefix}_names"] = np.asarray(names[used].tolist())
            pruned[f"{prefix}_idx"] = remap[idx]
        out.append(pruned)
    return out


def load_overlay_levels(root: str) -> list:
    """base ⊕ live deltas as finalized level dicts (write_levels input
    format); [] for an empty store."""
    dirs = overlay_dirs(root)
    if not dirs:
        return []
    return drop_zero_rows(merge_level_dirs(dirs))


def _write_buckets(root: str, cur: dict, live: list, tmp_path: str,
                   tcfg: dict) -> dict:
    """Stage the temporal bucket partition inside the compaction tmp
    dir (heatmap_tpu.temporal): carry the previous base's buckets
    forward, fold each live delta into the tier-0 bucket containing
    its watermark, coarsen old buckets up the geometric ladder, and
    write TEMPORAL.json — all under ``tmp_path`` so buckets and
    manifest publish atomically with the base itself.

    The top-level merged artifact is untouched: the all-time read path
    never sees buckets, which is what keeps it byte-identical to an
    un-bucketed store (the tier-1 identity gate); buckets are an
    additional, derived partition of the same journal entries.
    """
    from heatmap_tpu.temporal import buckets as tb

    base_name = cur.get("base")
    prev = (tb.read_manifest(os.path.join(root, base_name))
            if base_name else None)
    timed: list[dict] = []
    none_dirs: list[str] = []
    none_epochs: list[int] = []
    none_points = 0
    if prev is not None:
        bdir = os.path.join(root, base_name, tb.BUCKETS_DIRNAME)
        for b in prev.get("buckets") or []:
            d = os.path.join(bdir, b["name"])
            if os.path.isdir(d):
                timed.append({"t0": float(b["t0"]), "t1": float(b["t1"]),
                              "tier": int(b.get("tier", 0)), "dirs": [d],
                              "epochs": list(b.get("epochs") or []),
                              "points": int(b.get("points", 0))})
        pn = prev.get("none")
        if pn is not None:
            d = os.path.join(bdir, tb.NONE_NAME)
            if os.path.isdir(d):
                none_dirs.append(d)
                none_epochs += list(pn.get("epochs") or [])
                none_points += int(pn.get("points", 0))
    elif base_name and os.path.isdir(os.path.join(root, base_name)):
        # Pre-temporal base: its history has no per-batch resolution
        # left, so it folds into the timeless bucket — the all-time
        # layer is preserved exactly; temporal cuts treat the legacy
        # rows as always-present (docs/temporal.md).
        none_dirs.append(os.path.join(root, base_name))
    for e in live:
        d = os.path.join(root, e["artifact"])
        if not os.path.isdir(d):
            continue
        wm = e.get("watermark")
        if wm is None:
            none_dirs.append(d)
            none_epochs.append(int(e["epoch"]))
            none_points += int(e.get("points", 0))
            continue
        t0, t1 = tb.bucket_of(float(wm), tcfg)
        timed.append({"t0": t0, "t1": t1, "tier": 0, "dirs": [d],
                      "epochs": [int(e["epoch"])],
                      "points": int(e.get("points", 0))})
    entries = []
    if timed:
        max_edge = max(u["t1"] for u in timed)
        plan = tb.plan_partition(timed, tcfg, max_edge)
        for (t0, t1, tier), members in sorted(plan.items()):
            dirs = [d for u in members for d in u["dirs"]]
            levels = drop_zero_rows(merge_level_dirs(dirs))
            if not any(len(lvl["row"]) for lvl in levels):
                continue  # fully cancelled by retraction: no bucket
            name = tb.bucket_name(t0, t1)
            out = os.path.join(tmp_path, tb.BUCKETS_DIRNAME, name)
            LevelArraysSink(out).write_levels(levels)
            entries.append({
                "name": name, "t0": t0, "t1": t1, "tier": int(tier),
                "epochs": sorted({ep for u in members
                                  for ep in u["epochs"]}),
                "points": sum(u["points"] for u in members),
                "digest": tb.bucket_digest(out),
            })
    else:
        max_edge = None
    none_entry = None
    if none_dirs:
        levels = drop_zero_rows(merge_level_dirs(none_dirs))
        if any(len(lvl["row"]) for lvl in levels):
            out = os.path.join(tmp_path, tb.BUCKETS_DIRNAME, tb.NONE_NAME)
            LevelArraysSink(out).write_levels(levels)
            none_entry = {"name": tb.NONE_NAME,
                          "epochs": sorted(set(none_epochs)),
                          "points": none_points,
                          "digest": tb.bucket_digest(out)}
    manifest = {"schema": tb.TEMPORAL_SCHEMA, "config": tcfg,
                "max_edge": max_edge, "buckets": entries,
                "none": none_entry}
    tb.write_manifest(tmp_path, manifest)
    return manifest


def compact(root: str, *, retention: int = 2, inflight: int = 0) -> dict:
    """Fold the live delta stack into a new base and prune.

    Returns a summary dict; a store with no live deltas is a no-op
    (compacting nothing would only rewrite the base it already has).

    ``inflight`` is the caller's in-flight journal depth — batches
    queued for this root but not yet journaled (a write-plane pump's
    queue, an ingest loop's backlog). A ``retention`` below it is
    refused: pruning would shrink the exactly-once dedup window under
    batches that can still be replayed against this store, turning a
    crash-replay into a double count (docs/ingest.md).
    """
    from heatmap_tpu import obs
    from heatmap_tpu.delta import recover
    from heatmap_tpu.delta.metrics import COMPACTION_SECONDS
    from heatmap_tpu.obs import tracing

    if inflight > 0 and retention < inflight:
        raise ValueError(
            f"compact({root}): retention {retention} is below the "
            f"in-flight journal depth {inflight} — refusing to shrink "
            "the exactly-once dedup window under queued batches "
            "(docs/ingest.md)")
    recover.sweep(root)
    cur = read_current(root)
    journal = DeltaJournal(journal_dir(root))
    live = live_entries(root)
    base_name = cur.get("base")
    if not live:
        return {"status": "noop", "base": base_name, "deltas": 0,
                "applied_through": int(cur.get("applied_through", 0))}
    obs.emit("compaction_start", root=root, deltas=len(live),
             base=base_name)
    t0 = time.monotonic()
    tsp = tracing.begin_span("delta.compact", {"deltas": len(live)})
    try:
        dirs = overlay_dirs(root)
        merged = drop_zero_rows(merge_level_dirs(dirs)) if dirs else []
        new_epoch = max(e["epoch"] for e in live)
        new_name = f"base-{new_epoch:06d}"
        new_path = os.path.join(root, new_name)
        # The sweep above quarantined any orphan tmp/base dirs from a
        # crashed pass, so both staging and final paths start absent.
        tmp_path = new_path + ".tmp"
        # synopses=True / integrals=True rebuild the wavelet synopsis
        # and summed-area artifacts from the MERGED pyramid into the
        # staging dir, so the published base atomically carries exact
        # levels, synopses, and integrals consistent with base ⊕
        # deltas (heatmap_tpu.synopsis, heatmap_tpu.analytics; stale
        # ones would violate the stamped error / exact-sum contracts).
        # tilefs mirrors are inherited: if the old base was converted
        # (tools/tilefs_convert.py) or written by an arrays-tilefs
        # sink, the new base carries fresh zero-copy mirrors too — a
        # one-time conversion survives every later compaction.
        from heatmap_tpu.tilefs import sniff_tilefs

        keep_tilefs = bool(base_name) and sniff_tilefs(
            os.path.join(root, base_name))
        rows = LevelArraysSink(tmp_path, synopses=True, integrals=True,
                               tilefs=keep_tilefs).write_levels(merged)
        tcfg = cur.get("temporal")
        manifest = (_write_buckets(root, cur, live, tmp_path, tcfg)
                    if tcfg is not None else None)
        faults.retry_call(publish_dir, tmp_path, new_path,
                          site="compact.publish", key="base")
        cur = dict(cur)
        cur["base"] = new_name
        cur["applied_through"] = int(new_epoch)
        write_current(root, cur)  # the atomic commit point
        pruned = journal.prune(applied_through=new_epoch,
                               retention=retention)
        for entry in pruned:
            shutil.rmtree(os.path.join(root, entry["artifact"]),
                          ignore_errors=True)
        for name in os.listdir(root):
            if (name.startswith("base-") and name != new_name
                    and os.path.isdir(os.path.join(root, name))):
                shutil.rmtree(os.path.join(root, name),
                              ignore_errors=True)
        # Quarantine rides the same retention knob: keep the newest
        # ``retention`` quarantined items, but nothing younger than the
        # minimum age (an operator's incident-investigation window).
        recover.prune_quarantine(root, keep=retention,
                                 min_age_s=QUARANTINE_MIN_AGE_S)
        seconds = time.monotonic() - t0
        COMPACTION_SECONDS.observe(seconds)
        buckets = (len(manifest["buckets"]) +
                   (1 if manifest["none"] else 0)) if manifest else None
        extra = {"buckets": buckets} if buckets is not None else {}
        obs.emit("compaction_end", root=root, seconds=round(seconds, 6),
                 status="ok", base=new_name, levels=len(merged),
                 rows=int(rows), pruned_entries=len(pruned), **extra)
        return {"status": "ok", "base": new_name,
                "applied_through": int(new_epoch),
                "deltas": len(live), "levels": len(merged),
                "rows": int(rows), "pruned_entries": len(pruned),
                "buckets": buckets, "seconds": seconds}
    except BaseException as exc:
        obs.emit("compaction_end", root=root,
                 seconds=round(time.monotonic() - t0, 6),
                 status="error", error=repr(exc))
        raise
    finally:
        tracing.end_span(tsp)
