"""Delta pyramid computation: only the new points through the cascade.

No new kernels: a delta artifact is the ordinary batch job
(``pipeline.run_job`` — auto-routing included, so count batches take
the partitioned MXU path and compose with data parallelism exactly as
a full job does) run over just the incremental batch, written in the
same columnar level format (``io.sinks.LevelArraysSink``) that
``io/merge.py`` already merges. Because tile counts are pure sums,
base ⊕ delta is exact.

Retractions ride the same path with the sign flipped at egress: the
retraction points cascade normally (positive counts — the int32 MXU
route stays valid) and the finalized level values are negated before
the sink writes them. By linearity that equals cascading negative
weights, without teaching the device path about signs.
"""

from __future__ import annotations

import numpy as np

from heatmap_tpu.io.sinks import LevelArraysSink

#: Rendered formats a cached tile can exist in (serve/http.py routes).
#: Kept local so importing the delta engine never drags the serve
#: package in; pinned equal to serve.live.TILE_FORMATS in tests.
TILE_FORMATS = ("png", "json")


class ColumnsSource:
    """In-memory point columns as a batch source.

    The ingest path already holds the whole batch in hand (it is
    hashed for the journal before anything runs), so the cascade can
    read it back without a round-trip through a file. Slicing works on
    both ndarray and list columns, matching io.sources batch layout.
    """

    COLUMNS = ("latitude", "longitude", "user_id", "source",
               "timestamp", "value")

    def __init__(self, cols: dict):
        self.cols = {k: cols[k] for k in self.COLUMNS if k in cols}
        if "latitude" not in self.cols or "user_id" not in self.cols:
            raise ValueError("point columns need latitude/longitude/user_id")
        n = len(self.cols["latitude"])
        for k, v in self.cols.items():
            if len(v) != n:
                raise ValueError(
                    f"column {k!r} has {len(v)} rows, expected {n}")
        self._n = n

    def __len__(self) -> int:
        return self._n

    def batches(self, batch_size: int = 1 << 20):
        for lo in range(0, self._n, batch_size):
            yield {k: v[lo:lo + batch_size] for k, v in self.cols.items()}


def read_columns(source, batch_size: int = 1 << 20) -> dict:
    """Drain a source into one concatenated column dict (the delta
    batch must be materialized anyway to content-hash it)."""
    lat, lon, value = [], [], []
    obj: dict = {"user_id": [], "source": [], "timestamp": []}
    seen: set = set()
    for b in source.batches(batch_size):
        lat.append(np.asarray(b["latitude"], np.float64))
        lon.append(np.asarray(b["longitude"], np.float64))
        for k in obj:
            if k in b:
                seen.add(k)
                obj[k].extend(list(b[k]))
        if "value" in b:
            seen.add("value")
            value.append(np.asarray(b["value"], np.float64))
    cols = {
        "latitude": np.concatenate(lat) if lat else np.zeros(0),
        "longitude": np.concatenate(lon) if lon else np.zeros(0),
        "user_id": obj["user_id"],
    }
    for k in ("source", "timestamp"):
        if k in seen:
            cols[k] = obj[k]
    if "value" in seen:
        cols["value"] = np.concatenate(value)
    return cols


class _NegatingLevels:
    """Sink adapter for retraction deltas: negate finalized level
    values on the way into the columnar sink (run_job routes to
    ``write_levels`` by presence, so this slots in transparently —
    including the spill path's per-level calls)."""

    def __init__(self, inner):
        self._inner = inner

    def write_levels(self, levels) -> int:
        return self._inner.write_levels([
            {**lvl, "value": np.negative(np.asarray(lvl["value"]))}
            for lvl in levels
        ])


def compute_delta(source, out_dir: str, config, *, sign: int = 1,
                  batch_size: int = 1 << 20):
    """Run ``source`` through the full batch cascade into a delta
    artifact dir (LevelArraysSink format). Returns run_job's stats."""
    from heatmap_tpu.pipeline import run_job  # defers the jax import
    from heatmap_tpu.obs import tracing

    if sign not in (1, -1):
        raise ValueError("sign must be +1 (insert) or -1 (retraction)")
    sink = LevelArraysSink(out_dir)
    if sign == -1:
        sink = _NegatingLevels(sink)
    with tracing.span("delta.compute", sign=sign):
        return run_job(source, sink, config, batch_size=batch_size)


def affected_tile_keys(levels: dict,
                       alias: tuple = ("all|alltime", "default")) -> set:
    """Cache keys whose rendered bytes this delta can change.

    Mirrors serve/live.py ``LiveLayer.affected_keys``: every changed
    cell of the FINEST delta level (coarser delta cells are exactly
    its ancestors, by the cascade rollup), projected to every tile at
    request zooms 0..finest, per affected ``user|timespan`` layer
    (plus the ``default`` alias when the all|alltime pair changes),
    both formats. Requests finer than the stored detail zoom are not
    enumerated — the same bound live.py uses; give the cache a TTL if
    you serve those.

    ``levels`` is ``LevelArraysSink.load`` output: {zoom: columns with
    materialized string user/timespan}.
    """
    if not levels:
        return set()
    finest = int(max(levels))
    cols = levels[finest]
    row = np.asarray(cols["row"], np.int64)
    col = np.asarray(cols["col"], np.int64)
    if not len(row):
        return set()
    user = np.asarray(cols["user"]).astype(str)
    tspan = np.asarray(cols["timespan"]).astype(str)
    pair = np.char.add(np.char.add(user, "|"), tspan)
    keys: set = set()
    for name in np.unique(pair):
        names = [str(name)] + ([alias[1]] if str(name) == alias[0] else [])
        m = pair == name
        r, c = row[m], col[m]
        for z in range(finest + 1):
            shift = finest - z
            tiles = np.unique(np.stack([r >> shift, c >> shift], 1), axis=0)
            for tr, tc in tiles:
                for nm in names:
                    for fmt in TILE_FORMATS:
                        keys.add((nm, z, int(tc), int(tr), fmt))
    return keys
