"""Append-only ingest journal: content-hashed, epoch-numbered batches.

Every accepted batch is one journal entry — an empty-array checkpoint
written through ``utils/checkpoint.save_checkpoint`` (the atomic
tmp-write + rename contract) whose JSON meta carries the batch's
content hash, point count, timestamp watermark, monotonic epoch and
sign (+1 insert, -1 retraction). This extends the checkpoint module's
recovery model from "resume a partial cascade" to "replay-proof
ingest": re-submitting an already-journaled batch finds its hash and
is a no-op, so an at-least-once upstream (a retried queue consumer, a
re-run cron) converges to exactly-once pyramid updates.

The files are ``ckpt-<epoch>.npz`` under the journal directory —
``CheckpointManager``'s own naming — so epoch listing, latest-epoch
and the retention prune are all the manager's hardened code paths,
not a parallel implementation.

Idempotency is scoped to the retention window: once a compaction has
folded an entry into the base AND the retention pass has pruned it,
its hash is forgotten and a re-submit would double-count. Size the
retention window to cover the upstream's maximum redelivery horizon.
"""

from __future__ import annotations

import hashlib
import os
import time

import numpy as np

from heatmap_tpu import faults
from heatmap_tpu.utils.checkpoint import CheckpointManager, save_checkpoint

#: Columns hashed (when present) to derive a batch identity. Floats are
#: hashed as raw little-endian f64 bytes, strings NUL-joined — the hash
#: is a pure function of the point data, independent of batch chunking.
HASH_FLOAT_COLUMNS = ("latitude", "longitude", "value")
HASH_OBJECT_COLUMNS = ("user_id", "source", "timestamp")


def batch_content_hash(cols: dict, sign: int = 1) -> str:
    """Deterministic identity of a point batch (+ its sign).

    The sign participates so that retracting a batch is a different
    journal entry from inserting it — submitting both is the intended
    way to express a correction, not a duplicate.
    """
    h = hashlib.sha256()
    h.update(f"sign={int(sign)}".encode())
    for name in HASH_FLOAT_COLUMNS:
        if name in cols:
            arr = np.ascontiguousarray(np.asarray(cols[name], np.float64))
            h.update(name.encode())
            h.update(arr.tobytes())
    for name in HASH_OBJECT_COLUMNS:
        if name in cols and len(cols[name]):
            h.update(name.encode())
            h.update("\x00".join(str(v) for v in cols[name]).encode())
    return "sha256:" + h.hexdigest()


def entry_digest(root: str, *, content_hash: str, sign: int, points: int,
                 artifact: str) -> str:
    """Integrity digest binding a journal entry to its artifact bytes.

    Hashes the entry's identity fields plus every file in the artifact
    directory (sorted by name), so a torn artifact write, a swapped
    artifact, or a tampered ``content_hash`` in the entry meta all
    produce a digest mismatch the recovery sweep (delta/recover.py)
    quarantines. Stored in the entry meta as ``entry_digest``; entries
    from stores predating the field skip verification (legacy).
    """
    h = hashlib.sha256()
    h.update(f"{content_hash}|{int(sign)}|{int(points)}|{artifact}".encode())
    d = os.path.join(root, artifact)
    if os.path.isdir(d):
        for name in sorted(os.listdir(d)):
            full = os.path.join(d, name)
            if not os.path.isfile(full):
                continue
            h.update(name.encode())
            with open(full, "rb") as f:
                h.update(f.read())
    return "sha256:" + h.hexdigest()


class DeltaJournal:
    """Epoch-numbered journal entries in a directory.

    Appends never prune (``save_checkpoint`` is called directly, not
    ``CheckpointManager.save`` — the manager's keep-N would eat live
    entries); retention is an explicit post-compaction pass.
    """

    def __init__(self, directory: str):
        self._mgr = CheckpointManager(directory, keep=1)

    @property
    def directory(self) -> str:
        return self._mgr.directory

    def epochs(self) -> list[int]:
        return self._mgr.steps()

    def latest_epoch(self) -> int:
        return self._mgr.latest_step() or 0

    def next_epoch(self) -> int:
        return self.latest_epoch() + 1

    def entries(self) -> list[dict]:
        """All journal entry metas, oldest epoch first. An entry pruned
        between the listing and the read is skipped (same concurrent-
        maintenance stance as CheckpointManager.prune)."""
        out = []
        for epoch in self.epochs():
            try:
                _, meta = self._mgr.load(epoch)
            except FileNotFoundError:
                continue
            out.append(meta)
        return out

    def find(self, content_hash: str) -> dict | None:
        for meta in self.entries():
            if meta.get("content_hash") == content_hash:
                return meta
        return None

    def append(self, *, content_hash: str, points: int, sign: int,
               artifact: str, watermark: float | None = None) -> dict:
        """Record an accepted batch; returns the existing entry
        unchanged if the hash is already journaled (idempotent)."""
        existing = self.find(content_hash)
        if existing is not None:
            return existing
        epoch = self.next_epoch()
        root = os.path.dirname(os.path.abspath(self.directory))
        meta = {
            "epoch": epoch,
            "content_hash": content_hash,
            "points": int(points),
            "sign": int(sign),
            "artifact": artifact,
            "watermark": watermark,
            "ts": time.time(),
            "entry_digest": entry_digest(root, content_hash=content_hash,
                                         sign=sign, points=points,
                                         artifact=artifact),
        }
        # save_checkpoint is atomic, so a retried append (real transient
        # or injected journal.append fault) lands the entry exactly once.
        faults.retry_call(save_checkpoint, self._mgr._path(epoch), {}, meta,
                          site="journal.append")
        return meta

    def prune(self, *, applied_through: int, retention: int) -> list[dict]:
        """Drop entries already folded into a compacted base, keeping
        the newest ``retention`` of them as the idempotency window.
        Live entries (epoch > ``applied_through``) are always kept.
        Returns the pruned entries (the caller owns their artifacts).
        """
        if retention < 0:
            raise ValueError("retention must be >= 0")
        entries = self.entries()
        applied = [e for e in entries if e["epoch"] <= applied_through]
        doomed = applied[:-retention] if retention else applied
        # Entries are epoch-ordered and live ones are the newest, so
        # "keep all but the oldest len(doomed)" is exactly the
        # manager's hardened keep-N prune.
        self._mgr.prune(keep=len(entries) - len(doomed))
        return doomed
