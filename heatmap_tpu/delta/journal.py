"""Append-only ingest journal: content-hashed, epoch-numbered batches.

Every accepted batch is one journal entry — an empty-array checkpoint
written through ``utils/checkpoint.save_checkpoint`` (the atomic
tmp-write + rename contract) whose JSON meta carries the batch's
content hash, point count, timestamp watermark, monotonic epoch and
sign (+1 insert, -1 retraction). This extends the checkpoint module's
recovery model from "resume a partial cascade" to "replay-proof
ingest": re-submitting an already-journaled batch finds its hash and
is a no-op, so an at-least-once upstream (a retried queue consumer, a
re-run cron) converges to exactly-once pyramid updates.

The files are ``ckpt-<epoch>.npz`` under the journal directory —
``CheckpointManager``'s own naming — so epoch listing, latest-epoch
and the retention prune are all the manager's hardened code paths,
not a parallel implementation.

Idempotency is scoped to the retention window: once a compaction has
folded an entry into the base AND the retention pass has pruned it,
its hash is forgotten and a re-submit would double-count. Size the
retention window to cover the upstream's maximum redelivery horizon.
"""

from __future__ import annotations

import hashlib
import os
import time

import numpy as np

from heatmap_tpu import faults
from heatmap_tpu.utils.checkpoint import CheckpointManager, save_checkpoint

#: Columns hashed (when present) to derive a batch identity. Floats are
#: hashed as raw little-endian f64 bytes, strings NUL-joined — the hash
#: is a pure function of the point data, independent of batch chunking.
HASH_FLOAT_COLUMNS = ("latitude", "longitude", "value")
HASH_OBJECT_COLUMNS = ("user_id", "source", "timestamp")


def batch_content_hash(cols: dict, sign: int = 1,
                       salt: str | None = None) -> str:
    """Deterministic identity of a point batch (+ its sign).

    The sign participates so that retracting a batch is a different
    journal entry from inserting it — submitting both is the intended
    way to express a correction, not a duplicate. ``salt`` extends the
    identity for callers whose batches differ by something outside the
    point columns — predicate retraction salts with the overridden
    watermark, so cancelling identical rows out of two different
    temporal buckets is two entries, not one dedup'd no-op.
    """
    h = hashlib.sha256()
    h.update(f"sign={int(sign)}".encode())
    if salt is not None:
        h.update(f"salt={salt}".encode())
    for name in HASH_FLOAT_COLUMNS:
        if name in cols:
            arr = np.ascontiguousarray(np.asarray(cols[name], np.float64))
            h.update(name.encode())
            h.update(arr.tobytes())
    for name in HASH_OBJECT_COLUMNS:
        if name in cols and len(cols[name]):
            h.update(name.encode())
            h.update("\x00".join(str(v) for v in cols[name]).encode())
    return "sha256:" + h.hexdigest()


def entry_digest(root: str, *, content_hash: str, sign: int, points: int,
                 artifact: str) -> str:
    """Integrity digest binding a journal entry to its artifact bytes.

    Hashes the entry's identity fields plus every file in the artifact
    directory (sorted by name), so a torn artifact write, a swapped
    artifact, or a tampered ``content_hash`` in the entry meta all
    produce a digest mismatch the recovery sweep (delta/recover.py)
    quarantines. Stored in the entry meta as ``entry_digest``; entries
    from stores predating the field skip verification (legacy).
    """
    h = hashlib.sha256()
    h.update(f"{content_hash}|{int(sign)}|{int(points)}|{artifact}".encode())
    d = os.path.join(root, artifact)
    if os.path.isdir(d):
        for name in sorted(os.listdir(d)):
            full = os.path.join(d, name)
            if not os.path.isfile(full):
                continue
            h.update(name.encode())
            with open(full, "rb") as f:
                h.update(f.read())
    return "sha256:" + h.hexdigest()


#: Journal-payload encoding of point columns (delta retraction's scan
#: substrate). Floats stay raw f64 (exact); everything else is stored
#: as ``str(v)`` — identical to how batch_content_hash consumes it, and
#: exact under ``float()`` round-trip for numeric timestamps — with
#: ``str(None)`` decoding back to None.
_PAYLOAD_FLOAT = ("latitude", "longitude", "value")
_PAYLOAD_STR = ("user_id", "source", "timestamp")
_NONE_TOKEN = str(None)


def encode_points(cols: dict) -> dict:
    """Point columns -> npz-safe arrays (``pt_``-prefixed, no object
    dtypes, no pickle)."""
    arrays = {}
    for name in _PAYLOAD_FLOAT:
        if name in cols:
            arrays["pt_" + name] = np.asarray(cols[name], np.float64)
    for name in _PAYLOAD_STR:
        if name in cols:
            arrays["pt_" + name] = np.asarray(
                [_NONE_TOKEN if v is None else str(v)
                 for v in cols[name]])
    return arrays


def decode_points(arrays: dict) -> dict | None:
    """Inverse of :func:`encode_points`; None for a legacy entry that
    predates point payloads (retraction cannot scan it)."""
    cols: dict = {}
    for name in _PAYLOAD_FLOAT:
        key = "pt_" + name
        if key in arrays:
            cols[name] = np.asarray(arrays[key], np.float64)
    for name in _PAYLOAD_STR:
        key = "pt_" + name
        if key in arrays:
            cols[name] = [None if v == _NONE_TOKEN else v
                          for v in np.asarray(arrays[key], str).tolist()]
    return cols or None


class DeltaJournal:
    """Epoch-numbered journal entries in a directory.

    Appends never prune (``save_checkpoint`` is called directly, not
    ``CheckpointManager.save`` — the manager's keep-N would eat live
    entries); retention is an explicit post-compaction pass.
    """

    def __init__(self, directory: str):
        self._mgr = CheckpointManager(directory, keep=1)

    @property
    def directory(self) -> str:
        return self._mgr.directory

    def epochs(self) -> list[int]:
        return self._mgr.steps()

    def latest_epoch(self) -> int:
        return self._mgr.latest_step() or 0

    def next_epoch(self) -> int:
        return self.latest_epoch() + 1

    def entries(self) -> list[dict]:
        """All journal entry metas, oldest epoch first. An entry pruned
        between the listing and the read is skipped (same concurrent-
        maintenance stance as CheckpointManager.prune)."""
        out = []
        for epoch in self.epochs():
            try:
                _, meta = self._mgr.load(epoch)
            except FileNotFoundError:
                continue
            out.append(meta)
        return out

    def find(self, content_hash: str) -> dict | None:
        for meta in self.entries():
            if meta.get("content_hash") == content_hash:
                return meta
        return None

    def load_points(self, epoch: int) -> dict | None:
        """The point columns journaled with ``epoch`` (retraction's
        scan input), or None for a legacy entry without a payload."""
        arrays, _meta = self._mgr.load(int(epoch))
        return decode_points(arrays)

    def append(self, *, content_hash: str, points: int, sign: int,
               artifact: str, watermark: float | None = None,
               cols: dict | None = None) -> dict:
        """Record an accepted batch; returns the existing entry
        unchanged if the hash is already journaled (idempotent).

        ``cols`` (the batch's point columns) are stored in the entry's
        npz arrays — the extension point the empty-arrays checkpoint
        always reserved — so predicate retraction can reconstruct
        exact counter-batches by scanning retained entries
        (delta/retract.py). A torn payload fails the entry's npz load
        and is quarantined by the recovery sweep like any torn entry.
        """
        existing = self.find(content_hash)
        if existing is not None:
            return existing
        epoch = self.next_epoch()
        root = os.path.dirname(os.path.abspath(self.directory))
        meta = {
            "epoch": epoch,
            "content_hash": content_hash,
            "points": int(points),
            "sign": int(sign),
            "artifact": artifact,
            "watermark": watermark,
            "ts": time.time(),
            "entry_digest": entry_digest(root, content_hash=content_hash,
                                         sign=sign, points=points,
                                         artifact=artifact),
        }
        # save_checkpoint is atomic, so a retried append (real transient
        # or injected journal.append fault) lands the entry exactly once.
        arrays = encode_points(cols) if cols else {}
        faults.retry_call(save_checkpoint, self._mgr._path(epoch), arrays,
                          meta, site="journal.append")
        return meta

    def prune(self, *, applied_through: int, retention: int) -> list[dict]:
        """Drop entries already folded into a compacted base, keeping
        the newest ``retention`` of them as the idempotency window.
        Live entries (epoch > ``applied_through``) are always kept.
        Returns the pruned entries (the caller owns their artifacts).
        """
        if retention < 0:
            raise ValueError("retention must be >= 0")
        entries = self.entries()
        applied = [e for e in entries if e["epoch"] <= applied_through]
        doomed = applied[:-retention] if retention else applied
        # Entries are epoch-ordered and live ones are the newest, so
        # "keep all but the oldest len(doomed)" is exactly the
        # manager's hardened keep-N prune.
        self._mgr.prune(keep=len(entries) - len(doomed))
        return doomed
