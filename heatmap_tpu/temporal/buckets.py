"""Bucket ladder, naming, and the TEMPORAL.json base manifest.

A temporal store partitions journaled history by batch watermark into
time buckets on a geometric ladder (the telemetry store's 10s/1m/10m
tier shape): tier-0 buckets are ``width`` wide, tier-j buckets are
``width * fanout**j`` wide, and each tier keeps the newest ``keep``
intervals before coarsening into the next tier. All tier widths are
integer multiples of ``width`` aligned to 0, so intervals nest exactly
and a bucket never straddles its coarsening target.

The bucket config is BYTE-AFFECTING for temporal folds (which buckets
exist determines which cuts are expressible), so it is pinned in the
store's CURRENT pointer like the cascade config fingerprint
(delta/compact.py check_config) — first writer sets it, later writers
must match.

Bucket membership is *batch-granular*: a journal entry belongs to the
tier-0 bucket containing its watermark (the batch's max timestamp).
Entries with no timestamps land in the timeless ``bucket-none``, which
every fold includes with weight 1.0. Base dirs carry their buckets
under ``buckets/bucket-<t0>-<t1>/`` (plain LevelArraysSink level dirs)
plus one ``TEMPORAL.json`` manifest listing {name, t0, t1, tier,
epochs, points, digest} per bucket — staged in the compaction tmp dir,
so the manifest and buckets publish atomically with the base.
"""

from __future__ import annotations

import hashlib
import json
import os

TEMPORAL_SCHEMA = "heatmap-tpu.temporal.v1"
MANIFEST_NAME = "TEMPORAL.json"
BUCKETS_DIRNAME = "buckets"
#: The timeless bucket: journal entries whose batches carry no
#: timestamps. Included in every fold (all-time, as_of, window) with
#: decay weight 1.0 — rows with no time axis never age.
NONE_NAME = "bucket-none"

#: Named sliding windows accepted by ``?window=`` (seconds).
WINDOW_SECONDS = {"1h": 3600.0, "1d": 86400.0, "1w": 604800.0}

#: Keys of a temporal config (all byte-affecting for folds).
CONFIG_KEYS = ("width", "fanout", "keep", "tiers", "unit_s")

_DEFAULTS = {"width": 3600.0, "fanout": 4, "keep": 8, "tiers": 4,
             "unit_s": 1.0}


def normalize_config(cfg: dict | None = None, **overrides) -> dict:
    """Validated, canonical temporal config dict (json-able).

    ``width`` is in watermark units; ``unit_s`` converts named windows
    ("1h"/"1d"/"1w", defined in seconds) into watermark units for data
    whose timestamps are not seconds (ms feeds use unit_s=1000).
    """
    out = dict(_DEFAULTS)
    for src in (cfg or {}), overrides:
        for k, v in src.items():
            if v is None:
                continue
            if k not in _DEFAULTS:
                raise ValueError(f"unknown temporal config key {k!r}")
            out[k] = v
    out["width"] = float(out["width"])
    out["fanout"] = int(out["fanout"])
    out["keep"] = int(out["keep"])
    out["tiers"] = int(out["tiers"])
    out["unit_s"] = float(out["unit_s"])
    if out["width"] <= 0:
        raise ValueError("temporal width must be > 0")
    if out["fanout"] < 2:
        raise ValueError("temporal fanout must be >= 2")
    if out["keep"] < 1 or out["tiers"] < 1:
        raise ValueError("temporal keep and tiers must be >= 1")
    if out["unit_s"] <= 0:
        raise ValueError("temporal unit_s must be > 0")
    return out


def parse_window(text, cfg: dict) -> float:
    """``?window=`` value -> width in watermark units. Accepts the
    named windows (seconds scaled by unit_s) or a bare number already
    in watermark units."""
    if text in WINDOW_SECONDS:
        return WINDOW_SECONDS[text] * float(cfg.get("unit_s", 1.0))
    try:
        w = float(text)
    except (TypeError, ValueError):
        raise ValueError(
            f"window must be one of {sorted(WINDOW_SECONDS)} or a "
            f"number of watermark units, got {text!r}")
    if w <= 0:
        raise ValueError(f"window must be > 0, got {w}")
    return w


def tier_width(cfg: dict, tier: int) -> float:
    return float(cfg["width"]) * int(cfg["fanout"]) ** int(tier)


def bucket_of(watermark: float, cfg: dict, tier: int = 0):
    """(t0, t1) of the tier-aligned bucket containing ``watermark``."""
    w = tier_width(cfg, tier)
    import math

    t0 = math.floor(float(watermark) / w) * w
    return t0, t0 + w


def _fmt_edge(t: float) -> str:
    f = float(t)
    return str(int(f)) if f.is_integer() else repr(f)


def bucket_name(t0: float, t1: float) -> str:
    return f"bucket-{_fmt_edge(t0)}-{_fmt_edge(t1)}"


def age_tier(t1: float, cfg: dict, max_edge: float) -> int:
    """Target tier for a bucket ending at ``t1`` when the newest edge
    is ``max_edge``: each tier j spans ``keep`` intervals of width
    ``width * fanout**j`` before history coarsens into tier j+1; the
    top tier is unbounded."""
    age = float(max_edge) - float(t1)
    cum = 0.0
    for j in range(int(cfg["tiers"])):
        cum += int(cfg["keep"]) * tier_width(cfg, j)
        if age < cum:
            return j
    return int(cfg["tiers"]) - 1


def plan_partition(units: list[dict], cfg: dict, max_edge: float) -> dict:
    """Deterministic bucket partition for a compaction pass.

    ``units`` are the mergeable inputs — existing buckets from the
    previous base ({"t0","t1","tier", ...}) and tier-0 groups of new
    live deltas — and the result maps target ``(t0, t1, tier)`` ->
    list of member units. Each unit's target tier is the max of its own
    tier (a coarse bucket never splits back) and its age tier; nested
    target intervals then escalate into their containing interval, so
    the final intervals are disjoint. Pure function of (units, cfg,
    max_edge) — two compactions over the same history agree.
    """
    tagged = []
    for u in units:
        j = max(int(u.get("tier", 0)), age_tier(u["t1"], cfg, max_edge))
        t0, _ = bucket_of(u["t0"], cfg, tier=j)
        tagged.append([j, t0, t0 + tier_width(cfg, j), u])
    # Escalate intervals nested inside a coarser sibling's interval
    # until disjoint (at most ``tiers`` rounds — tiers is small).
    for _ in range(int(cfg["tiers"]) + 1):
        changed = False
        spans = {(j, t0, t1) for j, t0, t1, _ in tagged}
        for rec in tagged:
            j, t0, t1, u = rec
            for sj, s0, s1 in spans:
                if sj > j and s0 <= t0 and t1 <= s1:
                    nt0, _ = bucket_of(t0, cfg, tier=sj)
                    rec[0], rec[1], rec[2] = sj, nt0, nt0 + tier_width(
                        cfg, sj)
                    changed = True
                    break
        if not changed:
            break
    groups: dict = {}
    for j, t0, t1, u in tagged:
        groups.setdefault((t0, t1, j), []).append(u)
    return groups


def bucket_digest(bucket_dir: str) -> str:
    """Integrity digest over every file in a bucket dir (sorted by
    name) — same discipline as the journal's entry_digest, verified by
    the recovery sweep so a torn bucket quarantines instead of folding
    garbage into a temporal view."""
    h = hashlib.sha256()
    if os.path.isdir(bucket_dir):
        for name in sorted(os.listdir(bucket_dir)):
            full = os.path.join(bucket_dir, name)
            if not os.path.isfile(full):
                continue
            h.update(name.encode())
            with open(full, "rb") as f:
                h.update(f.read())
    return "sha256:" + h.hexdigest()


def write_manifest(base_dir: str, manifest: dict):
    """Write TEMPORAL.json into ``base_dir``. Callers stage this
    inside the compaction tmp dir before publish_dir, so the manifest
    rides the base's own atomic publish — no separate flip needed."""
    path = os.path.join(base_dir, MANIFEST_NAME)
    with open(path, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")


def read_manifest(base_dir: str) -> dict | None:
    """The base's temporal manifest, or None when the base predates
    the temporal plane (or the manifest was quarantined)."""
    try:
        with open(os.path.join(base_dir, MANIFEST_NAME)) as f:
            m = json.load(f)
    except (FileNotFoundError, NotADirectoryError, ValueError, OSError):
        return None
    if m.get("schema") != TEMPORAL_SCHEMA:
        return None
    return m
