"""Partial-pyramid folds: select buckets for a temporal cut and merge.

A fold is the temporal analogue of the all-time overlay
(delta/compact.py load_overlay_levels): pick the bucket dirs and live
delta artifacts inside the cut, merge them through the same
``io.merge`` re-aggregation core, drop exact-zero rows. Because the
pyramid is a pure sum and the merge is deterministic, a fold over ALL
buckets is byte-identical to the un-bucketed overlay — the fast tier-1
identity gate — and any sub-selection equals a clean recompute over
exactly the points whose batches landed inside the cut.

Cut semantics (batch-granular, aligned to bucket edges):

- ``as_of=T``  — cut at the largest bucket edge <= T; fold buckets
  ending at or before the cut plus live deltas whose watermark falls
  below it. History below a cut is immutable under ingest (new batches
  land above), so the fold token — and every cache entry keyed by it —
  survives unrelated writes; only retraction or compaction below the
  cut changes it.
- ``window=W`` — fold the trailing buckets whose end edge lies inside
  ``(ref - W, ref]`` where ``ref`` is the newest bucket edge (never
  wall clock: bytes must be a pure function of the data).
- decay       — per-bucket scalar weight ``0.5 ** ((ref - t1) /
  half_life)`` applied to bucket subtotals at fold time. Stored bytes
  are never restamped; linearity of the sum makes the weighted fold
  equal a clean recompute with per-point weight = its bucket's weight.

``bucket-none`` (batches with no timestamps) is timeless: included in
every fold with weight 1.0.

A selected bucket whose dir is missing or torn (quarantined by the
recovery sweep, or torn underneath us) raises ``TornBucketError`` —
the serve tier's stale-if-error cache then answers with the last good
bytes while the all-time path, which never reads buckets, is
unaffected (docs/robustness.md).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os

import numpy as np

from heatmap_tpu.delta.compact import (
    drop_zero_rows,
    live_entries,
    read_current,
    write_current,
)
from heatmap_tpu.io.merge import _loaded_to_finalized, merge_level_parts
from heatmap_tpu.io.sinks import LevelArraysSink
from heatmap_tpu.temporal import buckets as tb


class TornBucketError(RuntimeError):
    """A selected bucket (or live artifact) is missing or unreadable —
    the fold cannot be answered exactly; serve falls back to last-good
    cached bytes (stale-if-error) instead of folding garbage."""


def ensure_config(root: str, cfg: dict | None = None, **overrides):
    """Pin the temporal bucket config in CURRENT (byte-affecting for
    folds, same discipline as the cascade config fingerprint). First
    writer sets it; later writers must match exactly. Returns the
    active config, or None when the store has none and no config was
    offered."""
    cur = read_current(root)
    offered = None
    if cfg is not None or any(v is not None for v in overrides.values()):
        offered = tb.normalize_config(cfg, **overrides)
    existing = cur.get("temporal")
    if existing is None:
        if offered is None:
            return None
        cur = dict(cur)
        cur["temporal"] = offered
        write_current(root, cur)
        return offered
    existing = tb.normalize_config(existing)
    if offered is not None and offered != existing:
        raise ValueError(
            f"delta store {root} pinned temporal config {existing}; "
            f"refusing to proceed with {offered}")
    return existing


def temporal_config(root: str) -> dict | None:
    cfg = read_current(root).get("temporal")
    return tb.normalize_config(cfg) if cfg is not None else None


def _manifest_units(root: str, cur: dict):
    """(manifest bucket entries, none entry) of CURRENT's base."""
    base = cur.get("base")
    if not base:
        return [], None
    m = tb.read_manifest(os.path.join(root, base))
    if m is None:
        return [], None
    return list(m.get("buckets") or []), m.get("none")


def _live_units(root: str, cfg: dict):
    """Live journal entries tagged with their tier-0 bucket edges
    (t0/t1 None for watermark-less batches)."""
    out = []
    for e in live_entries(root):
        wm = e.get("watermark")
        if wm is None:
            t0 = t1 = None
        else:
            t0, t1 = tb.bucket_of(float(wm), cfg)
        out.append({"epoch": int(e["epoch"]), "artifact": e["artifact"],
                    "watermark": wm, "t0": t0, "t1": t1,
                    "sign": int(e.get("sign", 1))})
    return out


def newest_edge(root: str, cfg: dict | None = None) -> float | None:
    """The newest bucket edge the store's data reaches (max t1 over
    manifest buckets and live batches) — the temporal ``ref`` for
    window folds and decay. None for a store with no timestamped
    data."""
    if cfg is None:
        cfg = temporal_config(root)
    if cfg is None:
        return None
    cur = read_current(root)
    bucket_entries, _none = _manifest_units(root, cur)
    edges = [float(b["t1"]) for b in bucket_entries]
    edges += [u["t1"] for u in _live_units(root, cfg)
              if u["t1"] is not None]
    return max(edges) if edges else None


@dataclasses.dataclass(frozen=True)
class Selection:
    """A resolved temporal cut: which units fold, plus the token that
    names the fold (cache key component)."""

    buckets: tuple          # manifest bucket entries inside the cut
    live: tuple             # live unit dicts inside the cut
    none: dict | None       # bucket-none manifest entry (or None)
    ref: float | None       # decay/window reference edge
    lo: float | None        # exclusive lower cut (window), else None
    hi: float | None        # inclusive upper cut (as_of), else None
    token: str              # digest of the fold inputs


def select_fold(root: str, *, as_of: float | None = None,
                window: float | None = None,
                decay: float | None = None) -> Selection:
    """Resolve a temporal cut against the store's manifest + live
    journal. Raises ValueError when the store has no temporal config
    (buckets were never built — nothing to cut)."""
    cfg = temporal_config(root)
    if cfg is None:
        raise ValueError(
            f"store {root} has no temporal config — init it with "
            "ensure_config / the CLI --bucket-width flag before "
            "temporal queries")
    cur = read_current(root)
    bucket_entries, none_entry = _manifest_units(root, cur)
    live = _live_units(root, cfg)
    edges = sorted({float(b["t1"]) for b in bucket_entries}
                   | {u["t1"] for u in live if u["t1"] is not None})

    hi = None
    if as_of is not None:
        below = [e for e in edges if e <= float(as_of)]
        hi = below[-1] if below else None
    ref = hi if hi is not None else (edges[-1] if edges else None)
    lo = None
    if window is not None and ref is not None:
        lo = ref - float(window)

    def _in(t1) -> bool:
        if t1 is None:
            return False
        if hi is not None and t1 > hi:
            return False
        if as_of is not None and hi is None:
            return False  # as_of before all data: empty cut
        if lo is not None and t1 <= lo:
            return False
        return True

    sel_buckets = tuple(b for b in bucket_entries if _in(float(b["t1"])))
    sel_live = tuple(u for u in live if _in(u["t1"]))
    ident = {
        "buckets": sorted((b["name"], b.get("digest"))
                          for b in sel_buckets),
        "none": (none_entry or {}).get("digest"),
        "live": sorted(u["epoch"] for u in sel_live),
        "lo": lo, "hi": hi, "ref": ref,
        "decay": None if decay is None else float(decay),
    }
    token = hashlib.sha256(
        json.dumps(ident, sort_keys=True).encode()).hexdigest()[:16]
    return Selection(buckets=sel_buckets, live=sel_live, none=none_entry,
                     ref=ref, lo=lo, hi=hi, token=token)


def _unit_dirs(root: str, cur: dict, sel: Selection):
    """[(dir, t1-or-None)] for every unit in the selection; missing
    dirs raise TornBucketError (quarantined bucket / vanished
    artifact)."""
    base = cur.get("base")
    out = []
    for b in sel.buckets:
        d = os.path.join(root, base or "", tb.BUCKETS_DIRNAME, b["name"])
        if not os.path.isdir(d):
            raise TornBucketError(
                f"bucket {b['name']} missing from base {base!r} "
                "(quarantined or torn)")
        out.append((d, float(b["t1"])))
    if sel.none is not None:
        d = os.path.join(root, base or "", tb.BUCKETS_DIRNAME,
                         tb.NONE_NAME)
        if not os.path.isdir(d):
            raise TornBucketError(
                f"{tb.NONE_NAME} missing from base {base!r}")
        out.append((d, None))
    for u in sel.live:
        d = os.path.join(root, u["artifact"])
        if not os.path.isdir(d):
            raise TornBucketError(
                f"live artifact {u['artifact']} missing")
        out.append((d, u["t1"]))
    return out


def decay_weight(t1: float | None, ref: float, half_life: float) -> float:
    """Per-bucket decay scalar; timeless units (t1 None) never age."""
    if t1 is None:
        return 1.0
    return float(0.5 ** ((float(ref) - float(t1)) / float(half_life)))


def fold_levels(root: str, sel: Selection, *,
                decay_half_life: float | None = None) -> list:
    """Merge the selection into finalized level dicts (write_levels
    input format, the shape load_overlay_levels returns). With decay,
    each unit's ``value`` column is scaled by its bucket weight before
    the merge — weighting subtotals, never stored bytes."""
    cur = read_current(root)
    units = _unit_dirs(root, cur, sel)
    if not units:
        return []
    parts = []
    for d, t1 in units:
        try:
            loaded = LevelArraysSink.load(d)
        except Exception as e:
            raise TornBucketError(f"unreadable level dir {d}: {e!r}")
        w = 1.0
        if decay_half_life is not None and sel.ref is not None:
            w = decay_weight(t1, sel.ref, decay_half_life)
        part = []
        for zoom in sorted(loaded):
            cols = loaded[zoom]
            if w != 1.0:
                cols = dict(cols)
                cols["value"] = np.asarray(cols["value"], np.float64) * w
            part.append(_loaded_to_finalized(cols))
        parts.append(part)
    return drop_zero_rows(merge_level_parts(parts))


def window_variants(keys, window_params) -> list:
    """Window-fold cache-key variants of base tile keys: the serve
    tier keys an undecayed window tile as ``key + ("w", param)`` so
    the ingest loop's targeted invalidation can name exactly the
    entries a new batch or a bucket roll dirties."""
    out = []
    for p in window_params:
        out.extend(tuple(k) + ("w", str(p)) for k in keys)
    return out


def retiring_dirs(root: str, prev_ref: float, new_ref: float,
                  window_units) -> list[str]:
    """Unit dirs whose bucket just LEFT at least one active sliding
    window when the newest edge advanced prev_ref -> new_ref — the
    bucket-roll invalidation set. Only these units' tile keys need
    dropping; everything else in the window cache stays valid."""
    cfg = temporal_config(root)
    if cfg is None or new_ref <= prev_ref:
        return []
    cur = read_current(root)
    bucket_entries, _none = _manifest_units(root, cur)
    live = _live_units(root, cfg)
    base = cur.get("base")
    out = []

    def _retired(t1) -> bool:
        return any(prev_ref - w < t1 <= new_ref - w
                   for w in window_units)

    for b in bucket_entries:
        if _retired(float(b["t1"])):
            out.append(os.path.join(root, base or "", tb.BUCKETS_DIRNAME,
                                    b["name"]))
    for u in live:
        if u["t1"] is not None and _retired(u["t1"]):
            out.append(os.path.join(root, u["artifact"]))
    return [d for d in out if os.path.isdir(d)]
