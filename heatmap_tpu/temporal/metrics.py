"""Temporal-plane metric handles on the shared obs registry (the
delta/metrics.py pattern: module-level handles, created once, gated on
``registry.enabled``)."""

from __future__ import annotations

from heatmap_tpu import obs

_registry = obs.get_registry()

TEMPORAL_FOLD_SECONDS = _registry.histogram(
    "temporal_fold_seconds",
    "Wall-clock of one partial-pyramid fold (bucket select + merge + "
    "index build)",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0))
TEMPORAL_REQUESTS = _registry.counter(
    "temporal_requests_total",
    "Requests answered through a temporal fold",
    labelnames=("mode",))  # mode = as_of | window | decay | growth
