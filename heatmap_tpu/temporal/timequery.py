"""Time-axis queries: Haar wavelet histograms over per-bucket series.

``op=topk_growth&window=1w`` asks "which cells grew the most this
window". The exact answer needs every cell's full per-bucket series;
this module compresses each series with the 1D Haar transform
(synopsis/transform.py — the same substrate as the spatial synopsis,
pointed at the epoch axis) and evaluates the growth functional on the
top-m coefficients only, with a sound error bound stamped on the
answer (arxiv 1110.6649's wavelet-histogram playbook, the temporal
twin of PR 14's integral-histogram /query engine).

Growth is LINEAR in the series: ``growth(x) = q . x`` where ``q`` is
-1 on the older half of the window's slots, +1 on the newer half, 0 on
padding. Writing the inverse transform as ``x = B c`` gives
``growth = (B^T q) . c = g . c`` — so per-coefficient contributions
``c_i * g_i`` are exact, the approximation keeps the m largest by
magnitude, and the dropped tail bounds the error by the triangle
inequality: ``|approx - exact| <= sum_dropped |c_i * g_i|``. Bucket
values are integer counts (or bounded-integer weighted sums) and ``g``
entries are powers of two over the padded length, so every product and
sum here is exact in f64 — the stamped bound is sound, which the
brute-force oracle test pins (tests/test_temporal.py).

Slots are the ordered end-edges of the selected units; a coarsened
(higher-tier) bucket occupies one slot at its own edge. ``bucket-none``
has no time axis and never contributes to growth.
"""

from __future__ import annotations

import os

import numpy as np

from heatmap_tpu.delta.compact import read_current
from heatmap_tpu.io.sinks import LevelArraysSink
from heatmap_tpu.synopsis.transform import haar1d_np, inv_haar1d_np
from heatmap_tpu.temporal.fold import (
    Selection,
    TornBucketError,
    select_fold,
)

DEFAULT_COEFFS = 8


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _series_matrix(root: str, sel: Selection, *, user: str,
                   timespan: str, zoom: int):
    """-> (rows, cols, M) where M[i, j] is cell i's summed value in
    slot j (slots = sorted distinct unit end-edges), plus the slot
    edge list. Timed units only — bucket-none is timeless."""
    cur = read_current(root)
    base = cur.get("base")
    units = []
    for b in sel.buckets:
        d = os.path.join(root, base or "", "buckets", b["name"])
        units.append((d, float(b["t1"])))
    for u in sel.live:
        units.append((os.path.join(root, u["artifact"]), u["t1"]))
    edges = sorted({t1 for _, t1 in units})
    slot_of = {t1: j for j, t1 in enumerate(edges)}
    cells: dict = {}
    chunks = []  # (cell_idx array, slot, values)
    for d, t1 in units:
        if not os.path.isdir(d):
            raise TornBucketError(f"unit dir {d} missing (quarantined?)")
        try:
            loaded = LevelArraysSink.load(d)
        except Exception as e:
            raise TornBucketError(f"unreadable level dir {d}: {e!r}")
        lvl = loaded.get(int(zoom))
        if lvl is None:
            continue
        keep = ((np.asarray(lvl["user"], str) == user)
                & (np.asarray(lvl["timespan"], str) == timespan))
        if not keep.any():
            continue
        rr = np.asarray(lvl["row"])[keep]
        cc = np.asarray(lvl["col"])[keep]
        vv = np.asarray(lvl["value"], np.float64)[keep]
        idx = np.empty(len(rr), np.int64)
        for i, cell in enumerate(zip(rr.tolist(), cc.tolist())):
            idx[i] = cells.setdefault(cell, len(cells))
        chunks.append((idx, slot_of[t1], vv))
    m = np.zeros((len(cells), len(edges)), np.float64)
    for idx, j, vv in chunks:
        np.add.at(m[:, j], idx, vv)
    keys = np.empty((len(cells), 2), np.int64)
    for (r, c), i in cells.items():
        keys[i] = (r, c)
    return keys[:, 0], keys[:, 1], m, edges


def growth_series(m: np.ndarray, edges, ref: float, window: float,
                  coeffs: int):
    """Approximate growth per cell from the top-``coeffs`` wavelet
    contributions; -> (approx, bound, exact). ``exact`` is the full
    functional (cheap here, used for the stamped-bound invariant and
    the oracle test; a tiered deployment would keep only the retained
    coefficients per cell)."""
    nslots = m.shape[1]
    if nslots == 0:
        z = np.zeros(m.shape[0])
        return z, z.copy(), z.copy()
    pad = _next_pow2(nslots)
    mp = np.zeros((m.shape[0], pad), np.float64)
    mp[:, pad - nslots:] = m  # pad on the OLD side; recent slots last
    mid = float(ref) - float(window) / 2.0
    q = np.zeros(pad, np.float64)
    for j, t1 in enumerate(edges):
        q[pad - nslots + j] = 1.0 if t1 > mid else -1.0
    c = haar1d_np(mp)
    # g = B^T q: row i of inv_haar1d_np(I) is basis vector i, so the
    # matrix-vector product below is exactly (B^T q). pad is small
    # (window/width slots), so the dense identity transform is cheap.
    g = inv_haar1d_np(np.eye(pad)) @ q
    contrib = c * g[None, :]
    exact = contrib.sum(axis=1)
    k = min(int(coeffs), pad)
    order = np.argsort(np.abs(contrib), axis=1)  # ascending
    dropped = np.take_along_axis(contrib, order[:, :pad - k], axis=1)
    approx = exact - dropped.sum(axis=1)
    bound = np.abs(dropped).sum(axis=1)
    return approx, bound, exact


def topk_growth(root: str, *, user: str, timespan: str, zoom: int,
                window: float, k: int = 10,
                coeffs: int = DEFAULT_COEFFS) -> dict:
    """Top-k cells by approximate growth over the trailing window.

    One bounded-error scan: per-cell series from the window's buckets,
    1D Haar per cell, growth from the kept coefficients, achieved
    error bound stamped (``max_err`` = max bound among reported
    cells). Deterministic: ties break on (growth desc, row, col).
    """
    sel = select_fold(root, window=window)
    rows, cols, m, edges = _series_matrix(
        root, sel, user=user, timespan=timespan, zoom=int(zoom))
    approx, bound, _exact = growth_series(
        m, edges, sel.ref if sel.ref is not None else 0.0, window, coeffs)
    if len(approx):
        order = np.lexsort((cols, rows, -approx))[:int(k)]
    else:
        order = np.asarray([], np.int64)
    cells = [{"row": int(rows[i]), "col": int(cols[i]),
              "growth": float(approx[i]), "bound": float(bound[i])}
             for i in order]
    max_err = max((c["bound"] for c in cells), default=0.0)
    return {"op": "topk_growth", "zoom": int(zoom), "window": window,
            "slots": len(edges), "coeffs": int(coeffs), "cells": cells,
            "max_err": max_err, "token": sel.token}
