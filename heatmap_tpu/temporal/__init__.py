"""Temporal plane: epoch-bucketed partial pyramids over the delta store.

The delta store keeps history (epoch-numbered journal entries, compacted
bases) but serves only the all-time sum. This package makes that history
queryable without changing a byte of the all-time path:

- ``buckets``  — the geometric bucket ladder (telemetry-store style
  tiers), bucket naming, the ``TEMPORAL.json`` base manifest, and the
  deterministic compaction partition plan;
- ``fold``     — partial-pyramid folds: select buckets for ``as_of`` /
  ``window`` cuts, apply per-bucket decay weights at fold time, and
  merge through the same ``io.merge`` core as the all-time overlay, so
  a fold over *all* buckets is byte-identical to the un-bucketed store;
- ``timequery``— Haar wavelet histograms over the per-bucket cell
  series (synopsis/transform.py, applied to the time axis) backing the
  bounded-error ``op=topk_growth`` /query path.

Everything here is derived data: buckets are written by compaction
(delta/compact.py) from the same journal entries as the base, verified
by the recovery sweep (delta/recover.py), and folded lazily at serve
time (serve/store.py). Decay never restamps stored bytes — it is a
scalar weight applied to bucket subtotals at fold time (linearity of
the pure-sum pyramid). See docs/temporal.md.
"""

from heatmap_tpu.temporal.buckets import (
    BUCKETS_DIRNAME,
    MANIFEST_NAME,
    NONE_NAME,
    WINDOW_SECONDS,
    bucket_name,
    bucket_of,
    normalize_config,
    parse_window,
    read_manifest,
)
from heatmap_tpu.temporal.fold import (
    TornBucketError,
    ensure_config,
    fold_levels,
    select_fold,
    window_variants,
)

__all__ = [
    "BUCKETS_DIRNAME",
    "MANIFEST_NAME",
    "NONE_NAME",
    "WINDOW_SECONDS",
    "TornBucketError",
    "bucket_name",
    "bucket_of",
    "ensure_config",
    "fold_levels",
    "normalize_config",
    "parse_window",
    "read_manifest",
    "select_fold",
    "window_variants",
]
