"""Unified retry policy: bounded exponential backoff + full jitter + deadline.

One policy shape for every guarded boundary (``POLICIES`` is the
per-site table docs/robustness.md documents). Backoff for attempt *k*
is ``min(cap_s, base_s * 2**(k-1)) * U`` where ``U`` is *full jitter* in
[0, 1) — but deterministic: ``plane.hash01(seed, site, key, attempt)``
rather than RNG state, so a seeded chaos run sleeps the same schedule
every time. The installed plane's ``backoff_scale`` multiplies every
sleep (chaos tests set it to 0), and ``time.sleep`` lives only here and
in the plane-free fallback — the tests/test_obs.py grep guard keeps
hand-rolled retry sleeps out of every other module.

Only *transient* errors are retried (``RETRYABLE`` = OSError +
RuntimeError, which covers real I/O failures and :class:`InjectedFault`);
data errors (ValueError etc.) are deterministic and propagate
immediately. Both helpers run the plane's fault check for their site
*before* invoking the guarded operation, so an injected fault never
leaves a half-executed write behind — retrying is idempotent by
construction wherever the underlying operation is.

``retry_call`` guards a single operation. ``resumable_iter`` guards a
whole deterministic stream (the io sources): on a transient mid-stream
failure it rebuilds the iterator and fast-forwards past the
already-delivered prefix — sound because every source iterates
deterministically (pinned in io/sources.py docs) — and its
consecutive-failure budget resets whenever an item is delivered, so a
long stream survives many isolated transients while still bounding any
contiguous failure window by ``retries`` attempts and ``deadline_s``
seconds.
"""

from __future__ import annotations

import dataclasses
import time

from heatmap_tpu.faults.plane import check, get_plane, hash01

# Transient error classes worth retrying. InjectedFault is a
# RuntimeError; OSError covers real filesystem/network failures.
RETRYABLE = (OSError, RuntimeError)


class NonRetryable:
    """Marker mixin: an error that matches RETRYABLE by class but is
    deterministic (missing driver, bad config) — raised through the
    retry machinery without burning attempts or sleeping."""


#: Hard ceiling on iterator rebuilds at one stream position
#: (resumable_iter). The per-policy attempt budget already bounds a
#: contiguous failure window under the shipped POLICIES table, but a
#: permissive caller policy (retries=10**9, deadline_s=None) would
#: otherwise rebuild a deterministically-poisoned batch forever; this
#: cap turns that pathology into a typed PoisonedStream regardless of
#: how generous the policy is.
MAX_REBUILDS_PER_POSITION = 8


class PoisonedStream(NonRetryable, RuntimeError):
    """A stream failed :data:`MAX_REBUILDS_PER_POSITION` times at the
    same position — the batch is deterministically poisoned, not
    transient, so rebuilding again cannot help."""

    def __init__(self, site: str, position: int, rebuilds: int,
                 last_error: BaseException):
        super().__init__(
            f"{site}: stream poisoned at position {position} — "
            f"{rebuilds} rebuilds all failed there "
            f"(last: {last_error!r})")
        self.site = site
        self.position = int(position)
        self.rebuilds = int(rebuilds)
        self.last_error = last_error


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """retries = re-executions allowed after the first failure;
    deadline_s bounds one contiguous failure window (None = unbounded)."""

    retries: int = 3
    base_s: float = 0.05
    cap_s: float = 2.0
    deadline_s: float | None = 30.0


DEFAULT_POLICY = RetryPolicy()

# Per-site defaults (the docs/robustness.md policy table). Serving-path
# sites get zero retries: the degradation machinery (stale-if-error,
# typed 503) owns those failures, and a request handler must not sleep.
POLICIES = {
    "source.read": RetryPolicy(retries=4, base_s=0.05, cap_s=2.0,
                               deadline_s=60.0),
    "sink.write": RetryPolicy(retries=4, base_s=0.05, cap_s=2.0,
                              deadline_s=60.0),
    "journal.append": RetryPolicy(retries=3, base_s=0.02, cap_s=0.5,
                                  deadline_s=10.0),
    "compact.publish": RetryPolicy(retries=3, base_s=0.02, cap_s=0.5,
                                   deadline_s=10.0),
    "shard.compute": RetryPolicy(retries=2, base_s=0.05, cap_s=2.0,
                                 deadline_s=None),
    "tile.render": RetryPolicy(retries=0, base_s=0.0, cap_s=0.0,
                               deadline_s=None),
    "http.request": RetryPolicy(retries=0, base_s=0.0, cap_s=0.0,
                                deadline_s=None),
    "multihost.heartbeat": RetryPolicy(retries=0, base_s=0.0, cap_s=0.0,
                                       deadline_s=None),
    # Ingest-loop boundaries. A tick is idempotent end to end (the
    # journal's content hash turns a replay into a no-op), so retrying
    # the whole tick is safe; publish is pure cache invalidation +
    # artifact re-read, also safe to repeat. Short caps: a standing
    # loop must shed a poisoned tick quickly rather than stall the
    # queue behind a long backoff.
    "ingest.tick": RetryPolicy(retries=2, base_s=0.02, cap_s=0.5,
                               deadline_s=10.0),
    "ingest.publish": RetryPolicy(retries=3, base_s=0.02, cap_s=0.5,
                                  deadline_s=10.0),
    # Provisional synopsis publish (early serving). Best-effort by
    # contract — the exact apply supersedes it either way — so the
    # budget is small and the loop swallows a terminal failure instead
    # of dying.
    "ingest.synopsis": RetryPolicy(retries=2, base_s=0.02, cap_s=0.5,
                                   deadline_s=10.0),
    # Host->device feeder transfer (pipeline/feeder.py). device_put is
    # idempotent (nothing downstream saw the batch), so re-feeding is
    # always safe; short caps because the feeder thread stalling just
    # degrades overlap back to synchronous transfer.
    "feeder.put": RetryPolicy(retries=2, base_s=0.02, cap_s=0.5,
                              deadline_s=10.0),
    # Orphaned-shard re-execution on a surviving host. The shard
    # already failed once on the dead host, so the retry budget here
    # guards only the survivor's own transients; a shard that also
    # fails on the survivor should surface quickly rather than wander
    # the fleet.
    "elastic.reassign": RetryPolicy(retries=2, base_s=0.05, cap_s=2.0,
                                    deadline_s=None),
    # Fleet router forward: exactly one retry, and it lands on the
    # *next* replica in rendezvous order, never the same backend — so
    # base_s stays 0 (no sleep in a request handler; the failover IS
    # the backoff). Connection failures only; HTTP status codes pass
    # through untouched.
    "router.forward": RetryPolicy(retries=1, base_s=0.0, cap_s=0.0,
                                  deadline_s=None),
    # Active health probes are themselves the retry loop (the prober
    # re-probes every interval); a failed probe just feeds the breaker.
    "backend.probe": RetryPolicy(retries=0, base_s=0.0, cap_s=0.0,
                                 deadline_s=None),
    # tilefs mmap open. Zero retries: a torn/unreadable tilefs file is
    # deterministic, and the store's heap-npz fallback for that zoom IS
    # the recovery (serving stays byte-identical; the offline sweep
    # owns quarantining the file).
    "tilefs.read": RetryPolicy(retries=0, base_s=0.0, cap_s=0.0,
                               deadline_s=None),
    # Disk-cache write-through. Zero retries: the tile was already
    # rendered when the fill runs, so a failed write is just a skipped
    # optimization — never worth sleeping for on the serve path.
    "diskcache.write": RetryPolicy(retries=0, base_s=0.0, cap_s=0.0,
                                   deadline_s=None),
    # Write-plane boundaries (heatmap_tpu/writeplane/). A per-range
    # sub-apply is idempotent end to end (the range journal's content
    # hash), so retrying the whole apply is safe; short caps because a
    # stalling pump backs the router's bounded queue up — shed a
    # poisoned sub-batch quickly and let the replay heal it.
    "writeplane.append": RetryPolicy(retries=2, base_s=0.02, cap_s=0.5,
                                     deadline_s=10.0),
    # The manifest-epoch flip is atomic (tmp + rename, twice), so a
    # retried publish lands the same epoch bytes exactly once — same
    # stance as compact.publish.
    "writeplane.publish": RetryPolicy(retries=3, base_s=0.02, cap_s=0.5,
                                      deadline_s=10.0),
    # Re-split is rare, coordinator-only, and heavyweight (it compacts
    # the hot range first); one retry covers a transient, and a failed
    # rebalance is safe to abandon — the skew check re-fires later and
    # the sweep quarantines any orphan child range.
    "writeplane.rebalance": RetryPolicy(retries=1, base_s=0.05, cap_s=2.0,
                                        deadline_s=None),
}


def policy_for(site: str) -> RetryPolicy:
    return POLICIES.get(site, DEFAULT_POLICY)


def backoff_s(site: str, key, attempt: int, *, base_s: float,
              cap_s: float) -> float:
    """Full-jitter exponential backoff for retry ``attempt`` (1-based),
    deterministic under the installed plane's seed and scaled by its
    ``backoff_scale``."""
    if base_s <= 0 or attempt < 1:
        return 0.0
    plane = get_plane()
    seed = plane.seed if plane is not None else 0
    scale = plane.backoff_scale if plane is not None else 1.0
    exp = min(cap_s, base_s * (2.0 ** (attempt - 1)))
    return exp * hash01(seed, "backoff", site, key, attempt) * scale


def sleep_backoff(site: str, key, attempt: int, *, base_s: float,
                  cap_s: float) -> float:
    """Compute + sleep the backoff; returns the seconds slept. The only
    sanctioned retry sleep in the package (see the grep guard)."""
    delay = backoff_s(site, key, attempt, base_s=base_s, cap_s=cap_s)
    if delay > 0:
        time.sleep(delay)
    return delay


def retry_call(fn, *args, site: str, key=None,
               policy: RetryPolicy | None = None, clock=time.monotonic):
    """Run ``fn(*args)`` under the site's fault check + retry policy.

    Retries RETRYABLE errors with backoff until the policy's attempt or
    deadline budget is spent, then re-raises the last error. ``fn`` must
    be safe to re-execute (atomic or idempotent).
    """
    if policy is None:
        policy = policy_for(site)
    attempt = 0
    start = clock()
    while True:
        try:
            check(site, key)
            return fn(*args)
        except RETRYABLE as e:
            if isinstance(e, NonRetryable):
                raise
            attempt += 1
            if attempt > policy.retries:
                raise
            if (policy.deadline_s is not None
                    and clock() - start >= policy.deadline_s):
                raise
            from heatmap_tpu import obs

            obs.record_io_retry(site)
            sleep_backoff(site, key, attempt,
                          base_s=policy.base_s, cap_s=policy.cap_s)


def resumable_iter(make_iter, *, site: str, key=None,
                   policy: RetryPolicy | None = None, clock=time.monotonic,
                   max_rebuilds: int = MAX_REBUILDS_PER_POSITION):
    """Yield from ``make_iter()`` with transparent retry-with-resume.

    On a retryable failure (including an injected fault at the per-item
    site check) the iterator is rebuilt and the already-delivered prefix
    replayed and discarded — identical bytes, because sources iterate
    deterministically. Delivered items reset the attempt/deadline
    window; non-retryable errors and exhausted budgets propagate.

    The per-delivery window reset is what lets a long stream absorb
    many isolated transients, but it also means the *policy* never
    bounds total rebuilds of one poisoned position when the caller's
    policy is permissive. ``max_rebuilds`` is the independent
    poison-batch bound: once that many consecutive rebuilds fail at the
    same position the stream raises :class:`PoisonedStream`
    (NonRetryable) instead of rebuilding forever.
    """
    if policy is None:
        policy = policy_for(site)
    delivered = 0
    attempt = 0
    window_start = None
    poison_position = None  # stream position of the last failure
    poison_rebuilds = 0  # consecutive failures at that position
    while True:
        try:
            it = make_iter()
            for _ in range(delivered):
                next(it)  # replay prefix: no fault checks, no re-delivery
            while True:
                check(site, key)
                try:
                    item = next(it)
                except StopIteration:
                    return
                delivered += 1
                attempt = 0
                window_start = None
                yield item
        except StopIteration:
            return  # stream ended during replay
        except RETRYABLE as e:
            if isinstance(e, NonRetryable):
                raise
            if delivered == poison_position:
                poison_rebuilds += 1
            else:
                poison_position = delivered
                poison_rebuilds = 1
            if poison_rebuilds >= max_rebuilds:
                raise PoisonedStream(site, delivered, poison_rebuilds,
                                     e) from e
            attempt += 1
            now = clock()
            if window_start is None:
                window_start = now
            if attempt > policy.retries:
                raise
            if (policy.deadline_s is not None
                    and now - window_start >= policy.deadline_s):
                raise
            from heatmap_tpu import obs

            obs.record_io_retry(site)
            sleep_backoff(site, key, attempt,
                          base_s=policy.base_s, cap_s=policy.cap_s)
