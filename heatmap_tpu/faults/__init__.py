"""Deterministic fault injection + the unified retry policy.

``faults.plane`` owns the process-wide seeded, site-keyed injection
registry (``--chaos SPEC`` / ``HEATMAP_TPU_CHAOS`` / programmatic);
``faults.retry`` owns bounded-exponential-backoff-with-full-jitter
retries and the per-site policy table. See docs/robustness.md for the
fault model, the policy table, and the chaos-soak runbook.
"""

from heatmap_tpu.faults.plane import (ENV_VAR, SITES, FaultPlane,
                                      InjectedFault, check, get_plane,
                                      hash01, install, install_from_env,
                                      install_spec, parse_spec)
from heatmap_tpu.faults.retry import (DEFAULT_POLICY,
                                      MAX_REBUILDS_PER_POSITION, POLICIES,
                                      RETRYABLE, NonRetryable,
                                      PoisonedStream, RetryPolicy,
                                      backoff_s, policy_for, resumable_iter,
                                      retry_call, sleep_backoff)

__all__ = [
    "DEFAULT_POLICY", "ENV_VAR", "FaultPlane", "InjectedFault",
    "MAX_REBUILDS_PER_POSITION", "NonRetryable", "POLICIES",
    "PoisonedStream", "RETRYABLE", "RetryPolicy", "SITES",
    "backoff_s", "check", "get_plane", "hash01", "install",
    "install_from_env", "install_spec", "parse_spec", "policy_for",
    "resumable_iter", "retry_call", "sleep_backoff",
]
