"""Process-wide deterministic fault-injection plane.

One registry, a closed site allowlist, zero cost when off. Every I/O and compute
boundary in the pipeline calls ``faults.check(site, key=...)`` at the
top of the guarded operation; with no plane installed that is a single
module-global read. With a plane installed, rules decide — purely as a
function of ``(seed, site, key, per-rule check index)`` — whether the
check raises :class:`InjectedFault`. Two runs with the same plane spec
and the same call sequence inject the same faults at the same places,
which is what lets tools/chaos_soak.py pin byte-identical output under
hundreds of injected faults.

Sites (``SITES``): ``source.read`` (one check per batch yielded by any
io source), ``sink.write`` (blob/level writes), ``journal.append``
(delta journal entries), ``compact.publish`` (CURRENT flips + base
publishes), ``shard.compute`` (utils/recovery.run_shards — the site the
legacy ``FaultInjector`` maps onto), ``tile.render`` (serve render
functions), ``http.request`` (ServeApp dispatch), and
``multihost.heartbeat`` (a *lost* heartbeat: obs.heartbeat swallows the
fault and skips the liveness update instead of failing the caller),
``ingest.tick`` / ``ingest.publish`` (continuous-ingest micro-batch
boundaries), ``ingest.synopsis`` (the loop's best-effort provisional
synopsis publish for early serving — a terminal failure is swallowed,
never kills the loop), ``feeder.put`` (each host->device transfer the
double-buffered feeder makes — pipeline/feeder.py; re-feeding the same
batch is idempotent, and on the ingest path the journal's content hash
keeps a re-fed batch exactly-once), ``elastic.reassign`` (each orphaned-shard re-execution
on a surviving host — parallel/elastic.py), ``router.forward`` (one
check per fleet-router forward attempt to a backend — serve/router.py;
an injected fault reads as a connection failure and burns the
one-retry-on-next-replica budget), and ``backend.probe`` (each active
health probe the fleet prober sends — a fault reads as a failed probe
and feeds the breaker's passive signal).

Rule shapes:

- count rules fail the first N matching checks (``spacing=1``, the
  legacy ``FaultInjector`` semantics), or every K-th matching check
  until N faults fired (``spacing=K`` — isolated transients that a
  bounded retry policy absorbs one at a time);
- probability rules fire when a seeded hash of the check index lands
  under ``p`` (still fully deterministic for a given seed).

Checks are injected *before* the guarded operation touches anything, so
a retried operation never half-executed: retrying after an injected
fault is idempotent by construction.

Configuration: programmatic (``FaultPlane`` + ``install``), the CLI
``--chaos SPEC`` flag, or the ``HEATMAP_TPU_CHAOS`` env var; see
``parse_spec`` for the grammar. Every fired fault is recorded via
``obs.record_fault`` (a ``fault_injected`` event + the
``faults_injected_total{site}`` counter). With a flight recorder
installed that event also tail-promotes the ambient trace out of the
ring (obs/recorder.py) and feeds the incident manager's fault-storm
detector (obs/incident.py) — no per-site wiring here.
"""

from __future__ import annotations

import hashlib
import os
import threading

ENV_VAR = "HEATMAP_TPU_CHAOS"

SITES = (
    "source.read",
    "sink.write",
    "journal.append",
    "compact.publish",
    "shard.compute",
    "tile.render",
    "http.request",
    "multihost.heartbeat",
    "ingest.tick",
    "ingest.publish",
    "ingest.synopsis",
    "feeder.put",
    "elastic.reassign",
    "router.forward",
    "backend.probe",
    "tilefs.read",
    "diskcache.write",
    "writeplane.append",
    "writeplane.publish",
    "writeplane.rebalance",
)
_SITE_SET = frozenset(SITES)


class InjectedFault(RuntimeError):
    """A fault fired by the injection plane (transient by design).

    ``trace_id`` is the ambient trace at injection time (None with
    tracing off): the exception a retry layer logs and the
    ``fault_injected`` event — which obs.events stamps with the same
    identity — point at the same span tree.
    """

    def __init__(self, site: str, key=None, seq: int = 0):
        self.site = site
        self.key = key
        self.seq = seq
        from heatmap_tpu.obs import tracing

        ids = tracing.current_ids()
        self.trace_id = ids[0] if ids else None
        at = f"{site}@{key}" if key is not None else site
        super().__init__(f"injected fault #{seq} at {at}")


def hash01(seed, *parts) -> float:
    """Deterministic uniform-ish float in [0, 1) from (seed, *parts).

    Shared by probability rules and the retry jitter so a chaos run is a
    pure function of its seed — no RNG state threads through the
    pipeline.
    """
    msg = "|".join(str(p) for p in (seed, *parts)).encode()
    digest = hashlib.blake2b(msg, digest_size=8).digest()
    return int.from_bytes(digest, "big") / 2.0 ** 64


class _Rule:
    __slots__ = ("site", "key", "count", "left", "spacing", "prob", "checks")

    def __init__(self, site, key, count, spacing, prob):
        self.site = site
        self.key = key
        self.count = count
        self.left = count
        self.spacing = spacing
        self.prob = prob
        self.checks = 0  # matching checks seen (fired or not)

    def describe(self) -> str:
        target = self.site if self.key is None else f"{self.site}@{self.key}"
        if self.prob is not None:
            return f"{target}=p{self.prob}"
        if self.spacing != 1:
            return f"{target}={self.count}x{self.spacing}"
        return f"{target}={self.count}"


class FaultPlane:
    """A seeded, site-keyed set of fault rules with injection counters.

    ``backoff_scale`` multiplies every retry backoff computed while this
    plane is installed (``faults.retry``); chaos tests set it to 0 so
    hundreds of injected faults retry without sleeping.
    """

    def __init__(self, seed: int = 0, backoff_scale: float = 1.0):
        self.seed = int(seed)
        self.backoff_scale = float(backoff_scale)
        self._lock = threading.Lock()
        self._rules: list = []
        self._counts: dict = {}
        self._seq = 0

    def add_rule(self, site: str, *, count: int | None = None,
                 prob: float | None = None, key=None, spacing: int = 1):
        """Register one rule; exactly one of count/prob must be given."""
        if site not in _SITE_SET:
            raise ValueError(f"unknown fault site {site!r}; "
                             f"valid sites: {', '.join(SITES)}")
        if (count is None) == (prob is None):
            raise ValueError("exactly one of count= or prob= is required")
        if count is not None and count < 1:
            raise ValueError("count must be >= 1")
        if prob is not None and not 0.0 < prob <= 1.0:
            raise ValueError("prob must be in (0, 1]")
        if spacing < 1:
            raise ValueError("spacing must be >= 1")
        with self._lock:
            self._rules.append(_Rule(site, key, count, spacing, prob))
        return self

    def check(self, site: str, key=None):
        """Raise InjectedFault if a rule fires for this (site, key) check."""
        if site not in _SITE_SET:
            raise ValueError(f"unknown fault site {site!r}")
        fired = None
        with self._lock:
            for rule in self._rules:
                if rule.site != site:
                    continue
                if rule.key is not None and (
                        key is None or str(rule.key) != str(key)):
                    continue
                n = rule.checks
                rule.checks += 1
                if rule.prob is not None:
                    if hash01(self.seed, site, rule.key, key, n) >= rule.prob:
                        continue
                else:
                    if rule.left <= 0 or n % rule.spacing:
                        continue
                    rule.left -= 1
                fired = (self._seq, rule.describe())
                self._seq += 1
                self._counts[site] = self._counts.get(site, 0) + 1
                break
        if fired is not None:
            seq, rule_desc = fired
            from heatmap_tpu import obs

            obs.record_fault(site, seq, key=key, rule=rule_desc)
            raise InjectedFault(site, key, seq)

    @property
    def injected(self) -> int:
        """Total faults fired so far."""
        with self._lock:
            return self._seq

    def counts(self) -> dict:
        """Faults fired per site, ``{site: n}`` (only sites that fired)."""
        with self._lock:
            return dict(self._counts)


def parse_spec(spec: str) -> FaultPlane:
    """Build a FaultPlane from a comma-separated spec string.

    Grammar (tokens joined by ","):

    - ``seed=S``        plane seed (jitter + probability rules)
    - ``scale=F``       retry-backoff multiplier (0 = no sleeps)
    - ``SITE=N``        fail the first N checks at SITE
    - ``SITE=NxK``      fire N faults, one every K-th check
    - ``SITE=pP``       fire each check with probability P (seeded)
    - ``SITE@KEY=...``  same rule shapes, scoped to one key

    Example: ``seed=7,scale=0,source.read=40x3,tile.render=p0.25``.
    """
    seed, scale, rules = 0, 1.0, []
    for token in spec.split(","):
        token = token.strip()
        if not token:
            continue
        name, sep, value = token.partition("=")
        if not sep or not value:
            raise ValueError(f"bad chaos token {token!r} (want name=value)")
        if name == "seed":
            seed = int(value)
            continue
        if name == "scale":
            scale = float(value)
            continue
        site, _, key = name.partition("@")
        key = key or None
        if value.startswith("p"):
            rules.append(dict(site=site, key=key, prob=float(value[1:])))
        elif "x" in value:
            count, _, spacing = value.partition("x")
            rules.append(dict(site=site, key=key, count=int(count),
                              spacing=int(spacing)))
        else:
            rules.append(dict(site=site, key=key, count=int(value)))
    plane = FaultPlane(seed=seed, backoff_scale=scale)
    for rule in rules:
        plane.add_rule(rule.pop("site"), **rule)
    return plane


_plane: FaultPlane | None = None


def install(plane: FaultPlane | None):
    """Install (or clear, with None) the process-wide fault plane."""
    global _plane
    _plane = plane


def get_plane() -> FaultPlane | None:
    return _plane


def check(site: str, key=None):
    """Module-level check: one global read when no plane is installed."""
    plane = _plane
    if plane is not None:
        plane.check(site, key)


def install_spec(spec: str) -> FaultPlane:
    """Parse + install; returns the new plane."""
    plane = parse_spec(spec)
    install(plane)
    return plane


def install_from_env(cli_spec: str | None = None) -> FaultPlane | None:
    """Install from an explicit --chaos spec, else ``HEATMAP_TPU_CHAOS``.

    No-op (returns the current plane, usually None) when neither is set.
    """
    spec = cli_spec if cli_spec is not None else os.environ.get(ENV_VAR)
    if not spec:
        return _plane
    return install_spec(spec)
