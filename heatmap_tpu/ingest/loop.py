"""Continuous ingest: source -> bounded queue -> journaled apply -> publish.

One loop replaces the streaming-ticks-vs-delta-applies split (ROADMAP
"unify streaming.py with the delta engine"): a producer thread pulls
micro-batches from any ``io/sources.py`` source into a bounded queue
(a full queue blocks the producer — back-pressure, so an unbounded
source can never outrun the apply path), and the consumer runs one
**tick** per micro-batch:

1. journal + apply through the ordinary cascade (``delta.apply_batch``
   — exactly-once by content hash, so a retried or replayed tick is an
   idempotent no-op),
2. publish to a live serve store via ``delta.refresh_serving``
   (targeted invalidation, no generation bump),
3. compact the delta stack when the size/age policy says so.

The whole point of a standing loop is small batches, and small batches
are compile-bound (ROADMAP; BENCH_delta.json) — so ``run_ingest``
defaults the job config to the bucketed-padding compile cache
(``pipeline/bucketing.py``): arbitrary micro-batch sizes reuse one
cascade compilation per bucket.

The loop is a first-class citizen of the existing planes:

- obs: event-time watermark + ingest-to-servable lag on the registry
  (``ingest/metrics.py``), one ``ingest_tick`` event per tick, and the
  ``staleness`` SLO kind tracks tick recency (obs/slo.py).
- tracing: every tick is a span (root-on-demand under a CLI root).
- faults: ticks and publishes run under the ``ingest.tick`` /
  ``ingest.publish`` sites with their retry policies; both operations
  are idempotent end to end, which is what makes retrying the whole
  tick safe. Crash mid-tick heals byte-identical through
  ``delta/recover.py`` on the next apply's startup sweep. The
  host->device feeder (``pipeline/feeder.py``, ``feed_depth``) runs
  each transfer under ``feeder.put`` — a re-fed batch is idempotent by
  the same content-hash contract.

**Early serving** (docs/synopsis.md): before the exact apply, a tick
overlays the micro-batch's coarse cell counts onto the store's decoded
wavelet-synopsis views (``TileStore.publish_provisional``) under the
``ingest.synopsis`` fault site — a cheap numpy projection, no cascade.
``?synopsis=1`` tiles reflect the batch immediately, marked
``stale=1``, until the exact apply's ``refresh_serving`` supersedes
them. The publish is best-effort by contract: a terminal failure is
swallowed (the exact path is unaffected), and a duplicate tick's
overlay is discarded by an immediate ``refresh_layers``.

Timestamps: event time comes from the batches' ``timestamp`` column
(the watermark); loop durations use ``time.monotonic()``. Wall-clock
sleeps, prints, and perf_counter are banned here by the obs grep
guards — blocking happens only inside queue waits.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue as queue_mod
import threading
import time

from heatmap_tpu import faults, obs
from heatmap_tpu.obs import recorder as recorder_mod
from heatmap_tpu.obs import timeseries, tracing

_DONE = object()  # producer -> consumer end-of-stream sentinel
_POLL_S = 0.05    # producer put/abort poll interval (bounded wait, not a sleep)


@dataclasses.dataclass(frozen=True)
class TickContext:
    """Per-tick metadata ``run_ticks`` hands the tick callback."""

    index: int          #: 0-based tick number
    enqueued_at: float  #: time.monotonic() when the producer queued it
    queue_depth: int    #: items still waiting behind this one at dequeue


def run_ticks(items, tick, *, queue_depth: int | None = None,
              name: str = "ingest") -> dict:
    """Drive ``tick(item, ctx)`` over an iterable, optionally through a
    bounded producer/consumer queue.

    ``name`` labels the producer thread (``{name}-producer``) so
    multi-loop processes — the write plane's router runs this same
    loop per plane (writeplane/pumps.py) — stay tellable apart in
    stack dumps and the flight recorder.

    ``queue_depth=None`` runs synchronously in the calling thread (the
    legacy ``streaming.run_stream`` cadence). With a depth, a producer
    thread reads ``items`` into a ``queue.Queue(maxsize=depth)`` while
    ticks run here: at most ``depth`` micro-batches wait in memory and
    a slow consumer blocks the producer — the back-pressure bound
    (pinned in tests/test_ingest.py). Producer exceptions re-raise in
    the caller after in-flight ticks finish; a tick exception unblocks
    and stops the producer before propagating.

    Returns ``{"ticks": n, "max_queue_depth": m}`` where ``m`` is the
    largest resident backlog observed at any dequeue.
    """
    stats = {"ticks": 0, "max_queue_depth": 0}
    if queue_depth is None:
        for i, item in enumerate(items):
            tick(item, TickContext(i, time.monotonic(), 0))
            stats["ticks"] += 1
        return stats
    if queue_depth < 1:
        raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
    q: queue_mod.Queue = queue_mod.Queue(maxsize=queue_depth)
    abort = threading.Event()
    producer_error: list = []

    def _produce():
        try:
            payloads = ((item, time.monotonic()) for item in items)
            for payload in itertools.chain(payloads, (_DONE,)):
                while not abort.is_set():
                    try:
                        q.put(payload, timeout=_POLL_S)
                        break
                    except queue_mod.Full:
                        continue
                if abort.is_set():
                    return
        except BaseException as e:  # re-raised in the consumer
            producer_error.append(e)
            abort.set()

    producer = threading.Thread(
        target=_produce, name=f"{name}-producer", daemon=True)
    producer.start()
    try:
        index = 0
        while True:
            try:
                got = q.get(timeout=_POLL_S)
            except queue_mod.Empty:
                if abort.is_set():
                    break
                continue
            if got is _DONE:
                break
            item, enqueued_at = got
            backlog = q.qsize()
            stats["max_queue_depth"] = max(
                stats["max_queue_depth"], backlog + 1)
            tick(item, TickContext(index, enqueued_at, backlog))
            stats["ticks"] += 1
            index += 1
    finally:
        abort.set()
        producer.join(timeout=5.0)
    if producer_error:
        raise producer_error[0]
    return stats


@dataclasses.dataclass(frozen=True)
class IngestConfig:
    """Loop parameters (the job/pyramid config stays a BatchJobConfig)."""

    #: Points per micro-batch (the tick granularity).
    micro_batch: int = 1 << 14
    #: Bounded-queue depth (back-pressure bound). None = synchronous:
    #: no producer thread, read-next-batch happens between ticks.
    queue_depth: int | None = 4
    #: +1 inserts, -1 retracts every batch (journal-signed).
    sign: int = 1
    #: Compact when this many live (unfolded) deltas accumulate.
    #: 0 disables size-triggered compaction.
    compact_every: int = 16
    #: Compact when the oldest live delta is older than this many
    #: seconds (monotonic, measured from its apply). 0 disables.
    compact_max_age_s: float = 0.0
    #: Journal entries kept behind the fold (delta.compact retention).
    retention: int = 2
    #: Stop after this many ticks (None = drain the source).
    max_ticks: int | None = None
    #: Publish a provisional synopsis overlay before each exact apply
    #: (no-op when the serve store carries no synopsis views).
    provisional_synopsis: bool = True
    #: Host->device feeder depth (pipeline/feeder.py): micro-batch k+1's
    #: numeric columns transfer to the device while tick k computes,
    #: with at most this many fed batches resident ahead of the apply
    #: loop. 0 disables the feeder (columns transfer synchronously
    #: inside each tick). Byte-identical either way.
    feed_depth: int = 1

    def __post_init__(self):
        if self.micro_batch < 1:
            raise ValueError(
                f"micro_batch must be >= 1, got {self.micro_batch}")
        if self.sign not in (1, -1):
            raise ValueError("sign must be +1 (insert) or -1 (retraction)")
        if self.compact_every < 0 or self.compact_max_age_s < 0:
            raise ValueError("compaction thresholds must be >= 0")
        if self.feed_depth < 0:
            raise ValueError(
                f"feed_depth must be >= 0, got {self.feed_depth}")


@dataclasses.dataclass
class IngestStats:
    """Outcome of one ``run_ingest`` drain."""

    ticks: int = 0
    points: int = 0
    duplicates: int = 0
    epochs: list = dataclasses.field(default_factory=list)
    watermark: float | None = None
    max_queue_depth: int = 0
    compactions: int = 0
    keys_invalidated: int = 0
    seconds: float = 0.0
    #: Feeder outcome (zeros / 100.0 with feed_depth=0): worker seconds
    #: spent in host->device transfer, consumer seconds blocked waiting
    #: for a fed batch, share of transfer time hidden behind compute,
    #: and the high-water mark of fed batches resident ahead.
    feed_s: float = 0.0
    feed_wait_s: float = 0.0
    feed_overlap_pct: float = 100.0
    feeder_depth_hwm: int = 0


def _provisional_rows(store, cols, config, sign: int) -> dict:
    """Coarse cell rows for the serve store's synopsis zooms, computed
    from one micro-batch: ``{(user, timespan): {zoom: (rows, cols,
    values)}}`` in the shape ``TileStore.publish_provisional`` takes.

    A cheap host-side shadow of the cascade's grouping (route_user /
    'all' aggregation / timespan labels) — exact for the counts it
    covers, best-effort by contract: zooms with no synopsis view,
    timespan types the batch cannot label, and the ``amplify_all``
    compat recurrence (not reproducible per-batch) all fall out as
    empty, and the exact apply supersedes everything it publishes.
    """
    targets: dict[tuple, list] = {}
    for name in store.layer_names():
        layer = store.layer(name)
        syn = getattr(layer, "synopses", None)
        if syn:
            targets[(layer.user, layer.timespan)] = sorted(syn)
    if not targets or getattr(config, "amplify_all", False):
        return {}
    import numpy as np

    from heatmap_tpu.pipeline import groups, timespan
    from heatmap_tpu.tilemath.mercator import project_points_np

    lat = np.asarray(cols.get("latitude", ()), np.float64)
    n = len(lat)
    if n == 0:
        return {}
    lon = np.asarray(cols["longitude"], np.float64)
    user_ids = cols.get("user_id") or [""] * n
    routed = np.empty(n, object)  # None = excluded (x-prefix)
    for i, uid in enumerate(user_ids):
        routed[i] = groups.route_user(uid)
    if getattr(config, "weighted", False) and cols.get("value") is not None:
        weights = np.asarray(cols["value"], np.float64) * float(sign)
    else:
        weights = np.full(n, float(sign))
    vocab = timespan.TimespanVocab()
    label_cols = []
    stamps = cols.get("timestamp")
    for ts_type in getattr(config, "timespans", ("alltime",)):
        try:
            label_cols.append(vocab.label_ids(
                ts_type, stamps if stamps is not None else [None] * n))
        except (TypeError, ValueError):
            continue  # dated type without usable timestamps
        if getattr(config, "first_timespan_only", False):
            break
    if not label_cols:
        return {}
    umasks = {}
    for user, _ in targets:
        if user not in umasks:
            if user == groups.ALL_NAME:
                umasks[user] = np.array([r is not None for r in routed])
            else:
                umasks[user] = routed == user
    out: dict[tuple, dict] = {}
    zooms = sorted({z for zs in targets.values() for z in zs})
    for zoom in zooms:
        rr, cc, valid = project_points_np(lat, lon, zoom)
        for (user, ts_name), pair_zooms in targets.items():
            if zoom not in pair_zooms:
                continue
            tid = vocab.id_for(ts_name)
            tmask = np.zeros(n, bool)
            for ids in label_cols:
                tmask |= ids == tid
            sel = umasks[user] & tmask & np.asarray(valid, bool)
            if not sel.any():
                continue
            out.setdefault((user, ts_name), {})[zoom] = (
                np.asarray(rr, np.int64)[sel],
                np.asarray(cc, np.int64)[sel],
                weights[sel])
    return out


def _roll_windows(root: str, cache, edge_holder: list) -> int:
    """Targeted sliding-window invalidation on a bucket roll.

    A ``?window=`` tile's population changes for exactly two reasons:
    new points inside the window (refresh_serving already invalidates
    those keys, window variants included) and old buckets RETIRING off
    the window's trailing edge when the newest bucket edge advances.
    This handles the second: when the reference edge moves, invalidate
    precisely the retiring buckets' tile keys x the served window
    params — every other cached entry (all-time, as_of, untouched
    windows) survives, which tests/test_temporal.py pins.

    Best-effort by design: a torn bucket here means those keys go
    un-invalidated until their TTL, never a failed tick."""
    try:
        from heatmap_tpu.temporal import buckets as tb
        from heatmap_tpu.temporal import fold as tfold
        cfg = tfold.temporal_config(root)
        if cfg is None:
            return 0
        ref = tfold.newest_edge(root, cfg)
    except Exception:
        return 0
    if ref is None:
        return 0
    prev = edge_holder[0] if edge_holder else None
    edge_holder[:] = [ref]
    if prev is None or ref <= prev:
        return 0
    params = cache.window_params() if cache is not None else ()
    n = 0
    retired = 0
    if params:
        from heatmap_tpu.delta.compute import affected_tile_keys
        from heatmap_tpu.io.sinks import LevelArraysSink
        windows = []
        for p in params:
            try:
                windows.append(tb.parse_window(p, cfg))
            except ValueError:
                continue
        dirs = tfold.retiring_dirs(root, prev, ref, windows)
        retired = len(dirs)
        keys: set = set()
        for d in dirs:
            try:
                keys.update(affected_tile_keys(LevelArraysSink.load(d)))
            except Exception:
                continue
        if keys:
            n = cache.invalidate_keys(
                tfold.window_variants(sorted(keys), params))
    obs.emit("bucket_roll", root=root, prev_ref=prev, ref=ref,
             retired=retired, keys_invalidated=n,
             windows=list(params))
    return n


def _event_watermark(cols) -> float | None:
    """Max event-time timestamp of a column batch (None when absent)."""
    stamps = cols.get("timestamp")
    if stamps is None or not len(stamps):
        return None
    try:
        return max(float(t) for t in stamps if t is not None)
    except (TypeError, ValueError):
        return None


def run_ingest(root: str, source, config=None, *,
               ingest: IngestConfig | None = None,
               store=None, cache=None) -> IngestStats:
    """Drain ``source`` through the continuous-ingest loop into the
    delta store at ``root``, publishing to ``store``/``cache`` (a live
    ``serve.TileStore`` mounted on this root's ``delta:`` spec) when
    given.

    ``config=None`` defaults to ``BatchJobConfig(pad_bucketing="pow2")``
    — the loop exists for small batches and small batches are
    compile-bound, so the bucketed compile cache is on unless the
    caller explicitly opts out. Safe to restart after any crash: the
    journal's content hashes make every tick exactly-once, and the
    recovery sweep inside ``apply_batch`` quarantines torn state first.
    """
    from heatmap_tpu import delta as delta_mod
    from heatmap_tpu.ingest import metrics as ingest_metrics
    from heatmap_tpu.pipeline import BatchJobConfig

    ing = ingest or IngestConfig()
    if config is None:
        config = BatchJobConfig(pad_bucketing="pow2")
    stats = IngestStats()
    t_loop = time.monotonic()
    # Monotonic clock of the oldest live delta, for the age trigger.
    oldest_live: list = []
    # Last-seen newest bucket edge (temporal plane): a roll past it
    # retires window tiles via _roll_windows' targeted invalidation.
    bucket_edge: list = []
    metrics_on = obs.metrics_enabled()

    def _tick(cols, ctx: TickContext):
        t0 = time.monotonic()
        with tracing.span("ingest.tick", tick=ctx.index):
            provisional = 0
            if store is not None and ing.provisional_synopsis:
                def _early():
                    rows_by = _provisional_rows(store, cols, config,
                                                ing.sign)
                    return store.publish_provisional(rows_by)

                # Best-effort early serving: a terminal failure here
                # must not cost the tick its exact apply.
                try:
                    provisional = faults.retry_call(
                        _early, site="ingest.synopsis", key=ctx.index)
                except Exception:
                    provisional = 0

            def _apply():
                return delta_mod.apply_batch(
                    root, delta_mod.ColumnsSource(cols), config,
                    sign=ing.sign)

            result = faults.retry_call(
                _apply, site="ingest.tick", key=ctx.index)
            invalidated = 0
            if store is not None and result.duplicate and provisional:
                # The overlay double-counted an already-applied batch;
                # rebuilding the index discards every provisional view.
                store.refresh_layers()
            if store is not None and not result.duplicate:
                invalidated = faults.retry_call(
                    delta_mod.refresh_serving, result, store, cache,
                    site="ingest.publish", key=ctx.index)
            if cache is not None and not result.duplicate:
                invalidated += _roll_windows(root, cache, bucket_edge)
            compacted = False
            if not result.duplicate:
                if not oldest_live:
                    oldest_live.append(t0)
                live = (ing.compact_every or ing.compact_max_age_s) and \
                    len(delta_mod.live_entries(root))
                due_size = ing.compact_every and live >= ing.compact_every
                due_age = (ing.compact_max_age_s and live and
                           time.monotonic() - oldest_live[0]
                           >= ing.compact_max_age_s)
                if due_size or due_age:
                    delta_mod.compact(root, retention=ing.retention)
                    oldest_live.clear()
                    compacted = True
                    stats.compactions += 1
                    if store is not None:
                        # Compaction is byte-neutral (base ⊕ deltas
                        # pinned identical), so re-point the overlay
                        # without dropping any cache entries.
                        store.refresh_layers()
        seconds = time.monotonic() - t0
        # Tail-based retention: a tick past the recorder's latency
        # threshold promotes its whole (possibly unsampled) tree out
        # of the flight recorder as if it had been head-sampled.
        recorder_mod.maybe_promote(ms=seconds * 1e3)
        lag = max(0.0, time.monotonic() - ctx.enqueued_at)
        wm = _event_watermark(cols)
        if wm is not None and (stats.watermark is None
                               or wm > stats.watermark):
            stats.watermark = wm  # monotonic under out-of-order batches
        stats.ticks += 1
        stats.points += result.points if not result.duplicate else 0
        stats.keys_invalidated += invalidated
        if result.duplicate:
            stats.duplicates += 1
        else:
            stats.epochs.append(result.epoch)
        if metrics_on:
            ingest_metrics.INGEST_TICKS.inc(
                status="duplicate" if result.duplicate else "applied")
            if not result.duplicate:
                ingest_metrics.INGEST_POINTS.inc(result.points)
            if stats.watermark is not None:
                ingest_metrics.INGEST_WATERMARK.set(stats.watermark)
            ingest_metrics.INGEST_QUEUE_DEPTH.set(ctx.queue_depth)
            ingest_metrics.INGEST_LAG_SECONDS.observe(lag)
            ingest_metrics.INGEST_TICK_SECONDS.observe(seconds)
        obs.emit("ingest_tick", tick=ctx.index, points=result.points,
                 seconds=round(seconds, 6), epoch=result.epoch,
                 duplicate=result.duplicate, watermark=stats.watermark,
                 lag_s=round(lag, 6), queue_depth=ctx.queue_depth,
                 keys_invalidated=invalidated, compacted=compacted)

    batches = source.batches(ing.micro_batch)
    if ing.max_ticks is not None:
        batches = itertools.islice(batches, ing.max_ticks)
    fstats = None
    if ing.feed_depth:
        # Double-buffered host->device feeder: batch k+1's numeric
        # columns transfer while tick k journals/applies/publishes.
        # Order-preserving, so journal epochs and content hashes are
        # identical to the unfed drain (the hash reads values, and the
        # feeder moves buffers, never values).
        from heatmap_tpu.pipeline import feeder as feeder_mod

        fstats = feeder_mod.FeederStats()
        batches = feeder_mod.feed(
            batches, feeder_mod.device_put_columns,
            depth=ing.feed_depth, stats=fstats,
            thread_name="ingest-feeder")
    with tracing.span("ingest.loop"):
        try:
            pump = run_ticks(batches, _tick, queue_depth=ing.queue_depth)
        finally:
            # Crash-safe telemetry: persist the sampled history so far
            # (atomic publish, obs/timeseries.py) even when a tick
            # raised — the post-mortem wants the lag/tick-latency
            # trend leading up to the failure. No-op with the sampler
            # off or without a spill dir.
            timeseries.flush_spill()
    stats.max_queue_depth = pump["max_queue_depth"]
    stats.seconds = time.monotonic() - t_loop
    if fstats is not None:
        stats.feed_s = fstats.feed_s
        stats.feed_wait_s = fstats.wait_s
        stats.feed_overlap_pct = fstats.overlap_pct
        stats.feeder_depth_hwm = fstats.depth_hwm
    return stats
