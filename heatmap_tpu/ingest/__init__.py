"""Continuous-ingest subsystem: source -> journal -> cascade -> tiles.

``loop.py`` owns the bounded-queue pump and the tick loop
(``run_ingest`` is the entry; ``run_ticks`` is the shared pump the
legacy ``streaming.run_stream`` driver also delegates to);
``metrics.py`` the watermark/lag/queue handles on the obs registry.
Architecture, cost model, and the soak runbook live in docs/ingest.md.
"""

from heatmap_tpu.ingest.loop import (IngestConfig, IngestStats,
                                     TickContext, run_ingest, run_ticks)
from heatmap_tpu.ingest.metrics import (INGEST_LAG_SECONDS, INGEST_POINTS,
                                        INGEST_QUEUE_DEPTH, INGEST_TICKS,
                                        INGEST_TICK_SECONDS,
                                        INGEST_WATERMARK,
                                        record_stream_tick)

__all__ = [
    "INGEST_LAG_SECONDS", "INGEST_POINTS", "INGEST_QUEUE_DEPTH",
    "INGEST_TICKS", "INGEST_TICK_SECONDS", "INGEST_WATERMARK",
    "IngestConfig", "IngestStats", "TickContext", "record_stream_tick",
    "run_ingest", "run_ticks",
]
