"""Ingest-loop metric handles on the shared obs registry.

Module-level, created once at import (the delta/metrics.py pattern):
handles survive ``registry.reset()`` between tests and self-gate on
``registry.enabled``, so call sites pay one boolean when metrics are
off. Semantics are documented in docs/observability.md.
"""

from __future__ import annotations

from heatmap_tpu import obs

_registry = obs.get_registry()

INGEST_TICKS = _registry.counter(
    "ingest_ticks_total",
    "Continuous-ingest ticks completed (one micro-batch journaled, "
    "applied, published)",
    labelnames=("status",))  # status = applied | duplicate
INGEST_POINTS = _registry.counter(
    "ingest_points_total",
    "Points consumed by the continuous-ingest loop")
INGEST_WATERMARK = _registry.gauge(
    "ingest_watermark",
    "Event-time watermark: monotonic max of applied batch timestamps "
    "(event-time seconds, NOT wall clock)")
INGEST_QUEUE_DEPTH = _registry.gauge(
    "ingest_queue_depth",
    "Micro-batches waiting in the bounded queue at last dequeue")
INGEST_LAG_SECONDS = _registry.histogram(
    "ingest_lag_seconds",
    "Ingest-to-servable lag: micro-batch enqueue to publish complete",
    buckets=(0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0))
INGEST_TICK_SECONDS = _registry.histogram(
    "ingest_tick_seconds",
    "Wall-clock of one ingest tick (journal + cascade apply + publish)",
    buckets=(0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0))


def record_stream_tick(t: float):
    """Per-tick telemetry for the legacy streaming driver.

    Keeps the historical ``stream_decay_ticks_total`` /
    ``stream_time_seconds`` semantics (pinned in tests/test_obs.py) now
    that ``streaming.default_stream_hook`` is a shim over the unified
    loop. No-op unless a metrics sink is enabled.
    """
    if not obs.metrics_enabled():
        return
    obs.STREAM_TICKS.inc()
    obs.STREAM_TIME.set(float(t))
