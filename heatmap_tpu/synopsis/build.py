"""Top-B wavelet synopses of coarse pyramid levels.

``write_synopses`` turns every ``level_z*.npz`` below ``max_z`` in a
level directory into a ``synopsis-z{zoom:02d}.npz`` sitting alongside
it: per (user, timespan) pair, the top-B Haar coefficients of the
dense per-cell count grid by absolute value, plus the ACHIEVED L-inf
reconstruction error stamped into the artifact header.

Error contract (docs/synopsis.md): the stamped ``max_err`` is computed
at build time as ``max|inv_haar(kept) - grid|`` — not an analytic
upper bound but the exact worst cell error, measured after the same
f64 inverse transform the serving decoder runs. Decoding is
deterministic, so every decoded cell differs from the exact count by
<= the stamp, with equality somewhere. ``b=None`` picks
``default_b(nnz)`` per pair; ``b=math.inf`` keeps every nonzero
coefficient, which round-trips integer grids bit-exact (see
transform.py on why unnormalized Haar makes that true).

Artifact schema ``heatmap-tpu.synopsis.v1`` (compressed npz):
scalars ``zoom``/``coarse_zoom``/``n`` (grid side ``2**zoom``), per-pair
``users``/``timespans``/``b``/``max_err``/``offsets`` (CSR-style,
``n_pairs + 1``), and flat ``idx`` (int64 row-major coefficient index)
/ ``val`` (f64) slabs. Writes are atomic (tmp + os.replace) under the
``sink.write`` retry site, the same publish discipline as the exact
level files — a torn synopsis can only be a crash artifact, which the
delta recovery sweep quarantines (delta/recover.py).

Numpy-only: this module sits on the serve tier's decode path.
"""

from __future__ import annotations

import math
import os
import zipfile

import numpy as np

from heatmap_tpu import faults, obs
from heatmap_tpu.synopsis.transform import (grid_from_rows_np, haar2d_np,
                                            inv_haar2d_np)

__all__ = [
    "DEFAULT_MAX_Z", "HARD_MAX_Z", "SCHEMA", "default_b", "build_pair",
    "decode_pair", "write_synopses", "load_synopses", "synopsis_path",
    "verify_synopsis", "SynopsisPair",
]

SCHEMA = "heatmap-tpu.synopsis.v1"

#: Levels with zoom < DEFAULT_MAX_Z get a synopsis; finer levels stay
#: exact-only (their grids are big and their tiles are the leaf detail
#: users zoom into — bounded error is a coarse-overview trade).
DEFAULT_MAX_Z = 10

#: Refusal ceiling: a 2**HARD_MAX_Z square f64 grid is 128 MiB per
#: (user, timespan) pair — beyond this the dense transform is the
#: wrong tool and the caller gets a loud error, not an OOM.
HARD_MAX_Z = 12


def default_b(nnz: int) -> int:
    """Default coefficient budget for a pair with ``nnz`` occupied
    cells: an 8:1 cell-to-coefficient ratio, floored so tiny pairs
    keep enough structure to be useful."""
    return max(16, int(nnz) // 8)


class SynopsisPair:
    """One (user, timespan) slice of one level's synopsis."""

    __slots__ = ("user", "timespan", "zoom", "n", "b", "max_err", "idx",
                 "val")

    def __init__(self, user, timespan, zoom, n, b, max_err, idx, val):
        self.user = str(user)
        self.timespan = str(timespan)
        self.zoom = int(zoom)
        self.n = int(n)
        self.b = int(b)
        self.max_err = float(max_err)
        self.idx = np.asarray(idx, np.int64)
        self.val = np.asarray(val, np.float64)

    def decode(self, extra_rows=None) -> np.ndarray:
        """Dense ``(n, n)`` decoded count grid; ``extra_rows`` is an
        optional ``(rows, cols, values)`` triple scatter-added ON TOP
        of the decoded grid (delta overlays / provisional micro-batch
        counts). Extras are exact additions, so they never widen the
        stamped error bound."""
        grid = decode_pair(self.idx, self.val, self.n)
        if extra_rows is not None:
            rows, cols, values = extra_rows
            np.add.at(grid, (np.asarray(rows, np.int64),
                             np.asarray(cols, np.int64)),
                      np.asarray(values, np.float64))
        return grid


def build_pair(rows, cols, values, zoom: int, b=None):
    """Synopsis of one pair's level rows -> ``(idx, val, max_err)``.

    ``b=None`` -> :func:`default_b`; ``b=math.inf`` -> every nonzero
    coefficient (bit-exact round trip for integer grids)."""
    if zoom > HARD_MAX_Z:
        raise ValueError(
            f"synopsis grids stop at zoom {HARD_MAX_Z} "
            f"(2^{HARD_MAX_Z} side); got zoom {zoom}")
    n = 1 << int(zoom)
    grid = grid_from_rows_np(rows, cols, values, n)
    flat = haar2d_np(grid).ravel()
    nz = np.flatnonzero(flat)
    if b is None:
        b = default_b(len(rows))
    if math.isinf(b) or b >= len(nz):
        kept = np.sort(nz)
        return kept, flat[kept], _achieved_err(grid, kept, flat[kept], n)
    # Top-B by |coefficient|, ties broken by index: lexsort's last key
    # is primary, so (-|v|, idx) gives a deterministic artifact.
    order = np.lexsort((nz, -np.abs(flat[nz])))
    kept = np.sort(nz[order[:int(b)]])
    return kept, flat[kept], _achieved_err(grid, kept, flat[kept], n)


def _achieved_err(grid, idx, val, n) -> float:
    decoded = decode_pair(idx, val, n)
    return float(np.abs(decoded - grid).max()) if n else 0.0


def decode_pair(idx, val, n: int) -> np.ndarray:
    """Serving decoder: sparse coefficients -> dense count grid."""
    coeffs = np.zeros(n * n, np.float64)
    coeffs[np.asarray(idx, np.int64)] = np.asarray(val, np.float64)
    return inv_haar2d_np(coeffs.reshape(n, n))


def synopsis_path(level_dir: str, zoom: int) -> str:
    return os.path.join(level_dir, f"synopsis-z{int(zoom):02d}.npz")


def _pair_strings(cols):
    """user/timespan string columns from a loaded OR finalized level
    dict (LevelArraysSink.load materializes strings; the finalized
    egress/merge shape carries idx + name tables)."""
    if "user" in cols:
        return np.asarray(cols["user"], str), np.asarray(
            cols["timespan"], str)
    return (np.asarray(cols["user_names"], str)[cols["user_idx"]],
            np.asarray(cols["timespan_names"], str)[cols["timespan_idx"]])


def write_synopses(level_dir: str, levels=None, *, b=None,
                   max_z: int = DEFAULT_MAX_Z) -> dict:
    """Build + atomically publish synopsis artifacts for every level
    below ``max_z`` in ``level_dir``.

    ``levels`` (``{zoom: cols}``) skips re-reading the level files when
    the caller already holds them (the egress sink and compaction do).
    Returns ``{zoom: {"pairs": n, "bytes": n, "max_err": worst}}`` and
    emits one ``synopsis_built`` event per level.
    """
    from heatmap_tpu.io.sinks import LevelArraysSink
    from heatmap_tpu.synopsis import metrics

    if levels is None:
        levels = LevelArraysSink.load(level_dir)
    out: dict = {}
    for zoom in sorted(levels):
        if int(zoom) >= max_z:
            continue
        cols = levels[zoom]
        users, tss = _pair_strings(cols)
        rows = np.asarray(cols["row"], np.int64)
        cls = np.asarray(cols["col"], np.int64)
        vals = np.asarray(cols["value"], np.float64)
        pair_key = np.char.add(np.char.add(users, "|"), tss)
        p_users, p_tss, p_b, p_err = [], [], [], []
        offsets = [0]
        idx_parts, val_parts = [], []
        for pk in np.unique(pair_key):
            sel = pair_key == pk
            user, _, ts = str(pk).partition("|")
            idx, val, max_err = build_pair(rows[sel], cls[sel], vals[sel],
                                           int(zoom), b=b)
            p_users.append(user)
            p_tss.append(ts)
            p_b.append(len(idx))
            p_err.append(max_err)
            idx_parts.append(idx)
            val_parts.append(val)
            offsets.append(offsets[-1] + len(idx))
        final = synopsis_path(level_dir, int(zoom))
        payload = {
            "schema": np.asarray(SCHEMA),
            "zoom": np.asarray(int(zoom)),
            "coarse_zoom": np.asarray(int(cols["coarse_zoom"])),
            "n": np.asarray(1 << int(zoom)),
            "users": np.asarray(p_users, str),
            "timespans": np.asarray(p_tss, str),
            "b": np.asarray(p_b, np.int64),
            "max_err": np.asarray(p_err, np.float64),
            "offsets": np.asarray(offsets, np.int64),
            "idx": (np.concatenate(idx_parts) if idx_parts
                    else np.zeros(0, np.int64)),
            "val": (np.concatenate(val_parts) if val_parts
                    else np.zeros(0, np.float64)),
        }
        tmp = final + ".tmp"

        def _publish():
            with open(tmp, "wb") as f:
                np.savez_compressed(f, **payload)
            os.replace(tmp, final)

        faults.retry_call(_publish, site="sink.write", key="synopsis")
        nbytes = os.path.getsize(final)
        worst = float(max(p_err)) if p_err else 0.0
        out[int(zoom)] = {"pairs": len(p_users), "bytes": nbytes,
                          "max_err": worst}
        if obs.metrics_enabled():
            metrics.SYNOPSIS_BYTES.inc(nbytes, level=str(int(zoom)))
            metrics.SYNOPSIS_MAX_ERROR.set(worst, level=str(int(zoom)))
        obs.emit("synopsis_built", zoom=int(zoom), pairs=len(p_users),
                 coefficients=int(offsets[-1]), bytes=nbytes,
                 max_err=worst, path=final)
    return out


def verify_synopsis(path: str) -> str | None:
    """None when ``path`` is a readable v1 synopsis artifact, else a
    fault description (the recovery sweep's quarantine detail)."""
    try:
        with np.load(path) as z:
            if str(z["schema"]) != SCHEMA:
                return f"schema {z['schema']!r} != {SCHEMA!r}"
            offsets = z["offsets"]
            if len(offsets) != len(z["users"]) + 1:
                return "offsets/users length mismatch"
            if len(z["idx"]) != int(offsets[-1]):
                return "idx shorter than offsets claim"
            len(z["val"]), len(z["b"]), len(z["max_err"])
    except (OSError, ValueError, KeyError, zipfile.BadZipFile) as e:
        return repr(e)
    return None


def load_synopses(level_dir: str) -> dict:
    """``{zoom: [SynopsisPair, ...]}`` for every readable synopsis
    artifact in ``level_dir``. Unreadable or wrong-schema files are
    SKIPPED, not raised — serving falls back to exact levels and the
    recovery sweep owns quarantining torn artifacts."""
    out: dict = {}
    try:
        names = sorted(os.listdir(level_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("synopsis-z") and name.endswith(".npz")):
            continue
        full = os.path.join(level_dir, name)
        try:
            with np.load(full) as z:
                if str(z["schema"]) != SCHEMA:
                    continue
                zoom = int(z["zoom"])
                n = int(z["n"])
                users = z["users"]
                tss = z["timespans"]
                bs = z["b"]
                errs = z["max_err"]
                offsets = z["offsets"]
                idx = z["idx"]
                val = z["val"]
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            continue
        pairs = []
        for i in range(len(users)):
            lo, hi = int(offsets[i]), int(offsets[i + 1])
            pairs.append(SynopsisPair(users[i], tss[i], zoom, n,
                                      bs[i], errs[i], idx[lo:hi],
                                      val[lo:hi]))
        out[zoom] = pairs
    return out
