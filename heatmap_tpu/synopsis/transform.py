"""2D Haar transform twins: a jit-compatible JAX forward for the
cascade path and a numpy-only decoder for serving.

The transform is the UNNORMALIZED integer Haar: per 2x2 block
``(a b / c d)`` one scale pass emits

    approx = a + b + c + d        (top-left quadrant)
    dh     = a - b + c - d        (top-right: horizontal detail)
    dv     = a + b - c - d        (bottom-left: vertical detail)
    dd     = a - b - c + d        (bottom-right: diagonal detail)

and recurses on the approx quadrant (the standard square arrangement).
The inverse divides by 4 per pass. Both directions are EXACT in binary
f64 for integer-valued grids below 2^53: sums/differences of integers
round-trip bit-exact, and /4 is a power-of-two scale — this is what
makes a full-coefficient (B=inf) synopsis byte-identical to the exact
level (docs/synopsis.md). Orthonormal Haar (the 1/sqrt(2) flavour)
would lose that, which is why it is not used here.

Layering contract: this module must be importable from the serve tier,
so jax is only imported INSIDE the ``*_jax`` functions (the same lazy
idiom as ``obs.device_topology``). tests/test_obs.py greps for it.

The JAX forward is jit-compatible — ``n`` is static, the scale loop is
a Python loop over static slice shapes — and composes with the
bucketed-compile cascade path: :func:`grid_from_rows_jax` scatter-adds
emission rows under their ``valid`` mask, so the zero-weight pad lanes
``pipeline.bucketing.pad_emissions`` appends are byte-neutral and one
compilation serves every batch in a bucket.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "haar2d_np", "inv_haar2d_np", "haar2d_jax", "grid_from_rows_jax",
    "grid_from_rows_np", "haar1d_np", "inv_haar1d_np",
]


def _check_grid(grid) -> int:
    n = int(grid.shape[-1])
    if grid.ndim != 2 or grid.shape[0] != n:
        raise ValueError(f"haar2d wants a square 2D grid, got {grid.shape}")
    if n & (n - 1):
        raise ValueError(f"haar2d wants a power-of-two side, got {n}")
    return n


def haar2d_np(grid: np.ndarray) -> np.ndarray:
    """Full 2D Haar transform of a square power-of-two grid (f64)."""
    n = _check_grid(grid)
    out = np.asarray(grid, np.float64).copy()
    h = n // 2
    while h >= 1:
        a = out[0:2 * h:2, 0:2 * h:2].copy()
        b = out[0:2 * h:2, 1:2 * h:2].copy()
        c = out[1:2 * h:2, 0:2 * h:2].copy()
        d = out[1:2 * h:2, 1:2 * h:2].copy()
        out[:h, :h] = a + b + c + d
        out[:h, h:2 * h] = a - b + c - d
        out[h:2 * h, :h] = a + b - c - d
        out[h:2 * h, h:2 * h] = a - b - c + d
        h //= 2
    return out


def inv_haar2d_np(coeffs: np.ndarray) -> np.ndarray:
    """Inverse of :func:`haar2d_np` (numpy only: the serving decoder)."""
    n = _check_grid(coeffs)
    out = np.asarray(coeffs, np.float64).copy()
    h = 1
    while h < n:
        s = out[:h, :h].copy()
        dh = out[:h, h:2 * h].copy()
        dv = out[h:2 * h, :h].copy()
        dd = out[h:2 * h, h:2 * h].copy()
        out[0:2 * h:2, 0:2 * h:2] = (s + dh + dv + dd) / 4.0
        out[0:2 * h:2, 1:2 * h:2] = (s - dh + dv - dd) / 4.0
        out[1:2 * h:2, 0:2 * h:2] = (s + dh - dv - dd) / 4.0
        out[1:2 * h:2, 1:2 * h:2] = (s - dh - dv + dd) / 4.0
        h *= 2
    return out


def _check_series(series) -> int:
    n = int(series.shape[-1])
    if n & (n - 1) or n == 0:
        raise ValueError(f"haar1d wants a power-of-two length, got {n}")
    return n


def haar1d_np(series: np.ndarray) -> np.ndarray:
    """Full 1D Haar transform along the LAST axis (f64).

    Same unnormalized square-arrangement family as :func:`haar2d_np`,
    applied to one axis: per pair ``(a, b)`` emit ``a + b`` (front
    half) and ``a - b`` (back half), recursing on the front half. The
    temporal plane runs this over the per-bucket cell series (time as
    the axis), vectorized across cells via leading batch axes — the
    epoch-dimension reuse of the spatial synopsis substrate. Exact in
    f64 for integer series below 2^53, like the 2D twin.
    """
    n = _check_series(np.asarray(series))
    out = np.asarray(series, np.float64).copy()
    h = n // 2
    while h >= 1:
        a = out[..., 0:2 * h:2].copy()
        b = out[..., 1:2 * h:2].copy()
        out[..., :h] = a + b
        out[..., h:2 * h] = a - b
        h //= 2
    return out


def inv_haar1d_np(coeffs: np.ndarray) -> np.ndarray:
    """Inverse of :func:`haar1d_np` (divide-by-2 per pass — a
    power-of-two scale, so integer series round-trip bit-exact)."""
    n = _check_series(np.asarray(coeffs))
    out = np.asarray(coeffs, np.float64).copy()
    h = 1
    while h < n:
        s = out[..., :h].copy()
        d = out[..., h:2 * h].copy()
        out[..., 0:2 * h:2] = (s + d) / 2.0
        out[..., 1:2 * h:2] = (s - d) / 2.0
        h *= 2
    return out


def grid_from_rows_np(rows, cols, values, n: int) -> np.ndarray:
    """Scatter-add sparse (row, col, value) cells into a dense f64
    ``(n, n)`` grid. Duplicate cells accumulate."""
    grid = np.zeros((n, n), np.float64)
    np.add.at(grid, (np.asarray(rows, np.int64), np.asarray(cols, np.int64)),
              np.asarray(values, np.float64))
    return grid


def grid_from_rows_jax(rows, cols, values, n: int, valid=None):
    """Device twin of :func:`grid_from_rows_np` for the cascade path.

    ``valid`` masks pad lanes to weight zero (their coordinates are
    clamped to (0, 0)), so bucketed-padded emission arrays produce the
    same grid as the unpadded batch — one compiled executable per
    (bucket, n) signature. jit-compatible for static ``n``.
    """
    import jax
    import jax.numpy as jnp

    ftype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    rows = jnp.asarray(rows, jnp.int32)
    cols = jnp.asarray(cols, jnp.int32)
    values = jnp.asarray(values, ftype)
    if valid is not None:
        mask = jnp.asarray(valid, bool)
        rows = jnp.where(mask, rows, 0)
        cols = jnp.where(mask, cols, 0)
        values = jnp.where(mask, values, 0)
    grid = jnp.zeros((n, n), values.dtype)
    return grid.at[rows, cols].add(values)


def haar2d_jax(grid):
    """JAX forward transform — same arrangement as :func:`haar2d_np`.

    Plain jnp slice arithmetic under a static-shape Python scale loop:
    jit traces one executable per grid side ``n``. No Pallas kernel is
    warranted — the op is O(n^2) adds with trivial arithmetic
    intensity; XLA fuses the quadrant updates.
    """
    import jax
    import jax.numpy as jnp

    n = _check_grid(grid)
    ftype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    out = jnp.asarray(grid).astype(ftype)
    h = n // 2
    while h >= 1:
        a = out[0:2 * h:2, 0:2 * h:2]
        b = out[0:2 * h:2, 1:2 * h:2]
        c = out[1:2 * h:2, 0:2 * h:2]
        d = out[1:2 * h:2, 1:2 * h:2]
        out = out.at[:h, :h].set(a + b + c + d)
        out = out.at[:h, h:2 * h].set(a - b + c - d)
        out = out.at[h:2 * h, :h].set(a + b - c - d)
        out = out.at[h:2 * h, h:2 * h].set(a - b - c + d)
        h //= 2
    return out
