"""Wavelet-synopsis coarse levels: bounded-error compressed pyramids.

Coarse zoom levels aggregate every point and dominate stored bytes,
but a heatmap PNG quantizes counts through a colormap — they tolerate
bounded error visually. Following the top-B wavelet-histogram
construction (arxiv 1110.6649), this package compresses each coarse
level's per-cell count grid to its B largest Haar coefficients and
stamps the ACHIEVED L-inf reconstruction error into the artifact, so
serving can expose approximate tiles with an explicit accuracy
contract (``X-Heatmap-Synopsis: max_err=<n>``) and an exact/synopsis
choice per request.

- transform.py  2D Haar twins: jit-compatible JAX forward for the
                cascade path, numpy-only inverse for serving.
- build.py      top-B selection, error stamping, synopsis-z*.npz
                artifact read/write.
- metrics.py    obs registry handles (docs/observability.md).

Import discipline: everything importable from here is numpy-only; jax
loads lazily inside the ``*_jax`` functions (tests/test_obs.py greps).
"""

from heatmap_tpu.synopsis.build import (  # noqa: F401
    DEFAULT_MAX_Z, HARD_MAX_Z, SCHEMA, SynopsisPair, build_pair,
    decode_pair, default_b, load_synopses, synopsis_path, write_synopses,
)
from heatmap_tpu.synopsis.transform import (  # noqa: F401
    grid_from_rows_jax, grid_from_rows_np, haar2d_jax, haar2d_np,
    inv_haar2d_np,
)
