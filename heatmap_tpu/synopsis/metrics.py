"""Synopsis metric handles on the shared obs registry.

Module-level, created once at import (the delta/metrics.py pattern):
handles survive ``registry.reset()`` between tests and self-gate on
``registry.enabled``. Semantics are documented in
docs/observability.md.
"""

from __future__ import annotations

from heatmap_tpu import obs

_registry = obs.get_registry()

SYNOPSIS_BYTES = _registry.counter(
    "synopsis_bytes_total",
    "Bytes of synopsis artifacts published, per pyramid level",
    labelnames=("level",))
SYNOPSIS_DECODE_SECONDS = _registry.histogram(
    "synopsis_decode_seconds",
    "Wall-clock of decoding one synopsis level (inverse Haar + extras) "
    "into a servable index",
    buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0))
SYNOPSIS_MAX_ERROR = _registry.gauge(
    "synopsis_max_error",
    "Stamped L-inf error bound of the most recently published synopsis, "
    "per pyramid level (achieved worst cell error across pairs)",
    labelnames=("level",))
