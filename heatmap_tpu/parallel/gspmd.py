"""Device-resident GSPMD cascade: global-view NamedSharding programs.

The shard_map kernels in parallel/sharded.py are hand-routed: the host
pads and routes emissions before the kernel (parallel/partition.
route_emissions), and per-shard buffer widths are derived from the
routed segment length. This module re-expresses the same two cascade
pyramids — uniform data-parallel and Morton-range partitioned — as
*global-view* jax programs annotated with ``NamedSharding`` constraints
(mesh.named_sharding), so the WHOLE cascade — emission routing,
range-local rollup, boundary merge, and canonical egress ordering —
is one compiled program with no host round-trips between stages:

- routing happens on-device against a TRACED splits array
  (``searchsorted`` on the detail code bits) instead of a host numpy
  scatter, which is also what lets ``adaptive_capacity`` compose with
  Morton partitioning (the host router is shape-static; the traced
  router is not);
- every per-shard stage is a ``vmap`` over a leading ``(n_shards,)``
  axis pinned to the mesh's point axes, so XLA's SPMD partitioner
  places each row's compute on its owning device;
- the final canonical-order argsort (sorted uniques, sentinel pad)
  runs on-device inside the same program, byte-identical to the
  post-shard_map egress of parallel/sharded.py.

Byte identity with the shard_map kernels is the contract (pinned by
tests/test_gspmd.py and the chaos ``dispatch`` phase): counts and
bounded-integer weighted sums are exact in any summation order, and
float64 weighted sums accumulate per key in original lane order on
both paths (stable sorts; masked lanes carry sentinel keys that sort
past every real run, so they never interleave a segment).

Routing layout note: the range program replicates the batch across the
point axes and masks each shard to its owned lanes (``dest == k``) —
per-device memory O(n), same as the host router's input, and the
detail reduce scans the full batch per shard. That redundancy buys
zero host routing, zero host<->device round-trips, and a traced (plan-
agnostic) program; the dispatch-overhead bench (tools/bench_job.py
--dispatch-sweep) measures the trade. The uniform program has no
redundancy: it reduces contiguous 1/n_shards slices exactly like the
shard_map body.

Donation: `donating_jit` adds ``donate_argnums`` where the platform
supports in-place donation (TPU/GPU) and drops it where it does not
(CPU), while a platform-independent :class:`DonationLedger` makes
re-use of a donated buffer a typed :class:`DonatedBufferError` on
every backend — the classic pjit footgun caught at the API boundary
rather than as a backend-specific crash.
"""

from __future__ import annotations

import weakref

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from heatmap_tpu.ops import pyramid as pyramid_ops, sparse as sparse_ops
from heatmap_tpu.parallel.mesh import (
    DATA_AXIS,
    TILE_AXIS,
    named_sharding,
    shard_map,
)
from heatmap_tpu.parallel.sharded import (
    _local_detail_stage,
    _ones_like_weights,
    _shard_axes,
)

__all__ = [
    "DonatedBufferError",
    "DonationLedger",
    "donating_jit",
    "donation_supported",
    "ledger",
    "pyramid_gspmd_range",
    "pyramid_gspmd_uniform",
    "route_on_device",
]


# ---------------------------------------------------------------------------
# sharding helpers


def _point_spec(mesh: Mesh):
    """The PartitionSpec leading-axis entry the point-parallel programs
    shard their ``(n_shards, ...)`` layout over — the NamedSharding
    analog of sharded._shard_axes (tile==1 keeps the single data axis,
    else the leading axis flattens over both)."""
    axes, ndev = _shard_axes(mesh)
    return (axes[0] if len(axes) == 1 else tuple(axes)), ndev


def _mapped_stage(stage, mesh: Mesh, spec, backend: str):
    """Map the per-shard detail stage over the leading shard axis.

    The scatter stage is plain gather/segment arithmetic — ``vmap``
    keeps it global-view and the SPMD partitioner places each row on
    its owning device. The partitioned stage wraps a pallas_call, and
    vmapping a pallas_call whose scalar-prefetch operands are batched
    falls back to jax's sequential batch loop; the partitioner then
    threads every grid step's dynamic slices through cross-device
    collectives (and, under x64, trips an s64-vs-s32 HLO verifier
    error against its own s32 shard offsets — the CHANGES.md line 19
    failure). Run that stage under shard_map instead: the body is
    device-local by construction, so the kernel never meets the
    batching fallback or the partitioner. Same stage function, same
    per-shard blocks, byte-identical outputs.
    """
    if backend != "partitioned":
        return jax.vmap(stage)
    from jax.sharding import PartitionSpec as P

    row = P(spec, None)

    def body(k, w, v):
        u, s, n = stage(k[0], w[0], v[0])
        return u[None], s[None], jnp.asarray(n)[None]

    return shard_map(body, mesh, in_specs=(row, row, row),
                     out_specs=(row, row, P(spec)), check_vma=False)


def _constrain(x, mesh: Mesh, *spec):
    """``with_sharding_constraint`` under trace, ``device_put`` eagerly.

    The gspmd programs run both jitted (the production path — the
    constraint tells the SPMD partitioner where each stage lives) and
    eagerly (stage tracing, adaptive_capacity reads concrete counts);
    eager jax rejects bare sharding constraints, so commit the array
    instead — same placement, same values.
    """
    sharding = named_sharding(mesh, *spec)
    if isinstance(x, jax.core.Tracer):
        return lax.with_sharding_constraint(x, sharding)
    return jax.device_put(x, sharding)


# ---------------------------------------------------------------------------
# on-device routing


def route_on_device(keys, splits, *, code_bits: int, n_shards: int,
                    valid=None):
    """Owning-shard mask per emission lane, from a traced splits array.

    ``keys`` are composite cascade keys (slot bits above ``code_bits``
    detail-code bits); routing is by the detail Morton code alone,
    mirroring the host router (partition.route_emissions →
    shard_of_codes: ``shard = #{splits <= code}``). Returns a
    ``(n_shards, n)`` bool mask whose row ``k`` is True exactly on the
    valid lanes shard ``k`` owns — the replicate-and-mask layout the
    range program reduces over.
    """
    keys = jnp.asarray(keys)
    code = keys & ((1 << code_bits) - 1)
    dest = jnp.searchsorted(jnp.asarray(splits, keys.dtype), code,
                            side="right")
    owned = dest[None, :] == jnp.arange(n_shards)[:, None]
    if valid is not None:
        owned = owned & jnp.asarray(valid, bool)[None, :]
    return owned


# ---------------------------------------------------------------------------
# uniform data-parallel program


def pyramid_gspmd_uniform(
    codes,
    mesh: Mesh,
    weights=None,
    valid=None,
    levels: int = 0,
    capacity=None,
    acc_dtype=None,
    backend: str = "scatter",
    weight_bound: int | None = None,
    adaptive: bool = False,
):
    """Global-view uniform-DP sparse pyramid, byte-identical to
    :func:`parallel.sharded.pyramid_sparse_morton_sharded`.

    Same staging as the shard_map kernel — per-shard detail reduce,
    merge + rollup over the flattened compact partials — but the shard
    axis is an explicit leading dimension constrained to the mesh's
    point axes rather than a shard_map body, so the whole pyramid is
    one partitionable program (jit it together with projection and
    egress). Per-shard buffer widths reuse the shard_map formulas
    exactly so the merged partial stream is element-identical.

    ``adaptive`` (EAGER callers only, like ops.pyramid) forwards to the
    merged rollup: deep levels shrink to the real unique counts. The
    shard_map path cannot take this flag (its widths are baked into
    the body specs); here the rollup runs on the global view, so the
    composition is free — and result-neutral, the dropped slots are
    sentinel padding.
    """
    spec, ndev = _point_spec(mesh)
    codes = jnp.asarray(codes)
    n = codes.shape[0]
    if n % ndev != 0:
        raise ValueError(
            f"gspmd uniform cascade needs n % n_shards == 0, got "
            f"{n} % {ndev} (pad with mesh.pad_to_multiple)")
    caps = pyramid_ops._level_caps(capacity, n, levels)
    local_capacity = max(1, min(caps[0], n // ndev))
    if acc_dtype is None:
        acc_dtype = jnp.int32 if weights is None else jnp.float32
    counts_only = weights is None
    w = _ones_like_weights(weights, n, acc_dtype)
    v = jnp.ones((n,), bool) if valid is None else jnp.asarray(valid, bool)
    sentinel = jnp.iinfo(codes.dtype).max
    stage = _local_detail_stage(backend, counts_only, local_capacity,
                                acc_dtype, sentinel,
                                weight_bound=weight_bound)

    shard = n // ndev
    ck = _constrain(codes.reshape(ndev, shard), mesh, spec, None)
    cw = _constrain(w.reshape(ndev, shard), mesh, spec, None)
    cv = _constrain(v.reshape(ndev, shard), mesh, spec, None)
    u, s, ln = _mapped_stage(stage, mesh, spec, backend)(ck, cw, cv)
    u = _constrain(u, mesh, spec, None)
    s = _constrain(s, mesh, spec, None)
    gu, gs = u.reshape(-1), s.reshape(-1)
    out = pyramid_ops.pyramid_sparse_morton(
        gu,
        weights=gs,
        valid=gu != sentinel,
        levels=levels,
        capacity=caps,
        acc_dtype=acc_dtype,
        adaptive=adaptive,
    )
    local_overflow = (ln > local_capacity).any()
    return [
        (
            lu,
            ls,
            jnp.where(local_overflow, jnp.maximum(lnn, caps[lvl] + 1), lnn),
        )
        for lvl, (lu, ls, lnn) in enumerate(out)
    ]


# ---------------------------------------------------------------------------
# Morton-range partitioned program (on-device routing)


def pyramid_gspmd_range(
    keys,
    mesh: Mesh,
    splits,
    *,
    code_bits: int,
    slot_bound: int,
    weights=None,
    valid=None,
    levels: int = 0,
    capacity=None,
    acc_dtype=None,
    backend: str = "scatter",
    weight_bound: int | None = None,
    adaptive: bool = False,
):
    """Range-partitioned sparse pyramid with ON-DEVICE routing.

    Input is UNROUTED — the full emission stream plus the traced
    ``(n_shards - 1,)`` split codes; :func:`route_on_device` assigns
    lanes to shards inside the program (replicate-and-mask layout, see
    module docstring), replacing the host scatter the shard_map path
    requires. Every stage after routing mirrors
    :func:`parallel.sharded.pyramid_sparse_morton_range_sharded`
    verbatim, with the shard axis as an explicit vmapped leading
    dimension and the cross-shard boundary exchange written as plain
    array ops over that axis (the SPMD partitioner lowers them to the
    same all_gather):

    - detail reduce per shard (routing is by detail code, so shards
      never share a detail key and the boundary set is empty);
    - per coarse level: local parent rollup, boundary-tile extraction
      against the traced splits, fixed-width exchange, first-holder
      patch (cross-shard total lands on the lowest-indexed holder,
      every other holder drops its row), local reorder;
    - canonical egress: global argsort of the sentinel-padded shard
      blocks, truncated/padded to the level capacity — byte-identical
      to the shard_map path's host-graph egress.

    The loud-overflow contract holds: any shard-local buffer overflow
    forces every level's count past capacity. Because the replicated
    layout sizes per-shard buffers by the FULL level capacity rather
    than the routed segment length, some shapes that overflow a
    narrow routed segment do not overflow here; non-overflow shapes
    (the contract everything downstream serves) are byte-identical.
    """
    spec, ndev = _point_spec(mesh)
    keys = jnp.asarray(keys)
    n = keys.shape[0]
    splits = jnp.asarray(splits)
    if splits.shape != (ndev - 1,):
        raise ValueError(
            f"need {ndev - 1} split codes for {ndev} shards, got "
            f"shape {splits.shape}")
    caps = pyramid_ops._level_caps(capacity, n, levels)
    lcaps = [max(1, caps[lvl]) for lvl in range(levels + 1)]
    bcaps = [max(1, min(lcaps[lvl], 2 * slot_bound))
             for lvl in range(levels + 1)]
    if acc_dtype is None:
        acc_dtype = jnp.int32 if weights is None else jnp.float32
    counts_only = weights is None
    w = _ones_like_weights(weights, n, acc_dtype)
    v = jnp.ones((n,), bool) if valid is None else jnp.asarray(valid, bool)
    sentinel = jnp.iinfo(keys.dtype).max
    stage = _local_detail_stage(backend, counts_only, lcaps[0],
                                acc_dtype, sentinel,
                                weight_bound=weight_bound)

    owned = route_on_device(keys, splits, code_bits=code_bits,
                            n_shards=ndev, valid=v)
    bk = _constrain(jnp.broadcast_to(keys, (ndev, n)), mesh, spec, None)
    bw = _constrain(jnp.broadcast_to(w, (ndev, n)), mesh, spec, None)
    bv = _constrain(owned, mesh, spec, None)

    u, s, ln = _mapped_stage(stage, mesh, spec, backend)(bk, bw, bv)
    over = ln > lcaps[0]
    u = _constrain(u, mesh, spec, None)
    s = _constrain(s, mesh, spec, None)

    me = jnp.arange(ndev)
    spl = splits.astype(keys.dtype)
    per_level = [(u, s, jnp.sum(u != sentinel, axis=1))]
    cur_u, cur_s = u, s
    for lvl in range(1, levels + 1):
        if adaptive:
            # EAGER callers only (counts are concrete): shrink the
            # per-shard columns to the next power of two above the
            # widest shard's real unique count before the next rollup —
            # the ops.pyramid adaptive trick applied per shard. Rows
            # are sorted with sentinels last, so the dropped columns
            # are pure padding; never slice below any shard's n_real
            # (overflow detection relies on the true counts). This is
            # the composition the host-routed shard_map path cannot
            # express: its widths are baked into static body specs,
            # while the traced router leaves the rollup global-view.
            n_real = int(jnp.max(per_level[-1][2]))
            if n_real <= cur_u.shape[1]:
                keep = max(64, 1 << max(0, n_real - 1).bit_length())
                if keep < cur_u.shape[1]:
                    cur_u = cur_u[:, :keep]
                    cur_s = cur_s[:, :keep]
        parents = jnp.where(cur_u == sentinel, sentinel, cur_u >> 2)
        out_cap = (min(lcaps[lvl], cur_u.shape[1]) if adaptive
                   else lcaps[lvl])
        pu, ps, pn = jax.vmap(
            lambda p, ps_: sparse_ops.aggregate_sorted_keys(
                p, ps_, out_cap, sentinel=sentinel))(parents, cur_s)
        over = over | (pn > out_cap)
        # Boundary codes at this level, from the traced splits: the
        # split's ancestor, unless the split is tile-aligned.
        blk = (1 << (2 * lvl)) - 1
        b = jnp.where((spl & blk) != 0, spl >> (2 * lvl), sentinel)
        code_mask = (1 << (code_bits - 2 * lvl)) - 1
        is_b = (pu != sentinel) & jnp.any(
            (pu & code_mask)[:, :, None] == b[None, None, :], axis=2)
        cb = min(bcaps[lvl], pu.shape[1])
        over = over | (jnp.sum(is_b, axis=1) > cb)
        # Boundary rows to the front (sentinel-masked argsort), fixed
        # cb-wide send buffers — the all_gather payload of the
        # shard_map body, here simply the stacked (ndev, cb) arrays.
        bkey = jnp.where(is_b, pu, sentinel)
        border = jnp.argsort(bkey, axis=1)[:, :cb]
        send_u = jnp.take_along_axis(bkey, border, axis=1)
        send_s = jnp.take_along_axis(
            jnp.where(is_b, ps, jnp.zeros((), ps.dtype)), border, axis=1)

        def lookup(bu, bs, pu_k):
            pos = jnp.clip(jnp.searchsorted(bu, pu_k), 0, cb - 1)
            hit = (bu[pos] == pu_k) & (pu_k != sentinel)
            return jnp.where(hit, bs[pos], jnp.zeros((), bs.dtype)), hit

        # vals[k, j]: shard k's boundary keys looked up in shard j's
        # gathered block — (ndev, ndev, lcap); summed over j in block
        # order, exactly the shard_map body's gathered-axis sum.
        vals, hits = jax.vmap(
            lambda pu_k: jax.vmap(lookup, in_axes=(0, 0, None))(
                send_u, send_s, pu_k))(pu)
        total = jnp.sum(vals, axis=1)
        holder = me[jnp.argmax(hits, axis=1)]
        keep = ~is_b | (holder == me[:, None])
        new_u = jnp.where(keep, pu, sentinel)
        new_s = jnp.where(keep & is_b, total, ps)
        new_s = jnp.where(keep, new_s, jnp.zeros((), ps.dtype))
        reorder = jnp.argsort(new_u, axis=1)
        cur_u = jnp.take_along_axis(new_u, reorder, axis=1)
        cur_s = jnp.take_along_axis(new_s, reorder, axis=1)
        cur_u = _constrain(cur_u, mesh, spec, None)
        cur_s = _constrain(cur_s, mesh, spec, None)
        per_level.append((cur_u, cur_s, jnp.sum(cur_u != sentinel, axis=1)))

    any_over = over.any()
    out = []
    for lvl in range(levels + 1):
        cu, cs, cn = per_level[lvl]
        cap = caps[lvl]
        gu, gs = cu.reshape(-1), cs.reshape(-1)
        # Keys are globally disjoint post-patch, so a global argsort of
        # the sentinel-padded shard blocks IS the canonical merged
        # order (sentinels sort last, their sums are zero) — the same
        # egress the shard_map path runs, now inside the program.
        order = jnp.argsort(gu)
        su, ss = gu[order], gs[order]
        if su.shape[0] >= cap:
            su, ss = su[:cap], ss[:cap]
        else:
            su = jnp.concatenate(
                [su, jnp.full((cap - su.shape[0],), sentinel, su.dtype)])
            ss = jnp.concatenate(
                [ss, jnp.zeros((cap - ss.shape[0],), ss.dtype)])
        ln = cn.sum()
        out.append((su, ss,
                    jnp.where(any_over, jnp.maximum(ln, cap + 1), ln)))
    return out


# ---------------------------------------------------------------------------
# donation


def donation_supported(platform: str | None = None) -> bool:
    """True where XLA honors ``donate_argnums`` (TPU/GPU; CPU emits a
    "donated buffers were not usable" warning and copies instead)."""
    platform = platform or jax.default_backend()
    return platform in ("tpu", "gpu", "cuda", "rocm")


class DonatedBufferError(ValueError):
    """A buffer donated to a previous dispatch was passed again.

    On TPU/GPU the donated buffer's memory was reused in place, so a
    second read is undefined; on CPU donation is a no-op and the read
    would silently "work" — the ledger raises on every platform so the
    bug cannot hide behind the backend.
    """


class DonationLedger:
    """Tracks buffers consumed by donating dispatches, by identity.

    Entries are weak so the ledger never extends a donated buffer's
    lifetime (which would defeat donation); a collected buffer cannot
    be re-passed, so dropping its entry is safe.
    """

    def __init__(self):
        self._spent: dict[int, object] = {}

    def mark(self, *arrays) -> None:
        for a in arrays:
            if a is None or not isinstance(a, jax.Array):
                continue
            key = id(a)
            try:
                self._spent[key] = weakref.ref(
                    a, lambda _r, k=key: self._spent.pop(k, None))
            except TypeError:  # pragma: no cover - non-weakrefable array
                self._spent[key] = None

    def check(self, *arrays) -> None:
        for a in arrays:
            if a is not None and id(a) in self._spent:
                raise DonatedBufferError(
                    "buffer was donated to a previous cascade dispatch "
                    "and may have been overwritten in place; re-feed the "
                    "batch (pipeline/feeder.py) instead of re-passing it")

    def clear(self) -> None:
        self._spent.clear()


#: Process-wide ledger for the cascade dispatch path.
ledger = DonationLedger()


def donating_jit(fn, *, donate_argnums=(), donate_argnames=(),
                 static_argnames=(), ledger=None):
    """``jax.jit`` with donation where supported, ledger-guarded always.

    Returns a callable with the jitted function's signature plus two
    attributes: ``donation_active`` (whether donation was actually
    passed to jit on this platform) and ``ledger``. Donated arguments
    (positional via ``donate_argnums``, keyword via ``donate_argnames``)
    are checked against the ledger before dispatch and marked consumed
    after — so re-using a donated buffer raises
    :class:`DonatedBufferError` on CPU exactly as it would corrupt on
    TPU, and the byte-identity tests can run the same assertions on
    both.
    """
    active = donation_supported() and bool(donate_argnums
                                           or donate_argnames)
    jfn = jax.jit(fn, static_argnames=static_argnames,
                  donate_argnums=donate_argnums if active else (),
                  donate_argnames=donate_argnames if active else ())
    led = ledger if ledger is not None else globals()["ledger"]
    donate_argnums = tuple(donate_argnums)
    donate_argnames = tuple(donate_argnames)

    def call(*args, **kwargs):
        donated = [args[i] for i in donate_argnums if i < len(args)]
        donated += [kwargs[k] for k in donate_argnames if k in kwargs]
        led.check(*donated)
        out = jfn(*args, **kwargs)
        led.mark(*donated)
        return out

    call.donation_active = active
    call.ledger = led
    call.__wrapped__ = jfn
    return call
