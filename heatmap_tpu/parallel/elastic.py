"""Elastic multihost execution: shard lineage, failover, speculation.

The reference pipeline delegated all fault tolerance to Spark, whose
defining robustness feature is lineage-based task re-execution on
worker loss (PAPERS.md: arxiv 1811.04875 attributes Spark's resilience
edge over MPI to exactly this). `run_job_multihost` *detects* a dead
host (`check_heartbeats` -> typed StragglerTimeout) but the whole job
then dies. This module turns host failure from fatal into recoverable,
exploiting the same linearity the delta engine pinned:
pyramid(union) = merge of per-shard pyramids, so recovery is *exact* —
a re-executed shard contributes identical bytes.

Three pillars:

1. **Shard-lineage manifest** (:class:`ShardLineage`). The job is cut
   into contiguous batch-range shards; each is content-hashed over its
   input slice identity + the byte-affecting config fingerprint
   (``delta.compact.config_fingerprint``, the same dedup idiom as the
   delta journal's ``batch_content_hash``). A completed shard persists
   its partial pyramid through the existing atomic ``publish_dir``
   path, so finished work survives a crash and re-runs are
   exactly-once by hash: a second executor of the same shard either
   skips (manifest hit) or loses the publish race and is quarantined.

2. **Failover re-execution**. On :class:`StragglerTimeout` the
   coordinator — instead of raising — marks the stale host's
   unfinished shards orphaned and reassigns them round-robin to the
   surviving hosts (``on_straggler="reassign"`` on
   ``run_job_multihost``; the default ``"raise"`` preserves the
   historical behavior). Orphan re-execution runs under the
   ``elastic.reassign`` fault site/policy. The final merge draws each
   shard's pyramid from exactly one winner, so the output is
   byte-identical to an unfailed run.

3. **Speculative straggler duplication**. When a running shard's
   elapsed time exceeds ``speculative_factor`` x a quantile of
   completed-shard durations (the durations also feed the
   ``stage_duration_seconds{stage="elastic.shard"}`` histogram), an
   idle host launches a duplicate. First completion wins the atomic
   publish; the loser's artifact is quarantined, never merged.

Two drivers share the machinery:

- **Simulated hosts** (``jax.process_count() == 1``): ``n_hosts``
  worker threads over one process's devices — the testable path
  (tools/chaos_soak.py ``host_loss`` phase). Each simulated host
  heartbeats with its own identity (``obs.heartbeat(phase,
  process=h)``), so a chaos rule ``multihost.heartbeat@p2=999`` kills
  exactly one host's liveness while the monitor thread watches
  ``check_heartbeats``.
- **Real processes** (``jax.process_count() > 1``): every process runs
  its own shards against the shared ``lineage_dir``, then polls the
  manifest. Because per-process registries cannot see each other's
  heartbeats, failure detection is *progress-based*: if no new shard
  completes within the deadline, survivors claim the missing shards in
  deterministic order — publish atomicity dedups double-claims. No
  step uses a collective, so a dead host cannot hang the egress;
  process 0 merges from the manifest and writes the sink.

Quantified in docs/robustness.md (failure-mode matrix) and exercised
by tools/chaos_soak.py.
"""

from __future__ import annotations

import dataclasses
import errno
import hashlib
import itertools
import json
import os
import shutil
import threading
import time
from collections import deque

import numpy as np

from heatmap_tpu import faults, obs
from heatmap_tpu.io.merge import merge_level_dirs
from heatmap_tpu.io.sinks import LevelArraysSink
from heatmap_tpu.utils.checkpoint import publish_dir

SHARDS_DIRNAME = "shards"
QUARANTINE_DIRNAME = "quarantine"

#: Worker/monitor poll interval — every wait in this module is an
#: Event/join timeout (the ingest/loop.py idiom), never time.sleep.
_POLL_S = 0.02
#: Minimum completed-shard sample before speculation can trigger.
_MIN_SPECULATION_SAMPLES = 3


# ---------------------------------------------------------------------------
# Shard plan + lineage fingerprints
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class WorkShard:
    """One unit of elastic work: the contiguous batch range [lo, hi)
    of the job's source at the job's pinned batch size.

    Under ``partition="morton"`` the shard additionally owns the
    contiguous detail-zoom Morton code range ``[code_lo, code_hi)``
    (from a parallel.partition plan): every shard reads the same batch
    range but keeps only its own tile range, so failover re-execution
    touches exactly the dead host's tile ranges instead of a
    batch-range slice of the whole map. ``None`` (default) keeps the
    historical batch-range semantics."""

    index: int
    lo: int
    hi: int
    fingerprint: str
    code_lo: int | None = None
    code_hi: int | None = None

    @property
    def dirname(self) -> str:
        # Readable + hash-keyed: the hash is the dedup identity, the
        # index prefix keeps the manifest listable in plan order.
        return f"shard-{self.index:05d}-{self.fingerprint[:16]}"


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return str(v)


def job_fingerprint(source, config, batch_size: int, n_total: int) -> str:
    """Deterministic identity of the whole job: source descriptor +
    batch granularity + the byte-affecting config fingerprint. Sources
    iterate deterministically (pinned in io/sources.py), so slice
    identity under this fingerprint IS content identity — which is what
    lets a host check shard completion without re-reading the input."""
    from heatmap_tpu.delta.compact import config_fingerprint

    if dataclasses.is_dataclass(source) and not isinstance(source, type):
        src = {"class": type(source).__name__}
        for f in dataclasses.fields(source):
            src[f.name] = _jsonable(getattr(source, f.name))
    else:
        src = {"class": type(source).__name__, "repr": repr(source)}
    payload = json.dumps(
        {"source": src, "batch_size": int(batch_size),
         "n_total": int(n_total), "config": config_fingerprint(config)},
        sort_keys=True, default=str)
    return hashlib.sha256(payload.encode()).hexdigest()


def shard_fingerprint(job_fp: str, lo: int, hi: int,
                      code_lo=None, code_hi=None) -> str:
    ident = f"{job_fp}:{int(lo)}:{int(hi)}"
    if code_lo is not None:
        # Distinct namespace from batch-range shards: the same batch
        # slice filtered to a tile range is different content.
        ident += f":morton:{int(code_lo)}:{int(code_hi)}"
    return hashlib.sha256(ident.encode()).hexdigest()


def plan_shards(n_batches: int, n_shards: int, job_fp: str, *,
                code_ranges=None) -> list:
    """Contiguous balanced split of the batch index space into
    ``n_shards`` WorkShards (the process_shard_bounds shape).

    ``code_ranges`` switches to ``partition="morton"`` shards: one
    WorkShard per ``[code_lo, code_hi)`` detail-code range (from
    ``parallel.partition.PartitionPlan.code_ranges()``), each spanning
    the FULL batch range — ownership is spatial, not positional, so a
    re-executed shard reproduces exactly one tile range. Empty ranges
    (``code_lo == code_hi``) are planned too: they publish empty
    partials, keeping shard count == plan shard count so failover
    bookkeeping stays positional."""
    if code_ranges is not None:
        n_batches = max(0, int(n_batches))
        return [
            WorkShard(index=i, lo=0, hi=n_batches,
                      fingerprint=shard_fingerprint(
                          job_fp, 0, n_batches, code_lo=clo, code_hi=chi),
                      code_lo=int(clo), code_hi=int(chi))
            for i, (clo, chi) in enumerate(code_ranges)
        ]
    n_shards = max(1, min(int(n_shards), max(1, int(n_batches))))
    base, rem = divmod(max(0, int(n_batches)), n_shards)
    out, lo = [], 0
    for i in range(n_shards):
        hi = lo + base + (1 if i < rem else 0)
        out.append(WorkShard(index=i, lo=lo, hi=hi,
                             fingerprint=shard_fingerprint(job_fp, lo, hi)))
        lo = hi
    return out


def columns_digest(data: dict) -> str:
    """Content digest of ingested columns — stored in each shard's
    manifest meta as the integrity binding between the slice-identity
    fingerprint and the actual bytes that produced the artifact (the
    journal's batch_content_hash idiom)."""
    h = hashlib.sha256()
    for k in sorted(data):
        v = np.asarray(data[k])
        h.update(k.encode())
        if v.dtype == object:
            h.update("\x00".join(str(x) for x in v.ravel()).encode())
        else:
            h.update(str(v.dtype).encode())
            h.update(np.ascontiguousarray(v).tobytes())
    return "sha256:" + h.hexdigest()


# ---------------------------------------------------------------------------
# The on-disk lineage manifest
# ---------------------------------------------------------------------------


class ShardLineage:
    """Durable manifest of completed shards under ``root``.

    A shard is complete iff ``<root>/shards/<dirname>`` exists — and it
    can only exist via ``publish_dir`` (stage to a per-host tmp, fsync,
    atomic rename), so existence implies a whole artifact. Exactly-once
    follows from rename atomicity: of N racing executors of one shard,
    exactly one rename lands; losers are moved into
    ``<root>/quarantine/`` (inspectable, never merged — the
    delta/recover.py quarantine discipline)."""

    def __init__(self, root: str):
        self.root = root
        self.shards_dir = os.path.join(root, SHARDS_DIRNAME)
        self.quarantine_dir = os.path.join(root, QUARANTINE_DIRNAME)
        os.makedirs(self.shards_dir, exist_ok=True)

    def shard_path(self, shard: WorkShard) -> str:
        return os.path.join(self.shards_dir, shard.dirname)

    def is_complete(self, shard: WorkShard) -> bool:
        return os.path.isdir(self.shard_path(shard))

    def completed_count(self, shards) -> int:
        return sum(1 for s in shards if self.is_complete(s))

    def publish(self, shard: WorkShard, host, levels, meta: dict):
        """Stage + atomically publish one shard artifact.

        Returns ``(won, quarantined_path)``: ``won=False`` means
        another executor's artifact landed first — ours (if staged) is
        quarantined and must not be merged."""
        final = self.shard_path(shard)
        if os.path.isdir(final):
            return False, None
        tmp = final + f".tmp-h{host}"
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)  # this host's own crashed staging
        rows = LevelArraysSink(tmp).write_levels(levels)
        meta = dict(meta, rows=int(rows), host=str(host),
                    fingerprint=shard.fingerprint, index=shard.index,
                    lo=shard.lo, hi=shard.hi)
        with open(os.path.join(tmp, "shard.json"), "w") as f:
            json.dump(meta, f, sort_keys=True)
        try:
            publish_dir(tmp, final)
        except FileExistsError:
            return False, self._quarantine_loser(tmp, shard)
        except OSError as e:
            # The rename itself can lose the race: POSIX rename onto a
            # non-empty directory is ENOTEMPTY (EEXIST on some
            # platforms). Anything else is a real I/O error.
            if e.errno not in (errno.EEXIST, errno.ENOTEMPTY):
                raise
            return False, self._quarantine_loser(tmp, shard)
        return True, None

    def _quarantine_loser(self, tmp: str, shard: WorkShard) -> str:
        os.makedirs(self.quarantine_dir, exist_ok=True)
        base = shard.dirname + "-loser"
        dest = os.path.join(self.quarantine_dir, base)
        n = 0
        while os.path.exists(dest):
            n += 1
            dest = os.path.join(self.quarantine_dir, f"{base}.{n}")
        shutil.move(tmp, dest)
        return dest

    def merge(self, shards) -> list:
        """Final merge: each shard's pyramid from exactly one winner
        (the manifest entry), in plan order — deterministic output
        regardless of which host produced which artifact."""
        dirs, missing = [], []
        for s in shards:
            p = self.shard_path(s)
            (dirs if os.path.isdir(p) else missing).append(p)
        if missing:
            raise RuntimeError(
                f"elastic merge: {len(missing)} shard artifact(s) "
                f"missing from {self.shards_dir} (first: {missing[0]})")
        return merge_level_dirs(dirs)


# ---------------------------------------------------------------------------
# The in-memory coordinator (simulated-host driver)
# ---------------------------------------------------------------------------


class ElasticCoordinator:
    """Thread-safe shard scheduler for the simulated-host driver.

    Owns assignment (initial round-robin by shard index), orphan
    reassignment on host death, and the speculative-duplication
    decision. All clock values come in from the caller so tests can
    drive it with a fake clock."""

    PENDING, RUNNING, DONE = "pending", "running", "done"

    def __init__(self, shards, hosts, *, speculative_quantile=None,
                 speculative_factor: float = 2.0,
                 min_samples: int = _MIN_SPECULATION_SAMPLES):
        self._lock = threading.Lock()
        self.shards = list(shards)
        self.hosts = list(hosts)
        self.speculative_quantile = speculative_quantile
        self.speculative_factor = float(speculative_factor)
        self.min_samples = int(min_samples)
        self.status = {s.index: self.PENDING for s in self.shards}
        self.owner = {s.index: self.hosts[s.index % len(self.hosts)]
                      for s in self.shards}
        self.queues = {h: deque() for h in self.hosts}
        for s in self.shards:
            self.queues[self.owner[s.index]].append((s, "own"))
        self.starts = {}  # (shard index, host) -> start clock
        self.durations = []  # first-completion wall times
        self.dead = set()
        self.speculated = set()
        self.reassigned = 0

    # -- scheduling --------------------------------------------------------

    def next_work(self, host, now: float):
        """Claim the next unit for ``host``: its own queue first, then
        a speculative duplicate of a straggling shard. Returns
        ``(shard, mode)`` with mode in {"own", "orphan", "speculate"},
        or None when there is nothing for this host right now."""
        launch = None
        with self._lock:
            if host in self.dead:
                return None
            q = self.queues[host]
            while q:
                shard, mode = q.popleft()
                if self.status[shard.index] == self.DONE:
                    continue
                self.status[shard.index] = self.RUNNING
                self.starts[(shard.index, host)] = now
                return shard, mode
            cand = self._speculation_candidate(host, now)
            if cand is None:
                return None
            shard, elapsed, thr = cand
            self.speculated.add(shard.index)
            self.status[shard.index] = self.RUNNING
            self.starts[(shard.index, host)] = now
            launch = (shard, elapsed, thr)
        shard, elapsed, thr = launch
        obs.record_speculative_launch(shard.index, host,
                                      runtime_s=elapsed, threshold_s=thr)
        return shard, "speculate"

    def _speculation_candidate(self, host, now):
        # lock held
        thr = self.speculation_threshold()
        if thr is None:
            return None
        best = None
        for s in self.shards:
            i = s.index
            if (self.status[i] != self.RUNNING or i in self.speculated):
                continue
            runners = [h for (j, h) in self.starts if j == i]
            if host in runners:
                continue
            started = min(self.starts[(i, h)] for h in runners)
            elapsed = now - started
            if elapsed > thr and (best is None or elapsed > best[1]):
                best = (s, elapsed)
        return None if best is None else (best[0], best[1], thr)

    def speculation_threshold(self):
        """``factor`` x the q-quantile of completed-shard durations, or
        None while speculation is off / under-sampled. Durations also
        land in stage_duration_seconds{stage="elastic.shard"} via
        mark_done, so dashboards see the same distribution."""
        if self.speculative_quantile is None:
            return None
        dur = sorted(self.durations)
        if len(dur) < self.min_samples:
            return None
        q = min(max(float(self.speculative_quantile), 0.0), 1.0)
        return self.speculative_factor * dur[int(q * (len(dur) - 1))]

    def mark_done(self, shard: WorkShard, host, now: float) -> bool:
        """Record one executor's completion; True iff it was the shard's
        first (the winner whose duration feeds the histogram)."""
        with self._lock:
            start = self.starts.get((shard.index, host))
            first = self.status[shard.index] != self.DONE
            self.status[shard.index] = self.DONE
            if first and start is not None:
                self.durations.append(now - start)
        if first and start is not None:
            obs.record_stage("elastic.shard", now - start)
        return first

    def all_done(self) -> bool:
        with self._lock:
            return all(v == self.DONE for v in self.status.values())

    def done_count(self) -> int:
        with self._lock:
            return sum(1 for v in self.status.values() if v == self.DONE)

    # -- failover ----------------------------------------------------------

    def orphan_stale(self, stale_hosts, reason: str = "heartbeat") -> int:
        """Mark ``stale_hosts`` dead and reassign their unfinished
        shards round-robin to the surviving hosts. Idempotent for
        already-dead hosts; returns the number of reassignments."""
        stale = {str(h) for h in stale_hosts}
        events = []
        with self._lock:
            newly = [h for h in self.hosts
                     if str(h) in stale and h not in self.dead]
            if not newly:
                return 0
            self.dead.update(newly)
            survivors = sorted(h for h in self.hosts if h not in self.dead)
            if not survivors:
                raise RuntimeError(
                    "elastic failover: no surviving hosts to reassign to")
            orphans = []
            for h in newly:
                for shard, _mode in self.queues[h]:
                    if self.status[shard.index] != self.DONE:
                        orphans.append((shard, h))
                self.queues[h].clear()
                # Shards RUNNING on the dead host with no live
                # co-runner (no speculative duplicate) are orphans too.
                for s in self.shards:
                    i = s.index
                    if self.status[i] != self.RUNNING:
                        continue
                    runners = {hh for (j, hh) in self.starts if j == i}
                    if h in runners and not (runners - self.dead):
                        orphans.append((s, h))
            seen = set()
            for k, (shard, from_host) in enumerate(orphans):
                if shard.index in seen:
                    continue
                seen.add(shard.index)
                to_host = survivors[k % len(survivors)]
                self.owner[shard.index] = to_host
                self.status[shard.index] = self.PENDING
                self.queues[to_host].append((shard, "orphan"))
                self.reassigned += 1
                events.append((shard.index, from_host, to_host))
        for idx, from_host, to_host in events:
            obs.record_shard_orphaned(idx, from_host, reason=reason)
            obs.record_shard_reassigned(idx, from_host, to_host)
        return len(events)


# ---------------------------------------------------------------------------
# Shard execution (shared by both drivers)
# ---------------------------------------------------------------------------


def _make_executor(source, config, batch_size: int, exec_lock):
    """shard -> (levels, meta): read the shard's batch slice, run the
    ordinary cascade on it, capture the partial pyramid. The global
    lock serializes JAX execution across simulated-host threads.

    Morton shards (``shard.code_lo is not None``) read their batch
    slice and keep only rows whose projected detail code falls in
    ``[code_lo, code_hi)``. Rows with invalid projection belong to NO
    range — they contribute nothing in any path (``valid=False`` lanes
    in the ordinary cascade), so dropping them keeps the merged result
    byte-identical to batch-range sharding."""
    from heatmap_tpu.parallel.multihost import _CaptureLevels
    from heatmap_tpu.pipeline.batch import (
        _run_loaded,
        ingest_columns,
        project_detail_codes,
    )

    def execute(shard: WorkShard):
        batches = itertools.islice(source.batches(batch_size),
                                   shard.lo, shard.hi)
        with exec_lock:
            data = ingest_columns(batches, config)
            if data is not None and shard.code_lo is not None:
                codes, valid = project_detail_codes(
                    np.asarray(data["latitude"], np.float64),
                    np.asarray(data["longitude"], np.float64),
                    config.detail_zoom, prefer_device=False)
                codes = np.asarray(codes)
                keep = (np.asarray(valid)
                        & (codes >= shard.code_lo)
                        & (codes < shard.code_hi))
                if keep.any():
                    data = {k: np.asarray(v)[keep]
                            for k, v in data.items()}
                else:
                    data = None  # empty range: publish an empty partial
            cap = _CaptureLevels()
            meta = {"points": 0, "content_digest": None}
            if data is not None:
                meta["content_digest"] = columns_digest(data)
                meta["points"] = int(len(next(iter(data.values()))))
                _run_loaded(data, config, as_json=True, sink=cap)
        return cap.levels, meta

    return execute


# ---------------------------------------------------------------------------
# Simulated-host driver
# ---------------------------------------------------------------------------


def _run_simulated(plan, lineage, execute, *, n_hosts: int,
                   heartbeat_deadline_s, on_straggler: str,
                   speculative_quantile, speculative_factor: float,
                   wedge_host=None, wedge_after: int = 0,
                   wedge_spec: str | None = None,
                   beat_interval_s: float = 0.05,
                   clock=time.monotonic) -> ElasticCoordinator:
    """Drive ``plan`` to completion over ``n_hosts`` worker threads.

    ``wedge_host``/``wedge_after`` model a zombie host for chaos runs:
    once ``wedge_after`` shards have completed anywhere, that host
    stops claiming work but keeps *attempting* heartbeats. At the
    moment the wedge trips, ``wedge_spec`` (e.g.
    ``"scale=0,multihost.heartbeat@p2=999"``) is installed on the
    fault plane, so every later beat is lost in transit through the
    ``multihost.heartbeat`` site — the monitor then sees a live host go
    stale *mid-cascade* with unfinished shards still queued
    (guaranteeing orphans exist, not just suppressed gauges)."""
    hosts = list(range(n_hosts))
    coord = ElasticCoordinator(
        plan, hosts, speculative_quantile=speculative_quantile,
        speculative_factor=speculative_factor)
    abort = threading.Event()
    idle = threading.Event()  # never set; a shared timed-wait primitive
    wedge_armed = threading.Event()
    errors = []

    def worker(host):
        last_beat = None
        try:
            while not abort.is_set():
                now = clock()
                if last_beat is None or now - last_beat >= beat_interval_s:
                    obs.heartbeat("elastic", process=host)
                    last_beat = now
                wedged = (wedge_host is not None and host == wedge_host
                          and coord.done_count() >= wedge_after)
                if wedged and not wedge_armed.is_set():
                    wedge_armed.set()
                    if wedge_spec is not None:
                        faults.install_spec(wedge_spec)
                work = None if wedged else coord.next_work(host, now)
                if work is None:
                    if coord.all_done():
                        return
                    idle.wait(_POLL_S)
                    continue
                shard, mode = work
                if lineage.is_complete(shard):
                    coord.mark_done(shard, host, clock())
                    continue
                site = ("elastic.reassign" if mode == "orphan"
                        else "shard.compute")
                levels, meta = faults.retry_call(
                    execute, shard, site=site, key=shard.index)
                won, quarantined = lineage.publish(shard, host, levels,
                                                   meta)
                coord.mark_done(shard, host, clock())
                if mode == "speculate":
                    orig = coord.owner.get(shard.index)
                    obs.record_speculative_result(
                        shard.index, winner=host if won else orig,
                        loser=orig if won else host,
                        won=won, quarantined=quarantined)
        except BaseException as e:  # noqa: BLE001 — surfaced to driver
            # Tail-promote the dying worker's trace out of the flight
            # recorder before the driver re-raises (no-op when none).
            from heatmap_tpu.obs import recorder as recorder_mod

            recorder_mod.maybe_promote(error=True)
            errors.append((host, e))
            abort.set()

    workers = [threading.Thread(target=worker, args=(h,),
                                name=f"elastic-h{h}", daemon=True)
               for h in hosts]
    for w in workers:
        w.start()
    straggler = None
    try:
        while any(w.is_alive() for w in workers):
            for w in workers:
                w.join(timeout=_POLL_S)
            if errors or abort.is_set():
                break
            if (heartbeat_deadline_s is not None
                    and obs.get_registry().enabled):
                from heatmap_tpu.parallel.multihost import (
                    StragglerTimeout, check_heartbeats)

                try:
                    check_heartbeats(heartbeat_deadline_s)
                except StragglerTimeout as e:
                    if on_straggler == "raise":
                        straggler = e
                        abort.set()
                        break
                    coord.orphan_stale(e.stale)
    finally:
        if straggler is not None or errors:
            abort.set()
        for w in workers:
            w.join(timeout=5.0)
    if straggler is not None:
        raise straggler
    if errors:
        raise errors[0][1]
    return coord


# ---------------------------------------------------------------------------
# Real-process driver (manifest-based, collective-free)
# ---------------------------------------------------------------------------


def _run_multiprocess(plan, lineage, execute, *, rank: int, n_procs: int,
                      heartbeat_deadline_s, on_straggler: str,
                      clock=time.monotonic):
    """Each process executes its own shards, then polls the shared
    manifest. Failure detection is progress-based (per-process
    registries cannot see remote heartbeats): when no shard completes
    for a full deadline, survivors claim every still-missing shard in
    deterministic order — publish atomicity keeps the merge
    exactly-once even if two survivors double-claim."""
    from heatmap_tpu.parallel.multihost import StragglerTimeout

    deadline = heartbeat_deadline_s or 60.0
    reassigned = 0
    for s in plan:
        if s.index % n_procs != rank or lineage.is_complete(s):
            continue
        levels, meta = faults.retry_call(execute, s, site="shard.compute",
                                         key=s.index)
        lineage.publish(s, f"proc{rank}", levels, meta)
    obs.heartbeat("elastic_own_done")
    waiter = threading.Event()
    last_progress = clock()
    last_count = lineage.completed_count(plan)
    while True:
        pending = [s for s in plan if not lineage.is_complete(s)]
        if not pending:
            break
        count = len(plan) - len(pending)
        if count > last_count:
            last_count, last_progress = count, clock()
        elif clock() - last_progress > deadline:
            stale = {f"proc{s.index % n_procs}": clock() - last_progress
                     for s in pending}
            if on_straggler == "raise":
                raise StragglerTimeout(deadline, stale)
            for s in pending:
                if lineage.is_complete(s):
                    continue
                owner = s.index % n_procs
                obs.record_shard_orphaned(s.index, f"proc{owner}",
                                          reason="no manifest progress")
                obs.record_shard_reassigned(s.index, f"proc{owner}",
                                            f"proc{rank}")
                levels, meta = faults.retry_call(
                    execute, s, site="elastic.reassign", key=s.index)
                won, _ = lineage.publish(s, f"proc{rank}", levels, meta)
                reassigned += int(won)
            last_progress = clock()
        waiter.wait(_POLL_S)
    return reassigned


# ---------------------------------------------------------------------------
# The job entry point
# ---------------------------------------------------------------------------


_PLAN_SAMPLE_ROWS = 1 << 17


def _plan_source_partition(source, config, batch_size: int, n_shards: int):
    """Sample the source's leading batches and build a Morton-range
    PartitionPlan for ``n_shards`` ranges, or None when the source
    yields no projectable rows. Sources are re-iterable (the batch
    executors re-read them), so consuming a prefix here is safe."""
    from heatmap_tpu.parallel.partition import plan_partition
    from heatmap_tpu.pipeline.batch import project_detail_codes

    lats: list[np.ndarray] = []
    lons: list[np.ndarray] = []
    seen = 0
    for batch in source.batches(batch_size):
        lat = np.asarray(batch["latitude"], np.float64)
        lon = np.asarray(batch["longitude"], np.float64)
        lats.append(lat)
        lons.append(lon)
        seen += lat.size
        if seen >= _PLAN_SAMPLE_ROWS:
            break
    if not seen:
        return None
    lat = np.concatenate(lats)[:_PLAN_SAMPLE_ROWS]
    lon = np.concatenate(lons)[:_PLAN_SAMPLE_ROWS]
    codes, valid = project_detail_codes(lat, lon, config.detail_zoom,
                                        prefer_device=False)
    return plan_partition(np.asarray(codes), n_shards,
                          detail_zoom=config.detail_zoom,
                          valid=np.asarray(valid),
                          n_levels=config.cascade_config().n_levels)


def run_job_elastic(source, sink=None, config=None, *,
                    batch_size: int = 1 << 20,
                    n_total: int | None = None,
                    lineage_dir: str,
                    n_hosts: int | None = None,
                    shards_per_host: int = 2,
                    heartbeat_deadline_s: float | None = None,
                    on_straggler: str = "reassign",
                    speculative_quantile: float | None = None,
                    speculative_factor: float = 2.0,
                    wedge_host=None, wedge_after: int = 0,
                    wedge_spec: str | None = None,
                    beat_interval_s: float = 0.05,
                    partition: str = "batch",
                    clock=time.monotonic) -> dict:
    """Run a batch job elastically: shard-lineage manifest under
    ``lineage_dir``, failover re-execution on straggler timeout,
    optional speculative duplication of stragglers.

    ``partition`` picks the shard geometry: "batch" (default — the
    historical contiguous batch-range slices) or "morton" — a
    Morton-range plan sampled from the source's leading batches
    (parallel/partition.py) assigns each shard one contiguous
    detail-code range spanning ALL batches, so a dead host's failover
    re-executes only its tile ranges and the recovered bytes are
    pinned identical (tools/chaos_soak.py ``host_loss_morton``). A
    degenerate plan (all sampled mass effectively in one range) falls
    back to "batch" with a ``backend_resolved`` audit event. Morton
    shards each re-read the job's batch range and filter to their
    range: the trade is ingest read amplification for range-local
    recovery, the right side of the trade when recompute (cascade)
    dominates re-read (docs/parallel-partitioning.md).

    Single JAX process: ``n_hosts`` simulated hosts (threads) share the
    local devices; real multi-process: each process is one host (see
    the module docstring for the two drivers). The output is exact:
    the final merge draws each shard's partial pyramid from exactly one
    manifest winner, and merge_level_dirs re-aggregates rows
    deterministically — an interrupted-and-failed-over run is
    byte-identical to an unfailed one.

    ``sink`` must be columnar (``write_levels``, e.g. arrays:DIR — the
    serve tier reads these directly) or None. ``wedge_host`` /
    ``wedge_after`` / ``clock`` are chaos/test hooks, forwarded from
    ``run_job_multihost(elastic_opts=...)``.
    """
    import jax

    from heatmap_tpu.pipeline import BatchJobConfig

    config = config or BatchJobConfig()
    if on_straggler not in ("reassign", "raise"):
        raise ValueError(f"unknown on_straggler mode {on_straggler!r}")
    if sink is not None and not hasattr(sink, "write_levels"):
        raise ValueError(
            "elastic egress is columnar: pass a write_levels sink "
            "(arrays:DIR / LevelArraysSink — the serve tier reads "
            "these directly) or sink=None"
        )
    if n_total is None:
        n_total = getattr(source, "n", None)
        if n_total is None:
            raise ValueError(
                "elastic sharding needs n_total (source row count) or a "
                "source with an ``n`` attribute — shards are batch "
                "ranges, so the batch count must be known up front")
    if partition not in ("batch", "morton"):
        raise ValueError(
            f"unknown partition mode {partition!r}: expected 'batch' or "
            "'morton'")
    n_procs = jax.process_count()
    if n_hosts is None:
        n_hosts = n_procs if n_procs > 1 else 2
    n_batches = max(1, -(-int(n_total) // int(batch_size)))
    job_fp = job_fingerprint(source, config, batch_size, n_total)
    n_shards = n_hosts * max(1, int(shards_per_host))
    code_ranges = None
    if partition == "morton":
        plan_obj = _plan_source_partition(source, config, batch_size,
                                          n_shards)
        if plan_obj is None or plan_obj.degenerate:
            if obs.telemetry_enabled():
                mass = (max(plan_obj.shard_mass or [0.0])
                        if plan_obj is not None else 0.0)
                obs.emit(
                    "backend_resolved",
                    requested="partition=morton",
                    resolved="partition=batch",
                    reason=("degenerate partition plan (max shard mass "
                            f"{mass:.3f}) — Morton ranges would serialize "
                            "the job on one shard; falling back to batch "
                            "ranges"))
        else:
            code_ranges = plan_obj.code_ranges()
    plan = plan_shards(n_batches, n_shards, job_fp,
                       code_ranges=code_ranges)
    lineage = ShardLineage(lineage_dir)
    exec_lock = threading.Lock()
    execute = _make_executor(source, config, batch_size, exec_lock)

    reassigned = speculated = 0
    if n_procs > 1:
        reassigned = _run_multiprocess(
            plan, lineage, execute, rank=jax.process_index(),
            n_procs=n_procs, heartbeat_deadline_s=heartbeat_deadline_s,
            on_straggler=on_straggler, clock=clock)
        write = jax.process_index() == 0
    else:
        coord = _run_simulated(
            plan, lineage, execute, n_hosts=n_hosts,
            heartbeat_deadline_s=heartbeat_deadline_s,
            on_straggler=on_straggler,
            speculative_quantile=speculative_quantile,
            speculative_factor=speculative_factor,
            wedge_host=wedge_host, wedge_after=wedge_after,
            wedge_spec=wedge_spec,
            beat_interval_s=beat_interval_s, clock=clock)
        reassigned, speculated = coord.reassigned, len(coord.speculated)
        write = True
    merged = lineage.merge(plan)
    rows = 0
    if sink is not None and write:
        rows = sink.write_levels(merged)
    return {"egress": "levels-elastic", "levels": len(merged),
            "rows": int(rows), "shards": len(plan),
            "reassigned": int(reassigned), "speculated": int(speculated),
            "lineage_dir": lineage_dir}
