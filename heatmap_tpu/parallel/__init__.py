"""Device-mesh parallelism: the TPU-native replacement for Spark's cluster.

The reference scales by elastic Spark executors + a netty shuffle
service (reference submit-heatmap:9-13); here the same roles are played
by a ``jax.sharding.Mesh`` and XLA collectives over ICI/DCN:

- points are sharded over the ``data`` mesh axis (the RDD-partition
  analog, reference heatmap.py:154);
- partial tile rasters merge with ``lax.psum`` (reduceByKey analog) or
  ``lax.psum_scatter`` when the merged raster should itself stay
  sharded over the ``tile`` axis (groupByKey analog);
- sparse per-key aggregates merge via ``all_gather`` + local re-reduce.

Everything works identically on a single host (8 virtual CPU devices in
tests), one real TPU chip, or a multi-host DCN-spanning mesh — only the
mesh construction differs (mesh.py).
"""

from heatmap_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    TILE_AXIS,
    force_cpu_devices,
    make_mesh,
    named_sharding,
    pad_to_multiple,
)
from heatmap_tpu.parallel.gspmd import (  # noqa: F401
    DonatedBufferError,
    DonationLedger,
    donating_jit,
    donation_supported,
    pyramid_gspmd_range,
    pyramid_gspmd_uniform,
    route_on_device,
)
from heatmap_tpu.parallel.sharded import (  # noqa: F401
    aggregate_keys_sharded,
    bin_points_bandsharded,
    bin_points_replicated,
    bin_points_rowsharded,
    pyramid_rowsharded,
    pyramid_sparse_morton_prefix_sharded,
    pyramid_sparse_morton_range_sharded,
    pyramid_sparse_morton_sharded,
    splat_rowsharded,
)
from heatmap_tpu.parallel.partition import (  # noqa: F401
    PartitionPlan,
    plan_partition,
    route_emissions,
)
from heatmap_tpu.parallel.multihost import (  # noqa: F401
    StragglerTimeout,
    check_heartbeats,
    gather_blobs,
    initialize,
    make_hybrid_mesh,
    process_shard_bounds,
    run_job_multihost,
    shard_source,
    shard_source_rows,
)
from heatmap_tpu.parallel.elastic import (  # noqa: F401
    ElasticCoordinator,
    ShardLineage,
    WorkShard,
    job_fingerprint,
    plan_shards,
    run_job_elastic,
    shard_fingerprint,
)
