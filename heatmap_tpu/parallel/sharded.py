"""shard_map kernels: data-parallel binning with collective merges.

Communication mapping from the reference (SURVEY.md §2.3):

| reference (Spark)                   | here (XLA collectives)            |
|-------------------------------------|-----------------------------------|
| RDD partitions of `locations`       | points sharded on the data axis   |
| reduceByKey shuffle (heatmap.py:111)| lax.psum of partial rasters       |
| groupByKey shuffle (heatmap.py:112) | lax.psum_scatter (sharded raster) |
|                                     | / all_gather + local re-reduce    |
| external shuffle service            | — (ICI/DCN, no spill)             |

All kernels are pure and shard_map-traced over the mesh from
parallel.mesh; wrap in ``jax.jit`` for the compiled path. On a 2D
(data, tile) mesh the point-parallel kernels shard points over BOTH
axes (collectives run over the flattened axes), and
``bin_points_bandsharded`` uses the tile axis as true tile-space
parallelism: an ``all_to_all`` regroups points so each device only
ever materializes its own raster band — the groupByKey analog for
rasters too big for one device's HBM.

The two cascade pyramids here (uniform and Morton-range) also exist as
global-view NamedSharding programs in parallel/gspmd.py — one compiled
program with on-device routing, byte-identical outputs (pinned by
tests/test_gspmd.py). This shard_map formulation stays selectable via
``dispatch="shard_map"`` as the differential-testing oracle.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from heatmap_tpu.ops import (
    histogram,
    pyramid as pyramid_ops,
    sparse as sparse_ops,
    sparse_partitioned,
)
from heatmap_tpu.parallel.mesh import DATA_AXIS, TILE_AXIS, shard_map
from heatmap_tpu.tilemath import mercator


def _shard_axes(mesh: Mesh):
    """(axis names, total shards) the point-parallel kernels span.

    tile == 1 keeps the single ``data`` axis; tile > 1 flattens points
    over (data, tile) so a 2D mesh still uses every device — the tile
    axis only becomes *spatial* in bin_points_bandsharded.
    """
    if mesh.shape[TILE_AXIS] == 1:
        return (DATA_AXIS,), mesh.shape[DATA_AXIS]
    return (DATA_AXIS, TILE_AXIS), mesh.shape[DATA_AXIS] * mesh.shape[TILE_AXIS]


def _ones_like_weights(weights, n, dtype):
    return jnp.ones((n,), dtype) if weights is None else jnp.asarray(weights, dtype)


def _local_detail_stage(backend, counts_only, local_capacity, acc_dtype,
                        sentinel, weight_bound=None):
    """The per-device reduce-by-key the sharded pyramids run inside
    their shard_map bodies: "scatter" (ops/sparse.py sort +
    segment-scatter) or "partitioned" (sort + the multi-channel MXU
    segment kernel, ops/sparse_partitioned.py). Both return the same
    compact (unique[cap], sums[cap], n_unique) contract — sorted
    uniques, sentinel/zero padding, n_unique past capacity on overflow
    — so the cross-device merge and rollup are backend-agnostic and
    results stay bit-identical (counts and bounded-integer weighted
    sums are exact in any summation order)."""
    if backend == "scatter":
        def stage(k, w, v):
            return sparse_ops.aggregate_keys(
                k, weights=w, valid=v, capacity=local_capacity,
                acc_dtype=acc_dtype,
            )
        return stage
    if backend != "partitioned":
        raise ValueError(f"unknown cascade backend {backend!r}")

    def stage(k, w, v):
        masked = jnp.where(v, k, sentinel)
        if counts_only:
            # Unstable sort: equal keys are indistinguishable payloads.
            u, s, n = sparse_partitioned.aggregate_sorted_keys_partitioned(
                jnp.sort(masked), local_capacity, sentinel=sentinel,
            )
        else:
            order = jnp.argsort(masked, stable=True)
            u, s, n = sparse_partitioned.aggregate_sorted_keys_partitioned(
                masked[order], local_capacity, sentinel=sentinel,
                sorted_weights=w[order], weight_bound=weight_bound,
            )
        # The kernel upcasts keys (and its sentinel pad) to int64; the
        # stage contract is scatter's — uniques in the INPUT dtype.
        # Downstream re-reductions derive their pad sentinel from the
        # array dtype, so an int64 partial from int32 keys would make
        # the prefix merge's rollup treat int64-max pad lanes as real
        # keys (they no longer equal the int32-max sentinel). Real
        # keys and the sentinel both fit the input dtype by
        # construction, so the cast is lossless.
        return u.astype(k.dtype), s.astype(acc_dtype), n

    return stage


def bin_points_replicated(
    latitude,
    longitude,
    window: histogram.Window,
    mesh: Mesh,
    weights=None,
    valid=None,
    proj_dtype=None,
    dtype=None,
    backend: str = "auto",
):
    """Bin sharded points into a window raster, psum-merged -> replicated.

    The direct reduceByKey replacement: every device bins its point
    shard into a full local (H, W) raster, then one ``lax.psum`` over
    ICI merges them. Point arrays must be divisible by the number of
    point shards (see mesh.pad_to_multiple).

    ``backend`` routes the shard-local binning (ops.histogram backends;
    "auto" picks the measured-fastest kernel per window/platform — the
    same 2.2x partitioned-MXU routing single-chip jobs get). Count jobs
    keep the count-only kernels: the unit weights materialized for the
    uniform shard_map specs are NOT passed to the histogram.
    """
    axes, _ = _shard_axes(mesh)
    if dtype is None:
        dtype = jnp.int32 if weights is None else jnp.float32
    counts_only = weights is None
    n = latitude.shape[0]
    w = _ones_like_weights(weights, n, dtype)
    v = jnp.ones((n,), bool) if valid is None else jnp.asarray(valid, bool)

    def local(la, lo, w, v):
        raster = histogram.bin_points_window(
            la, lo, window, weights=None if counts_only else w, valid=v,
            proj_dtype=proj_dtype, dtype=dtype, backend=backend,
        )
        return lax.psum(raster, axes)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axes), P(axes), P(axes), P(axes)),
        out_specs=P(),
        # pallas_call outputs carry no varying-mesh-axes metadata, so
        # the vma check rejects backend="pallas"/"partitioned" routing;
        # collective placement here is pinned by the mesh equality tests.
        check_vma=False,
    )
    return fn(latitude, longitude, w, v)


def bin_points_rowsharded(
    latitude,
    longitude,
    window: histogram.Window,
    mesh: Mesh,
    weights=None,
    valid=None,
    proj_dtype=None,
    dtype=None,
    backend: str = "auto",
):
    """Bin sharded points into a raster left row-sharded across devices.

    The groupByKey replacement: ``lax.psum_scatter`` merges partial
    rasters AND leaves device i owning row block i — each device holds
    its slice of merged tile space, like a Spark reducer holding its key
    range, but the "shuffle" rides ICI as one fused collective. Global
    result shape (H, W), sharded (H/shards, W) per device;
    window.height must divide by the number of point shards.
    ``backend`` as in bin_points_replicated (shard-local kernel
    routing; count jobs keep the count-only kernels).
    """
    axes, ndev = _shard_axes(mesh)
    if window.height % ndev:
        raise ValueError(f"window height {window.height} not divisible by {ndev}")
    if dtype is None:
        dtype = jnp.int32 if weights is None else jnp.float32
    counts_only = weights is None
    n = latitude.shape[0]
    w = _ones_like_weights(weights, n, dtype)
    v = jnp.ones((n,), bool) if valid is None else jnp.asarray(valid, bool)

    def local(la, lo, w, v):
        raster = histogram.bin_points_window(
            la, lo, window, weights=None if counts_only else w, valid=v,
            proj_dtype=proj_dtype, dtype=dtype, backend=backend,
        )
        return lax.psum_scatter(raster, axes, scatter_dimension=0, tiled=True)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axes), P(axes), P(axes), P(axes)),
        out_specs=P(axes),
        check_vma=False,  # same pallas-routing rationale as above
    )
    return fn(latitude, longitude, w, v)


def pyramid_rowsharded(raster, levels: int, mesh: Mesh):
    """Pyramid over a row-sharded raster (output of bin_points_rowsharded).

    Levels coarsen locally (vma-checked shard_map) while every device's
    row block stays evenly divisible; the remaining coarse levels run
    as plain jit ops on the by-then-tiny global array, with GSPMD
    choosing their layout. Returns ``levels+1`` rasters: the first
    ``local_levels+1`` row-sharded; for the trailing levels use the
    VALUES, not ``.sharding`` — their placement is the compiler's.
    """
    axes, ndev = _shard_axes(mesh)
    h, w = raster.shape
    block_h = h // ndev
    local_levels = 0
    while local_levels < levels and (block_h >> local_levels) % 2 == 0:
        local_levels += 1
    gather_levels = levels - local_levels

    def body(block):
        outs = [block]
        for _ in range(local_levels):
            block = pyramid_ops.coarsen_raster(block)
            outs.append(block)
        return tuple(outs)

    out_specs = tuple([P(axes)] * (local_levels + 1))
    # vma-checked: every in-shard_map output is genuinely row-sharded.
    # The remaining coarse levels (shard rows no longer divisible by 2)
    # run outside as plain jit ops on the global array — GSPMD gathers
    # the by-then-tiny raster instead of an explicit all_gather.
    fn = shard_map(body, mesh=mesh, in_specs=(P(axes),), out_specs=out_specs)
    outs = list(fn(raster))
    full = outs[-1]
    for _ in range(gather_levels):
        full = pyramid_ops.coarsen_raster(full)
        outs.append(full)
    return outs


def aggregate_keys_sharded(
    keys, mesh: Mesh, weights=None, valid=None, capacity=None, acc_dtype=None,
    local_capacity=None,
):
    """Global reduce-by-key over sharded keys -> replicated uniques/sums.

    Per-device sort+segment-sum (ops/sparse.py), then an ``all_gather``
    of the compact per-device results and a local re-reduce — the
    all-reduce formulation of reduceByKey for sparse keys. ``capacity``
    bounds the merged unique count; ``local_capacity`` the per-device
    stage (default ``min(capacity, n // ndev)``, clamped to the shard
    row count — a shard can never hold more distinct keys than rows).
    Lower it when shards are known to carry few distinct keys: the
    all_gather moves ndev * local_capacity entries, so a tight bound
    directly shrinks the collective.
    """
    axes, ndev = _shard_axes(mesh)
    keys = jnp.asarray(keys)
    n = keys.shape[0]
    capacity = n if capacity is None else capacity
    # Per-device stage: an evenly-distributed shard holds at most
    # n//ndev distinct keys, so sizing it by the global capacity would
    # only inflate the all_gather.
    if local_capacity is None:
        local_capacity = min(capacity, n // ndev)
    # A shard can never hold more distinct keys than its row count, so
    # anything above n//ndev only pads the all_gather for nothing.
    local_capacity = max(1, min(local_capacity, n // ndev))
    if acc_dtype is None:
        acc_dtype = jnp.int32 if weights is None else jnp.float32
    w = _ones_like_weights(weights, n, acc_dtype)
    v = jnp.ones((n,), bool) if valid is None else jnp.asarray(valid, bool)
    sentinel = jnp.iinfo(keys.dtype).max

    def body(k, w, v):
        u, s, local_n = sparse_ops.aggregate_keys(
            k, weights=w, valid=v, capacity=local_capacity, acc_dtype=acc_dtype
        )
        return u, s, local_n[None]

    # The per-device compact partials come back as ordinary sharded
    # global arrays; the merge re-reduce runs OUTSIDE shard_map as
    # plain jit ops (GSPMD inserts the gather for the global sort).
    # Keeping the collective stage vma-checked means a spec regression
    # here fails at trace time instead of producing wrong numbers.
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axes), P(axes), P(axes)),
        out_specs=(P(axes), P(axes), P(axes)),
    )
    gu, gs, gn = fn(keys, w, v)
    mu, ms, mn = sparse_ops.aggregate_keys(
        gu, weights=gs, valid=gu != sentinel, capacity=capacity,
        acc_dtype=acc_dtype,
    )
    # Keep the documented overflow contract (ops/sparse.py): if ANY
    # device overflowed its local stage, keys were already dropped
    # before the merge and the merged count can look clean — force
    # the returned n_unique past capacity so callers detect it.
    local_overflow = (gn > local_capacity).any()
    mn = jnp.where(local_overflow, jnp.maximum(mn, capacity + 1), mn)
    return mu, ms, mn


def pyramid_sparse_morton_sharded(
    codes,
    mesh: Mesh,
    weights=None,
    valid=None,
    levels: int = 0,
    capacity=None,
    acc_dtype=None,
    backend: str = "scatter",
    weight_bound: int | None = None,
):
    """Sharded sparse pyramid: merge detail level once, then roll up.

    Each device reduces its shard at detail zoom; one all_gather merges
    the compact per-device results; the full pyramid then rolls up from
    the merged (already sorted) uniques via Morton shifts — replicated,
    since post-merge work is O(levels * capacity), tiny next to binning.

    ``capacity`` may be an int (same for all levels) or a per-level
    list, as in ops.pyramid.pyramid_sparse_morton — the composite-key
    cascade passes its zoom-clamped per-level capacities through here
    (pipeline/cascade.py build_cascade with a mesh). The per-device
    detail stage is sized by ``min(caps[0], shard rows)``: a shard's
    distinct keys are a subset of the global distinct keys, so a global
    capacity that holds the data also holds every shard.

    ``backend`` routes the per-device detail reduction (the hot stage —
    everything after it is O(capacity)): "scatter" or "partitioned"
    (see _local_detail_stage; weighted partitioned needs the
    bounded-integer ``weight_bound`` contract, enforced upstream by
    pipeline/cascade.py). The merge + rollup stay on the scatter ops
    either way: they run over compact partials where the MXU kernel
    has nothing to win, and re-aggregating sums as weights is exactly
    the shape the partitioned slab bound does not cover.
    """
    axes, ndev = _shard_axes(mesh)
    codes = jnp.asarray(codes)
    n = codes.shape[0]
    caps = pyramid_ops._level_caps(capacity, n, levels)
    local_capacity = max(1, min(caps[0], n // ndev))
    if acc_dtype is None:
        acc_dtype = jnp.int32 if weights is None else jnp.float32
    counts_only = weights is None
    w = _ones_like_weights(weights, n, acc_dtype)
    v = jnp.ones((n,), bool) if valid is None else jnp.asarray(valid, bool)
    sentinel = jnp.iinfo(codes.dtype).max
    stage = _local_detail_stage(backend, counts_only, local_capacity,
                                acc_dtype, sentinel,
                                weight_bound=weight_bound)

    def body(k, w, v):
        u, s, local_n = stage(k, w, v)
        return u, s, local_n[None]

    # Same structure as aggregate_keys_sharded: vma-checked sharded
    # stage -> per-device compact partials, merge + rollup outside as
    # plain jit ops on the global arrays. The partitioned stage's
    # pallas_call outputs carry no varying-mesh-axes metadata, so the
    # vma check only holds for the scatter body (same rationale as
    # bin_points_replicated); equality vs the single-device cascade is
    # pinned by tests/test_parallel.py either way.
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axes), P(axes), P(axes)),
        out_specs=(P(axes), P(axes), P(axes)),
        check_vma=backend == "scatter",
    )
    gu, gs, gn = fn(codes, w, v)
    out = pyramid_ops.pyramid_sparse_morton(
        gu,
        weights=gs,
        valid=gu != sentinel,
        levels=levels,
        capacity=caps,
        acc_dtype=acc_dtype,
    )
    # Propagate per-device overflow into every level's n_unique so the
    # ops/sparse.py overflow contract holds (see aggregate_keys_sharded).
    local_overflow = (gn > local_capacity).any()
    return [
        (
            lu,
            ls,
            jnp.where(local_overflow, jnp.maximum(ln, caps[lvl] + 1), ln),
        )
        for lvl, (lu, ls, ln) in enumerate(out)
    ]


def pyramid_sparse_morton_range_sharded(
    codes,
    mesh: Mesh,
    splits,
    *,
    code_bits: int,
    slot_bound: int,
    weights=None,
    valid=None,
    levels: int = 0,
    capacity=None,
    acc_dtype=None,
    backend: str = "scatter",
    weight_bound: int | None = None,
):
    """Range-sharded sparse pyramid: local rollup, boundary-only merge.

    Inputs are PRE-ROUTED host-side by a Morton partition plan
    (parallel/partition.route_emissions): shard ``k``'s contiguous
    block holds only composite keys whose detail Morton code lies in
    range ``k`` of ``splits`` (``#{splits <= code} == k``). Because the
    pyramid parent is ``code >> 2`` (order-preserving), each shard's
    rollup is entirely local except for *boundary tiles* — parents
    whose children straddle a split code. There are at most
    ``len(splits)`` such codes per level globally and at most 2 per
    shard (a shard's local keys live in tiles intersecting its own
    contiguous range, and the only straddling tiles that can intersect
    it are the ones covering its two endpoints), so the cross-chip
    exchange is an all_gather of ``<= 2 * slot_bound`` rows per shard
    per level instead of full-pyramid partials.

    Boundary merge is patch-then-rollup: at every coarse level each
    shard extracts its boundary rows, all_gathers them, and the FIRST
    holder (lowest gathered block that holds the key) replaces its
    partial with the cross-shard total while every other holder drops
    its row — each key then lives on exactly one shard again, so the
    next level's local rollup stays exact by induction. Totals are
    plain sums of the gathered partials, exact in any order for counts
    and bounded-integer weighted sums (the same contract every other
    merge in this file relies on).

    ``splits`` is a TRACED ``(n_shards - 1,)`` int array of detail
    Morton codes (code part only, no slot bits) so every plan shares
    one compilation; ``code_bits = 2 * detail_zoom`` and ``slot_bound``
    (the slot vocabulary size) are static. Final per-level results are
    compacted to canonical sorted order outside the shard_map (keys are
    globally disjoint, so a global argsort + truncate reproduces the
    replicated path's arrays byte-for-byte).
    """
    axes, ndev = _shard_axes(mesh)
    codes = jnp.asarray(codes)
    n = codes.shape[0]
    if n % ndev != 0:
        raise ValueError(
            f"range-sharded cascade needs n % n_shards == 0, got "
            f"{n} % {ndev} (the host router pads each segment)")
    splits = jnp.asarray(splits)
    if splits.shape != (ndev - 1,):
        raise ValueError(
            f"need {ndev - 1} split codes for {ndev} shards, got "
            f"shape {splits.shape}")
    caps = pyramid_ops._level_caps(capacity, n, levels)
    local_capacity = max(1, min(caps[0], n // ndev))
    lcaps = [max(1, min(caps[lvl], local_capacity))
             for lvl in range(levels + 1)]
    # Per-shard boundary rows: <= 2 straddling tiles x slot_bound slots
    # (docstring argument); clamped to the level's local width.
    bcaps = [max(1, min(lcaps[lvl], 2 * slot_bound))
             for lvl in range(levels + 1)]
    if acc_dtype is None:
        acc_dtype = jnp.int32 if weights is None else jnp.float32
    counts_only = weights is None
    w = _ones_like_weights(weights, n, acc_dtype)
    v = jnp.ones((n,), bool) if valid is None else jnp.asarray(valid, bool)
    sentinel = jnp.iinfo(codes.dtype).max
    stage = _local_detail_stage(backend, counts_only, local_capacity,
                                acc_dtype, sentinel,
                                weight_bound=weight_bound)

    def body(k, w, v, spl):
        me = lax.axis_index(axes[0])
        for ax in axes[1:]:
            me = me * mesh.shape[ax] + lax.axis_index(ax)
        spl = spl.astype(k.dtype)
        u, s, ln = stage(k, w, v)
        over = ln > local_capacity
        # Detail level: routing is by detail code, so no two shards
        # share a key and the boundary set is empty (an integer split
        # cannot fall strictly inside a single-code range).
        outs = [u, s, jnp.sum(u != sentinel)[None]]
        cur_u, cur_s = u, s
        for lvl in range(1, levels + 1):
            parents = jnp.where(cur_u == sentinel, sentinel, cur_u >> 2)
            pu, ps, pn = sparse_ops.aggregate_sorted_keys(
                parents, cur_s, lcaps[lvl], sentinel=sentinel)
            over = over | (pn > lcaps[lvl])
            # Boundary codes at this level, from the traced splits: the
            # split's ancestor, unless the split is tile-aligned.
            blk = (1 << (2 * lvl)) - 1
            b = jnp.where((spl & blk) != 0, spl >> (2 * lvl), sentinel)
            code_mask = (1 << (code_bits - 2 * lvl)) - 1
            is_b = (pu != sentinel) & jnp.any(
                (pu & code_mask)[:, None] == b[None, :], axis=1)
            cb = bcaps[lvl]
            over = over | (jnp.sum(is_b) > cb)
            # Sort boundary rows to the front (sentinel-masked argsort)
            # and gather the fixed-width buffers + each block's shard id.
            bkey = jnp.where(is_b, pu, sentinel)
            border = jnp.argsort(bkey)[:cb]
            send_u = bkey[border]
            send_s = jnp.where(is_b, ps, jnp.zeros((), ps.dtype))[border]
            g_u = lax.all_gather(send_u, axes)     # (ndev, cb)
            g_s = lax.all_gather(send_s, axes)
            g_id = lax.all_gather(me, axes)        # (ndev,)

            def lookup(bu, bs):
                pos = jnp.clip(jnp.searchsorted(bu, pu), 0, cb - 1)
                hit = (bu[pos] == pu) & (pu != sentinel)
                return jnp.where(hit, bs[pos], jnp.zeros((), bs.dtype)), hit

            vals, hits = jax.vmap(lookup)(g_u, g_s)  # (ndev, lcap)
            total = jnp.sum(vals, axis=0)
            holder = g_id[jnp.argmax(hits, axis=0)]
            keep = ~is_b | (holder == me)
            new_u = jnp.where(keep, pu, sentinel)
            new_s = jnp.where(keep & is_b, total, ps)
            new_s = jnp.where(keep, new_s, jnp.zeros((), ps.dtype))
            reorder = jnp.argsort(new_u)
            cur_u, cur_s = new_u[reorder], new_s[reorder]
            outs.extend([cur_u, cur_s, jnp.sum(cur_u != sentinel)[None]])
        return (*outs, over[None])

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axes), P(axes), P(axes), P()),
        out_specs=(P(axes),) * (3 * (levels + 1) + 1),
        check_vma=backend == "scatter",
    )
    parts = fn(codes, w, v, splits)
    gover = parts[-1]
    any_over = gover.any()
    out = []
    for lvl in range(levels + 1):
        gu, gs, gn = parts[3 * lvl], parts[3 * lvl + 1], parts[3 * lvl + 2]
        cap = caps[lvl]
        # Keys are globally disjoint post-patch, so a global argsort of
        # the sentinel-padded shard blocks IS the canonical merged
        # order (sentinels sort last, their sums are zero).
        order = jnp.argsort(gu)
        su, ss = gu[order], gs[order]
        if su.shape[0] >= cap:
            su, ss = su[:cap], ss[:cap]
        else:
            su = jnp.concatenate(
                [su, jnp.full((cap - su.shape[0],), sentinel, su.dtype)])
            ss = jnp.concatenate(
                [ss, jnp.zeros((cap - ss.shape[0],), ss.dtype)])
        ln = gn.sum()
        # Same loud-overflow contract as the other sharded pyramids:
        # any shard-local overflow already dropped keys, so force the
        # count past capacity rather than return a clean-looking level.
        out.append((su, ss,
                    jnp.where(any_over, jnp.maximum(ln, cap + 1), ln)))
    return out


def pyramid_sparse_morton_prefix_sharded(
    codes,
    mesh: Mesh,
    weights=None,
    valid=None,
    levels: int = 0,
    capacity=None,
    acc_dtype=None,
    send_capacity: int | None = None,
    prefix_levels: int | None = None,
    backend: str = "scatter",
    weight_bound: int | None = None,
):
    """Sharded sparse pyramid with a coarse-prefix regrouped merge.

    The O(n/k)-per-stage formulation of
    :func:`pyramid_sparse_morton_sharded` (docs/DESIGN.md §4; the
    replicated variant re-reduces the gathered partials on EVERY
    device, O(global uniques) replicated — fine for clustered data,
    the scaling wall for unique-heavy data). Reference analog: Spark's
    hash-partitioned reducers never replicate the keyspace
    (reference heatmap.py:112).

    Stages, all inside one shard_map:

    1. per-device detail reduction to compact (key, sum) partials —
       unchanged from the replicated variant, routed by ``backend``
       ("scatter" sort + segment-sum, or "partitioned" for the MXU
       segment kernel — see pyramid_sparse_morton_sharded; the range
       merges below stay on the scatter ops, they are O(uniques/k));
    2. range splitters by regular sampling (the PSRS bound: with k
       evenly-spaced samples per device, no range holds more than
       2·n/k of the partials), each splitter rounded DOWN to a
       multiple of ``4^prefix_levels`` so a key and its first
       ``prefix_levels`` rollup ancestors (``key >> 2i``) land in the
       same range — cross-device parents are impossible through those
       levels;
    3. one ``lax.all_to_all`` regroups the compact partials to their
       range owner;
    4. each device merges (sort + segment-sum) and rolls up its
       keyspace range through ``prefix_levels`` levels — each stays
       O(uniques/k) per device;
    5. per-level results return range-sharded; the host-side
       compaction concatenates the (disjoint, ascending) range
       segments with a searchsorted gather — no sort, no re-reduce.
       Levels past ``prefix_levels`` roll up replicated from the
       compacted arrays — the same cheap tail the replicated merge
       runs for EVERY level, kept only where zoom-clamped capacities
       (or collapsed unique counts) have already made it small.

    ``prefix_levels`` trades locality depth against range balance:
    rounding a splitter down moves at most the ``4^prefix_levels``
    distinct keys of one block (times their <= k cross-device copies)
    into the lower range, so per-range load is bounded by
    ``2*local_capacity + k*4^prefix_levels``. The default picks the
    deepest value whose skew term stays within ``local_capacity``
    (and caps it at ``levels``) — full-depth locality for shallow
    pyramids, bounded-skew hybrid for the z21 cascade, where a
    ``4^15`` block could otherwise swallow a whole metro area's keys
    (measured: the hot-cluster bench overflowed exactly there).

    Results match the replicated merge EXACTLY for counts and
    integer-valued weighted sums (same sorted uniques, integer
    addition in any order); fractional weighted sums agree to f64
    summation-order rounding — the same contract as the replicated
    variant vs the single-device cascade.

    ``send_capacity`` bounds the per-(source, destination) all_to_all
    rows. The default (the per-device partial capacity) can NEVER
    drop entries; tightening it shrinks the exchange and the merge
    sort toward true O(n/k) but makes extreme skew (one source
    holding most of one range) overflow. Every overflow — send drop,
    range-buffer, or local-stage — is detected and propagated into
    every level's ``n_unique`` per the ops/sparse.py contract, never
    silent.
    """
    axes, ndev = _shard_axes(mesh)
    codes = jnp.asarray(codes)
    n = codes.shape[0]
    caps = pyramid_ops._level_caps(capacity, n, levels)
    local_capacity = max(1, min(caps[0], n // ndev))
    if prefix_levels is None:
        prefix_levels = 0
        while (prefix_levels < levels
               and ndev * (4 ** (prefix_levels + 1)) <= local_capacity):
            prefix_levels += 1
    prefix_levels = max(0, min(prefix_levels, levels))
    # PSRS bound + the rounding skew term (one 4^prefix_levels block's
    # distinct keys, each on up to ndev devices); a range can never
    # hold more uniques than the whole level either.
    slack = ndev * (4 ** prefix_levels)
    range_caps = [min(caps[lvl], 2 * local_capacity + slack)
                  for lvl in range(prefix_levels + 1)]
    send_cap = (local_capacity if send_capacity is None
                else max(1, min(send_capacity, local_capacity)))
    if acc_dtype is None:
        acc_dtype = jnp.int32 if weights is None else jnp.float32
    counts_only = weights is None
    w = _ones_like_weights(weights, n, acc_dtype)
    v = jnp.ones((n,), bool) if valid is None else jnp.asarray(valid, bool)
    sentinel = jnp.iinfo(codes.dtype).max
    prefix_bits = 2 * prefix_levels
    stage = _local_detail_stage(backend, counts_only, local_capacity,
                                acc_dtype, sentinel,
                                weight_bound=weight_bound)

    def body(k, w, v):
        u, s, ln = stage(k, w, v)
        # Regular sampling: ndev evenly-spaced picks from my sorted
        # valid partials (sentinel when fewer than sampled — empty
        # shards push their splitters to the top, shrinking their
        # influence instead of corrupting ranges).
        pos = (jnp.arange(ndev, dtype=jnp.int32)
               * jnp.minimum(ln, local_capacity)) // ndev
        samp = u[jnp.clip(pos, 0, local_capacity - 1)]
        all_samp = lax.all_gather(samp, axes, tiled=True)
        spl = jnp.sort(all_samp)[(jnp.arange(ndev - 1) + 1) * ndev]
        # Round each splitter down to a 4^levels block boundary so a
        # range owns whole rollup subtrees (sentinel splitters stay
        # above every real 58-bit key even after rounding).
        spl = (spl >> prefix_bits) << prefix_bits
        # Partition my (sorted) partials: dest is non-decreasing, so
        # per-destination runs are contiguous; sentinel pad lanes get
        # dest=ndev and fall out of the send buffers via mode="drop".
        lane_ok = u != sentinel
        dest = jnp.searchsorted(spl, u, side="right").astype(jnp.int32)
        dest = jnp.where(lane_ok, dest, ndev)
        bounds = jnp.searchsorted(
            dest, jnp.arange(ndev + 1, dtype=jnp.int32), side="left"
        )
        starts = bounds[:ndev]
        per_dest = bounds[1:] - bounds[:ndev]
        dropped = jnp.maximum(per_dest - send_cap, 0).sum().astype(jnp.int32)
        slot = (jnp.arange(local_capacity, dtype=jnp.int32)
                - starts[jnp.clip(dest, 0, ndev - 1)])
        send_u = jnp.full((ndev, send_cap), sentinel, u.dtype).at[
            dest, slot].set(u, mode="drop")
        send_s = jnp.zeros((ndev, send_cap), s.dtype).at[
            dest, slot].set(s, mode="drop")
        # The regroup "shuffle": row d goes to range owner d; row j of
        # the result came from source j (ascending ranges = ascending
        # device ids, which the host-side concatenation relies on).
        recv_u = lax.all_to_all(send_u, axes, 0, 0, tiled=True)
        recv_s = lax.all_to_all(send_s, axes, 0, 0, tiled=True)
        ru = recv_u.reshape(-1)
        mu, ms, mn = sparse_ops.aggregate_keys(
            ru, weights=recv_s.reshape(-1), valid=ru != sentinel,
            capacity=range_caps[0], acc_dtype=acc_dtype,
        )
        outs = [(mu, ms, mn[None])]
        for lvl in range(1, prefix_levels + 1):
            parents = jnp.where(mu == sentinel, sentinel, mu >> 2)
            mu, ms, mn = sparse_ops.aggregate_sorted_keys(
                parents, ms, range_caps[lvl], sentinel=sentinel
            )
            outs.append((mu, ms, mn[None]))
        return tuple(outs), ln[None], dropped[None]

    level_specs = tuple((P(axes), P(axes), P(axes))
                        for _ in range(prefix_levels + 1))
    # check_vma: pallas outputs carry no varying-mesh-axes metadata, so
    # the check only holds for the scatter detail stage (see
    # pyramid_sparse_morton_sharded).
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axes), P(axes), P(axes)),
        out_specs=(level_specs, P(axes), P(axes)),
        check_vma=backend == "scatter",
    )
    level_parts, gln, gdrop = fn(codes, w, v)
    # Anything lost BEFORE the range merge (local-stage overflow or a
    # tightened send_capacity dropping entries) poisons every level:
    # keys are already missing from the merged totals.
    pre_overflow = (gln > local_capacity).any() | (gdrop > 0).any()
    out = []
    any_range_over = pre_overflow
    for lvl, (gu, gs, gn) in enumerate(level_parts):
        rc = range_caps[lvl]
        cap = caps[lvl]
        any_range_over = any_range_over | (gn > rc).any()
        cn = jnp.minimum(gn, rc)
        csum = jnp.cumsum(cn)
        total = csum[-1]
        # Concatenate the k disjoint ascending range segments, skipping
        # each segment's sentinel pad: output slot j maps to (device,
        # local) via one searchsorted over the k segment offsets.
        j = jnp.arange(cap, dtype=jnp.int32)
        dev = jnp.clip(jnp.searchsorted(csum, j, side="right"), 0, ndev - 1)
        local = j - (csum[dev] - cn[dev])
        idx = jnp.clip(dev * rc + local, 0, gu.shape[0] - 1)
        u = jnp.where(j < total, gu[idx], sentinel)
        s = jnp.where(j < total, gs[idx], jnp.zeros((), gs.dtype))
        n_l = jnp.where(any_range_over, jnp.maximum(total, cap + 1), total)
        out.append((u, s, n_l))
    # Replicated tail: levels past the prefix-local depth roll up from
    # the compacted (sorted, sentinel-padded) arrays — identical math
    # to the replicated merge's rollup, paid only where capacities are
    # already small.
    u, s, _ = out[-1]
    for lvl in range(prefix_levels + 1, levels + 1):
        parents = jnp.where(u == sentinel, sentinel, u >> 2)
        u, s, n_l = sparse_ops.aggregate_sorted_keys(
            parents, s, caps[lvl], sentinel=sentinel
        )
        n_l = jnp.where(any_range_over,
                        jnp.maximum(n_l, caps[lvl] + 1), n_l)
        out.append((u, s, n_l))
    return out


def splat_rowsharded(raster, kernel_1d, mesh: Mesh):
    """Gaussian splat over a row-sharded raster via halo exchange.

    The stencil analog of the binning path's collectives: each device
    owns a horizontal band of the raster (as produced by
    bin_points_rowsharded); the vertical convolution needs
    ``len(kernel)//2`` rows from each neighbor, exchanged with two
    ``lax.ppermute`` shifts over ICI (zeros arrive at the global
    edges, matching SAME zero padding). The horizontal pass is purely
    local. Compute stays distributed — no device ever holds the full
    raster.
    """
    axes, ndev = _shard_axes(mesh)
    k = jnp.asarray(kernel_1d)
    if k.ndim != 1 or k.shape[0] % 2 == 0:
        raise ValueError(f"kernel must be 1D with odd length, got shape {k.shape}")
    half = (k.shape[0] - 1) // 2
    h, w = raster.shape
    if h % ndev:
        raise ValueError(f"raster height {h} not divisible by {ndev} devices")
    if half and h // ndev < half:
        raise ValueError(
            f"shard height {h // ndev} smaller than kernel half-width "
            f"{half}: halo exchange needs >= one kernel radius per shard"
        )

    def body(block):
        out_dtype = (
            block.dtype
            if jnp.issubdtype(block.dtype, jnp.floating)
            else k.dtype
        )
        x = block.astype(out_dtype)
        if half == 0:
            padded = x
        else:
            # Halo exchange: my last rows -> next device's top halo; my
            # first rows -> previous device's bottom halo. ppermute
            # yields zeros where no source sends (global edges).
            down = [(i, i + 1) for i in range(ndev - 1)]
            up = [(i, i - 1) for i in range(1, ndev)]
            top_halo = lax.ppermute(x[-half:], axes, down)
            bot_halo = lax.ppermute(x[:half], axes, up)
            padded = jnp.concatenate([top_halo, x, bot_halo], axis=0)
        kd = k.astype(out_dtype)
        # Vertical pass VALID over the halo-padded block, horizontal
        # pass SAME — same math as ops.splat.splat_raster globally.
        y = lax.conv_general_dilated(
            padded[None, None], kd[None, None, :, None], (1, 1),
            [(0, 0), (0, 0)],
        )
        y = lax.conv_general_dilated(
            y, kd[None, None, None, :], (1, 1), [(0, 0), (half, half)]
        )
        return y[0, 0]

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axes, None),),
        out_specs=P(axes, None),
    )
    return fn(raster)


def bin_points_bandsharded(
    latitude,
    longitude,
    window: histogram.Window,
    mesh: Mesh,
    weights=None,
    valid=None,
    proj_dtype=None,
    dtype=None,
    send_capacity: int | None = None,
    backend: str = "xla",
):
    """Tile-space-parallel binning: no device materializes the raster.

    The true groupByKey analog (SURVEY.md §2.3 spatial parallelism;
    reference heatmap.py:112 hash-partitions tile space across
    reducers): points are sharded over the whole (data, tile) mesh;
    each device projects its shard, an ``lax.all_to_all`` over the
    ``tile`` axis regroups points to the device owning their horizontal
    raster band, and each device bins ONLY its (H/T, W) band —
    per-device raster memory is H*W/T, unlike
    bin_points_rowsharded, whose psum_scatter needs the full local
    (H, W) raster before scattering. Copies across the data axis merge
    with a psum. Returns the (H, W) raster row-sharded over the tile
    axis (replicated over data).

    Returns ``(band_raster, dropped)`` — always a pair, regardless of
    arguments, so the call site's unpacking cannot depend on which
    knobs were passed. ``send_capacity`` bounds the per-destination
    all_to_all buffer (default: the per-device point count, which
    cannot overflow — ``dropped`` is then structurally zero). Smaller
    values save memory but drop points past the capacity; ``dropped``
    is the replicated global count of points lost to the cap — the
    ops/sparse.py overflow contract applied to the collective: callers
    must check ``dropped == 0`` and fail/retry with a larger capacity
    rather than trust a skew assumption (the pattern is pinned by
    tests/test_parallel.py's skewed-band test).

    ``backend`` routes the band binning; unlike the replicated /
    rowsharded kernels it defaults to "xla", not "auto": this function
    needs tile >= 2 — i.e. real multi-chip hardware — so no 1-device
    on-chip gate can verify its pallas routing (docs/DESIGN.md §9
    verification ladder); opt in explicitly once a pod run verifies it.
    """
    T = mesh.shape[TILE_AXIS]
    D = mesh.shape[DATA_AXIS]
    if T < 2:
        raise ValueError(
            "bin_points_bandsharded needs a tile axis >= 2 "
            "(use bin_points_replicated/rowsharded on a data-only mesh)"
        )
    if window.height % T:
        raise ValueError(f"window height {window.height} not divisible by tile={T}")
    band_h = window.height // T
    if dtype is None:
        dtype = jnp.int32 if weights is None else jnp.float32
    n = latitude.shape[0]
    if n % (D * T):
        raise ValueError(f"{n} points not divisible by {D * T} devices")
    n_local = n // (D * T)
    cap = n_local if send_capacity is None else min(send_capacity, n_local)
    band_window = histogram.Window(
        zoom=window.zoom, row0=0, col0=0, height=band_h, width=window.width
    )

    counts_only = weights is None
    w = _ones_like_weights(weights, n, dtype)
    v = jnp.ones((n,), bool) if valid is None else jnp.asarray(valid, bool)

    def local(la, lo, w, v):
        row, col, pvalid = mercator.project_points(
            la, lo, window.zoom, dtype=proj_dtype
        )
        r = jnp.asarray(row, jnp.int32) - window.row0
        c = jnp.asarray(col, jnp.int32) - window.col0
        ok = (
            pvalid & v
            & (r >= 0) & (r < window.height)
            & (c >= 0) & (c < window.width)
        )
        dest = jnp.where(ok, r // band_h, T).astype(jnp.int32)
        # Sort by destination band so each band's points are contiguous
        # (invalid points sort last under sentinel T), then scatter
        # whole runs into fixed (T, cap) send buffers.
        order = jnp.argsort(dest)
        sd = dest[order]
        m = sd.shape[0]
        bounds = jnp.searchsorted(sd, jnp.arange(T + 1, dtype=sd.dtype))
        starts = bounds[:T]
        # Points past a destination's capacity fall out of the send
        # buffer (mode="drop" below); count them so the loss is LOUD —
        # psum'd across the whole mesh and returned to the caller.
        per_dest = bounds[1:] - bounds[:T]
        local_dropped = jnp.maximum(per_dest - cap, 0).sum().astype(jnp.int32)
        slot = jnp.arange(m, dtype=jnp.int32) - starts[jnp.clip(sd, 0, T - 1)]
        send_r = jnp.full((T, cap), -1, jnp.int32).at[sd, slot].set(
            r[order], mode="drop"
        )
        send_c = jnp.zeros((T, cap), jnp.int32).at[sd, slot].set(
            c[order], mode="drop"
        )
        send_w = jnp.zeros((T, cap), dtype).at[sd, slot].set(
            w[order], mode="drop"
        )
        # The regroup "shuffle": row t of the send buffer goes to tile
        # position t; row j of the result came from tile position j.
        recv_r = lax.all_to_all(send_r, TILE_AXIS, 0, 0, tiled=True)
        recv_c = lax.all_to_all(send_c, TILE_AXIS, 0, 0, tiled=True)
        recv_w = lax.all_to_all(send_w, TILE_AXIS, 0, 0, tiled=True)
        t_idx = lax.axis_index(TILE_AXIS)
        rloc = recv_r.reshape(-1) - t_idx * band_h
        # Count jobs drop the regrouped unit weights (fill lanes carry
        # r=-1 and are masked by `valid` alone), keeping the band bin
        # on the count-only kernels under backend="auto".
        band = histogram.bin_rowcol_window(
            rloc,
            recv_c.reshape(-1),
            band_window,
            weights=None if counts_only else recv_w.reshape(-1),
            valid=recv_r.reshape(-1) >= 0,
            dtype=dtype,
            backend=backend,
        )
        # Different data-axis rows hold disjoint point shards of the
        # same band: merge, leaving the band replicated over data.
        merged = lax.psum(band, DATA_AXIS)
        dropped = lax.psum(local_dropped, (DATA_AXIS, TILE_AXIS))
        return merged, dropped

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P((DATA_AXIS, TILE_AXIS)),
            P((DATA_AXIS, TILE_AXIS)),
            P((DATA_AXIS, TILE_AXIS)),
            P((DATA_AXIS, TILE_AXIS)),
        ),
        out_specs=(P(TILE_AXIS, None), P()),
        check_vma=False,
    )
    return fn(latitude, longitude, w, v)
